"""Device-mesh sharding for the pods × nodes solve (SURVEY §6.7).

The reference's only parallelism is a 16-goroutine parallel-for across
nodes inside one pod's cycle (framework/parallelize/parallelism.go) plus
node sampling and active/passive replication. The TPU framework's
parallelism is the hardware kind: the NODE axis is this problem's
"sequence/context" dimension, sharded over a `jax.sharding.Mesh` so per-
step reductions (argmax, cumsum, segment sums) become XLA collectives over
ICI — the scaling-book recipe: pick a mesh, annotate shardings, let GSPMD
insert the collectives.

Coverage: the PRODUCTION solve path is sharded end to end —
`ExactSolver.solve(mesh=...)` (per-pod scan, grouped fast path, the
compact wire, and the chained sub-batch split all dispatch against
node-axis-sharded resident tables), the device session (dirty-column
heals scatter into the sharded residents; only the owning shard's slice
changes), and the scheduler (`SchedulerConfig.mesh_devices` threads one
mesh through both scheduling loops, so overlap/carry/sync batches all
run sharded). `SingleShotSolver.solve(mesh=...)` and the driver's
`dryrun_multichip` ride the same helpers.

Conventions (used by both solvers, the device session, and
tests/test_sharding.py):
- node-resident arrays carry the node axis LAST -> P(None, ..., "nodes")
  for n-D tables, P("nodes") for 1-D columns; the node padding must be a
  device-count multiple (Snapshot.pad_multiple / schema.pad_to handle
  this), with padded rows masked unschedulable everywhere;
- per-pod / per-class / per-instance arrays replicate (they are small and
  every shard needs them for its local mask/score block) —
  REPLICATED_TABLE_NAMES is the authoritative name set for the class
  tables without a node axis;
- results are device-count invariant BIT-EXACTLY: integer score
  arithmetic and stable reductions make sharded == unsharded, which
  tests/test_sharding.py asserts for BOTH solvers (and end-to-end
  through the Scheduler) on the 8-device virtual CPU mesh.
"""

from __future__ import annotations

import numpy as np

NODE_AXIS = "nodes"

# Class/instance tables WITHOUT a trailing node axis (per-instance spread
# scalars, per-class term index rows, per-term flags): replicated. Every
# other solver table shards over its trailing node axis.
REPLICATED_TABLE_NAMES = frozenset(
    {
        # spread (SpreadTensors device dict)
        "max_skew",
        "min_domains",
        "self_match",
        "is_hostname",
        "hard",
        "soft",
        # interpod (InterpodTensors device dict)
        "in_pref_w",
        "cls_req_aff",
        "cls_req_anti",
        "cls_pref",
        "ex_anti",
    }
)


def node_mesh(n_devices: int | None = None):
    """A 1-D mesh over the node axis (the v5e-8 shape: 8 chips, ICI ring).

    Uses the first ``n_devices`` visible devices (default: all)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=(NODE_AXIS,))


def resolve_mesh(mesh_devices: int, mesh_slice: tuple | None = None):
    """SchedulerConfig.mesh_devices (+ optional mesh_slice) -> Mesh | None.

    Without a slice: 0 = all visible devices; 1 = single-device (no
    mesh, the unsharded fast path); N > 1 = the first min(N, visible)
    devices. A resolved count of 1 returns None — a 1-way mesh would
    pay GSPMD lowering for nothing.

    ``mesh_slice=(rank, count)`` is the fleet's device-tier partition
    (config key fleet.meshSlice = "rank/count"): the visible device
    list is cut into ``count`` contiguous first-N slices of equal size
    and this process owns slice ``rank`` EXCLUSIVELY — N replicas on
    one host therefore dispatch against disjoint device sets, which is
    what lets the fleet tier multiply the streaming dispatcher instead
    of fighting over one accelerator. ``mesh_devices`` then applies
    WITHIN the slice (0 = the whole slice). Unlike the no-slice path, a
    1-device slice still returns a 1-way Mesh: the mesh is what pins
    the solve to THIS replica's device — falling back to the default
    device would silently stack every replica on device 0, the exact
    sharing violation the slice exists to prevent."""
    if mesh_slice is None:
        if mesh_devices == 1:
            return None
        import jax

        visible = len(jax.devices())
        n = visible if mesh_devices <= 0 else min(mesh_devices, visible)
        if n < 2:
            return None
        return node_mesh(n)

    import jax
    from jax.sharding import Mesh

    rank, count = int(mesh_slice[0]), int(mesh_slice[1])
    if count < 1 or not 0 <= rank < count:
        raise ValueError(
            f"mesh_slice must be (rank, count) with 0 <= rank < count; "
            f"got {mesh_slice!r}"
        )
    devices = jax.devices()
    share = len(devices) // count
    if share < 1:
        raise ValueError(
            f"mesh_slice {rank}/{count} needs at least {count} visible "
            f"devices for disjoint per-replica slices; only "
            f"{len(devices)} are visible"
        )
    mine = devices[rank * share : (rank + 1) * share]
    n = len(mine) if mesh_devices <= 0 else min(mesh_devices, len(mine))
    return Mesh(np.array(mine[:n]), axis_names=(NODE_AXIS,))


def mesh_fingerprint(mesh) -> tuple | None:
    """Hashable identity for jit/session cache keys: device set + shape.
    None for the unsharded path."""
    if mesh is None:
        return None
    return (
        tuple(d.id for d in mesh.devices.flat),
        mesh.axis_names,
    )


def node_sharding(mesh, ndim: int):
    """NamedSharding for a node-resident array: node axis last."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if ndim == 1:
        return NamedSharding(mesh, P(NODE_AXIS))
    return NamedSharding(mesh, P(*([None] * (ndim - 1) + [NODE_AXIS])))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def put_node_table(mesh, a, node_pad: int | None = None):
    """Place one solver table: sharded over its trailing node axis when
    that axis is the node padding (or ``node_pad`` is None), replicated
    otherwise (dummy [1, 1] placeholders). Known scalar class tables are
    placed by NAME via REPLICATED_TABLE_NAMES in the callers — the shape
    test here is only for arrays that are either true node tables or
    trailing-dim-1 dummies, where it cannot collide."""
    import jax

    a = np.asarray(a)
    if node_pad is not None and (a.ndim == 0 or a.shape[-1] != node_pad):
        return jax.device_put(a, replicated(mesh))
    return jax.device_put(a, node_sharding(mesh, a.ndim))


def placers(mesh, node_pad: int | None = None):
    """The (replicated-put, node-table-put) pair every solve-side
    placement site needs: ``dev`` replicates (per-pod packed arrays,
    scalars, heal payloads), ``dev_n`` shards over the trailing node
    axis via put_node_table. mesh=None degrades both to jnp.asarray —
    the unsharded single-device path."""
    import jax
    import jax.numpy as jnp

    if mesh is None:
        return jnp.asarray, jnp.asarray
    repl = replicated(mesh)

    def dev(a):
        return jax.device_put(np.ascontiguousarray(a), repl)

    def dev_n(a):
        return put_node_table(mesh, a, node_pad)

    return dev, dev_n


def shard_node_tree(mesh, tree, replicate_names: frozenset[str] = frozenset()):
    """Map a pytree of arrays to shardings: arrays shard over their
    trailing node axis unless their dict key is in ``replicate_names``
    (per-class / per-instance tables without a node axis)."""
    import jax.tree_util as jtu

    repl = replicated(mesh)

    def one(path, a):
        key = path[-1].key if path and hasattr(path[-1], "key") else None
        if key in replicate_names:
            return repl
        return node_sharding(mesh, np.ndim(a))

    return jtu.tree_map_with_path(one, tree)


def device_put_tree(tree, shardings):
    """jax.device_put each leaf with its sharding."""
    import jax
    import jax.tree_util as jtu

    return jtu.tree_map(jax.device_put, tree, shardings)
