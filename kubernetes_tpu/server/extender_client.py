"""Outbound scheduler-extender client — the scheduler-side half of the
extender boundary (pkg/scheduler/extender.go#HTTPExtender), so the
``extenders[]`` section of KubeSchedulerConfiguration is HONORED, not just
parsed: configured extenders are consulted during the solve
(schedule_one.go#findNodesThatPassExtenders / #prioritizeNodes) and can
own the bind (#Bind).

TPU-shaped consultation model: the reference calls extenders once per
pod. Here Filter/Prioritize verdicts fold into the per-scheduling-class
device tables (like out-of-tree framework plugins): ONE filter + ONE
prioritize HTTP round trip per (class, extender) per batch, amortizing
the wire across every pod in the class. The divergence this buys is
documented and narrow: an extender is not re-consulted between two pods
of the same batch, so extender-side state that changes per placement is
not observed mid-batch — the same contract a nodeCacheCapable extender
already accepts between cache syncs.

Wire shapes are extender/v1 (lowercase JSON tags like the server half in
server/extender.py): ExtenderArgs{pod, nodes|nodenames} ->
ExtenderFilterResult{nodes|nodenames, failedNodes,
failedAndUnresolvableNodes, error} / HostPriorityList, and
ExtenderBindingArgs{podName, podNamespace, podUID, node} ->
ExtenderBindingResult{error}.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Sequence

from ..api.objects import Node, Pod
from ..config.types import Extender

# extender/v1/types.go#MaxExtenderPriority; scores rescale into the
# framework's MaxNodeScore range by MAX_NODE_SCORE / MAX_EXTENDER_PRIORITY
MAX_EXTENDER_PRIORITY = 10
MAX_NODE_SCORE = 100


class ExtenderError(Exception):
    """A non-ignorable extender failed: the reference aborts the pod's
    scheduling cycle with an error status (not Unschedulable)."""


class HTTPExtenderClient:
    """One configured extender (extender.go#HTTPExtender).

    ``transport`` is the injectable wire seam: a callable
    ``(verb, payload) -> parsed JSON`` that replaces the real HTTP POST.
    Production leaves it None (urllib against ``url_prefix``); the
    cluster simulator injects a fault transport here so extender
    latency/timeout/5xx scenarios exercise the REAL client paths —
    ignorable-skip, non-ignorable batch abort, malformed-result
    rejection — without a socket. A transport signals failure by raising
    ``OSError`` (connection/timeout analog) or ``ValueError`` (bad
    body); both map to ExtenderError exactly like the HTTP path."""

    def __init__(
        self, cfg: Extender, timeout: float = 5.0, transport=None
    ) -> None:
        self.cfg = cfg
        self.timeout = timeout
        self.transport = transport
        # cross-process trace propagation (kubernetes_tpu/obs): when
        # set — the scheduler points it at the current batch's trace
        # context before folding — every outbound verb carries it as
        # the payload's optional ``traceContext`` member, so an
        # extender server sharing the obs layer attributes its
        # micro-batched evaluation to the CALLER's trace. Servers that
        # don't know the field ignore it (extender/v1 parsers skip
        # unknown members; the reference server does).
        self.trace_context: dict | None = None

    @property
    def name(self) -> str:
        return self.cfg.url_prefix

    @property
    def is_binder(self) -> bool:
        return bool(self.cfg.bind_verb)

    @property
    def ignorable(self) -> bool:
        return self.cfg.ignorable

    def is_interested(self, pod: Pod) -> bool:
        """extender.go#IsInterested: no managedResources = all pods;
        otherwise any container requesting a managed resource."""
        if not self.cfg.managed_resources:
            return True
        managed = {
            m.get("name") for m in self.cfg.managed_resources if m.get("name")
        }
        return any(r in managed for r in pod.resource_request())

    # -- verbs --

    def _post(self, verb: str, payload: dict) -> dict | list:
        if self.trace_context is not None and isinstance(payload, dict):
            payload = dict(payload, traceContext=self.trace_context)
        if self.transport is not None:
            try:
                return self.transport(verb, payload)
            except (OSError, ValueError) as e:
                raise ExtenderError(
                    f"extender {self.name}/{verb}: {e}"
                ) from e
        req = urllib.request.Request(
            f"{self.cfg.url_prefix.rstrip('/')}/{verb}",
            json.dumps(payload).encode(),
            {"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ExtenderError(f"extender {self.name}/{verb}: {e}") from e

    def _args(self, pod: Pod, nodes: Sequence[Node]) -> dict:
        if self.cfg.node_cache_capable:
            return {
                "pod": pod.to_dict(),
                "nodenames": [n.name for n in nodes],
            }
        return {
            "pod": pod.to_dict(),
            "nodes": {"items": [n.to_dict() for n in nodes]},
        }

    def filter(
        self, pod: Pod, nodes: Sequence[Node]
    ) -> tuple[set, dict, dict]:
        """(kept node names, failedNodes, failedAndUnresolvableNodes)."""
        out = self._post(self.cfg.filter_verb, self._args(pod, nodes))
        if not isinstance(out, dict):
            raise ExtenderError(
                f"extender {self.name}: malformed filter result"
            )
        if out.get("error"):
            raise ExtenderError(f"extender {self.name}: {out['error']}")
        if out.get("nodenames") is not None:
            kept = set(out["nodenames"])
        else:
            kept = {
                d.get("metadata", {}).get("name")
                for d in (out.get("nodes") or {}).get("items") or []
            }
        return (
            kept,
            dict(out.get("failedNodes") or {}),
            dict(out.get("failedAndUnresolvableNodes") or {}),
        )

    def prioritize(self, pod: Pod, nodes: Sequence[Node]) -> dict:
        """node name -> weighted score contribution, already rescaled
        into the framework range: score * weight *
        (MaxNodeScore / MaxExtenderPriority) — prioritizeNodes' math."""
        out = self._post(self.cfg.prioritize_verb, self._args(pod, nodes))
        if not isinstance(out, list):
            raise ExtenderError(
                f"extender {self.name}: malformed HostPriorityList"
            )
        factor = self.cfg.weight * (MAX_NODE_SCORE // MAX_EXTENDER_PRIORITY)
        scores: dict[str, int] = {}
        try:
            for item in out:
                host, score = item.get("host"), int(item.get("score", 0))
                if host is None:
                    continue
                if not 0 <= score <= MAX_EXTENDER_PRIORITY:
                    raise ExtenderError(
                        f"extender {self.name}: score {score} for {host} "
                        f"outside [0, {MAX_EXTENDER_PRIORITY}]"
                    )
                scores[host] = score * factor
        except (TypeError, ValueError, AttributeError) as e:
            # malformed items stay inside the ExtenderError hierarchy so
            # an ignorable extender's bad response is skippable
            raise ExtenderError(
                f"extender {self.name}: malformed HostPriorityList "
                f"item: {e}"
            ) from e
        return scores

    def bind(self, pod: Pod, node_name: str) -> None:
        """Delegate the bind (extender.go#Bind): the extender commits the
        binding subresource; an {error} result fails the binding cycle."""
        out = self._post(
            self.cfg.bind_verb,
            {
                "podName": pod.name,
                "podNamespace": pod.namespace,
                "podUID": pod.uid or "",
                "node": node_name,
            },
        )
        if isinstance(out, dict) and out.get("error"):
            raise ExtenderError(f"extender {self.name}: {out['error']}")


def fold_extenders(
    clients: Sequence[HTTPExtenderClient],
    reps: Sequence[Pod],
    slot_nodes: Sequence[Node | None],
    mask,
    extra_score,
) -> None:
    """Fold extender Filter/Prioritize verdicts into the per-class device
    tables (the out-of-tree-plugin folding pattern,
    framework/runtime.py#fold_out_of_tree): per scheduling class, each
    extender in configured order filters the class's surviving candidate
    set and its prioritize scores accumulate weighted into extra_score.
    failedNodes and failedAndUnresolvableNodes both clear the mask (the
    unresolvable distinction only matters to preemption, which re-checks
    candidates itself). An ignorable extender's failure skips that
    extender; a non-ignorable failure raises ExtenderError, aborting the
    batch — an outage must not silently read as Unschedulable."""
    for c, rep in enumerate(reps):
        interested = [cl for cl in clients if cl.is_interested(rep)]
        if not interested:
            continue
        for cl in interested:
            candidates = [
                (slot, node)
                for slot, node in enumerate(slot_nodes)
                if node is not None and mask[c, slot]
            ]
            if not candidates:
                break
            nodes = [node for _, node in candidates]
            if cl.cfg.filter_verb:
                try:
                    kept, _failed, _unresolvable = cl.filter(rep, nodes)
                except ExtenderError:
                    if cl.ignorable:
                        continue
                    raise
                for slot, node in candidates:
                    if node.name not in kept:
                        mask[c, slot] = False
            if cl.cfg.prioritize_verb:
                # re-read the mask: prioritize only the set that SURVIVED
                # this extender's own filter pass (the reference
                # prioritizes the feasible set, and a partial-view server
                # may reject names it just failed)
                survivors = [
                    (slot, node)
                    for slot, node in candidates
                    if mask[c, slot]
                ]
                if not survivors:
                    continue
                try:
                    scores = cl.prioritize(
                        rep, [node for _, node in survivors]
                    )
                except ExtenderError:
                    if cl.ignorable:
                        continue
                    raise
                for slot, node in survivors:
                    s = scores.get(node.name)
                    if s:
                        extra_score[c, slot] += s
