"""Crash/restart statelessness (SURVEY §6.3): the scheduler holds no
durable state — a fresh Scheduler over the same ClusterState resyncs via
the initial informer sync and continues correctly, including in-flight
preemption intent persisted in pod.status.nominatedNodeName.

PR 8 made the restart a first-class RECOVERY pass: a fresh incarnation
(``SchedulerConfig.incarnation > 1``) re-adopts every orphaned unbound
pod with a terminal ``recovered`` journal record, rolls back
half-committed claim reservations, and deliberately RESETS
quarantine/breaker state (a poison pod re-quarantines through the
ordinary bisection path — tested below)."""

import json
import tempfile

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.obs import ObsConfig
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock
from kubernetes_tpu.utils import tracing


def _cfg(**kw):
    kw.setdefault("solver", ExactSolverConfig(tie_break="first"))
    return SchedulerConfig(**kw)


def test_restart_resumes_pending_and_nominations():
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    s1 = Scheduler(cs, _cfg(), clock=clock)

    # schedule one pod, preempt for another, then "crash" (drop s1)
    victim = MakePod().name("victim").priority(0).req({"cpu": "2"}).obj()
    cs.create_pod(victim)
    cs.bind("default", "victim", "n")
    cs.create_pod(MakePod().name("preemptor").priority(10).req({"cpu": "2"}).obj())
    r = s1.schedule_batch()
    assert r.preemptions
    assert cs.get_pod("default", "preemptor").nominated_node_name == "n"

    # restart: a NEW scheduler over the same cluster state must pick up the
    # pending preemptor (initial sync), honor its persisted nomination, and
    # protect it from a thief that arrived during the outage
    cs.create_pod(MakePod().name("thief").priority(1).req({"cpu": "2"}).obj())
    clock.advance(30.0)
    s2 = Scheduler(cs, _cfg(), clock=clock)
    assert "default/preemptor" in s2.nominated_pods
    r = s2.schedule_batch()
    placed = dict(r.scheduled)
    assert placed.get("default/preemptor") == "n"
    assert "default/thief" in r.unschedulable


def test_restart_reconstructs_bound_state():
    """Bound pods re-enter the cache on restart: a full node stays full."""
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    s1 = Scheduler(cs, _cfg(), clock=clock)
    cs.create_pod(MakePod().name("a").req({"cpu": "2"}).obj())
    assert dict(s1.schedule_batch().scheduled).get("default/a") == "n"

    s2 = Scheduler(cs, _cfg(), clock=clock)
    cs.create_pod(MakePod().name("b").req({"cpu": "2"}).obj())
    r = s2.schedule_batch()
    assert "default/b" in r.unschedulable or r.preemptions == []


def _journal_outcomes(sched):
    return [json.loads(line)["outcome"] for line in sched.journal.lines]


def test_restart_journals_recovered_for_orphans():
    """A restarted incarnation terminally journals `recovered` for
    every unbound pod it re-adopts — closing histories the crash left
    dangling — tagged with the incarnation number."""
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}
        ).obj()
    )
    cs.create_pod(MakePod().name("a").req({"cpu": "1"}).obj())
    cs.create_pod(MakePod().name("b").req({"cpu": "1"}).obj())
    s2 = Scheduler(
        cs, _cfg(incarnation=2, obs=ObsConfig(journal=True)), clock=clock
    )
    recs = [json.loads(line) for line in s2.journal.lines]
    assert [r["outcome"] for r in recs] == ["recovered", "recovered"]
    assert all(r["incarnation"] == 2 for r in recs)
    assert {r["pod"] for r in recs} == {"default/a", "default/b"}
    # the re-adopted pods schedule normally
    r = s2.schedule_batch()
    assert len(r.scheduled) == 2
    assert _journal_outcomes(s2)[-2:] == ["bound", "bound"]


def test_first_start_journals_no_recovered():
    """incarnation=1 (a first start) must NOT journal recovered records
    — there is no predecessor whose histories need closing, and the
    journal bytes of existing runs must not change."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}
        ).obj()
    )
    cs.create_pod(MakePod().name("a").req({"cpu": "1"}).obj())
    s1 = Scheduler(cs, _cfg(obs=ObsConfig(journal=True)), clock=FakeClock())
    assert s1.journal.lines == []
    assert "incarnation" not in s1.journal.tags


def test_restart_rolls_back_half_committed_claim():
    """A claim reserved for an UNBOUND pod can only mean a crash hit
    between the PreBind claim write and the bind commit: recovery
    releases the reservation (and the allocation when nobody else
    holds it), like the deallocating controller would on delete."""
    from kubernetes_tpu.api.dra import (
        DeviceRequest,
        DeviceResult,
        ResourceClaim,
    )
    from kubernetes_tpu.utils.featuregate import FeatureGates

    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}
        ).obj()
    )
    cs.create_pod(
        MakePod().name("orphan").req({"cpu": "1"}).resource_claim("c").obj()
    )
    cs.create_resource_claim(
        ResourceClaim(
            name="c",
            requests=(DeviceRequest(name="r", device_class_name="tpu"),),
            allocated_node="n",
            results=(DeviceResult(request="r", driver="d", pool="p", device="0"),),
            reserved_for=("default/orphan",),
        )
    )
    Scheduler(
        cs,
        _cfg(
            incarnation=2,
            feature_gates=FeatureGates.parse(
                "DynamicResourceAllocation=true"
            ),
        ),
        clock=clock,
    )
    c = cs.get_resource_claim("default", "c")
    assert c.reserved_for == ()
    assert c.allocated_node == ""  # devices freed


def test_restart_leaves_bound_pod_claims_alone():
    """Reservations naming BOUND pods are legitimate committed
    occupancy: recovery must not touch them."""
    from kubernetes_tpu.api.dra import (
        DeviceRequest,
        DeviceResult,
        ResourceClaim,
    )
    from kubernetes_tpu.utils.featuregate import FeatureGates

    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}
        ).obj()
    )
    cs.create_pod(
        MakePod().name("ok").req({"cpu": "1"}).resource_claim("c").obj()
    )
    cs.bind("default", "ok", "n")
    cs.create_resource_claim(
        ResourceClaim(
            name="c",
            requests=(DeviceRequest(name="r", device_class_name="tpu"),),
            allocated_node="n",
            results=(DeviceResult(request="r", driver="d", pool="p", device="0"),),
            reserved_for=("default/ok",),
        )
    )
    Scheduler(
        cs,
        _cfg(
            incarnation=2,
            feature_gates=FeatureGates.parse(
                "DynamicResourceAllocation=true"
            ),
        ),
        clock=FakeClock(),
    )
    c = cs.get_resource_claim("default", "c")
    assert c.reserved_for == ("default/ok",)
    assert c.allocated_node == "n"


def test_restart_leaves_foreign_scheduler_claims_alone():
    """A claim reserved for an unbound pod owned by a FOREIGN
    scheduler (spec.schedulerName outside our profiles) is not ours to
    roll back — that scheduler may be between its own PreBind claim
    write and bind right now."""
    from kubernetes_tpu.api.dra import (
        DeviceRequest,
        DeviceResult,
        ResourceClaim,
    )
    from kubernetes_tpu.utils.featuregate import FeatureGates

    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}
        ).obj()
    )
    cs.create_pod(
        MakePod()
        .name("theirs")
        .scheduler_name("other-scheduler")
        .req({"cpu": "1"})
        .resource_claim("c")
        .obj()
    )
    cs.create_resource_claim(
        ResourceClaim(
            name="c",
            requests=(DeviceRequest(name="r", device_class_name="tpu"),),
            allocated_node="n",
            results=(DeviceResult(request="r", driver="d", pool="p", device="0"),),
            reserved_for=("default/theirs",),
        )
    )
    Scheduler(
        cs,
        _cfg(
            incarnation=2,
            feature_gates=FeatureGates.parse(
                "DynamicResourceAllocation=true"
            ),
        ),
        clock=FakeClock(),
    )
    c = cs.get_resource_claim("default", "c")
    assert c.reserved_for == ("default/theirs",)
    assert c.allocated_node == "n"


def test_restart_recovers_permit_parked_orphan():
    """A pod parked at Permit when the process dies is assumed but
    unbound: the fresh incarnation re-adopts it from truth (the
    WaitingPods map evaporated with the dead process) and schedules it
    to completion."""
    from kubernetes_tpu.framework.interface import (
        PermitPlugin,
        Status,
        StatusCode,
    )

    class HoldAtPermit(PermitPlugin):
        def permit(self, state, pod, node_name):
            return Status(StatusCode.WAIT), 30.0

    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}
        ).obj()
    )
    s1 = Scheduler(
        cs, _cfg(out_of_tree_plugins=(HoldAtPermit(),)), clock=clock
    )
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    s1.schedule_batch()
    assert list(s1.waiting_pods()) == ["default/p"]  # parked + assumed

    # crash: s1 evaporates with the pod assumed-but-unbound
    cs.unsubscribe(s1._on_event)
    s2 = Scheduler(
        cs, _cfg(incarnation=2, obs=ObsConfig(journal=True)), clock=clock
    )
    assert _journal_outcomes(s2) == ["recovered"]
    r = s2.schedule_batch()
    assert dict(r.scheduled).get("default/p") == "n"


def test_restart_requarantines_poison_pod():
    """Quarantine state deliberately RESETS on restart (documented in
    Scheduler._recover): a poison pod that crashed its first
    incarnation is re-discovered by the fresh incarnation through the
    ordinary bisection path — re-quarantined, not crash-looped."""
    from kubernetes_tpu.resilience import SolverFaultError

    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "10"}
        ).obj()
    )
    cs.create_pod(
        MakePod().name("poison").label("poison", "1").req({"cpu": "1"}).obj()
    )
    cs.create_pod(MakePod().name("fine").req({"cpu": "1"}).obj())

    def poison_fault(pods, tier):
        if any(p.labels.get("poison") for p in pods):
            raise SolverFaultError("data poison breaks every tier")

    s1 = Scheduler(cs, _cfg(), clock=clock)
    s1._solve_fault = poison_fault
    s1.run_until_settled()
    assert "default/poison" in s1._quarantine
    # crash: incarnation 1 (and its quarantine map) evaporates
    cs.unsubscribe(s1._on_event)

    s2 = Scheduler(
        cs, _cfg(incarnation=2, obs=ObsConfig(journal=True)), clock=clock
    )
    assert s2._quarantine == {}  # reset, not carried over
    s2._solve_fault = poison_fault
    r = s2.run_until_settled()
    # re-discovered within the first batches, healthy pod unaffected
    assert "default/poison" in s2._quarantine
    assert any("quarantined" == o for o in _journal_outcomes(s2))
    assert cs.get_pod("default", "fine").node_name == "n"
    assert r is not None


def _hist_count(hist) -> float:
    for metric in hist.collect():
        for s in metric.samples:
            if s.name.endswith("_count"):
                return s.value
    raise AssertionError("histogram has no _count sample")


def test_recovery_metric_and_span_observed():
    """The recovery pass reports scheduler_restart_recovery_seconds and
    a `recover` root span with counts."""
    from kubernetes_tpu import metrics

    before = _hist_count(metrics.restart_recovery_seconds)
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}
        ).obj()
    )
    cs.create_pod(MakePod().name("a").req({"cpu": "1"}).obj())
    clock = FakeClock()
    clock.advance(1.0)
    s2 = Scheduler(
        cs, _cfg(incarnation=2, obs=ObsConfig(journal=True, spans=True)),
        clock=clock,
    )
    # FakeClock makes the duration 0.0 — the observation COUNT proves
    # the metric fired (the sum stays equal on virtual time)
    assert _hist_count(metrics.restart_recovery_seconds) == before + 1
    assert s2.journal.lines  # recovered record written under the span


def test_tracing_wraps_schedule_batch(tmp_path):
    """--trace-dir plumbing: enabling tracing must not change behavior and
    must produce a trace directory when solves run."""
    tracing.enable(str(tmp_path))
    try:
        clock = FakeClock()
        cs = ClusterState()
        cs.create_node(
            MakeNode().name("n").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
        )
        sched = Scheduler(cs, _cfg(), clock=clock)
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        r = sched.schedule_batch()
        assert dict(r.scheduled).get("default/p") == "n"
    finally:
        tracing.stop()
        tracing._trace_dir = None
    assert any(tmp_path.iterdir())  # the profiler wrote a session dir
