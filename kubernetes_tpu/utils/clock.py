"""Injectable clock, mirroring k8s.io/utils/clock — the queue/cache tests
need deterministic time (reference queue tests inject
k8s.io/utils/clock/testing#FakeClock).

Two faces:

- ``now()``   — the scheduling clock (backoff expiry, assume TTLs, permit
  deadlines, e2e latency bases). Monotonic wall time on the real clock.
- ``perf()``  — the duration clock (metric observations, solve/host wall
  splits). ``time.perf_counter`` on the real clock.

``FakeClock`` drives BOTH from one virtual timeline so the cluster
simulator (``kubernetes_tpu/sim``) runs fully virtual-time: no test ever
sleeps, and a recorded trace replays bit-for-bit regardless of host
speed.
"""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        return time.monotonic()

    def perf(self) -> float:
        return time.perf_counter()

    def sleep(self, seconds: float) -> None:
        """Blocking wait on the clock's timeline (BulkClient's retry
        backoff); the fake clock advances virtually instead, so
        backoff paths are testable without real delay."""
        time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 0.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def perf(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)
