"""DRA allocation oracle — the dynamicresources plugin's candidate-node
and device-picking logic (pkg/scheduler/framework/plugins/dynamicresources/
[U], structured parameters), host-side.

Device accounting model ([BOUNDARY], api/dra.py documents the scope): a
device is identified by (driver, pool, name) on one node; it is free
unless some allocated ResourceClaim's results contain it. A claim is
allocatable on a node iff, walking its requests in order and taking
devices greedily (lowest slice/device index first — deterministic), every
request finds `count` free devices matching its DeviceClass. Allocated
claims pin their pods to the allocation's node.

The per-class node-count view feeds the solver's static mask the same way
the fused volume filter does: scheduling classes whose claims cannot be
satisfied on a node get that node masked before the device solve.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Mapping, Sequence

import numpy as np

from ...api.dra import DeviceClass, DeviceResult, ResourceClaim, ResourceSlice
from ...api.objects import Node, Pod


class ClaimError(Exception):
    """Unresolvable claim reference / unsupported shape — the pod is
    unschedulable with this message (UnschedulableAndUnresolvable)."""


@dataclass
class _NodeDevices:
    # parallel lists, slice order then device order (deterministic
    # picking); identity of row i is ids[i] = (driver, pool, name)
    drivers: list[str] = field(default_factory=list)
    ids: list[tuple[str, str, str]] = field(default_factory=list)
    devices: list = field(default_factory=list)  # Device objects


@dataclass
class DraContext:
    classes: dict[str, DeviceClass]
    claims: dict[str, ResourceClaim]  # key = ns/name
    by_node: dict[str, _NodeDevices]
    # (driver, pool, device name) identities already taken, per node
    taken: dict[str, set[tuple[str, str, str]]]

    @staticmethod
    def build(
        slices: Iterable[ResourceSlice],
        classes: Iterable[DeviceClass],
        claims: Iterable[ResourceClaim],
    ) -> "DraContext":
        by_node: dict[str, _NodeDevices] = {}
        for s in sorted(slices, key=lambda s: s.name):
            nd = by_node.setdefault(s.node_name, _NodeDevices())
            for dv in s.devices:
                nd.drivers.append(s.driver)
                nd.ids.append((s.driver, s.pool, dv.name))
                nd.devices.append(dv)
        taken: dict[str, set[tuple[str, str, str]]] = {}
        claim_map = {c.key: c for c in claims}
        for c in claim_map.values():
            if c.allocated:
                t = taken.setdefault(c.allocated_node, set())
                for r in c.results:
                    t.add((r.driver, r.pool, r.device))
        return DraContext(
            classes={c.name: c for c in classes},
            claims=claim_map,
            by_node=by_node,
            taken=taken,
        )

    # -- feasibility --

    def pod_claims(self, pod: Pod) -> list[ResourceClaim]:
        """Resolve the pod's claim references; ClaimError on a missing
        claim, an unknown DeviceClass, or an unexpanded claim template."""
        if pod.claim_templates_unresolved:
            raise ClaimError(
                "pod references a resourceClaimTemplateName; claim "
                "generation from templates is out of scope (create the "
                "ResourceClaim and reference it by resourceClaimName)"
            )
        out = []
        # dedupe repeated references: a pod listing one claim twice uses
        # ONE claim, not two allocations
        for name in dict.fromkeys(pod.resource_claim_names):
            key = f"{pod.namespace}/{name}"
            c = self.claims.get(key)
            if c is None:
                raise ClaimError(f"resourceclaim {key} not found")
            for r in c.requests:
                if r.device_class_name not in self.classes:
                    raise ClaimError(
                        f"resourceclaim {key}: deviceclass "
                        f"{r.device_class_name!r} not found"
                    )
            out.append(c)
        return out

    def _free_indices(
        self, node_name: str, cls: DeviceClass, extra_taken: set
    ) -> list[int]:
        nd = self.by_node.get(node_name)
        if nd is None:
            return []
        t = self.taken.get(node_name, set())
        return [
            i
            for i, did in enumerate(nd.ids)
            if did not in t
            and did not in extra_taken
            and cls.matches(nd.drivers[i], nd.devices[i])
        ]

    def pick(
        self, node_name: str, claims: Sequence[ResourceClaim]
    ) -> dict[str, list[DeviceResult]] | None:
        """Greedy deterministic allocation of every unallocated claim's
        requests on one node; None when it doesn't fit. Allocated claims
        must already sit on this node (else None). Returns
        claim key -> device results."""
        picked: dict[str, list[DeviceResult]] = {}
        extra: set[tuple[str, str, str]] = set()
        nd = self.by_node.get(node_name)
        for c in claims:
            if c.allocated:
                if c.allocated_node != node_name:
                    return None
                continue
            results: list[DeviceResult] = []
            for req in c.requests:
                cls = self.classes[req.device_class_name]
                free = self._free_indices(node_name, cls, extra)
                if len(free) < req.count:
                    return None
                for i in free[: req.count]:
                    drv, pool, dev = nd.ids[i]
                    extra.add(nd.ids[i])
                    results.append(
                        DeviceResult(
                            request=req.name,
                            driver=drv,
                            device=dev,
                            pool=pool,
                        )
                    )
            picked[c.key] = results
        return picked

    def feasible_mask(
        self, pod: Pod, slot_nodes: Sequence[Node | None]
    ) -> np.ndarray:
        """[N] bool: nodes where every claim of ``pod`` can be satisfied
        (allocated claims pin to their node). Raises ClaimError for
        unresolvable references — the caller reports the pod
        unschedulable rather than masking silently."""
        claims = self.pod_claims(pod)
        n = len(slot_nodes)
        mask = np.zeros(n, dtype=bool)
        if not claims:
            mask[:] = True
            return mask
        pinned = {c.allocated_node for c in claims if c.allocated}
        if len(pinned) > 1:
            return mask  # claims allocated on different nodes: infeasible
        for i, node in enumerate(slot_nodes):
            if node is None:
                continue
            if pinned and node.name not in pinned:
                continue
            mask[i] = self.pick(node.name, claims) is not None
        return mask
