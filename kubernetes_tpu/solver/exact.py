"""Exact-parity solver: a lax.scan over pods in queue order (SURVEY.md §8.4
mode 1).

This replaces the reference's scheduleOne hot path
(pkg/scheduler/schedule_one.go#schedulePod -> findNodesThatFitPod ->
prioritizeNodes -> selectHost) with one compiled program: each scan step is a
dense filter-mask + score over ALL nodes at once (the per-(pod,node) Go
interface-call overhead becomes one fused XLA loop body), and the
assume-pod state mutation (cache.AssumePod) becomes an in-carry scatter so
the next step sees updated node state — preserving the reference's strict
pod-by-pod sequential semantics, which is what "binding parity" means.

Filter pipeline per step (runtime/framework.go#RunFilterPlugins, fused):
  NodeResourcesFit ∧ static class mask (NodeName ∧ NodeUnschedulable ∧
  TaintToleration ∧ NodeAffinity, precompiled per pod class) ∧ NodePorts
  (occupancy matvec over the port vocab) ∧ PodTopologySpread hard
  constraints (segment reductions over domain ids).

Score pipeline (runtime/framework.go#RunScorePlugins: score, normalize,
weight — default-profile weights from apis/config/v1/default_plugins.go):
  1·LeastAllocated + 1·BalancedAllocation + 3·TaintToleration(norm reverse)
  + 2·NodeAffinity(norm) + 1·ImageLocality + 2·PodTopologySpread(norm).

selectHost tie-break: the reference reservoir-samples uniformly among
max-score ties with an unseeded RNG (schedule_one.go#selectHost). Bit-parity
is impossible; we offer:
- "random": uniform among ties from a seeded PRNG key (documented divergence)
- "first":  lowest node index among ties (deterministic, used by parity tests)
Either way the pick is provably inside the reference's tie set, which is the
parity definition from SURVEY.md §8.8.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import ops as jops

from .. import metrics
from ..ops import fastmath
from ..ops import interpod as ip
from ..ops import noderesources as nr
from ..ops import plugins as pl
from ..ops import spread as sp
from ..parallel.sharding import (
    REPLICATED_TABLE_NAMES,
    mesh_fingerprint,
    placers,
    replicated,
)
from ..tensorize.interpod import InterpodTensors, trivial_interpod_tensors
from ..tensorize.plugins import (
    PortTensors,
    StaticPluginTensors,
    trivial_port_tensors,
    trivial_static_tensors,
)
from ..tensorize.spread import SpreadTensors, trivial_spread_tensors
from ..tensorize.schema import MEM_IDX, NodeBatch, PodBatch

TIE_RANDOM = "random"
TIE_FIRST = "first"


@dataclass(frozen=True)
class ExactSolverConfig:
    tie_break: str = TIE_RANDOM
    seed: int = 0
    # Score-plugin weights; defaults mirror the default profile
    # (apis/config/v1/default_plugins.go): TaintToleration 3, NodeAffinity 2,
    # PodTopologySpread 2, Fit/Balanced/ImageLocality 1.
    fit_weight: int = 1
    balanced_weight: int = 1
    # NodeResourcesFitArgs.scoringStrategy.type: LeastAllocated (default) |
    # MostAllocated | RequestedToCapacityRatio (shape + per-resource
    # weights below)
    scoring_strategy: str = "LeastAllocated"
    # NodeResourcesFitArgs.scoringStrategy.resources weights for the two
    # scoring resources the NonZero pipeline tracks (cpu milli, memory
    # bytes); other resources are rejected with a config warning
    cpu_weight: int = 1
    mem_weight: int = 1
    # RequestedToCapacityRatio shape: ((utilization, score), ...) ascending,
    # scores 0..10 (requested_to_capacity_ratio.go)
    rtc_shape: tuple = ()
    taint_weight: int = 3
    node_affinity_weight: int = 2
    image_weight: int = 1
    spread_weight: int = 2
    interpod_weight: int = 2
    # InterPodAffinityArgs.hardPodAffinityWeight (default 1) — consumed by
    # the interpod tensorizer when building m_w rows (the scheduler passes
    # it through to build_interpod_tensors)
    hard_pod_affinity_weight: int = 1
    balanced_fdtype: str = "float32"  # float64 for bit-parity on CPU tests
    # Grouped fast path (§8.4 batched variant): chunk size for runs of
    # identical pods; 0/1 disables. Engages for plain batches and — via
    # the kind-2/3 quota chunks — for hard-only spread and anti-only
    # interpod batches (grouped_eligible + _chunk_kinds hold the exact
    # conditions); soft spread / preferred terms / nominated pods route
    # through the per-pod scan. With tie_break="random" the grouped path
    # places q DISTINCT tie-set nodes per iteration (without replacement)
    # while the per-pod scan samples ties with replacement: every grouped
    # result is a sequentially valid outcome, but the placement
    # DISTRIBUTION differs from the ungrouped solver for the same seed, so
    # random-mode runs are not reproducible across group_size settings.
    # tie_break="first" is bit-identical either way.
    group_size: int = 64
    # Compact wire mode: when every chunk of a grouped batch is uniform
    # (host-verified; see _solve_grouped), upload one representative row
    # per chunk instead of [P, *] per-pod arrays. Results are bit-identical
    # to the full upload; this knob exists as an escape hatch and for the
    # equivalence tests.
    compact_wire: bool = True
    # plugins.filter.disabled for this profile (runtime/framework.go):
    # names whose Filter stage is skipped. Static-mask plugins are handled
    # by the tensorizer; these flags gate the in-scan filters. A non-empty
    # set also disables the grouped fast path (rare config; keep it exact).
    disabled_filters: tuple = ()
    # NodeAffinityArgs.addedAffinity, parsed into an api.objects.NodeAffinity
    # (consumed by the tensorizer via the scheduler; kept here so profile
    # construction is one object)
    added_affinity: object = None
    # PodTopologySpreadArgs.defaultingType: System (upstream default —
    # service-selected pods without explicit constraints get soft
    # zone/hostname spreading) | List (no cluster defaults)
    spread_defaulting: str = "System"
    # Pallas-kernel tier (config key tpuSolver.pallas, VERDICT r5
    # missing #8): route the InterPodAffinity (term, domain) count
    # aggregation through ops/pallas_kernels.domain_counts_pallas (the
    # MXU one-hot-contraction kernel) instead of the flattened
    # segment_sum, inside the production per-pod scan. Default OFF: the
    # measured negative results stand (pallas_kernels.py module
    # docstring — the x64 lowering defect on this toolchain, and the
    # identity fast path already removing the hot hostname case), so
    # the wiring exists for a build where the lowering works; on
    # non-TPU backends the kernel runs in interpret mode, which is what
    # the tier-1 parity tests exercise. The ident fast path still wins
    # when the tensorizer proves unique domains.
    pallas: bool = False


def grouped_eligible(
    cfg: "ExactSolverConfig",
    pod_pad: int,
    node_pad: int,
    use_spread: bool,
    use_interpod: bool,
    use_nominated: bool = False,
    spread_groupable: bool = False,
    interpod_groupable: bool = False,
) -> bool:
    """Single source of truth for the grouped fast path's dispatch
    condition — the scheduler consults it when choosing the pod-axis
    padding bucket, and ExactSolver.solve when picking the executable, so
    the two can never drift into padding-without-grouping. Nominated-pod
    load (rare, preemption aftermath) routes through the per-pod scan.

    ``spread_groupable``/``interpod_groupable``: the batch-level facts
    that make the kind-2/3 quota chunks possible (hard-only spread with no
    soft constraints; anti-affinity-only interpod). Solve derives them
    from the tensors; the scheduler mirrors them from the pods for its
    padding decision — a mismatch degrades to padded-slow, never to a
    wrong result (unqualified chunks replay the full pipeline)."""
    return (
        cfg.group_size > 1
        and not cfg.disabled_filters
        and (not use_spread or spread_groupable)
        and (not use_interpod or interpod_groupable)
        and not use_nominated
        and pod_pad % cfg.group_size == 0
        and node_pad >= cfg.group_size  # order[:group] gather needs N >= G
    )


def _fit_scorer(scoring_strategy, rtc_shape):
    """Scoring-strategy dispatch shared by the per-pod pipeline and the
    grouped fast path (resource_allocation.go scorer selection). All
    callers evaluate per-step-class shapes ([R, N] / [R, 2N]) where the
    kernels' default float-estimate exact division wins
    (ops/fastmath.py)."""
    if scoring_strategy == "RequestedToCapacityRatio" and rtc_shape:
        # ktpu: ignore[TPU001]: rtc_shape is a static argname, coerced once at trace time on Python ints
        sx = jnp.asarray([int(p[0]) for p in rtc_shape], dtype=jnp.int64)
        # ktpu: ignore[TPU001]: rtc_shape is a static argname, coerced once at trace time on Python ints
        sy = jnp.asarray([int(p[1]) for p in rtc_shape], dtype=jnp.int64)
        return lambda requested, alloc, w: nr.rtc_score(
            requested, alloc, w, sx, sy
        )
    if scoring_strategy == "MostAllocated":
        return nr.most_allocated_score
    return nr.least_allocated_score


def _mask_and_score(
    tables,
    st,
    x,
    *,
    scoring_strategy: str,
    w_cpu: int,
    w_mem: int,
    rtc_shape: tuple,
    disabled: tuple,
    w_fit: int,
    w_balanced: int,
    w_taint: int,
    w_nodeaff: int,
    w_image: int,
    w_spread: int,
    w_interpod: int,
    use_spread: bool,
    use_interpod: bool,
    d_pad: int,
    ipa_d_pad: int,
    fdtype,
    spread_soft: bool = True,
    ipa_ident: bool = False,
    ipa_score: bool = True,
    use_nominated: bool = False,
    use_nominated_ports: bool = False,
    use_extra_score: bool = False,
    pallas: bool = False,
):
    """One pod's full filter+score pipeline over all nodes against node
    state ``st`` (runtime/framework.go#RunFilterPlugins + #RunScorePlugins,
    fused). Returns ``score`` [N] int32 with -1 on infeasible lanes (the
    mask is recoverable as ``score >= 0``). Shared by the sequential scan
    step (which adds tie-break + assume scatter) and the stateless batch
    evaluator behind the extender boundary (solver/evaluate.py).

    ``spread_soft``/``ipa_ident``/``ipa_score`` are batch-static facts the
    tensorizers proved (no soft constraints; unique-domain topologies; no
    preferred terms): each one statically removes work from the compiled
    step — the measured difference is large (SURVEY §8.8: the per-pod scan
    budget is per-step microseconds, not milliseconds)."""
    alloc = tables["alloc"]
    alloc2 = alloc[: MEM_IDX + 1]  # cpu, memory rows for scoring
    weights2 = jnp.asarray([w_cpu, w_mem], dtype=alloc.dtype)
    fit_scorer = _fit_scorer(scoring_strategy, rtc_shape)
    spr = tables.get("spr")
    ipa = tables.get("ipa")
    cls = x["class_of"]

    mask = tables["static_mask"][cls] & tables["node_valid"]
    used = st["used"]
    pod_count = st["pod_count"]
    port_used = st["port_used"]
    if use_nominated:
        # addNominatedPods: nominated pods with priority >= this pod's
        # count as placed for the monotone filters; the pod's own
        # nomination (always inside its own level row) is subtracted out
        lvl = x["nom_level"]
        s = x["nominated_slot"]
        is_nom = s >= 0
        ss = jnp.maximum(s, 0)
        # nom_corr_* carries the load of nominated pods already PLACED by
        # earlier scan steps (the nominator-map removal on assume) so their
        # requests aren't counted twice — once as real used, once as
        # nominated load
        extra_u = tables["nom_used"][lvl] - st["nom_corr_used"][lvl]
        extra_c = tables["nom_cnt"][lvl] - st["nom_corr_cnt"][lvl]
        extra_u = extra_u.at[:, ss].add(-x["req"] * is_nom.astype(extra_u.dtype))
        extra_c = extra_c.at[ss].add(-is_nom.astype(extra_c.dtype))
        used = used + extra_u
        pod_count = pod_count + extra_c
        if use_nominated_ports:
            # NodePorts is as monotone as resources: nominated hostPorts
            # occupy their reserved node for lower-priority pods too
            extra_p = tables["nom_ports"][lvl] - st["nom_corr_ports"][lvl]
            extra_p = extra_p.at[:, ss].add(
                -x["pod_takes"] * is_nom.astype(extra_p.dtype)
            )
            port_used = port_used + extra_p
    if "NodeResourcesFit" not in disabled:
        mask = mask & nr.fit_mask(
            x["req"], x["req_mask"], alloc, used,
            pod_count, tables["max_pods"],
        )
    if "NodePorts" not in disabled:
        mask = mask & ~pl.ports_conflict_mask(
            x["pod_conflict"], port_used
        )
    if use_spread and "PodTopologySpread" not in disabled:
        mask = mask & ~sp.hard_violations(spr, st["spr_cnt"], cls, d_pad)
    if use_interpod:
        ipa_allowed, ipa_raw = ip.filter_and_score(
            ipa, st["ipa_in"], st["ipa_ex"], cls, x, ipa_d_pad,
            tables["node_valid"],
            ident=ipa_ident, score=ipa_score and w_interpod > 0,
            pallas=pallas,
        )
        if "InterPodAffinity" not in disabled:
            mask = mask & ipa_allowed

    requested = nr.scoring_requested(x["nonzero_req"], st["nonzero_used"])
    score = w_fit * fit_scorer(requested, alloc2, weights2)
    score = score + w_balanced * nr.balanced_allocation_score(
        requested, alloc2, fdtype=fdtype
    )
    score = score.astype(jnp.int32)
    if w_taint:
        score = score + w_taint * pl.normalize_score(
            tables["taint_cnt"][cls], mask, reverse=True
        )
    if w_nodeaff:
        score = score + w_nodeaff * pl.normalize_score(
            tables["nodeaff_pref"][cls], mask, reverse=False
        )
    if w_image:
        score = score + w_image * tables["image_score"][cls]
    if use_extra_score:
        # out-of-tree ScorePlugins + the gang heterogeneity objective
        # (gang/throughput.py's workload-class x accelerator-class
        # effective-throughput term), folded per class with weights
        # pre-applied — the kernel stays objective-agnostic
        score = score + tables["extra_score"][cls]
    if use_spread and w_spread and spread_soft:
        score = score + w_spread * sp.soft_scores(
            spr, st["spr_cnt"], cls, mask, d_pad, fdtype=fdtype
        )
    if use_interpod and w_interpod and ipa_score:
        score = score + w_interpod * ip.normalize(ipa_raw, mask)
    return jnp.where(mask, score, -1)


def _make_step(
    tables,
    *,
    tie_break: str,
    **pipe_kw,
):
    """Builds the per-pod scan step (one full filter+score pipeline over all
    nodes + assume scatter). Shared by the per-pod scan and the grouped
    solver's non-uniform fallback branch."""
    alloc = tables["alloc"]
    use_spread = pipe_kw["use_spread"]
    use_interpod = pipe_kw["use_interpod"]

    def step(carry, x):
        st, k = carry
        score = _mask_and_score(tables, st, x, **pipe_kw)
        mask = score >= 0

        best = jnp.max(score)
        feasible = best >= 0
        ties = (score == best) & mask
        csum = jnp.cumsum(ties)
        if tie_break == TIE_RANDOM:
            k, sub = jax.random.split(k)
            n_ties = csum[-1]
            pick_rank = jax.random.randint(sub, (), 0, jnp.maximum(n_ties, 1))
        else:
            pick_rank = 0
        pick = jnp.argmax(csum > pick_rank).astype(jnp.int32)
        if pipe_kw.get("use_nominated"):
            # schedule_one.go#evaluateNominatedNode: a pod carrying a
            # nomination takes that node if it is feasible, before any
            # scoring of alternatives
            s = x["nominated_slot"]
            nom_ok = (s >= 0) & mask[jnp.maximum(s, 0)]
            pick = jnp.where(nom_ok, jnp.maximum(s, 0).astype(jnp.int32), pick)

        found = feasible & x["pod_valid"]
        d = found.astype(alloc.dtype)
        di = found.astype(jnp.int32)
        new_st = dict(
            used=st["used"].at[:, pick].add(x["req"] * d),
            nonzero_used=st["nonzero_used"].at[:, pick].add(x["nonzero_req"] * d),
            pod_count=st["pod_count"].at[pick].add(di),
            port_used=st["port_used"].at[:, pick].add(x["pod_takes"] * di),
            spr_cnt=(
                st["spr_cnt"].at[:, pick].add(x["spr_placed"].astype(jnp.int32) * di)
                if use_spread
                else st["spr_cnt"]
            ),
            ipa_in=(
                st["ipa_in"].at[:, pick].add(x["ipa_in_match"] * di)
                if use_interpod
                else st["ipa_in"]
            ),
            ipa_ex=(
                st["ipa_ex"].at[:, pick].add(x["ipa_ex_owned"] * di)
                if use_interpod
                else st["ipa_ex"]
            ),
        )
        if pipe_kw.get("use_nominated"):
            # a placed nominated pod leaves the nominator map: accumulate
            # its load (at its NOMINATED slot, where nom_used counted it)
            # into the correction rows its priority contributed to
            s_nom = x["nominated_slot"]
            placed_nom = found & (s_nom >= 0)
            ssn = jnp.maximum(s_nom, 0)
            rows = st["nom_corr_cnt"].shape[0]
            lev_mask = (
                jnp.arange(rows, dtype=jnp.int32) >= x["nom_level"]
            ) & placed_nom
            new_st["nom_corr_used"] = st["nom_corr_used"].at[:, :, ssn].add(
                lev_mask[:, None].astype(alloc.dtype) * x["req"][None, :]
            )
            new_st["nom_corr_cnt"] = st["nom_corr_cnt"].at[:, ssn].add(
                lev_mask.astype(jnp.int32)
            )
            if pipe_kw.get("use_nominated_ports"):
                new_st["nom_corr_ports"] = st["nom_corr_ports"].at[
                    :, :, ssn
                ].add(
                    lev_mask[:, None].astype(jnp.int32)
                    * x["pod_takes"][None, :]
                )
        st = new_st
        assignment = jnp.where(found, pick, -1).astype(jnp.int32)
        return (st, k), assignment

    return step


def _solve_scan(
    tables,  # dict of read-only node/class tables (see ExactSolver.solve)
    state0,  # dict of carried node state (donated)
    xs,  # dict of per-pod scanned inputs, leading axis P
    key,  # PRNG key
    **kw,  # pipeline shape/weight params, see _make_step
):
    step = _make_step(tables, **kw)
    (state, _), assignments = jax.lax.scan(step, (state0, key), xs)
    return assignments, state


def _solve_grouped(
    tables,
    state0,
    xs,  # per-pod scanned inputs: leading axis P (P % group == 0), or —
    #      compact mode — one representative row per chunk, leading axis C
    kinds,  # [C] int32 chunk dispatch (see _chunk_kinds)
    key,
    *,
    group: int,
    vcnt=None,  # [C] int32 valid-pod count per chunk (compact mode)
    compact: bool = False,
    **kw,
):
    """Grouped exact scan (SURVEY §8.4 'batched variant').

    The pod axis is cut into chunks of ``group`` consecutive pods; a
    host-computed per-chunk KIND picks the executable branch:

      0  slow: inner per-pod scan with the full pipeline — bit-identical
         to the ungrouped solver (mixed chunks, anything unproven).
      1  plain fast: identical pods whose class is spread/interpod-NEUTRAL
         (host-verified zero involvement) — node-local frontier stepping
         with multi-placement, as before.
      2  spread fast: identical pods with exactly ONE hard topology-spread
         constraint (no soft, no min_domains, zero taint/nodeaff
         preference rows, interpod-neutral). Domain-quota multi-placement:
         per iteration, up to quota_d = globalMin + maxSkew - count_d pods
         may land in domain d on distinct eligible nodes. Each placement
         is sequentially valid: counts only grow within quota (its own
         skew check holds at its turn since globalMin can only rise), and
         with zero preference rows every score is placement-count
         independent, so a chosen tie node is still an argmax tie at its
         turn even if other nodes leave the mask.
      3  anti fast: identical pods with exactly ONE required anti-affinity
         term (self-selecting, symmetric ex term on the same topology,
         no affinity/preferred, zero preference rows, spread-neutral).
         Same machinery with quota_d = 1 while the domain is empty — on
         hostname topology every node is its own domain, so a whole chunk
         places in ~one iteration (the scheduler_perf
         SchedulingPodAntiAffinity shape).

    Random-mode multi-placement (all fast kinds) produces a sequentially
    VALID outcome whose distribution differs from the per-pod scan for the
    same seed (ExactSolverConfig.group_size documents this); "first" mode
    places one pod per iteration and is bit-identical to the scan.

    COMPACT mode (host-verified precondition: within every chunk, validity
    is a prefix and all valid rows are identical): ``xs`` carries ONE
    representative row per chunk plus ``vcnt`` valid counts instead of P
    per-pod rows — the fast branches only ever read row 0, and the slow
    branch replays the representative broadcast ``group`` times with
    ``pod_valid = iota < vcnt``, which is bit-identical to the full-row
    replay for uniform chunks. This exists because per-pod uploads
    dominate the 50k-pod solve's wire cost on the axon tunnel.
    """
    tie_break = kw["tie_break"]
    w_cpu = kw["w_cpu"]
    w_mem = kw["w_mem"]
    rtc_shape = kw["rtc_shape"]
    w_fit = kw["w_fit"]
    w_balanced = kw["w_balanced"]
    w_taint = kw["w_taint"]
    w_nodeaff = kw["w_nodeaff"]
    w_image = kw["w_image"]
    fdtype = kw["fdtype"]
    scoring_strategy = kw["scoring_strategy"]

    alloc = tables["alloc"]
    alloc2 = alloc[: MEM_IDX + 1]
    weights2 = jnp.asarray([w_cpu, w_mem], dtype=alloc.dtype)
    fit_scorer = _fit_scorer(scoring_strategy, rtc_shape)
    n = alloc.shape[1]
    step = _make_step(tables, **kw)

    use_spread = kw["use_spread"]
    use_interpod = kw["use_interpod"]
    use_extra = kw.get("use_extra_score", False)
    d_pad = kw["d_pad"]
    ipa_d_pad = kw["ipa_d_pad"]
    iota_n = jnp.arange(n, dtype=jnp.int32)

    iota_group = jnp.arange(group, dtype=jnp.int32)

    def row(a):
        """Chunk-representative row: leading pod axis already stripped in
        compact mode."""
        return a if compact else a[0]

    def slow_chunk(st, k, cxs, vc):
        if compact:
            cxs = {
                n: jnp.broadcast_to(a[None], (group,) + a.shape)
                for n, a in cxs.items()
            }
            cxs["pod_valid"] = iota_group < vc
        (st, k), asg = jax.lax.scan(step, (st, k), cxs)
        return st, k, asg

    def make_fast(mode):
        """mode: None (plain) | "spread" | "anti" — the quota machinery is
        shared; mode picks the domain model (host preconditions in
        _chunk_kinds guarantee each branch only sees chunks it is exact
        for)."""

        def fast_chunk(st, k, cxs, vc):
            req = row(cxs["req"])  # [K] int64
            req_mask = row(cxs["req_mask"])
            nz = row(cxs["nonzero_req"])  # [2] int64
            takes = row(cxs["pod_takes"])
            conflict_row = row(cxs["pod_conflict"])
            cls = row(cxs["class_of"])
            # number of pods to place: `group` for a uniform chunk, 0 for
            # an all-padding chunk (kinds marks both; this makes
            # fixed-bucket pod padding nearly free)
            vcnt = (
                vc
                if compact
                else jnp.sum(cxs["pod_valid"].astype(jnp.int32)).astype(
                    jnp.int32
                )
            )

            # capacity: how many MORE identical pods each node can take.
            # floor_div_exact is only exact below 2^23 quotients, but the
            # result is clamped to [0, group] right after: a true quotient
            # >= 2^23 has relative f32 error ~2^-23, so the estimate stays
            # >> group and clamps identically; below 2^23 it is exact.
            free = alloc - st["used"]
            cap_res = jnp.where(
                req_mask[:, None],
                fastmath.floor_div_exact(
                    jnp.maximum(free, 0), jnp.maximum(req, 1)[:, None]
                ),
                group,
            )
            cap = jnp.min(cap_res, axis=0)
            cap = jnp.minimum(
                cap, (tables["max_pods"] - st["pod_count"]).astype(cap.dtype)
            )
            conflict_now = pl.ports_conflict_mask(
                conflict_row, st["port_used"]
            )
            has_ports = jnp.any(takes > 0)
            self_conf = jnp.any((takes > 0) & conflict_row)
            cap = jnp.where(conflict_now & has_ports, 0, cap)
            cap = jnp.where(
                self_conf & ~conflict_now, jnp.minimum(cap, 1), cap
            )
            base_mask = tables["static_mask"][cls] & tables["node_valid"]
            cap = jnp.clip(jnp.where(base_mask, cap, 0), 0, group).astype(
                jnp.int32
            )

            # Frontier scores are computed LAZILY per iteration instead of
            # precomputing the full [group, N] table: the multi-placement
            # loop typically runs 1-3 iterations per chunk and reads only
            # the current and next frontier rows, so the eager table wasted
            # ~group/2x the division work (measured 13 ms vs 0.5 ms per
            # chunk at group=256 x 10k nodes on this device — it WAS the
            # exact-parity north star's dominant cost).
            static_row = jnp.zeros((n,), dtype=jnp.int32)
            if w_image:
                static_row = static_row + w_image * tables["image_score"][cls]
            if use_extra:
                # out-of-tree scores are per-(class, node) constants, same
                # shape as ImageLocality: fold into the frontier rows
                static_row = static_row + tables["extra_score"][cls]

            def frontier_rows(m, rows):
                """fit+balanced (+static rows) score of placing the
                (m+1)-th .. (m+rows)-th identical pod per node:
                [rows, N] int32 — same kernels as the per-pod pipeline,
                evaluated only at the frontier rows the loop body reads
                (rows=2 for the random multi-place body, rows=1 for the
                deterministic one-per-iteration body)."""
                jj = jnp.stack(
                    [m + 1 + i for i in range(rows)]
                ).astype(alloc.dtype)  # [rows, N]
                req_g = (
                    st["nonzero_used"][:, None, :]
                    + nz[:, None, None] * jj[None, :, :]
                ).reshape(2, rows * n)
                alloc_g = jnp.broadcast_to(
                    alloc2[:, None, :], (2, rows, n)
                ).reshape(2, rows * n)
                s = w_fit * fit_scorer(req_g, alloc_g, weights2)
                s = s + w_balanced * nr.balanced_allocation_score(
                    req_g, alloc_g, fdtype=fdtype
                )
                return (
                    s.astype(jnp.int32).reshape(rows, n)
                    + static_row[None, :]
                )

            taint_row = tables["taint_cnt"][cls]
            nodeaff_row = tables["nodeaff_pref"][cls]

            # -- domain model (mode-static) --
            if mode == "spread":
                spr = tables["spr"]
                jj = jnp.maximum(spr["hard"][cls, 0], 0)
                dom_row = spr["dom"][jj]  # [N] (-1 = key missing)
                hk = dom_row >= 0
                dd = jnp.where(hk, dom_row, 0)
                counted = spr["elig"][jj] & hk
                base_cnt = st["spr_cnt"][jj]
                skew_lim = spr["max_skew"][jj]
                dom_present = (
                    jops.segment_sum(
                        counted.astype(jnp.int32), dd, num_segments=d_pad
                    )
                    > 0
                )
                dpad_local = d_pad
            elif mode == "anti":
                ipa = tables["ipa"]
                jj = jnp.maximum(ipa["cls_req_anti"][cls, 0], 0)
                dom_row = ipa["in_dom"][jj]
                hk = dom_row >= 0
                dd = jnp.where(hk, dom_row, 0)
                # own symmetric ex term (host precondition: exactly one,
                # same topology/domain row): its counts also block
                ex_owned_row = row(cxs["ipa_ex_owned"])  # [Te]
                ee = jnp.argmax(ex_owned_row > 0).astype(jnp.int32)
                v_in = row(cxs["ipa_in_match"])[jj]
                v_ex = ex_owned_row[ee]
                base_cnt = st["ipa_in"][jj] + st["ipa_ex"][ee]
                dpad_local = ipa_d_pad

            def domain_eval(m):
                """(extra feasibility mask [N], quota_d [D], charged [N],
                dc [D] current domain counts). charged=False nodes
                (missing key / not counted) affect no domain totals and
                bypass quotas."""
                if mode == "spread":
                    cnt_now = jnp.where(counted, base_cnt + m, 0)
                    dc = jops.segment_sum(cnt_now, dd, num_segments=dpad_local)
                    mn = jnp.min(
                        jnp.where(dom_present, dc, jnp.int32(2**30))
                    )
                    node_dc = dc[dd]
                    ok = hk & (node_dc + 1 - mn <= skew_lim)
                    quota_d = jnp.clip(mn + skew_lim - dc, 0, group)
                    return ok, quota_d, counted, dc
                if mode == "anti":
                    cnt_now = jnp.where(
                        hk, base_cnt + (v_in + v_ex) * m, 0
                    )
                    dc = jops.segment_sum(cnt_now, dd, num_segments=dpad_local)
                    node_dc = dc[dd]
                    ok = (~hk) | (node_dc == 0)
                    quota_d = jnp.where(dc == 0, 1, 0).astype(jnp.int32)
                    return ok, quota_d, hk, dc
                ones_d = jnp.ones(1, dtype=jnp.int32)
                return (
                    jnp.ones(n, dtype=bool),
                    ones_d,
                    jnp.zeros(n, dtype=bool),
                    ones_d,
                )

            def scores_at(m, extra_ok, f):
                """Total score at frontier row ``f``
                (= frontier_rows(m, ...)[0])."""
                mask_t = (m < cap) & extra_ok
                total = f
                # DefaultNormalizeScore, recomputed per iteration because
                # the feasible mask shifts as nodes saturate. In quota
                # modes the host precondition makes these rows all-zero,
                # so the terms are the same constant on every node — they
                # cannot move an argmax and are skipped at trace time
                # (normalize costs a real per-iteration int division).
                if mode is None:
                    if w_taint:
                        total = total + w_taint * pl.normalize_score(
                            taint_row, mask_t, reverse=True
                        )
                    if w_nodeaff:
                        total = total + w_nodeaff * pl.normalize_score(
                            nodeaff_row, mask_t, reverse=False
                        )
                return jnp.where(mask_t, total, -1), mask_t

            m0 = jnp.zeros(n, dtype=jnp.int32)
            asg0 = jnp.full(group, -1, dtype=jnp.int32)
            iota_g = jnp.arange(group, dtype=jnp.int32)

            if tie_break == TIE_RANDOM:
                # Multi-placement (see _solve_grouped docstring for the
                # validity argument per mode). Terminates: each iteration
                # places >= 1 pod or proves infeasibility.
                def cond(state):
                    m, asg, placed, k = state
                    return placed < vcnt

                def body(state):
                    m, asg, placed, k = state
                    extra_ok, quota_d, charged, dc_now = domain_eval(m)
                    # anti mode never reads the next frontier row
                    # (eligible = tie): score only the row consumed
                    n_rows = 1 if mode == "anti" else 2
                    fr = frontier_rows(m, n_rows)
                    f_now, next_f = fr[0], fr[n_rows - 1]
                    total, mask_t = scores_at(m, extra_ok, f_now)
                    best = jnp.max(total)
                    feasible = best >= 0
                    tie = (total == best) & mask_t
                    # Node-local multi-place eligibility differs by mode:
                    # - plain: a chosen node must stay in the mask with a
                    #   non-increasing frontier, else DefaultNormalizeScore
                    #   and the tie set shift for later pods this iteration.
                    # - anti: a placed node's domain becomes quota-blocked,
                    #   removing it from the mask — its risen frontier can
                    #   never out-tie later pods, so tie alone suffices.
                    # - spread: a placed node may STAY in the mask (domain
                    #   quota remaining), so the frontier-rise exclusion is
                    #   still required; saturation is harmless (constant
                    #   normalize rows by host precondition).
                    if mode is None:
                        eligible = tie & ((m + 1) < cap) & (next_f <= f_now)
                    elif mode == "spread":
                        eligible = tie & (next_f <= f_now)
                    else:  # anti
                        eligible = tie

                    k, s1 = jax.random.split(k)
                    if mode is None:
                        r = jax.random.uniform(s1, (n,))
                        pick_key = jnp.where(tie, r, 2.0)
                        accept = eligible
                        order = jnp.argsort(
                            jnp.where(accept, r, 2.0)
                        ).astype(jnp.int32)
                        n_acc = jnp.sum(accept.astype(jnp.int32))
                        q = jnp.minimum(n_acc, vcnt - placed)
                    else:
                        ec = eligible & charged
                        rb = (
                            jax.random.randint(
                                s1, (n,), 0, 1 << 20, dtype=jnp.int32
                            ).astype(jnp.int64)
                            * n
                            + iota_n
                        )  # unique per-node random keys
                        if mode == "spread":
                            # WATER-FILL: when every present domain sits at
                            # the same count (totally balanced — the steady
                            # state of a spread workload) and no
                            # skew-blocked node could strictly out-score
                            # today's best after re-entering, k full
                            # ROUNDS are sequentially valid at once: the
                            # round-robin replay keeps the profile within
                            # 1 of balanced at every step, so each
                            # placement's skew bound holds for any
                            # maxSkew >= 1, and mask changes can only add
                            # ties or remove non-chosen nodes.
                            seg_elig = jops.segment_sum(
                                ec.astype(jnp.int32),
                                dd,
                                num_segments=dpad_local,
                            )
                            d_present = jnp.sum(
                                dom_present.astype(jnp.int32)
                            )
                            # dc_now comes from this iteration's
                            # domain_eval — no second segment_sum
                            mx_dc = jnp.max(
                                jnp.where(dom_present, dc_now, -1)
                            )
                            mn_dc = jnp.min(
                                jnp.where(dom_present, dc_now, 2**30)
                            )
                            blocked_over = jnp.any(
                                (m < cap)
                                & hk
                                & ~extra_ok
                                & (f_now > best)
                            )
                            kk = jnp.minimum(
                                jnp.min(
                                    jnp.where(
                                        dom_present, seg_elig, 2**30
                                    )
                                ),
                                (vcnt - placed)
                                // jnp.maximum(d_present, 1),
                            )
                            waterfill = (
                                (mx_dc == mn_dc)
                                & ~blocked_over
                                & (kk >= 1)
                            )
                        else:
                            waterfill = jnp.bool_(False)
                            kk = jnp.int32(0)

                        def wf_accept(_):
                            # one sort per iteration, amortized over k*D
                            # placements: rank eligible nodes within their
                            # domain by random key, accept rank < k.
                            # POSITIONS interleave domains round-robin
                            # (round r of every present domain before
                            # round r+1 of any) — the emitted assignment
                            # order IS the sequential replay order, and
                            # only the interleaved order keeps every
                            # step's skew bound valid.
                            keyf = jnp.where(
                                ec,
                                dd.astype(jnp.float32) * 2.0
                                + jax.random.uniform(s1, (n,)),
                                jnp.float32(jnp.inf),
                            )
                            si = jnp.argsort(keyf)
                            sd = dd[si]
                            elig_s = ec[si]
                            is_start = elig_s & (
                                (iota_n == 0) | (sd != jnp.roll(sd, 1))
                            )
                            start_pos = jax.lax.associative_scan(
                                jnp.maximum,
                                jnp.where(is_start, iota_n, -1),
                            )
                            rank = iota_n - start_pos
                            accept_s = elig_s & (rank < kk)
                            accept = (
                                jnp.zeros(n, dtype=bool)
                                .at[si]
                                .set(accept_s)
                            )
                            d_rank = (
                                jnp.cumsum(dom_present.astype(jnp.int32))
                                - 1
                            )
                            # clamp the scattered rank to `group` before
                            # the position product: accepted lanes have
                            # rank < kk <= group (values unchanged), and
                            # unaccepted lanes' positions are never read
                            # — without the clamp, rank_n * d_present
                            # reaches node_pad * d_pad (~1.7e10 at the
                            # 512k x 102k hostname-domain shape) and
                            # wraps int32 (solver/budget.py
                            # assert_index_headroom polices the clamped
                            # bound host-side)
                            rank_n = (
                                jnp.zeros(n, dtype=jnp.int32)
                                .at[si]
                                .set(
                                    jnp.minimum(rank, group).astype(
                                        jnp.int32
                                    )
                                )
                            )
                            pos = rank_n * d_present + d_rank[dd]
                            return accept, pos.astype(jnp.int32)

                        def winner_accept(_):
                            # sort-free single-round selection: one
                            # segment_max winner per domain with quota
                            # (TPU sorts cost ~1 ms per [5k] vector; the
                            # 1-3 placements of an unbalanced iteration
                            # can't amortize one)
                            seg_key = jops.segment_max(
                                jnp.where(ec, rb, -1),
                                dd,
                                num_segments=dpad_local,
                            )
                            if mode == "spread":
                                # re-entry gate for maxSkew > 1 (min may
                                # rise mid-iteration; maxSkew == 1 places
                                # only into distinct current-min domains)
                                blocked_high = jnp.any(
                                    (m < cap)
                                    & hk
                                    & ~extra_ok
                                    & (f_now >= best)
                                )
                                quota_eff = jnp.where(
                                    (skew_lim > 1) & blocked_high,
                                    0,
                                    quota_d,
                                )
                            else:
                                quota_eff = quota_d
                            win = (
                                ec
                                & (rb == seg_key[dd])
                                & (quota_eff[dd] >= 1)
                            )
                            # uncharged nodes affect no totals: no quota.
                            # Single-round placements are order-free (each
                            # accepted node sits in a distinct domain
                            # within old-min quota), so index-order
                            # positions via prefix sums are fine.
                            acc = win | (eligible & ~charged)
                            return acc, (
                                jnp.cumsum(acc.astype(jnp.int32)) - 1
                            ).astype(jnp.int32)

                        # waterfill accepts EXACTLY k per present domain —
                        # quota-free nodes would let the q-truncation cut
                        # into the charged set unevenly, breaking the
                        # round-robin replay; they place in later
                        # iterations instead
                        if mode == "spread":
                            accept, pos_iter = jax.lax.cond(
                                waterfill, wf_accept, winner_accept, None
                            )
                        else:
                            accept, pos_iter = winner_accept(None)
                        q = jnp.minimum(
                            jnp.sum(accept.astype(jnp.int32)),
                            vcnt - placed,
                        )

                    # q == 0 but feasible: single placement on one tie node
                    # (possibly saturating — next iteration recomputes).
                    # Picked by extremal random key among ties (uniform,
                    # since the keys are iid): min of `r` (non-ties padded
                    # to 2.0) in plain mode, max of `rb` (non-ties -1) in
                    # quota modes — reusing this iteration's draw instead
                    # of a second [N] cumsum + randint.
                    if mode is None:
                        pick = jnp.argmin(pick_key).astype(jnp.int32)
                    else:
                        pick = jnp.argmax(
                            jnp.where(tie, rb, jnp.int64(-1))
                        ).astype(jnp.int32)

                    multi = q > 0
                    n_placed = jnp.where(
                        feasible, jnp.where(multi, q, 1), 0
                    ).astype(jnp.int32)

                    if mode is None:
                        chosen = jnp.where(
                            multi,
                            jnp.where(iota_g < q, order[:group], -1),
                            jnp.where(iota_g < 1, pick, -1),
                        )  # [G] node ids for this iteration's pods, -1 pad
                        chosen = jnp.where(feasible, chosen, -1)
                        pos = jnp.where(chosen >= 0, placed + iota_g, group)
                        asg = asg.at[pos].set(chosen, mode="drop")
                        m = m.at[jnp.where(chosen >= 0, chosen, n)].add(
                            jnp.int32(1), mode="drop"
                        )
                    else:
                        take = accept & (pos_iter < q) & multi & feasible
                        idx_multi = jnp.where(
                            take, placed + pos_iter, group
                        )
                        asg = asg.at[idx_multi].set(iota_n, mode="drop")
                        single = (~multi) & feasible
                        asg = asg.at[
                            jnp.where(single, placed, group)
                        ].set(pick, mode="drop")
                        delta_m = take.astype(jnp.int32) + (
                            jnp.zeros(n, dtype=jnp.int32)
                            .at[pick]
                            .set(jnp.int32(1))
                            * single.astype(jnp.int32)
                        )
                        m = m + delta_m
                    placed = jnp.where(feasible, placed + n_placed, vcnt)
                    return m, asg, placed, k

                m, asg, _, k = jax.lax.while_loop(
                    cond, body, (m0, asg0, jnp.int32(0), k)
                )
            else:
                # Deterministic lowest-index tie-break: one placement per
                # iteration, exactly the per-pod pipeline's argmax.
                def body(t, acc):
                    m, asg = acc
                    extra_ok, _, _, _ = domain_eval(m)
                    total, _ = scores_at(
                        m, extra_ok, frontier_rows(m, 1)[0]
                    )
                    best = jnp.max(total)
                    feasible = (best >= 0) & (t < vcnt)
                    pick = jnp.argmax(total).astype(jnp.int32)
                    m = m.at[pick].add(feasible.astype(jnp.int32))
                    asg = asg.at[t].set(jnp.where(feasible, pick, -1))
                    return m, asg

                m, asg = jax.lax.fori_loop(0, group, body, (m0, asg0))

            d = m.astype(alloc.dtype)
            st = dict(
                st,
                used=st["used"] + req[:, None] * d[None, :],
                nonzero_used=st["nonzero_used"] + nz[:, None] * d[None, :],
                pod_count=st["pod_count"] + m,
                port_used=st["port_used"] + takes[:, None] * m[None, :],
            )
            # family occupancy updates (rows are zero for neutral chunks,
            # making these no-ops for kind-1 chunks in active batches)
            if use_spread:
                st["spr_cnt"] = st["spr_cnt"] + row(
                    cxs["spr_placed"]
                ).astype(jnp.int32)[:, None] * m[None, :]
            if use_interpod:
                st["ipa_in"] = st["ipa_in"] + row(cxs["ipa_in_match"])[
                    :, None
                ] * m[None, :]
                st["ipa_ex"] = st["ipa_ex"] + row(cxs["ipa_ex_owned"])[
                    :, None
                ] * m[None, :]
            return st, k, asg

        return fast_chunk

    branches = [slow_chunk, make_fast(None)]
    branches.append(make_fast("spread") if use_spread else branches[1])
    branches.append(make_fast("anti") if use_interpod else branches[1])

    def chunk_step(carry, x):
        st, k = carry
        cxs, kind, vc = x
        st, k, asg = jax.lax.switch(kind, branches, st, k, cxs, vc)
        return (st, k), asg

    c = kinds.shape[0]
    if compact:
        cxs_all = xs  # already one representative row per chunk
    else:
        cxs_all = jax.tree.map(
            lambda a: a.reshape((c, group) + a.shape[1:]), xs
        )
        vcnt = jnp.zeros(c, dtype=jnp.int32)  # unread by the branches
    (state, _), assignments = jax.lax.scan(
        chunk_step, (state0, key), (cxs_all, kinds, vcnt)
    )
    return assignments.reshape(c * group), state


# -- packed transfer layer ---------------------------------------------------
#
# The `axon` PJRT tunnel on this box has millisecond-class latency per
# host<->device transfer and per fresh-content buffer, so the per-solve wire
# protocol is collapsed to a handful of arrays:
#   xi64 / xi32 / xbool — per-pod inputs concatenated along the trailing axis
#                         per dtype class, unpacked by a static slice spec
#                         inside the compiled program (free on device);
#   bstate              — per-batch node-state rows (ports/spread/interpod
#                         occupancy) stacked into one int32 [B, N], uploaded
#                         fresh each batch (its dims differ per batch, so
#                         donation would never reuse the buffer);
#   persist             — used/nonzero_used/pod_count, DEVICE-RESIDENT between
#                         batches in session mode (donated through each call);
#   assignments         — the only per-batch download in session mode.


def _run_packed(
    nt,  # node tables {alloc, max_pods, node_valid}
    ct,  # class tables {static_mask, taint_cnt, nodeaff_pref, image_score, spr, ipa}
    persist,  # {used, nonzero_used, pod_count} — donated; with chain_in it
    #           ALSO carries the batch-state rows from the previous
    #           chained sub-solve (BatchCarriedUsage)
    bstate,  # [B, N] int32 packed per-batch state ([1, 1] dummy with chain_in)
    xi64,  # [P, *] int64 packed per-pod inputs ([C, *] in compact mode)
    xi32,  # [P, *] int32
    xbool,  # [P, *] bool
    kinds,  # [P // group] int32 chunk kinds (grouped) or [1] dummy
    vcnt,  # [C] int32 per-chunk valid counts (compact mode) or [1] dummy
    nom_used,  # [L+1, K, N] int64 cumulative nominated load ([1,1,1] unused)
    nom_ports,  # [L+1, B, N] int32 nominated hostPort occupancy ([1,1,1] unused)
    key,
    *,
    bspec,  # tuple of (name, start, width)
    xspec,  # tuple of (name, src, start, width, squeeze)
    grouped: bool,
    group: int,
    **kw,
):
    pack_result = kw.pop("pack_result", False)
    compact = kw.pop("compact", False)
    # chained sub-batch dispatch (run_pipelined's RTT-hiding batch split):
    # chain_in consumes the previous sub-solve's carried batch-state rows
    # (port/spread/interpod occupancy) straight from the donated persist
    # dict instead of re-uploading host bstate — the occupancy the earlier
    # sub-batches placed stays device-resident. chain_out returns the full
    # carried state so the next sub-solve can chain on it.
    chain_in = kw.pop("chain_in", False)
    chain_out = kw.pop("chain_out", False)
    tables = {**nt, **ct}
    state0 = dict(persist)
    if not chain_in:
        for name, s, w in bspec:
            state0[name] = bstate[s : s + w]
    if kw.get("use_nominated"):
        tables["nom_used"] = nom_used
        tables["nom_cnt"] = state0.pop("nom_cnt")
        # placed-nominated correction carry (starts empty each batch)
        state0["nom_corr_used"] = jnp.zeros_like(nom_used)
        state0["nom_corr_cnt"] = jnp.zeros(
            (nom_used.shape[0], nom_used.shape[2]), dtype=jnp.int32
        )
        if kw.get("use_nominated_ports"):
            tables["nom_ports"] = nom_ports
            state0["nom_corr_ports"] = jnp.zeros_like(nom_ports)
    srcs = {"i64": xi64, "i32": xi32, "bool": xbool}
    xs = {}
    for name, src, s, w, squeeze in xspec:
        a = srcs[src][:, s : s + w]
        xs[name] = a[:, 0] if squeeze else a
    if grouped:
        assignments, state = _solve_grouped(
            tables, state0, xs, kinds, key, group=group, vcnt=vcnt,
            compact=compact, **kw,
        )
    else:
        assignments, state = _solve_scan(tables, state0, xs, key, **kw)
    if chain_out:
        # the whole carried state rides to the next chained sub-solve
        # (fit rows AND the batch occupancy rows)
        out_state = dict(state)
    else:
        out_state = {
            k: state[k] for k in ("used", "nonzero_used", "pod_count")
        }
    if pack_result:
        # Standalone mode downloads everything host-side; on the axon
        # tunnel EACH device->host read costs ~0.25 s regardless of size
        # (measured round 4), so the four result arrays are flattened into
        # ONE int64 buffer for a single read. Session mode keeps the dict
        # (state stays device-resident; only assignments download).
        return jnp.concatenate(
            [
                out_state["used"].reshape(-1),
                out_state["nonzero_used"].reshape(-1),
                out_state["pod_count"].astype(jnp.int64),
                assignments.astype(jnp.int64),
            ]
        )
    return assignments, out_state


_RUN_PACKED_STATICS = (
    "bspec",
    "xspec",
    "grouped",
    "group",
    "tie_break",
    "scoring_strategy",
    "w_cpu",
    "w_mem",
    "rtc_shape",
    "disabled",
    "w_fit",
    "w_balanced",
    "w_taint",
    "w_nodeaff",
    "w_image",
    "w_spread",
    "w_interpod",
    "use_spread",
    "use_interpod",
    "d_pad",
    "ipa_d_pad",
    "fdtype",
    "spread_soft",
    "ipa_ident",
    "ipa_score",
    "pallas",
    "use_nominated",
    "use_nominated_ports",
    "use_extra_score",
    "pack_result",
    "compact",
    "chain_in",
    "chain_out",
)

# Session mode donates the device-resident persist buffers through each call.
_run_packed_jit = jax.jit(
    _run_packed, static_argnames=_RUN_PACKED_STATICS, donate_argnums=(2,)
)

# Standalone (pack_result) solves flatten the result, so the donated persist
# buffers could never be reused as outputs — a non-donating wrapper avoids
# the spurious donation warning on every standalone call.
_run_packed_jit_nodonate = jax.jit(
    _run_packed, static_argnames=_RUN_PACKED_STATICS
)


def _heal(nt, persist, cols_i64, cols_i32, cols_bool, idx):
    """Scatter dirty snapshot columns onto the device-resident node tables
    and carried state (cache.go#UpdateSnapshot's O(changed) contract, device
    side). idx may contain repeats (shape bucketing pads with idx[0]) —
    set-scatter with identical payload is idempotent."""
    k = nt["alloc"].shape[0]
    nt = dict(
        nt,
        alloc=nt["alloc"].at[:, idx].set(cols_i64[:k]),
        max_pods=nt["max_pods"].at[idx].set(cols_i32[0]),
        node_valid=nt["node_valid"].at[idx].set(cols_bool[0]),
    )
    persist = dict(
        persist,
        used=persist["used"].at[:, idx].set(cols_i64[k : 2 * k]),
        nonzero_used=persist["nonzero_used"].at[:, idx].set(
            cols_i64[2 * k : 2 * k + 2]
        ),
        pod_count=persist["pod_count"].at[idx].set(cols_i32[1]),
    )
    return nt, persist


_heal_jit = jax.jit(_heal, donate_argnums=(0, 1))


def _pack_cols(arrs: list[np.ndarray]) -> np.ndarray:
    """Stack row-blocks (each [*, D] or [D]) into one array for upload."""
    rows = [a[None, :] if a.ndim == 1 else a for a in arrs]
    return np.concatenate(rows, axis=0)


class SessionDrainRequired(Exception):
    """Raised by a deferred-heal sync (allow_heal=False) when the device
    session would need a FULL re-upload (node/vocab shape change): a full
    upload from host truth while an earlier solve is still unapplied
    would erase that solve's carried placements. The pipelined driver
    catches this BEFORE any device mutation, drains the in-flight solve,
    and re-dispatches with healing allowed."""


class DeferredAssignments:
    """Handle to a dispatched-but-unread session solve (VERDICT r4 #1).

    The device→host copy is initiated asynchronously at construction
    (``copy_to_host_async``), so the tunnel round trip overlaps whatever
    host work happens before ``get()`` — on axon the post-overlap read
    costs ~0.2 ms instead of ~1 RTT. ``get()`` blocks until the transfer
    lands and returns the trimmed int32 assignment vector.

    ``lo``/``count`` locate a chained sub-batch's pods within the popped
    batch (solve(..., split=K)): this handle covers batch pods
    [lo, lo + count). An unsplit solve is the trivial chain lo=0,
    count=num_pods."""

    __slots__ = ("_dev", "_num_pods", "lo")

    def __init__(self, dev, num_pods: int, lo: int = 0) -> None:
        self._dev = dev
        self._num_pods = num_pods
        self.lo = lo
        try:
            dev.copy_to_host_async()
        except Exception:
            pass  # platform without async D2H: get() falls back to a sync read

    @property
    def count(self) -> int:
        return self._num_pods

    # sanctioned deferred-read point (analysis/registry.py) — the async
    # D2H copy started in __init__ makes this read post-overlap: ktpu: hot
    def get(self) -> np.ndarray:
        return np.asarray(self._dev)[: self._num_pods]

    # sanctioned deferred-read point (analysis/registry.py): the
    # streaming dispatcher's COMPLETION THREAD parks here so the tunnel
    # RTT is paid off the driver thread — it only waits for the async
    # D2H started in __init__ to land, it never converts the value (the
    # driver's get() stays the one read): ktpu: hot
    def wait(self) -> None:
        try:
            self._dev.block_until_ready()
        except Exception:
            pass  # get() surfaces any real transfer death to the driver


class BatchCarriedUsage:
    """Device-resident occupancy carry between chained sub-batch solves
    of ONE popped batch (the RTT-hiding batch split): the port-vocab
    occupancy rows, spread domain counts, and interpod term counts the
    earlier sub-batches' placements advanced, alongside the fit rows —
    everything ``_run_packed`` needs as ``state0`` for the next chained
    dispatch. Sub-batches of one batch share one tensorize (one
    occupancy vocab / domain id space / class table), which is exactly
    what makes the device-side carry well-defined; the carry dies with
    the chain (the next popped batch re-tensorizes a fresh vocab from
    host truth)."""

    __slots__ = ("state",)

    def __init__(self, state: dict) -> None:
        self.state = state  # device arrays, donated through the chain


def _class_table_arrays(static, spread, interpod) -> list:
    """The flat array list behind one class-table upload — the content
    hash AND the transfer-byte accounting both walk exactly this."""
    arrays = [
        static.mask, static.taint_cnt, static.nodeaff_pref,
        static.image_score, spread.dom, spread.elig, spread.max_skew,
        spread.min_domains, spread.self_match, spread.is_hostname,
        spread.hard, spread.soft, interpod.in_dom, interpod.in_pref_w,
        interpod.cls_req_aff, interpod.cls_req_anti, interpod.cls_pref,
        interpod.ex_dom, interpod.ex_anti,
    ]
    if static.extra_score is not None:
        arrays.append(static.extra_score)
    return arrays


def _class_table_digest(static, spread, interpod) -> bytes:
    """Content hash of the class-table arrays — the one digest both the
    session's class-table cache key AND the streaming dispatcher's
    stream_chain_key are built from, so a streaming dispatch hashes the
    tables once (stream_chain_key computes it, solve hands it to
    class_tables via the chain key) instead of twice per batch."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for a in _class_table_arrays(static, spread, interpod):
        arr = np.ascontiguousarray(a)
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.digest()


def _place_class_tables(static, spread, interpod, mesh, node_pad: int):
    """Device placement for the per-batch class tables: tables with a
    trailing node axis ([*, N]) shard over the mesh's node axis, the
    per-class / per-instance scalar tables replicate BY NAME
    (parallel.sharding.REPLICATED_TABLE_NAMES — a shape test alone
    could collide when an instance-axis pow2 pad happens to equal the
    node pad). mesh=None is the plain single-device upload."""
    dev, dev_n = placers(mesh, node_pad)

    def put(name, a):
        return dev(a) if name in REPLICATED_TABLE_NAMES else dev_n(a)

    names_arrays = {
        "static_mask": static.mask,
        "taint_cnt": static.taint_cnt,
        "nodeaff_pref": static.nodeaff_pref,
        "image_score": static.image_score,
        **(
            {"extra_score": static.extra_score}
            if static.extra_score is not None
            else {}
        ),
        "spr": {
            "dom": spread.dom,
            "elig": spread.elig,
            "max_skew": spread.max_skew,
            "min_domains": spread.min_domains,
            "self_match": spread.self_match,
            "is_hostname": spread.is_hostname,
            "hard": spread.hard,
            "soft": spread.soft,
        },
        "ipa": {
            "in_dom": interpod.in_dom,
            "in_pref_w": interpod.in_pref_w,
            "cls_req_aff": interpod.cls_req_aff,
            "cls_req_anti": interpod.cls_req_anti,
            "cls_pref": interpod.cls_pref,
            "ex_dom": interpod.ex_dom,
            "ex_anti": interpod.ex_anti,
        },
    }
    return {
        name: (
            {n: put(n, a) for n, a in v.items()}
            if isinstance(v, dict)
            else put(name, v)
        )
        for name, v in names_arrays.items()
    }


class _DeviceSession:
    """Device-resident mirror of one snapshot's node tensors (SURVEY §8.3).

    Engaged by Scheduler-driven solves (col_versions provided): node tables
    and the carried used/nonzero_used/pod_count live in HBM across batches;
    dirty snapshot columns heal by scatter; class-table uploads dedupe by
    content hash. Standalone solves (tests, one-shot callers) bypass it.
    """

    def __init__(self) -> None:
        self.padded = -1
        self.k = -1
        self.nt = None
        self.persist = None
        self.seen_versions: np.ndarray | None = None
        self.class_cache: dict[tuple, object] = {}
        # node-axis mesh the resident tables are sharded over (None =
        # single-device). A mesh change is a full re-upload: the resident
        # buffers' shardings no longer match the dispatch's expectations.
        self.mesh = None
        self.mesh_key: tuple | None = None
        # cross-BATCH occupancy carry (the streaming dispatcher): the
        # FULL carried state of the last stream solve — fit rows plus
        # the port/spread/interpod occupancy rows — kept device-resident
        # so the next batch with an identical occupancy vocabulary
        # (stream_key) chains on it instead of re-uploading host bstate.
        # Its fit buffers are the SAME objects as ``persist``'s, so any
        # donation of persist (ordinary solves, heals) invalidates it —
        # every such path must null it out. ``stream_versions`` is the
        # carry's own host-column baseline: the scheduler advances it
        # after each CLEAN ring-slot apply (the device assumed those
        # placements at solve time, so host truth catching up is not
        # drift), which is what keeps chaining alive past the first
        # ring fill — ``seen_versions`` stays the heal baseline.
        self.stream_carry: dict | None = None
        self.stream_key: tuple | None = None
        self.stream_versions: np.ndarray | None = None

    def sync(
        self,
        nodes: NodeBatch,
        col_versions: np.ndarray,
        allow_heal: bool = True,
        mesh=None,
    ) -> int:
        """Bring resident node tables/state up to date with the snapshot.

        ``allow_heal=False`` (pipelined dispatch with an EARLIER solve
        still unapplied): dirty columns are NOT healed and seen_versions
        is NOT advanced, so the next healing sync picks them up. Host
        truth can only understate device usage under the pipeline's
        conflict fence (external usage-increasing events discard the
        in-flight solve; own-apply effects are either already in the
        device carry or usage-decreasing rollbacks), so deferring the
        heal is conservative — never a capacity violation. A shape
        change in this mode raises SessionDrainRequired instead of
        re-uploading over the in-flight solve's carried state.

        With ``mesh`` set, the resident node tables and carried state
        live SHARDED over the mesh's node axis (node axis last); dirty-
        column heals scatter into the sharded residents, so only the
        owning shard's slice actually changes. Returns the host->device
        bytes this sync uploaded (the per-solve transfer counters)."""
        mesh_key = mesh_fingerprint(mesh)
        if (
            self.padded != nodes.padded
            or self.k != nodes.allocatable.shape[0]
            or self.mesh_key != mesh_key
        ):
            if not allow_heal and self.padded != -1:
                raise SessionDrainRequired()
            self.padded = nodes.padded
            self.k = nodes.allocatable.shape[0]
            self.mesh = mesh
            self.mesh_key = mesh_key
            # a full re-upload replaces the resident state wholesale:
            # any cross-batch occupancy carry is gone with it
            self.stream_carry = None
            self.stream_key = None
            self.stream_versions = None
            _, put = placers(mesh, nodes.padded)
            self.nt = {
                "alloc": put(nodes.allocatable),
                "max_pods": put(nodes.max_pods),
                "node_valid": put(nodes.valid),
            }
            self.persist = {
                "used": put(nodes.used),
                "nonzero_used": put(nodes.nonzero_used),
                "pod_count": put(nodes.pod_count),
            }
            self.seen_versions = col_versions[: nodes.padded].copy()
            return sum(
                a.nbytes
                for a in (
                    nodes.allocatable, nodes.max_pods, nodes.valid,
                    nodes.used, nodes.nonzero_used, nodes.pod_count,
                )
            )
        dirty = np.nonzero(
            col_versions[: self.padded] > self.seen_versions
        )[0]
        if dirty.size and not allow_heal:
            return 0  # defer: seen_versions untouched, a later sync heals
        if dirty.size:
            d_pad = 1
            while d_pad < dirty.size:
                d_pad *= 2
            idx = np.full(d_pad, dirty[0], dtype=np.int32)
            idx[: dirty.size] = dirty
            cols_i64 = _pack_cols(
                [
                    nodes.allocatable[:, idx],
                    nodes.used[:, idx],
                    nodes.nonzero_used[:, idx],
                ]
            )
            cols_i32 = _pack_cols(
                [nodes.max_pods[idx], nodes.pod_count[idx]]
            )
            cols_bool = _pack_cols([nodes.valid[idx]])
            # heal payloads replicate (every shard scatters; GSPMD keeps
            # only the owning shard's columns — the others are out of its
            # index range)
            put_r, _ = placers(self.mesh)
            # the heal donates persist's fit buffers, which the stream
            # carry shares — a dirty-column heal therefore breaks any
            # cross-batch chain (the streaming dispatcher refuses to
            # chain over dirty columns for exactly this reason:
            # ExactSolver.can_chain checks seen_versions first)
            self.stream_carry = None
            self.stream_key = None
            self.stream_versions = None
            self.nt, self.persist = _heal_jit(
                self.nt,
                self.persist,
                put_r(cols_i64),
                put_r(cols_i32),
                put_r(cols_bool),
                put_r(idx),
            )
        self.seen_versions = col_versions[: self.padded].copy()
        return (
            cols_i64.nbytes + cols_i32.nbytes + cols_bool.nbytes + idx.nbytes
            if dirty.size
            else 0
        )

    def class_tables(self, static, spread, interpod, mesh=None, digest=None):
        """Content-addressed device cache of the per-batch class tables.
        Returns (tables, bytes_uploaded) — 0 bytes on a cache hit. The
        cache key includes the mesh fingerprint: the same content placed
        for a different topology is a different device resident.
        ``digest`` short-circuits the content hash with a precomputed
        _class_table_digest (the streaming path already computed it for
        the chain key)."""
        arrays = _class_table_arrays(static, spread, interpod)
        if digest is None:
            digest = _class_table_digest(static, spread, interpod)
        key = (digest, mesh_fingerprint(mesh))
        ct = self.class_cache.pop(key, None)
        if ct is not None:
            self.class_cache[key] = ct  # re-insert: LRU refresh on hit
            return ct, 0
        ct = _place_class_tables(static, spread, interpod, mesh, self.padded)
        if len(self.class_cache) >= 8:
            self.class_cache.pop(next(iter(self.class_cache)))
        self.class_cache[key] = ct
        return ct, sum(np.asarray(a).nbytes for a in arrays)


def _capture_config_fingerprint(cfg: "ExactSolverConfig") -> dict:
    """JSON-safe config snapshot for the telemetry capture hook (lazy
    import: the solver must not pull the obs layer in at module load)."""
    from ..obs.bundle import config_fingerprint

    return config_fingerprint(cfg)


class ExactSolver:
    """Host-facing wrapper: NodeBatch/PodBatch (+ plugin tensors) in,
    assignments out, node state written back (the device-side 'assume')."""

    def __init__(self, config: ExactSolverConfig | None = None, mesh=None):
        self.config = config or ExactSolverConfig()
        # default jax.sharding.Mesh for every solve (node axis sharded over
        # its devices); solve(mesh=...) overrides per call. None = the
        # single-device path. The scheduler threads its
        # SchedulerConfig.mesh_devices mesh through here.
        self.mesh = mesh
        self._step_count = 0
        self._session = _DeviceSession()
        # flight-telemetry input snapshot hook (obs/bundle.py): when
        # set, solve() hands over its resolved inputs — pre-PRNG-
        # increment, pre-default-filling — so a capture-on-anomaly
        # bundle can re-execute the exact solve offline. Host-side
        # callable, never touches device state.
        self.capture_hook = None
        # Cumulative executable-dispatch histogram: "scan" counts whole
        # per-pod-scan solves, "kindK" counts grouped chunks by the
        # _chunk_kinds dispatch (0 slow replay / 1 plain / 2 spread
        # quota / 3 anti quota). Benchmarks report THIS instead of
        # asserting which path a workload takes (a round-3 bench label
        # claimed grouping was disabled on workloads where the quota
        # chunks in fact engaged).
        from collections import Counter

        self.dispatch_counts: Counter = Counter()
        # int64 resource arithmetic is non-negotiable (memory bytes overflow
        # int32); jax 0.9+axon ignores the JAX_ENABLE_X64 env var, so enable
        # it here rather than trusting the embedding application.
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        # SURVEY §6.4: the XLA executable cache is the solver's only durable
        # warm state — restarts deserialize instead of recompiling.
        from ..utils.compile_cache import enable_persistent_cache

        enable_persistent_cache()

    def reset_session(self) -> None:
        """Drop the device-resident session so the next solve re-uploads
        node tables and carried state from the host snapshot. Called when
        a deferred solve is DISCARDED (run_pipelined's fence): the
        discarded scan already advanced the carried used/pod_count on
        device, and host cache truth no longer matches it. The per-class
        table cache is content-addressed — it cannot be stale — so it
        survives the reset (only node tables + carry are invalidated)."""
        fresh = _DeviceSession()
        fresh.class_cache = self._session.class_cache
        self._session = fresh

    # -- cross-batch occupancy chaining (the streaming dispatcher) --

    def stream_chain_key(
        self,
        nodes: NodeBatch,
        pods: PodBatch,
        static: StaticPluginTensors,
        ports: PortTensors | None = None,
        spread: SpreadTensors | None = None,
        interpod: InterpodTensors | None = None,
        mesh=None,
    ) -> tuple:
        """Fingerprint of everything that makes one batch's device-
        resident occupancy carry (BatchCarriedUsage) semantically AND
        shape-compatible with the next batch's dispatch: the class-table
        content (spread instance/domain tables, interpod term tables,
        static masks — the index spaces the carried rows are keyed by),
        the ordered port vocabulary, the bstate row layout, the node
        padding/resource-vocab width, the domain paddings, and the mesh
        topology. Two consecutive batches with equal keys may chain: the
        occupancy rows the earlier batch's placements advanced stay
        device-resident instead of round-tripping through host
        tensorize. Conservative by construction — any difference falls
        back to the drain-then-retensorize path, never to a wrong
        chain. ``spread``/``interpod``/``ports`` may be None — the same
        trivial tensors ``solve`` would build are keyed then, so a
        plain batch's key matches the dispatch it fingerprints."""
        if mesh is None:
            mesh = self.mesh
        if ports is None:
            ports = trivial_port_tensors(pods, nodes.padded)
        if spread is None:
            spread = trivial_spread_tensors(pods, nodes.padded, static.c_pad)
        if interpod is None:
            interpod = trivial_interpod_tensors(
                pods, nodes.padded, static.c_pad
            )
        import hashlib

        # component 0 is exactly the class-table cache digest, so the
        # dispatch can hand it to _DeviceSession.class_tables instead of
        # hashing the same arrays a second time in the hot loop
        return (
            _class_table_digest(static, spread, interpod),
            hashlib.blake2b(
                repr(ports.vocab).encode(), digest_size=16
            ).digest(),
            mesh_fingerprint(mesh),
            nodes.padded,
            nodes.allocatable.shape[0],
            ports.used.shape[0],
            spread.cnt0.shape[0],
            interpod.in_cnt0.shape[0],
            interpod.ex_cnt0.shape[0],
            spread.d_pad,
            interpod.d_pad,
        )

    def can_chain(self, key: tuple, col_versions: np.ndarray) -> bool:
        """True when the next solve may consume the resident stream
        carry: a carry exists, its key matches, and NO snapshot column
        went dirty past the carry's OWN baseline (``stream_versions``) —
        unexplained dirt means host truth moved under the carry (node
        table change, assume-failure touch), and healing it would
        donate the carry's fit buffers, so the chain refuses instead
        (the caller drains and re-tensorizes, which is always correct).
        The baseline starts at the carry's dispatch and is advanced by
        ``note_stream_applied`` after each clean ring-slot apply: the
        scheduler's own applies only write usage the device already
        assumed at solve time, so they must not kill the chain —
        without the advance, chaining would die permanently the moment
        the stream ring first fills (every apply dirties columns, and
        in-flight dispatches defer heals, so ``seen_versions`` never
        catches up)."""
        s = self._session
        if s.stream_carry is None or s.stream_key != key:
            return False
        if s.padded == -1 or s.stream_versions is None:
            return False
        if col_versions is None or s.padded > len(col_versions):
            return False
        return not bool(
            np.any(col_versions[: s.padded] > s.stream_versions)
        )

    def note_stream_applied(self, col_versions: np.ndarray) -> None:
        """Advance the stream carry's column baseline after the
        scheduler applied a ring slot CLEANLY (no fence discard, no
        assume/bind failure): the apply wrote exactly the usage the
        device session assumed at that slot's solve, so host truth
        catching up is not drift — the carry stays chainable. Any
        UNCLEAN apply skips this call; its assume-failure ``touch``
        then trips ``can_chain`` and the next dispatch drains + heals
        the phantom placement."""
        s = self._session
        if s.stream_carry is None or s.padded == -1:
            return
        if col_versions is None or s.padded > len(col_versions):
            return
        s.stream_versions = col_versions[: s.padded].copy()

    def invalidate_stream_carry(self) -> None:
        """Drop the resident stream carry. Called by the scheduler when
        a ring-slot apply was UNCLEAN (fence discard, assume/bind
        failure): the session persist may hold a phantom placement, and
        a later clean apply must not advance the baseline past the
        failure's ``touch`` — with the carry gone, the next dispatch
        takes the drain-then-heal path, which clears the phantom."""
        s = self._session
        s.stream_carry = None
        s.stream_key = None
        s.stream_versions = None

    def solve(
        self,
        nodes: NodeBatch,
        pods: PodBatch,
        static: StaticPluginTensors | None = None,
        ports: PortTensors | None = None,
        spread: SpreadTensors | None = None,
        interpod: InterpodTensors | None = None,
        col_versions: np.ndarray | None = None,
        nominated=None,  # NominatedTensors | None
        nominated_slot: np.ndarray | None = None,  # [num_pods] int32, -1 none
        defer_read: bool = False,
        allow_heal: bool = True,
        split: int = 1,
        mesh=None,
        chain_occupancy: bool = False,
        stream_carry_out: bool = False,
        chain_key: tuple | None = None,
    ) -> np.ndarray | DeferredAssignments | list[DeferredAssignments]:
        """Returns assignments [num_pods] of node indices (-1 = unschedulable).

        Standalone mode (col_versions=None): uploads everything, downloads
        the updated node state and writes it back into ``nodes`` in place.

        Session mode (col_versions from a Snapshot): node tables and the
        carried used/nonzero_used/pod_count stay device-resident between
        calls; only columns whose snapshot version advanced re-upload, and
        ONLY the assignments download — ``nodes`` is NOT written back (the
        cache/snapshot generation path is authoritative host-side).

        ``defer_read`` (session mode only): return a DeferredAssignments
        handle instead of blocking on the device→host read. The carried
        device state advances immediately either way, so a later solve may
        be dispatched before the handle is read — the double-buffered
        scheduling loop's overlap point (the caller is responsible for
        discarding/fencing stale handles; see Scheduler.run_pipelined).

        ``split`` (session + defer_read only): chop the padded pod axis
        into up to ``split`` contiguous sub-batches dispatched
        back-to-back, each chained on the previous one's device-resident
        carried state (fit rows AND the batch occupancy rows —
        BatchCarriedUsage), and return one DeferredAssignments per
        sub-batch. The assignment read of sub-batch i then overlaps the
        solve of i+1 — only the LAST read pays an un-hidden tunnel RTT.
        Sequential semantics are identical to the unsplit solve (same
        scan order over the same carried state); with tie_break="first"
        the assignments are bit-identical, with "random" each sub-batch
        draws its own fold_in(key, i) stream, so placements are a valid
        sequential outcome whose distribution differs from the unsplit
        solve (the grouped-path caveat, ExactSolverConfig.group_size).
        The requested split is clamped to the largest feasible divisor
        of the padded pod axis (group-aligned when the grouped path
        engages); nominated-pod batches always dispatch unsplit (their
        correction carry is per-solve). When ``split > 1`` the return
        value is ALWAYS a list, even if the clamp lands on one
        sub-batch.

        ``stream_carry_out`` (session + defer_read only): after the
        solve, keep the FULL carried state — fit rows AND the batch
        occupancy rows — device-resident as the session's stream carry,
        tagged with ``chain_key`` (stream_chain_key). The next solve
        whose key matches may pass ``chain_occupancy=True`` to consume
        it: its first dispatch chains on the resident carry instead of
        uploading host bstate, so the occupancy the earlier batch's
        placements advanced never round-trips. This is the streaming
        dispatcher's cross-BATCH extension of the within-batch
        ``split`` chain; the caller is responsible for only chaining
        when its fences prove no conflicting event landed in between
        (``can_chain`` re-checks the vocabulary + dirty columns).
        Nominated batches never stream (their correction carry is
        per-solve).

        ``mesh`` (default: the constructor's mesh): a jax.sharding.Mesh
        with a "nodes" axis — every node-resident table/state array
        shards over its trailing node axis (which must be a multiple of
        the device count; Snapshot.pad_multiple guarantees this on the
        scheduler path), per-pod/per-class inputs replicate, and GSPMD
        inserts the cross-shard collectives. Assignments are bit-
        identical to the single-device solve for any device count
        (integer scores, stable reductions — tests/test_sharding.py).

        Without ``static``/``ports``/``spread``/``interpod`` tensors, a
        trivial single-class mask (valid ∧ schedulable) reproduces the
        resources-only pipeline.
        """
        cfg = self.config
        if mesh is None:
            mesh = self.mesh
        if self.capture_hook is not None:
            # BEFORE the PRNG derivation and the trivial-tensor default
            # filling: step_count is exactly what a replay must restore,
            # and None containers stay None (the replayed solve
            # re-derives the identical trivial tensors, and the bundle
            # stays small). Raw references — the hook copies host-side.
            self.capture_hook(
                nodes=nodes,
                pods=pods,
                static=static,
                ports=ports,
                spread=spread,
                interpod=interpod,
                nominated=nominated,
                nominated_slot=nominated_slot,
                step_count=self._step_count,
                split=split,
                defer_read=defer_read,
                session=col_versions is not None,
                allow_heal=allow_heal,
                chain_occupancy=chain_occupancy,
                config=_capture_config_fingerprint(cfg),
            )
        fdtype = jnp.float64 if cfg.balanced_fdtype == "float64" else jnp.float32
        key = jax.random.PRNGKey(cfg.seed + self._step_count)
        self._step_count += 1
        if static is None:
            static = trivial_static_tensors(pods, nodes.padded, nodes.schedulable)
        if ports is None:
            ports = trivial_port_tensors(pods, nodes.padded)
        if spread is None:
            spread = trivial_spread_tensors(pods, nodes.padded, static.c_pad)
        if interpod is None:
            interpod = trivial_interpod_tensors(pods, nodes.padded, static.c_pad)
        use_spread = not spread.empty
        use_interpod = not interpod.empty
        use_nominated = nominated is not None and not nominated.empty
        session = col_versions is not None

        # index-dtype audit (solver/budget.py): the flattened-index
        # products this dispatch's compiled program forms must fit
        # their container dtypes — a 2^31-scale shape fails loudly
        # here instead of silently wrapping on device. Host ints, ~ns.
        from .budget import assert_index_headroom

        assert_index_headroom(
            pods.padded,
            nodes.padded,
            d_pad=max(spread.d_pad, interpod.d_pad),
            group=max(cfg.group_size, 1),
        )

        h2d_bytes = 0
        if session:
            h2d_bytes += self._session.sync(
                nodes, col_versions, allow_heal=allow_heal, mesh=mesh
            )
            nt = self._session.nt
            persist = self._session.persist
            ct, ct_bytes = self._session.class_tables(
                static, spread, interpod, mesh=mesh,
                digest=chain_key[0] if chain_key is not None else None,
            )
            h2d_bytes += ct_bytes
        else:
            _, put = placers(mesh, nodes.padded)
            nt = {
                "alloc": put(nodes.allocatable),
                "max_pods": put(nodes.max_pods),
                "node_valid": put(nodes.valid),
            }
            persist = {
                "used": put(nodes.used),
                "nonzero_used": put(nodes.nonzero_used),
                "pod_count": put(nodes.pod_count),
            }
            ct = _place_class_tables(
                static, spread, interpod, mesh, nodes.padded
            )
            h2d_bytes += sum(
                a.nbytes
                for a in (
                    nodes.allocatable, nodes.max_pods, nodes.valid,
                    nodes.used, nodes.nonzero_used, nodes.pod_count,
                )
            ) + sum(
                np.asarray(a).nbytes
                for a in _class_table_arrays(static, spread, interpod)
            )

        # per-batch node-state rows, one int32 upload
        b_arrs = [ports.used]
        bspec = [("port_used", 0, ports.used.shape[0])]
        off = ports.used.shape[0]
        for name, arr in (
            ("spr_cnt", spread.cnt0),
            ("ipa_in", interpod.in_cnt0),
            ("ipa_ex", interpod.ex_cnt0),
        ):
            b_arrs.append(arr)
            bspec.append((name, off, arr.shape[0]))
            off += arr.shape[0]
        if use_nominated:
            b_arrs.append(nominated.count)
            bspec.append(("nom_cnt", off, nominated.count.shape[0]))
            off += nominated.count.shape[0]
        bstate = np.concatenate(b_arrs, axis=0)
        nom_used = (
            nominated.used if use_nominated else np.zeros((1, 1, 1), np.int64)
        )
        use_nominated_ports = (
            use_nominated and nominated.port_takes is not None
        )
        nom_ports = (
            nominated.port_takes
            if use_nominated_ports
            else np.zeros((1, 1, 1), np.int32)
        )

        # per-pod inputs, one upload per dtype class
        pod_valid = (pods.valid & pods.feasible_static)[:, None]
        i64_cols = [("req", pods.req), ("nonzero_req", pods.nonzero_req)]
        i32_cols = [
            ("class_of", np.asarray(static.class_of)[:, None]),
            ("pod_takes", np.asarray(ports.pod_takes)),
        ]
        if use_nominated:
            slots = np.full(pods.padded, -1, dtype=np.int32)
            if nominated_slot is not None:
                slots[: len(nominated_slot)] = nominated_slot
            levels = nominated.level_of(
                np.asarray(pods.priority, dtype=np.int32)
            )
            i32_cols += [
                ("nom_level", levels[:, None]),
                ("nominated_slot", slots[:, None]),
            ]
        bool_cols = [
            ("req_mask", pods.req_mask),
            ("pod_valid", pod_valid),
            ("pod_conflict", np.asarray(ports.pod_conflict)),
        ]
        if use_spread:
            bool_cols.append(("spr_placed", np.asarray(spread.placed_match)))
        if use_interpod:
            i32_cols += [
                ("ipa_in_match", np.asarray(interpod.in_match)),
                ("ipa_ex_owned", np.asarray(interpod.ex_owned)),
                ("ipa_m_w", np.asarray(interpod.m_w)),
            ]
            bool_cols += [
                ("ipa_m_anti", np.asarray(interpod.m_anti)),
                ("ipa_self_aff", np.asarray(interpod.self_aff)[:, None]),
            ]
        squeeze_names = {
            "class_of", "pod_valid", "ipa_self_aff", "nom_level",
            "nominated_slot",
        }

        def pack_x(cols):
            spec = []
            off = 0
            for name, arr in cols:
                spec.append((name, off, arr.shape[1], name in squeeze_names))
                off += arr.shape[1]
            return np.concatenate([a for _, a in cols], axis=1), spec

        xi64, spec64 = pack_x(i64_cols)
        xi32, spec32 = pack_x(i32_cols)
        xbool, specb = pack_x(bool_cols)
        xspec = tuple(
            [(n, "i64", s, w, sq) for n, s, w, sq in spec64]
            + [(n, "i32", s, w, sq) for n, s, w, sq in spec32]
            + [(n, "bool", s, w, sq) for n, s, w, sq in specb]
        )

        kw = dict(
            tie_break=cfg.tie_break,
            scoring_strategy=cfg.scoring_strategy,
            w_cpu=cfg.cpu_weight,
            w_mem=cfg.mem_weight,
            rtc_shape=tuple(tuple(p) for p in cfg.rtc_shape),
            disabled=tuple(sorted(cfg.disabled_filters)),
            w_fit=cfg.fit_weight,
            w_balanced=cfg.balanced_weight,
            # batch-static dead-weight elimination: an all-zero preference
            # row normalizes to the SAME value on every feasible node, and
            # a constant term can't move an argmax or its tie set — so the
            # plugin's weight is dropped at trace time, removing two [N]
            # integer-division normalizes from every scan step / grouped
            # iteration. Assignments are bit-identical either way; only
            # internal (never returned) score values shift by a constant.
            w_taint=cfg.taint_weight if np.any(static.taint_cnt) else 0,
            w_nodeaff=(
                cfg.node_affinity_weight
                if np.any(static.nodeaff_pref)
                else 0
            ),
            w_image=cfg.image_weight if np.any(static.image_score) else 0,
            w_spread=cfg.spread_weight,
            w_interpod=cfg.interpod_weight,
            use_spread=use_spread,
            use_interpod=use_interpod,
            d_pad=spread.d_pad,
            ipa_d_pad=interpod.d_pad,
            fdtype=fdtype,
            spread_soft=spread.has_soft,
            ipa_ident=interpod.ident,
            ipa_score=interpod.has_score,
            pallas=cfg.pallas,
            use_nominated=use_nominated,
            use_nominated_ports=use_nominated_ports,
            use_extra_score=static.extra_score is not None,
        )
        group = cfg.group_size
        grouped = grouped_eligible(
            cfg, pods.padded, nodes.padded, use_spread, use_interpod,
            use_nominated,
            spread_groupable=not spread.has_soft,
            interpod_groupable=interpod.anti_only,
        )
        compact = False
        vcnt_host = np.zeros(1, dtype=np.int32)
        if grouped:
            kinds_host = self._chunk_kinds(
                pods, static, ports, spread, interpod, group,
                use_spread, use_interpod,
            )
            for v, cnt in zip(*np.unique(kinds_host, return_counts=True)):
                self.dispatch_counts[f"kind{int(v)}"] += int(cnt)
            kinds = jnp.asarray(kinds_host)
            # COMPACT eligibility (wire-cost fast path, _solve_grouped
            # docstring): every chunk's validity is a prefix and its valid
            # per-pod rows are identical — then one representative row per
            # chunk + a valid count replaces the [P, *] uploads, and even
            # kind-0 chunks replay bit-identically from the broadcast.
            c = pods.padded // group
            pvc = pod_valid[:, 0].reshape(c, group)
            vc = pvc.sum(axis=1).astype(np.int32)
            if cfg.compact_wire and bool(
                (pvc == (np.arange(group)[None, :] < vc[:, None])).all()
            ):
                pv_off = next(
                    s for n, s, w, _ in specb if n == "pod_valid"
                )
                xb_cmp = xbool.copy()
                xb_cmp[:, pv_off] = True  # reconstructed from vcnt on device

                def _uniform(x):
                    a = x.reshape(c, group, -1)
                    return bool(
                        ((a == a[:, :1]) | ~pvc[:, :, None]).all()
                    )

                if _uniform(xi64) and _uniform(xi32) and _uniform(xb_cmp):
                    compact = True
                    vcnt_host = vc
                    xi64 = np.ascontiguousarray(
                        xi64.reshape(c, group, -1)[:, 0]
                    )
                    xi32 = np.ascontiguousarray(
                        xi32.reshape(c, group, -1)[:, 0]
                    )
                    xbool = np.ascontiguousarray(
                        xbool.reshape(c, group, -1)[:, 0]
                    )
                    self.dispatch_counts["compact_batches"] += 1
        else:
            group = 1
            kinds = jnp.zeros(1, dtype=jnp.int32)
            kinds_host = None
            self.dispatch_counts["scan"] += 1

        # streaming chain eligibility: session + deferred + un-nominated
        stream = (
            session
            and defer_read
            and not use_nominated
            and (chain_occupancy or stream_carry_out)
        )
        chain_occupancy = chain_occupancy and stream
        if chain_occupancy and not self.can_chain(
            chain_key, col_versions
        ):
            # the caller's pre-dispatch check and this one race nothing
            # (single driver thread); a mismatch here is a logic error
            # upstream — refuse loudly rather than chain wrongly
            raise ValueError(
                "chain_occupancy requested but the session carry does "
                "not match (stale key or dirty columns)"
            )

        # per-solve transfer accounting + mesh placement: per-pod packed
        # arrays and scalars replicate; node-axis rows (bstate, nominated
        # load) shard over the mesh's node axis. A chained dispatch
        # consumes the resident carry instead of uploading bstate.
        h2d_bytes += (
            (0 if chain_occupancy else bstate.nbytes)
            + xi64.nbytes + xi32.nbytes + xbool.nbytes
            + vcnt_host.nbytes + np.asarray(nom_used).nbytes
            + np.asarray(nom_ports).nbytes
        )
        if grouped:
            h2d_bytes += kinds_host.nbytes
        metrics.h2d_bytes_total.inc(int(h2d_bytes))
        if session:
            # the only per-batch download: the (padded) assignment vector
            metrics.d2h_bytes_total.inc(int(pods.padded) * 4)
        else:
            metrics.d2h_bytes_total.inc(
                ((nodes.allocatable.shape[0] + 3) * nodes.padded
                 + pods.padded) * 8
            )
        dev, dev_n = placers(mesh, nodes.padded)
        if mesh is not None:
            _repl = replicated(mesh)
            key = jax.device_put(key, _repl)
            kinds = jax.device_put(kinds, _repl)

        want_chain = split > 1 and session and defer_read
        if (want_chain or stream) and not use_nominated:
            k_split = self._feasible_split(
                max(split, 1), pods.padded, grouped, group
            )
            if k_split > 1 or stream:
                # stream solves route through the chain dispatcher even
                # unsplit (k_split == 1): it is the one path that can
                # consume/produce the cross-batch occupancy carry
                handles = self._solve_chain(
                    k_split, nt, ct, bstate, xi64, xi32, xbool,
                    kinds_host if grouped else None, vcnt_host, compact,
                    nom_used, nom_ports, key, pods, mesh,
                    bspec=tuple(bspec), xspec=xspec, grouped=grouped,
                    group=group,
                    chain_start=(
                        self._session.stream_carry
                        if chain_occupancy
                        else None
                    ),
                    carry_out=stream_carry_out,
                    chain_key=chain_key,
                    **kw,
                )
                if self._session.stream_carry is not None:
                    # the kept carry's chain baseline: host columns as
                    # of this dispatch (note_stream_applied advances it
                    # as ring-slot applies land cleanly)
                    self._session.stream_versions = col_versions[
                        : self._session.padded
                    ].copy()
                return handles

        if session:
            # this dispatch donates the session persist, whose fit
            # buffers any saved stream carry shares: the carry cannot
            # survive a non-streaming solve
            self._session.stream_carry = None
            self._session.stream_key = None
            self._session.stream_versions = None
        run = _run_packed_jit if session else _run_packed_jit_nodonate
        out = run(
            nt,
            ct,
            persist,
            dev_n(bstate),
            dev(xi64),
            dev(xi32),
            dev(xbool),
            kinds,
            dev(vcnt_host),
            dev_n(nom_used),
            dev_n(nom_ports),
            key,
            bspec=tuple(bspec),
            xspec=xspec,
            grouped=grouped,
            group=group,
            # packed single-buffer download only on the unsharded path:
            # the SPMD partitioner rejects the flatten+concat of the
            # sharded state with a dtype-mixed dynamic_update_slice
            # (s64 index vs s32 shard offset, XLA verifier error), and a
            # sharded standalone solve is a dryrun/bench/test context
            # where four reads instead of one is acceptable
            pack_result=not session and mesh is None,
            compact=compact,
            **kw,
        )
        if session:
            assignments, new_persist = out
            self._session.persist = new_persist
            if defer_read:
                handle = DeferredAssignments(assignments, pods.num_pods)
                # split requested but clamped/ineligible (nominated batch,
                # indivisible padding): the contract stays "list in, list
                # out" so the pipelined caller never type-switches
                return [handle] if want_chain else handle
            return np.asarray(assignments)[: pods.num_pods]
        if mesh is not None:
            # sharded standalone: unpacked results (see pack_result above)
            assignments, out_state = out
            nodes.used = np.array(out_state["used"])
            nodes.nonzero_used = np.array(out_state["nonzero_used"])
            nodes.pod_count = np.array(out_state["pod_count"]).astype(
                np.int32
            )
            return np.asarray(assignments).astype(np.int32)[: pods.num_pods]
        # standalone: ONE packed download (np.array = writable copy; the
        # unpacked slices below are views of it, so later in-place
        # dirty-column writes to ``nodes`` stay legal)
        flat = np.array(out)
        k = nodes.allocatable.shape[0]
        npad = nodes.padded
        o = 0
        nodes.used = flat[o : o + k * npad].reshape(k, npad)
        o += k * npad
        nodes.nonzero_used = flat[o : o + 2 * npad].reshape(2, npad)
        o += 2 * npad
        nodes.pod_count = flat[o : o + npad].astype(np.int32)
        o += npad
        return flat[o:].astype(np.int32)[: pods.num_pods]

    @staticmethod
    def _feasible_split(
        split: int, pod_pad: int, grouped: bool, group: int
    ) -> int:
        """Largest K <= split such that the padded pod axis cuts into K
        equal sub-batches the dispatch machinery can chain: K divides
        pod_pad, and — when the grouped path engages — each sub-batch
        stays a whole number of group chunks (the chunk-kind dispatch
        and the compact-wire representative rows both slice along the
        chunk axis)."""
        for k in range(min(split, pod_pad), 1, -1):
            if pod_pad % k:
                continue
            if grouped and (pod_pad // k) % group:
                continue
            return k
        return 1

    def _solve_chain(
        self,
        k_split: int,
        nt,
        ct,
        bstate,
        xi64,
        xi32,
        xbool,
        kinds_host,  # [C] int32 (grouped) | None (per-pod scan)
        vcnt_host,
        compact: bool,
        nom_used,
        nom_ports,
        key,
        pods: PodBatch,
        mesh=None,
        *,
        bspec,
        xspec,
        grouped: bool,
        group: int,
        chain_start: dict | None = None,
        carry_out: bool = False,
        chain_key: tuple | None = None,
        **kw,
    ) -> list[DeferredAssignments]:
        """Dispatch one tensorized batch as ``k_split`` chained
        sub-solves (see ``solve``'s ``split`` doc). The per-pod packed
        arrays slice along the (chunk-aligned) pod axis; sub-solve i+1's
        ``state0`` is sub-solve i's full carried state
        (BatchCarriedUsage) donated straight through — no host sync
        anywhere in the chain. Trailing all-padding sub-batches are
        never dispatched.

        ``chain_start`` (the streaming dispatcher's cross-batch chain):
        the PREVIOUS batch's full carried state — the first sub-solve
        chains on it exactly like a mid-chain sub-solve would, so the
        occupancy rows the previous batch's placements advanced never
        re-upload from host. ``carry_out`` keeps the final carried
        state resident as the session's stream carry under
        ``chain_key`` for the next batch to consume."""
        sub = pods.padded // k_split
        cpk = sub // group  # chunks per sub-batch (grouped/compact axes)
        handles: list[DeferredAssignments] = []
        carry: BatchCarriedUsage | None = (
            BatchCarriedUsage(chain_start)
            if chain_start is not None
            else None
        )
        if chain_start is not None:
            self.dispatch_counts["stream_chained"] += 1
            # the carry is consumed (donated) by the first dispatch —
            # it can no longer be offered to anyone else
            self._session.stream_carry = None
            self._session.stream_key = None
            self._session.stream_versions = None
        dummy_b = np.zeros((1, 1), dtype=np.int32)
        # node pad = bstate's trailing axis (chained solves are
        # session-mode only; nominated dummies replicate)
        dev, dev_n = placers(mesh, bstate.shape[1])
        nom_used_j = dev_n(nom_used)
        nom_ports_j = dev_n(nom_ports)
        try:
            for i in range(k_split):
                lo = i * sub
                if lo >= pods.num_pods:
                    break
                sl = slice(i * cpk, (i + 1) * cpk) if compact else slice(
                    lo, lo + sub
                )
                first = carry is None
                out = _run_packed_jit(
                    nt,
                    ct,
                    self._session.persist if first else carry.state,
                    dev_n(bstate) if first else dev(dummy_b),
                    dev(xi64[sl]),
                    dev(xi32[sl]),
                    dev(xbool[sl]),
                    dev(kinds_host[i * cpk : (i + 1) * cpk])
                    if grouped
                    else dev(np.zeros(1, dtype=np.int32)),
                    dev(vcnt_host[i * cpk : (i + 1) * cpk])
                    if compact
                    else dev(np.zeros(1, dtype=np.int32)),
                    nom_used_j,
                    nom_ports_j,
                    jax.random.fold_in(key, i),
                    bspec=bspec,
                    xspec=xspec,
                    grouped=grouped,
                    group=group,
                    pack_result=False,
                    compact=compact,
                    chain_in=not first,
                    chain_out=True,
                    **kw,
                )
                assignments, st = out
                carry = BatchCarriedUsage(st)
                handles.append(
                    DeferredAssignments(
                        assignments, min(sub, pods.num_pods - lo), lo=lo
                    )
                )
        except Exception:
            # the chain donated session buffers before dying: the resident
            # state is unusable — drop it so the next solve re-uploads
            self.reset_session()
            raise
        self._session.persist = {
            name: carry.state[name]
            for name in ("used", "nonzero_used", "pod_count")
        }
        if carry_out and chain_key is not None:
            # keep the FULL carried state resident for the next batch's
            # chain (its fit entries are the same buffers as persist's;
            # every donating path nulls this out before reusing them)
            self._session.stream_carry = carry.state
            self._session.stream_key = chain_key
        else:
            self._session.stream_carry = None
            self._session.stream_key = None
            self._session.stream_versions = None
        self.dispatch_counts["chained_subbatches"] += len(handles)
        return handles

    @staticmethod
    def _chunk_kinds(
        pods: PodBatch,
        static: StaticPluginTensors,
        ports: PortTensors,
        spread: SpreadTensors,
        interpod: InterpodTensors,
        group: int,
        use_spread: bool,
        use_interpod: bool,
    ) -> np.ndarray:
        """[P // group] int32 chunk dispatch for _solve_grouped:
        0 slow / 1 plain fast / 2 spread fast / 3 anti fast.

        A fast kind requires `group` consecutive IDENTICAL valid pods
        (class, requests, port rows, and — when active — the spread/
        interpod per-pod rows). Kind 2/3 additionally require the single-
        constraint, zero-preference-row shapes whose sequential validity
        the device branches prove (see _solve_grouped); anything else is
        kind 0 and replays the full per-pod pipeline."""
        gn = pods.padded // group

        def same(arr: np.ndarray) -> np.ndarray:
            a = arr.reshape(gn, group, -1)
            return (a == a[:, :1]).all(axis=(1, 2))

        valid = pods.valid & pods.feasible_static
        vchunk = valid.reshape(gn, group)
        uniform = vchunk.all(axis=1)
        arrays = [
            np.asarray(static.class_of),
            pods.req,
            pods.req_mask,
            pods.nonzero_req,
            np.asarray(ports.pod_conflict),
            np.asarray(ports.pod_takes),
        ]
        if use_spread:
            arrays.append(np.asarray(spread.placed_match))
        if use_interpod:
            arrays += [
                np.asarray(interpod.in_match),
                np.asarray(interpod.ex_owned),
                np.asarray(interpod.m_anti),
                np.asarray(interpod.m_w),
                np.asarray(interpod.self_aff)[:, None],
            ]
        for arr in arrays:
            uniform &= same(arr)
        padding = ~vchunk.any(axis=1)

        kinds = np.zeros(gn, dtype=np.int32)
        # all-padding chunks are trivially fast: vcnt == 0 places nothing
        kinds[padding] = 1
        if not (use_spread or use_interpod):
            kinds[uniform] = 1
            return kinds

        class_of = np.asarray(static.class_of)
        taint = np.asarray(static.taint_cnt)
        nodeaff = np.asarray(static.nodeaff_pref)
        # hoist tensor->ndarray conversions out of the per-chunk loop
        if use_spread:
            spr_hard = np.asarray(spread.hard)
            spr_soft = np.asarray(spread.soft)
            spr_placed = np.asarray(spread.placed_match)
            spr_min_dom = np.asarray(spread.min_domains)
        if use_interpod:
            ipa_anti = np.asarray(interpod.cls_req_anti)
            ipa_aff = np.asarray(interpod.cls_req_aff)
            ipa_pref = np.asarray(interpod.cls_pref)
            ipa_in_m = np.asarray(interpod.in_match)
            ipa_ex_o = np.asarray(interpod.ex_owned)
            ipa_m_anti = np.asarray(interpod.m_anti)
            ipa_m_w = np.asarray(interpod.m_w)
            ipa_ex_anti = np.asarray(interpod.ex_anti)
            ipa_in_dom = np.asarray(interpod.in_dom)
            ipa_ex_dom = np.asarray(interpod.ex_dom)
        first = np.arange(gn) * group  # first pod index per chunk
        for g in np.nonzero(uniform & ~padding)[0]:
            i = int(first[g])
            c = int(class_of[i])
            no_pref_rows = not taint[c].any() and not nodeaff[c].any()

            if use_spread:
                hard_row = spr_hard[c]
                soft_row = spr_soft[c]
                placed_row = spr_placed[i]
                spr_neutral = (
                    (hard_row < 0).all()
                    and (soft_row < 0).all()
                    and not placed_row.any()
                )
                j = int(hard_row[0])
                spr_fast = (
                    j >= 0
                    and (hard_row[1:] < 0).all()
                    and (soft_row < 0).all()
                    and no_pref_rows
                    and bool(placed_row[j])
                    and not placed_row[np.arange(len(placed_row)) != j].any()
                    and int(spr_min_dom[j]) < 0
                )
            else:
                spr_neutral, spr_fast = True, False

            if use_interpod:
                anti_row = ipa_anti[c]
                aff_row = ipa_aff[c]
                pref_row = ipa_pref[c]
                in_m = ipa_in_m[i]
                ex_o = ipa_ex_o[i]
                m_anti = ipa_m_anti[i]
                m_w = ipa_m_w[i]
                ipa_neutral = (
                    (anti_row < 0).all()
                    and (aff_row < 0).all()
                    and (pref_row < 0).all()
                    and not in_m.any()
                    and not ex_o.any()
                    and not m_anti.any()
                    and not m_w.any()
                )
                j = int(anti_row[0])
                ex_idx = np.nonzero(ex_o)[0]
                ipa_fast = (
                    j >= 0
                    and (anti_row[1:] < 0).all()
                    and (aff_row < 0).all()
                    and (pref_row < 0).all()
                    and no_pref_rows
                    and not m_w.any()
                    and in_m[j] > 0
                    and not in_m[np.arange(len(in_m)) != j].any()
                    and len(ex_idx) == 1
                    and bool(m_anti[ex_idx[0]])
                    and m_anti.sum() == 1
                    and bool(ipa_ex_anti[ex_idx[0]])
                    and np.array_equal(
                        ipa_in_dom[j], ipa_ex_dom[ex_idx[0]]
                    )
                )
            else:
                ipa_neutral, ipa_fast = True, False

            if spr_fast and ipa_neutral:
                kinds[g] = 2
            elif ipa_fast and spr_neutral:
                kinds[g] = 3
            elif spr_neutral and ipa_neutral:
                kinds[g] = 1
        return kinds
