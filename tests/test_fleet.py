"""Fleet tier (kubernetes_tpu/fleet): occupancy exchange, cross-shard
reconciliation, membership, per-shard leases, BulkClient retry
hygiene, and the Scheduler's fleet dispatch mode end to end (two
replicas sharding one live ClusterState)."""

import pytest

from kubernetes_tpu.fleet import (
    COMMITTED,
    FleetConfig,
    FleetMembership,
    NodeRow,
    OccupancyExchange,
    PodRow,
    decode_rows,
    encode_rows,
)
from kubernetes_tpu.fleet.reconciler import CrossShardReconciler
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.sim.generators import make_node, make_pod
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock

ZONE = "topology.kubernetes.io/zone"


# -- occupancy exchange --


def test_exchange_stage_commit_withdraw_versions():
    ex = OccupancyExchange()
    v0 = ex.version
    row = PodRow(
        pod="default/p1", node="n1", zone="z0", namespace="default",
        labels=(("app", "x"),),
    )
    ex.stage("r0", row)
    assert ex.version > v0
    view = ex.peers_view("r1")
    assert view.pod_rows == (row,)
    assert ex.peers_view("r0").pod_rows == ()  # own rows excluded
    ex.commit("r0", "default/p1")
    assert ex.peers_view("r1").pod_rows[0].state == COMMITTED
    v1 = ex.version
    ex.commit("r0", "default/p1")  # idempotent: no version bump
    assert ex.version == v1
    ex.withdraw("r0", "default/p1")
    assert ex.peers_view("r1").pod_rows == ()
    ex.withdraw("r0", "default/p1")  # idempotent


def test_exchange_retire_drops_all_rows_and_handoffs():
    ex = OccupancyExchange()
    ex.publish_nodes("r1", [NodeRow("n1", "z0")])
    ex.stage(
        "r1",
        PodRow(
            pod="default/p", node="n1", zone="z0", namespace="default",
            labels=(("a", "b"),),
        ),
    )
    ex.hand_off("r1", "default/q", 1)
    ex.retire("r1")
    view = ex.peers_view("r0")
    assert view.node_rows == () and view.pod_rows == ()
    assert ex.pending_handoff_keys() == set()


def test_exchange_handoff_claim_deterministic():
    ex = OccupancyExchange()
    ex.hand_off("r1", "default/b", 1)
    ex.hand_off("r1", "default/a", 2)
    assert ex.pending_handoff_keys() == {"default/a", "default/b"}
    claimed = ex.claim_handoffs("r1")
    # sorted; each claim carries the journey trace the handoff shipped
    assert claimed == [("default/a", 2, ""), ("default/b", 1, "")]
    assert ex.claim_handoffs("r1") == []
    assert ex.pending_handoff_keys() == set()


def test_occupancy_rows_wire_roundtrip():
    """encode_rows/decode_rows: the tensorcodec-framed occupancy
    payload (the ExchangeOccupancy RPC's message) survives a round
    trip byte-exactly in content."""
    nodes = [NodeRow("n1", "z0"), NodeRow("n2", "")]
    pods = [
        PodRow(
            pod="default/p1", node="n1", zone="z0", namespace="default",
            labels=(("app", "x"), ("tier", "web")), state=COMMITTED,
        ),
        PodRow(
            pod="ns2/p2", node="n2", zone="", namespace="ns2",
            labels=(), state="pending",
        ),
    ]
    data = encode_rows("r0", 7, nodes, pods)
    replica, version, nodes2, pods2 = decode_rows(data)
    assert replica == "r0" and version == 7
    assert nodes2 == nodes
    assert pods2 == pods


def test_bulk_core_exchange_occupancy_roundtrip():
    """The bulk service method (no socket): publish r0's rows, get
    back the other replicas' merged view."""
    from kubernetes_tpu.server.bulk import BulkCore

    ex = OccupancyExchange()
    ex.publish_nodes("r1", [NodeRow("n9", "z9")])
    core = BulkCore(ClusterState(), exchange=ex)
    reply = core.exchange_occupancy(
        encode_rows("r0", 0, [NodeRow("n1", "z0")], [])
    )
    _, version, nodes, pods = decode_rows(reply)
    assert [n.node for n in nodes] == ["n9"]  # peers only
    assert version == ex.version
    # r0's inventory landed on the hub
    assert [n.node for n in ex.peers_view("r1").node_rows] == ["n1"]


# -- membership + per-shard leases --


def test_membership_transitions_bump_version():
    m = FleetMembership(("r0", "r1", "r2"), "r0")
    assert m.alive() == ("r0", "r1", "r2")
    v = m.version
    assert m.mark_dead("r1")
    assert m.version == v + 1 and m.alive() == ("r0", "r2")
    assert not m.mark_dead("r1")  # already dead: no change
    assert m.mark_alive("r1")
    assert m.alive() == ("r0", "r1", "r2")
    # self can never be marked dead
    assert not m.mark_dead("r0")
    with pytest.raises(ValueError):
        FleetMembership(("a", "b"), "ghost")


def test_membership_from_per_shard_leases():
    """Production liveness: peers are alive while their per-shard
    lease (<base>-shard-<i>, utils/leaderelection.py shard=) is held
    and fresh."""
    from kubernetes_tpu.utils.leaderelection import LeaderElector

    cs = ClusterState()
    clock = FakeClock()
    universe = ("r0", "r1")
    # r1 (shard index 1 in the sorted universe) elects on ITS lease
    e1 = LeaderElector(
        cs, identity="r1", name="ktpu", shard=1, clock=clock,
    )
    assert e1.try_acquire_or_renew()
    m = FleetMembership(universe, "r0")
    assert m.refresh_from_leases(cs, "ktpu", clock.now()) is False
    assert m.alive() == ("r0", "r1")  # fresh lease: alive
    # lease expires: r1 drops out of the view
    clock.advance(30.0)
    assert m.refresh_from_leases(cs, "ktpu", clock.now()) is True
    assert m.alive() == ("r0",)
    # r1 comes back
    assert e1.try_acquire_or_renew()
    assert m.refresh_from_leases(cs, "ktpu", clock.now()) is True
    assert m.alive() == ("r0", "r1")


def test_per_shard_leases_do_not_contend():
    """Two fleet replicas on DIFFERENT shards both hold leadership
    concurrently; two on the SAME shard contend classically."""
    from kubernetes_tpu.utils.leaderelection import LeaderElector

    cs = ClusterState()
    clock = FakeClock()
    a = LeaderElector(cs, identity="r0", name="ktpu", shard=0, clock=clock)
    b = LeaderElector(cs, identity="r1", name="ktpu", shard=1, clock=clock)
    assert a.name == "ktpu-shard-0" and b.name == "ktpu-shard-1"
    assert a.try_acquire_or_renew() and b.try_acquire_or_renew()
    assert a.is_leader and b.is_leader  # N leases, N leaders
    # same shard: classic active/passive contention
    b2 = LeaderElector(cs, identity="r2", name="ktpu", shard=1, clock=clock)
    assert not b2.try_acquire_or_renew()


def test_shard_lease_validation():
    from kubernetes_tpu.utils.leaderelection import LeaderElector

    cs = ClusterState()
    with pytest.raises(ValueError, match="shard must be non-negative"):
        LeaderElector(cs, identity="x", shard=-1)
    # timing validation still precedes (ordering preserved)
    with pytest.raises(ValueError, match="retry_period must be positive"):
        LeaderElector(cs, identity="x", shard=0, retry_period=0.0)


# -- cross-shard reconciler --


class _FakeCache:
    """Minimal cache shape for the reconciler: nodes dict of
    HostNodeInfo-alikes."""

    class _Info:
        def __init__(self, node, pods):
            self.node = node
            self.pods = pods

    def __init__(self, placements):
        # placements: list of (node_name, zone, [pods])
        self.nodes = {}
        for name, zone, pods in placements:
            node = make_node(name, "8", "32Gi", labels={ZONE: zone})
            self.nodes[name] = self._Info(
                node, {p.key: p for p in pods}
            )


def _peer_view(node_rows=(), pod_rows=()):
    from kubernetes_tpu.fleet.occupancy import PeerView

    return PeerView(0, tuple(node_rows), tuple(pod_rows))


def test_reconciler_rejects_cross_shard_skew():
    """My shard holds z0 only; the peer's z1 is empty — placing a 2nd
    spread pod in z0 would exceed maxSkew=1 against the fleet
    minimum."""
    rec = CrossShardReconciler("r0")
    placed = make_pod("placed", "1", shape="spread")
    cache = _FakeCache([("n0", "z0", [placed])])
    peers = _peer_view(node_rows=[NodeRow("n9", "z1")])
    pod = make_pod("incoming", "1", shape="spread")
    why = rec.admit(pod, "n0", "z0", cache, peers)
    assert why is not None and "maxSkew" in why
    # with a matching peer pod in z1 the counts balance: admitted
    peers2 = _peer_view(
        node_rows=[NodeRow("n9", "z1")],
        pod_rows=[
            PodRow(
                pod="default/peer", node="n9", zone="z1",
                namespace="default", labels=(("app", "spread"),),
            )
        ],
    )
    assert rec.admit(pod, "n0", "z0", cache, peers2) is None


def test_reconciler_counts_peer_pending_rows():
    """A peer's PENDING (assumed, not yet bound) row counts — that is
    the entire point of exchanging before commit."""
    rec = CrossShardReconciler("r0")
    cache = _FakeCache([("n0", "z0", [])])
    pod = make_pod("incoming", "1", shape="spread")
    # peer staged 2 pending matches in z1; my z0 has 0: placing in z0
    # keeps skew <= 1 -> admitted
    rows = [
        PodRow(
            pod=f"default/pp{i}", node="n9", zone="z1",
            namespace="default", labels=(("app", "spread"),),
            state="pending",
        )
        for i in range(2)
    ]
    peers = _peer_view(node_rows=[NodeRow("n9", "z1")], pod_rows=rows)
    assert rec.admit(pod, "n0", "z0", cache, peers) is None


def test_reconciler_zone_anti_affinity_against_peer():
    from kubernetes_tpu.api.wrappers import MakePod

    rec = CrossShardReconciler("r0")
    cache = _FakeCache([("n0", "z0", [])])
    pod = (
        MakePod().name("incoming").req({"cpu": "1"})
        .label("app", "anti")
        .pod_anti_affinity(ZONE, {"app": "anti"})
        .obj()
    )
    peers = _peer_view(
        pod_rows=[
            PodRow(
                pod="default/peer", node="n9", zone="z0",
                namespace="default", labels=(("app", "anti"),),
            )
        ]
    )
    why = rec.admit(pod, "n0", "z0", cache, peers)
    assert why is not None and "anti" in why
    # a peer in ANOTHER zone does not conflict
    peers2 = _peer_view(
        pod_rows=[
            PodRow(
                pod="default/peer", node="n9", zone="z1",
                namespace="default", labels=(("app", "anti"),),
            )
        ]
    )
    assert rec.admit(pod, "n0", "z0", cache, peers2) is None


# -- fleet scheduler end to end --


def _mk_fleet(n_nodes=8, zones=2, universe=("r0", "r1"), clock=None):
    clock = clock or FakeClock()
    cluster = ClusterState(clock=clock)
    for i in range(n_nodes):
        cluster.create_node(
            make_node(
                f"n{i}", "8", "32Gi", labels={ZONE: f"z{i % zones}"}
            )
        )
    ex = OccupancyExchange()
    scheds = [
        Scheduler(
            cluster,
            SchedulerConfig(
                batch_size=16,
                mesh_devices=1,
                solver=ExactSolverConfig(tie_break="first"),
                fleet=FleetConfig(
                    replica=rid, replicas=universe, exchange=ex
                ),
            ),
            clock=clock,
        )
        for rid in universe
    ]
    return cluster, scheds, ex, clock


def _drive_all(scheds, clock, rounds=10):
    bound = []
    for _ in range(rounds):
        for s in scheds:
            for r in s.run_until_settled():
                bound.extend(r.scheduled)
        clock.advance(11.0)
    return bound


def test_fleet_shards_are_disjoint_and_cover():
    cluster, scheds, _, _ = _mk_fleet()
    shards = [set(s.cache.nodes) for s in scheds]
    assert shards[0].isdisjoint(shards[1])
    assert shards[0] | shards[1] == {f"n{i}" for i in range(8)}


def test_fleet_binds_all_plain_pods_on_owned_nodes():
    cluster, scheds, _, clock = _mk_fleet()
    for i in range(20):
        cluster.create_pod(make_pod(f"p{i:02}", "500m"))
    bound = _drive_all(scheds, clock, rounds=4)
    assert len(bound) == 20
    # each bind landed on a node exactly ONE replica caches (disjoint
    # shards: the no-global-overcommit precondition)
    for pod_key, node in dict(bound).items():
        owners = [s for s in scheds if node in s.cache.nodes]
        assert len(owners) == 1


def test_fleet_spread_converges_via_handoff():
    """6 zone-spread pods over 2 zones split across 2 shards: the
    statically mis-routed tail is handed off through the exchange and
    the fleet lands a perfect 3/3 — the single-scheduler outcome."""
    cluster, scheds, ex, clock = _mk_fleet()
    for i in range(6):
        cluster.create_pod(make_pod(f"s{i}", "250m", shape="spread"))
    bound = _drive_all(scheds, clock, rounds=10)
    assert len(bound) == 6
    zones = {}
    for p in cluster.list_pods():
        z = f"z{int(p.node_name[1:]) % 2}"
        zones[z] = zones.get(z, 0) + 1
    assert zones == {"z0": 3, "z1": 3}
    from kubernetes_tpu.sim.invariants import (
        check_capacity,
        check_constraints,
    )

    viol: list = []
    check_capacity(cluster, 0, viol)
    check_constraints(cluster, 0, viol)
    assert viol == []


def test_fleet_journal_records_carry_replica_tag():
    cluster, scheds, _, clock = _mk_fleet()
    from kubernetes_tpu.obs import ObsConfig

    # rebuild one replica with the journal on
    sched = Scheduler(
        cluster,
        SchedulerConfig(
            batch_size=16,
            mesh_devices=1,
            solver=ExactSolverConfig(tie_break="first"),
            obs=ObsConfig(journal=True),
            fleet=FleetConfig(replica="r9", replicas=("r9",)),
        ),
        clock=clock,
    )
    cluster.create_pod(make_pod("tagme", "500m"))
    sched.run_until_settled()
    import json

    recs = [json.loads(line) for line in sched.journal.lines]
    assert recs and all(r.get("replica") == "r9" for r in recs)


def test_fleet_replica_loss_adopts_orphans():
    """Kill r1: r0's membership flip re-owns the whole cluster and
    adopts r1's queued pods; everything still binds."""
    cluster, scheds, ex, clock = _mk_fleet()
    r0, r1 = scheds
    for i in range(12):
        cluster.create_pod(make_pod(f"p{i:02}", "500m"))
    # r1 dies before ever scheduling: unsubscribe + retire, like the
    # fleet sim's crash model
    cluster.unsubscribe(r1._on_event)
    ex.retire("r1")
    r0.fleet.set_alive(["r0"])
    bound = []
    for _ in range(4):
        for r in r0.run_until_settled():
            bound.extend(r.scheduled)
        clock.advance(11.0)
    assert len(bound) == 12
    assert len(r0.cache.nodes) == 8  # the whole cluster re-owned


def test_resync_rebuilds_pod_rows_from_truth():
    """A node changing shard owner takes its pods' DELETE events to
    the NEW owner's filter — the old owner must not keep ghost
    occupancy rows for pods it no longer owns (review-caught leak)."""
    cluster, scheds, ex, clock = _mk_fleet()
    r0, r1 = scheds
    for i in range(8):
        pod = make_pod(f"p{i:02}", "500m")
        pod.labels["cohort"] = "web"  # label-bearing: rows on the wire
        cluster.create_pod(pod)
    _drive_all(scheds, clock, rounds=3)
    # r1 dies: r0 adopts its shard; r0's rebuilt rows must cover every
    # labeled bound pod in the cluster and nothing else
    cluster.unsubscribe(r1._on_event)
    ex.retire("r1")
    r0.fleet.set_alive(["r0"])
    r0.run_until_settled()  # triggers maybe_resync
    _nodes, rows = ex.replica_rows("r0")
    live = {
        p.key
        for p in cluster.list_pods()
        if p.node_name and p.labels
    }
    assert {r.pod for r in rows} == live
    # delete a pod: r0 (now the owner) withdraws its row
    victim = sorted(live)[0]
    ns, name = victim.split("/", 1)
    cluster.delete_pod(ns, name)
    _nodes, rows2 = ex.replica_rows("r0")
    assert victim not in {r.pod for r in rows2}


def test_lease_membership_polling_detects_peer_death():
    """FleetConfig.lease_membership: a peer's stale shard lease flips
    membership at the next cycle and the survivor re-owns the
    cluster."""
    from kubernetes_tpu.utils.leaderelection import LeaderElector

    clock = FakeClock()
    cluster = ClusterState(clock=clock)
    for i in range(4):
        cluster.create_node(
            make_node(f"n{i}", "8", "32Gi", labels={ZONE: f"z{i % 2}"})
        )
    universe = ("r0", "r1")
    # r1 holds its shard lease (shard 1 of the sorted universe)
    e1 = LeaderElector(
        cluster, identity="r1", name="ktpu", shard=1, clock=clock
    )
    assert e1.try_acquire_or_renew()
    r0 = Scheduler(
        cluster,
        SchedulerConfig(
            batch_size=16,
            mesh_devices=1,
            solver=ExactSolverConfig(tie_break="first"),
            fleet=FleetConfig(
                replica="r0", replicas=universe, lease="ktpu",
                lease_membership=True, lease_poll_s=1.0,
            ),
        ),
        clock=clock,
    )
    assert len(r0.cache.nodes) == 2  # half the cluster while r1 lives
    # r1's lease expires; the next scheduling cycle polls and re-owns
    clock.advance(30.0)
    r0.schedule_batch()
    assert r0.fleet.membership.alive() == ("r0",)
    assert len(r0.cache.nodes) == 4


def test_fleet_ownership_fence_rejects_foreign_node():
    """admit() is the no-global-overcommit fence: a placement on a
    node outside the replica's current partition is rejected even
    when the cache is stale."""
    cluster, scheds, _, _ = _mk_fleet()
    r0 = scheds[0]
    foreign = next(
        f"n{i}" for i in range(8) if f"n{i}" not in r0.cache.nodes
    )
    pod = make_pod("x", "500m")
    why = r0.fleet.admit(pod, foreign, r0.cache)
    assert why is not None and "no longer owned" in why


# -- BulkClient retry hygiene --


class _FakeRpcError(Exception):
    def __init__(self, code_name):
        self._code_name = code_name

    def code(self):
        class _C:
            pass

        c = _C()
        c.name = self._code_name
        return c


def _mk_client(monkeypatch):
    """BulkClient without a socket: stub grpc + channel plumbing."""
    import kubernetes_tpu.server.bulk as bulk

    class _FakeGrpc:
        RpcError = _FakeRpcError

        @staticmethod
        def insecure_channel(target):
            class _Ch:
                def unary_unary(self, *_a, **_k):
                    return lambda payload, timeout=None: b""

                def close(self):
                    pass

            return _Ch()

    import sys

    monkeypatch.setitem(sys.modules, "grpc", _FakeGrpc)
    return bulk.BulkClient(
        "127.0.0.1:1", retries=3, backoff_base_s=0.01, clock=FakeClock()
    )


def test_bulk_client_retries_transient_then_succeeds(monkeypatch):
    from kubernetes_tpu import metrics

    client = _mk_client(monkeypatch)
    calls = {"n": 0}

    def flaky(payload, timeout=None):
        calls["n"] += 1
        if calls["n"] < 3:
            raise _FakeRpcError("UNAVAILABLE")
        return b"ok"

    before = metrics.bulk_retry_total.labels("Solve")._value.get()
    assert client._call("Solve", flaky, b"x") == b"ok"
    assert calls["n"] == 3
    assert client._clock.now() > 0  # backoff slept on the clock
    after = metrics.bulk_retry_total.labels("Solve")._value.get()
    assert after - before == 2


def test_bulk_client_gives_up_after_budget(monkeypatch):
    client = _mk_client(monkeypatch)

    def always_down(payload, timeout=None):
        raise _FakeRpcError("UNAVAILABLE")

    with pytest.raises(_FakeRpcError):
        client._call("Evaluate", always_down, b"x")


def test_bulk_client_does_not_retry_non_transient(monkeypatch):
    client = _mk_client(monkeypatch)
    calls = {"n": 0}

    def fatal(payload, timeout=None):
        calls["n"] += 1
        raise _FakeRpcError("INVALID_ARGUMENT")

    with pytest.raises(_FakeRpcError):
        client._call("Solve", fatal, b"x")
    assert calls["n"] == 1


def test_bulk_client_commit_solve_never_retries(monkeypatch):
    """A committing Solve mutates state: a lost reply must surface,
    not double-create via retry."""
    client = _mk_client(monkeypatch)
    calls = {"n": 0}

    def flaky(payload, timeout=None):
        calls["n"] += 1
        raise _FakeRpcError("UNAVAILABLE")

    client._solve = flaky
    with pytest.raises(_FakeRpcError):
        client.solve([100], [200], names=["p"], commit=True)
    assert calls["n"] == 1


def test_bulk_client_deadline_passed_through(monkeypatch):
    client = _mk_client(monkeypatch)
    seen = {}

    def record(payload, timeout=None):
        seen["timeout"] = timeout
        return b""

    client._call("SyncNodes", record, b"x")
    assert seen["timeout"] == client.deadline_s
