"""InterPodAffinity tensorizer (SURVEY.md §8.7 step 7, the memory-hard one).

Two term-instance spaces, both with per-node count state carried through the
scan (pods placed mid-batch immediately affect later pods — including the
symmetry direction):

INCOMING terms (T_in) — the batch pod classes' own affinity terms:
  req-affinity / req-anti-affinity / preferred(±weight). State
  in_cnt[T_in, N] counts existing pods matching the term per node;
  placed batch pods fold in via in_match[P, T_in].

EXISTING-side terms (T_ex) — terms OWNED by pods (placed or batch), needed
for the symmetry checks (filtering.go#satisfyExistingPodsAntiAffinity,
scoring's symmetric preferred/hard-affinity contributions): required-anti
(filter-blocking), preferred ±w and required-affinity (scored with
hardPodAffinityWeight). State ex_cnt[T_ex, N] counts OWNER pods per node;
batch pods that own terms fold in via ex_owned[P, T_ex]. Whether instance u
concerns incoming pod p (selector+namespace vs p) is the per-pod bit/weight
matrix m_anti[P, T_ex] / m_w[P, T_ex] — precompiled host-side, so the
device never touches label strings.

Domain aggregation on device uses one flattened segment-sum over
(term, domain) pairs per step (ops/interpod.py) — the dense-tensor
restructuring of the reference's topologyToMatchedTermCount maps.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from ..api.objects import Node, Pod, PodAffinityTerm
from ..ops.oracle import interpod as oip
from .schema import PodBatch, bucket_pow2

INST_PAD = 8
DOM_PAD = 8

# existing-term kinds
K_REQ_ANTI = 0
K_PREF_AFF = 1
K_PREF_ANTI = 2
K_REQ_AFF = 3


@dataclass
class InterpodTensors:
    num_in: int
    num_ex: int
    d_pad: int
    # per incoming-term tables
    in_dom: np.ndarray  # [Ti, Np] int32 (-1 = node lacks key)
    in_cnt0: np.ndarray  # [Ti, Np] int32
    in_pref_w: np.ndarray  # [Ti] int32 signed weight (preferred terms only)
    # class tables (-1 pad)
    cls_req_aff: np.ndarray  # [Cp, Sa]
    cls_req_anti: np.ndarray  # [Cp, Sb]
    cls_pref: np.ndarray  # [Cp, Sp]
    # per existing-term tables
    ex_dom: np.ndarray  # [Te, Np] int32
    ex_cnt0: np.ndarray  # [Te, Np] int32 — owner pods per node
    ex_anti: np.ndarray  # [Te] bool — required-anti (filter)
    # per-pod matrices (xs)
    in_match: np.ndarray  # [Pp, Ti] int32 — placed pod matches incoming term
    ex_owned: np.ndarray  # [Pp, Te] int32 — pod owns the term (count)
    m_anti: np.ndarray  # [Pp, Te] bool — ex required-anti term selects pod
    m_w: np.ndarray  # [Pp, Te] int32 — signed score weight vs pod
    self_aff: np.ndarray  # [Pp] bool — pod matches all own req-aff terms

    @property
    def empty(self) -> bool:
        return self.num_in == 0 and self.num_ex == 0

    @property
    def ident(self) -> bool:
        """True when every term row maps each valid node to a UNIQUE domain
        (hostname topologies with per-node hostname labels) — verified
        numerically, enabling domain_counts' no-aggregation fast path.
        Rows are deduped by content first: terms sharing a topology key
        share byte-identical rows (dom_cache), so each distinct row is
        checked once."""
        seen: set[bytes] = set()
        for dom in (self.in_dom, self.ex_dom):
            for row in dom:
                key = row.tobytes()
                if key in seen:
                    continue
                seen.add(key)
                v = row[row >= 0]
                if v.size and np.unique(v).size != v.size:
                    return False
        return True

    @property
    def has_score(self) -> bool:
        """False when no preferred terms / symmetry weights exist anywhere
        in the batch: the scoring section is statically all-zero."""
        return bool((self.in_pref_w != 0).any() or (self.m_w != 0).any())

    @property
    def anti_only(self) -> bool:
        """True when the batch carries required ANTI-affinity only — no
        required affinity, no preferred terms anywhere. The shape the
        grouped solver's quota fast path can handle (solver/exact.py
        _chunk_kinds refines per chunk)."""
        return bool((self.cls_req_aff < 0).all()) and not self.has_score


def trivial_interpod_tensors(
    pbatch: PodBatch, padded_n: int, c_pad: int
) -> InterpodTensors:
    zi = np.zeros((INST_PAD, padded_n), dtype=np.int32)
    return InterpodTensors(
        num_in=0,
        num_ex=0,
        d_pad=DOM_PAD,
        in_dom=zi - 1,
        in_cnt0=zi.copy(),
        in_pref_w=np.zeros(INST_PAD, dtype=np.int32),
        cls_req_aff=np.full((c_pad, 1), -1, dtype=np.int32),
        cls_req_anti=np.full((c_pad, 1), -1, dtype=np.int32),
        cls_pref=np.full((c_pad, 1), -1, dtype=np.int32),
        ex_dom=zi - 1,
        ex_cnt0=zi.copy(),
        ex_anti=np.zeros(INST_PAD, dtype=bool),
        in_match=np.zeros((pbatch.padded, INST_PAD), dtype=np.int32),
        ex_owned=np.zeros((pbatch.padded, INST_PAD), dtype=np.int32),
        m_anti=np.zeros((pbatch.padded, INST_PAD), dtype=bool),
        m_w=np.zeros((pbatch.padded, INST_PAD), dtype=np.int32),
        self_aff=np.zeros(pbatch.padded, dtype=bool),
    )


def _ex_terms_of(pod: Pod):
    """(kind, term, weight) triples owned by ``pod`` that the symmetry
    machinery needs. Terms are made EFFECTIVE here (matchLabelKeys merged
    from the owner's labels) because the dedup key and the per-pod match
    rows depend on the owner-resolved selector, not the raw spec."""
    out = []
    for t in oip._required_anti_terms(pod):
        out.append((K_REQ_ANTI, oip.effective_term(t, pod), 0))
    for wt in oip._preferred_terms(pod, anti=False):
        out.append((K_PREF_AFF, oip.effective_term(wt.term, pod), wt.weight))
    for wt in oip._preferred_terms(pod, anti=True):
        out.append((K_PREF_ANTI, oip.effective_term(wt.term, pod), -wt.weight))
    for t in oip._required_aff_terms(pod):
        out.append((K_REQ_AFF, oip.effective_term(t, pod), 0))
    return out


def build_interpod_tensors(
    pods: Sequence[Pod],
    class_reps: Sequence[Pod],
    pbatch: PodBatch,
    slot_nodes: Sequence[Node | None],
    placed_by_slot: Mapping[int, Sequence[Pod]],
    padded_n: int,
    c_pad: int,
    hard_pod_affinity_weight: int = 1,
    nominated: Sequence[tuple[Pod, int]] = (),
) -> InterpodTensors:
    """``nominated`` carries (pod, node slot) pairs for unbound pods whose
    ``status.nominatedNodeName`` resolved to a live slot: they fold into
    ``in_cnt0`` and ``ex_cnt0`` exactly like placed pods (the
    RunFilterPluginsWithNominatedPods convention), so both the incoming
    terms and the symmetry direction see a nominated peer at its slot."""
    # ---- incoming terms per class ----
    in_terms: list[tuple[int, PodAffinityTerm, int, int]] = []  # (cls, term, kind, w)
    per_class: list[tuple[list[int], list[int], list[int]]] = []
    for c, rep in enumerate(class_reps):
        aff_ids, anti_ids, pref_ids = [], [], []
        for t in oip._required_aff_terms(rep):
            aff_ids.append(len(in_terms))
            in_terms.append((c, t, K_REQ_AFF, 0))
        for t in oip._required_anti_terms(rep):
            anti_ids.append(len(in_terms))
            in_terms.append((c, t, K_REQ_ANTI, 0))
        for wt in oip._preferred_terms(rep, anti=False):
            pref_ids.append(len(in_terms))
            in_terms.append((c, wt.term, K_PREF_AFF, wt.weight))
        for wt in oip._preferred_terms(rep, anti=True):
            pref_ids.append(len(in_terms))
            in_terms.append((c, wt.term, K_PREF_ANTI, -wt.weight))
        per_class.append((aff_ids, anti_ids, pref_ids))

    # ---- existing-side terms (owned by placed AND batch pods), deduped ----
    ex_index: dict = {}
    ex_terms: list[tuple[int, PodAffinityTerm, int, str]] = []  # kind, term, w, owner_ns

    def ex_intern(kind: int, term: PodAffinityTerm, w: int, owner_ns: str) -> int:
        key = (kind, term, w, owner_ns)
        i = ex_index.get(key)
        if i is None:
            i = len(ex_terms)
            ex_index[key] = i
            ex_terms.append((kind, term, w, owner_ns))
        return i

    placed_pods: list[tuple[int, Pod]] = [
        (slot, p) for slot, ps in placed_by_slot.items() for p in ps
    ]
    # nominated pods count exactly like placed pods at their slot — both
    # in the incoming count state and as existing-side term owners
    placed_pods += [
        (n_i, p) for p, n_i in nominated if 0 <= n_i < padded_n
    ]
    owner_map_placed: list[tuple[int, int]] = []  # (slot, ex_id)
    for slot, p in placed_pods:
        for kind, t, w in _ex_terms_of(p):
            owner_map_placed.append((slot, ex_intern(kind, t, w, p.namespace)))
    owner_map_batch: list[tuple[int, int]] = []  # (pod idx, ex_id)
    for p_i, p in enumerate(pods):
        for kind, t, w in _ex_terms_of(p):
            owner_map_batch.append((p_i, ex_intern(kind, t, w, p.namespace)))

    if not in_terms and not ex_terms:
        return trivial_interpod_tensors(pbatch, padded_n, c_pad)

    ti_pad = bucket_pow2(max(len(in_terms), 1), floor=INST_PAD)
    te_pad = bucket_pow2(max(len(ex_terms), 1), floor=INST_PAD)

    # ---- domain vocab per topology key ----
    all_keys = {t.topology_key for _, t, _, _ in in_terms} | {
        t.topology_key for _, t, _, _ in ex_terms
    }
    key_vocab: dict[str, dict[str, int]] = {k: {} for k in all_keys}
    for node in slot_nodes:
        if node is None:
            continue
        for key in all_keys:
            v = node.labels.get(key)
            if v is not None:
                vocab = key_vocab[key]
                vocab.setdefault(v, len(vocab))
    d_pad = bucket_pow2(
        max((len(v) for v in key_vocab.values()), default=1), floor=DOM_PAD
    )

    def dom_row(key: str) -> np.ndarray:
        row = np.full(padded_n, -1, dtype=np.int32)
        vocab = key_vocab[key]
        for n_i, node in enumerate(slot_nodes):
            if node is None or n_i >= padded_n:
                continue
            v = node.labels.get(key)
            if v is not None:
                row[n_i] = vocab[v]
        return row

    dom_cache: dict[str, np.ndarray] = {}

    def dom_for(key: str) -> np.ndarray:
        if key not in dom_cache:
            dom_cache[key] = dom_row(key)
        return dom_cache[key]

    # ---- incoming tables ----
    in_dom = np.full((ti_pad, padded_n), -1, dtype=np.int32)
    in_cnt0 = np.zeros((ti_pad, padded_n), dtype=np.int32)
    in_pref_w = np.zeros(ti_pad, dtype=np.int32)
    in_match = np.zeros((pbatch.padded, ti_pad), dtype=np.int32)
    sa = max(max((len(a) for a, _, _ in per_class), default=0), 1)
    sb = max(max((len(b) for _, b, _ in per_class), default=0), 1)
    sp = max(max((len(p) for _, _, p in per_class), default=0), 1)
    cls_req_aff = np.full((c_pad, sa), -1, dtype=np.int32)
    cls_req_anti = np.full((c_pad, sb), -1, dtype=np.int32)
    cls_pref = np.full((c_pad, sp), -1, dtype=np.int32)
    for c, (aff_ids, anti_ids, pref_ids) in enumerate(per_class):
        cls_req_aff[c, : len(aff_ids)] = aff_ids
        cls_req_anti[c, : len(anti_ids)] = anti_ids
        cls_pref[c, : len(pref_ids)] = pref_ids

    for t_i, (c, term, kind, w) in enumerate(in_terms):
        rep = class_reps[c]
        in_dom[t_i] = dom_for(term.topology_key)
        in_pref_w[t_i] = w
        for slot, q in placed_pods:
            if slot < padded_n and oip.term_matches_pod(term, rep, q):
                in_cnt0[t_i, slot] += 1
        for p_i, q in enumerate(pods):
            if oip.term_matches_pod(term, rep, q):
                in_match[p_i, t_i] = 1

    # ---- existing tables ----
    ex_dom = np.full((te_pad, padded_n), -1, dtype=np.int32)
    ex_cnt0 = np.zeros((te_pad, padded_n), dtype=np.int32)
    ex_anti = np.zeros(te_pad, dtype=bool)
    ex_owned = np.zeros((pbatch.padded, te_pad), dtype=np.int32)
    m_anti = np.zeros((pbatch.padded, te_pad), dtype=bool)
    m_w = np.zeros((pbatch.padded, te_pad), dtype=np.int32)

    for e_i, (kind, term, w, owner_ns) in enumerate(ex_terms):
        ex_dom[e_i] = dom_for(term.topology_key)
        ex_anti[e_i] = kind == K_REQ_ANTI
        score_w = w if kind in (K_PREF_AFF, K_PREF_ANTI) else (
            hard_pod_affinity_weight if kind == K_REQ_AFF else 0
        )
        for p_i, p in enumerate(pods):
            if not term.matches_namespace(owner_ns, p.namespace):
                continue
            if term.label_selector is not None and term.label_selector.matches(
                p.labels
            ):
                if kind == K_REQ_ANTI:
                    m_anti[p_i, e_i] = True
                elif score_w:
                    m_w[p_i, e_i] = score_w
    for slot, e_i in owner_map_placed:
        if slot < padded_n:
            ex_cnt0[e_i, slot] += 1
    for p_i, e_i in owner_map_batch:
        ex_owned[p_i, e_i] += 1

    # ---- self-affinity bits (first-pod special case) ----
    self_aff = np.zeros(pbatch.padded, dtype=bool)
    for p_i, p in enumerate(pods):
        terms = oip._required_aff_terms(p)
        self_aff[p_i] = bool(terms) and all(
            oip.term_matches_pod(t, p, p) for t in terms
        )

    return InterpodTensors(
        num_in=len(in_terms),
        num_ex=len(ex_terms),
        d_pad=d_pad,
        in_dom=in_dom,
        in_cnt0=in_cnt0,
        in_pref_w=in_pref_w,
        cls_req_aff=cls_req_aff,
        cls_req_anti=cls_req_anti,
        cls_pref=cls_pref,
        ex_dom=ex_dom,
        ex_cnt0=ex_cnt0,
        ex_anti=ex_anti,
        in_match=in_match,
        ex_owned=ex_owned,
        m_anti=m_anti,
        m_w=m_w,
        self_aff=self_aff,
    )
