"""Scheduling Framework plugin interfaces — the in-process, extension-
point-shaped API of SURVEY §8.2, mirroring
pkg/scheduler/framework/interface.go so plugin code and plugin tests read
like their upstream counterparts:

- `Status` / `StatusCode` (interface.go#Status, #Code): Success,
  Unschedulable, UnschedulableAndUnresolvable, Wait, Skip, Error;
- `CycleState` (framework/cycle_state.go): per-pod keyed scratch with
  read/write/clone;
- plugin protocols named for their extension points (PreFilterPlugin,
  FilterPlugin, ScorePlugin) with the upstream method shapes.

Two consumption paths:
1. `framework.runtime.Framework` runs the points host-side over API
   objects — the fixture upstream plugin tests build with
   runtime.NewFramework.
2. Out-of-tree plugins plug into the TPU solve itself via
   SchedulerConfig.out_of_tree_plugins: because the device pipeline is
   class-vectorized, a custom plugin's Filter/Score run host-side once
   per (pod scheduling class, node) and fold into the per-class static
   mask / score tables the fused kernel already consumes — the TPU-shaped
   equivalent of registering an in-process Go plugin. Contract for
   solver-path plugins: depend only on node state plus the pod fields in
   the scheduling-class identity — labels, annotations, and the in-tree
   spec fields (selectors, affinity, tolerations, requests, ports,
   spread) — never on other pending pods or on per-pod uniqueness like
   the name (two pods identical in those fields share one verdict by
   construction).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Mapping

from ..api.objects import Node, Pod

MAX_NODE_SCORE = 100  # interface.go#MaxNodeScore
MIN_NODE_SCORE = 0


class StatusCode(enum.IntEnum):
    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


@dataclass(frozen=True)
class Status:
    code: StatusCode = StatusCode.SUCCESS
    reasons: tuple[str, ...] = ()

    @staticmethod
    def success() -> "Status":
        return Status()

    @staticmethod
    def unschedulable(*reasons: str) -> "Status":
        return Status(StatusCode.UNSCHEDULABLE, tuple(reasons))

    @staticmethod
    def error(*reasons: str) -> "Status":
        return Status(StatusCode.ERROR, tuple(reasons))

    @property
    def is_success(self) -> bool:
        return self.code == StatusCode.SUCCESS

    @property
    def is_rejection(self) -> bool:
        return self.code in (
            StatusCode.UNSCHEDULABLE,
            StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE,
        )


class CycleState:
    """Per-scheduling-cycle keyed scratch (cycle_state.go#CycleState)."""

    def __init__(self) -> None:
        self._data: dict[str, Any] = {}

    def read(self, key: str) -> Any:
        if key not in self._data:
            raise KeyError(key)  # cycle_state.go#ErrNotFound
        return self._data[key]

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        c._data = dict(self._data)
        return c


class Plugin:
    """Base: every plugin has a Name (interface.go#Plugin)."""

    def name(self) -> str:
        return type(self).__name__


@dataclass(frozen=True)
class PreFilterResult:
    """interface.go#PreFilterResult: a set of node names the pod could
    possibly schedule on — every other node is skipped by the Filter
    stage (folded into the static class mask on the solver path).
    ``node_names=None`` means all nodes (AllNodes())."""

    node_names: frozenset | None = None

    def all_nodes(self) -> bool:
        return self.node_names is None


class PreFilterPlugin(Plugin):
    def pre_filter(
        self, state: CycleState, pod: Pod
    ) -> "Status | tuple[Status, PreFilterResult | None]":
        """interface.go#PreFilterPlugin.PreFilter. May return a bare
        Status (common case) or (Status, PreFilterResult) to narrow the
        candidate node set."""
        return Status.success()


def run_pre_filter(
    plugin: PreFilterPlugin, state: CycleState, pod: Pod
) -> tuple[Status, "PreFilterResult | None"]:
    """Normalize the two allowed pre_filter return shapes."""
    out = plugin.pre_filter(state, pod)
    if isinstance(out, tuple):
        return out
    return out, None


class PreEnqueuePlugin(Plugin):
    """interface.go#PreEnqueuePlugin: gates a pod's entry into the active
    queue (the schedulinggates plugin's point). A non-success status
    parks the pod as gated until a pod update re-evaluates it."""

    def pre_enqueue(self, pod: Pod) -> Status:
        raise NotImplementedError


class QueueSortPlugin(Plugin):
    """interface.go#QueueSortPlugin: total order on the active queue.
    Replaces the default PrioritySort when registered (the reference
    allows exactly one queue-sort plugin)."""

    def less(self, info1, info2) -> bool:
        """True if info1 should pop before info2. Arguments are
        state.queue.QueuedPodInfo (pod, timestamp, attempts...)."""
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    """interface.go#PostFilterPlugin: runs when no node fit the pod
    (defaultpreemption's point). Returning (node_name, success) nominates
    the pod onto that node; plugins run in registration order after the
    in-tree default preemption, stopping at the first success/error."""

    def post_filter(
        self, state: CycleState, pod: Pod, filtered_nodes: Mapping[str, str]
    ) -> "tuple[str | None, Status]":
        """``filtered_nodes``: node name -> rejection reason for this
        cycle. Returns (nominated node name or None, status)."""
        raise NotImplementedError


class ReservePlugin(Plugin):
    """interface.go#ReservePlugin: Reserve runs after a node is chosen
    and the pod is assumed; Unreserve rolls back on any later failure
    (reverse registration order), and must be idempotent."""

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        return None


class PermitPlugin(Plugin):
    """interface.go#PermitPlugin: approve / reject / delay binding.
    Returns (Status, timeout_seconds): SUCCESS approves, WAIT parks the
    pod in the WaitingPods map until every waiting plugin allows it or
    the timeout rejects it (runtime/waiting_pods_map.go)."""

    def permit(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> tuple[Status, float]:
        raise NotImplementedError


class PreBindPlugin(Plugin):
    """interface.go#PreBindPlugin: last gate before the bind API call
    (volumebinding's BindPodVolumes point); failure unreserves."""

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return Status.success()


class PostBindPlugin(Plugin):
    """interface.go#PostBindPlugin: informational, after a successful
    bind."""

    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        return None


class FilterPlugin(Plugin):
    def filter(
        self, state: CycleState, pod: Pod, node: Node,
        placed: tuple[Pod, ...] = (),
    ) -> Status:
        """interface.go#FilterPlugin.Filter. ``placed`` carries the node's
        resident pods (the NodeInfo view) for host-side runs; solver-path
        plugins should ignore it (class-vectorized folding evaluates
        against node state only)."""
        raise NotImplementedError

    def weight(self) -> int:  # parity with ScorePlugin for registries
        return 0


class ScorePlugin(Plugin):
    def score(self, state: CycleState, pod: Pod, node: Node) -> int:
        """interface.go#ScorePlugin.Score: 0..MAX_NODE_SCORE."""
        raise NotImplementedError

    def normalize_score(
        self, state: CycleState, pod: Pod, scores: Mapping[str, int]
    ) -> dict[str, int] | None:
        """Optional ScoreExtensions#NormalizeScore: node name -> score.
        Return None to keep raw scores."""
        return None

    def weight(self) -> int:
        return 1


@dataclass
class Registry:
    """plugins by extension point (runtime/registry.go shape)."""

    pre_enqueue: list[PreEnqueuePlugin] = field(default_factory=list)
    queue_sort: list[QueueSortPlugin] = field(default_factory=list)
    pre_filter: list[PreFilterPlugin] = field(default_factory=list)
    filter: list[FilterPlugin] = field(default_factory=list)
    post_filter: list[PostFilterPlugin] = field(default_factory=list)
    score: list[ScorePlugin] = field(default_factory=list)
    reserve: list[ReservePlugin] = field(default_factory=list)
    permit: list[PermitPlugin] = field(default_factory=list)
    pre_bind: list[PreBindPlugin] = field(default_factory=list)
    post_bind: list[PostBindPlugin] = field(default_factory=list)

    @staticmethod
    def classify(plugins) -> "Registry":
        """Sort a flat plugin sequence into extension-point lists by the
        protocols each implements (one object may serve several points,
        like upstream multi-point plugins)."""
        r = Registry()
        for p in plugins:
            if isinstance(p, PreEnqueuePlugin):
                r.pre_enqueue.append(p)
            if isinstance(p, QueueSortPlugin):
                r.queue_sort.append(p)
            if isinstance(p, PreFilterPlugin):
                r.pre_filter.append(p)
            if isinstance(p, FilterPlugin):
                r.filter.append(p)
            if isinstance(p, PostFilterPlugin):
                r.post_filter.append(p)
            if isinstance(p, ScorePlugin):
                r.score.append(p)
            if isinstance(p, ReservePlugin):
                r.reserve.append(p)
            if isinstance(p, PermitPlugin):
                r.permit.append(p)
            if isinstance(p, PreBindPlugin):
                r.pre_bind.append(p)
            if isinstance(p, PostBindPlugin):
                r.post_bind.append(p)
        if len(r.queue_sort) > 1:
            # profile.go: exactly one queue-sort plugin per profile
            raise ValueError(
                "at most one QueueSortPlugin may be registered; got "
                + ", ".join(p.name() for p in r.queue_sort)
            )
        return r
