"""Quantity parsing vs the reference's documented semantics
(apimachinery/pkg/api/resource/quantity.go)."""

import pytest
from fractions import Fraction

from kubernetes_tpu.api.quantity import (
    MAX_INT64,
    QuantityError,
    canonical,
    canonical_requests,
    format_canonical,
    parse_quantity,
    quantity_milli_value,
    quantity_value,
)


class TestParse:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("0", 0),
            ("1", 1),
            ("100m", Fraction(1, 10)),
            ("1500m", Fraction(3, 2)),
            ("1Ki", 1024),
            ("1Mi", 1024**2),
            ("1Gi", 1024**3),
            ("1Ti", 1024**4),
            ("1Pi", 1024**5),
            ("1Ei", 1024**6),
            ("1k", 1000),
            ("1M", 10**6),
            ("1G", 10**9),
            ("1T", 10**12),
            ("1P", 10**15),
            ("1E", 10**18),
            ("500M", 5 * 10**8),
            ("1e3", 1000),
            ("1E3", 1000),  # E as exponent when followed by digits
            ("1.5e2", 150),
            ("12e-3", Fraction(12, 1000)),
            ("0.5", Fraction(1, 2)),
            (".5", Fraction(1, 2)),
            ("2.", 2),
            ("+2", 2),
            ("-2", -2),
            ("100n", Fraction(1, 10**7)),
            ("100u", Fraction(1, 10**4)),
        ],
    )
    def test_values(self, s, expected):
        assert parse_quantity(s) == expected

    @pytest.mark.parametrize("s", ["", "abc", "1.2.3", "1Zi", "1kk", "--1", "1 Gi x"])
    def test_invalid(self, s):
        with pytest.raises(QuantityError):
            parse_quantity(s)


class TestCanonical:
    def test_cpu_milli(self):
        assert canonical("cpu", "100m") == 100
        assert canonical("cpu", "2") == 2000
        assert canonical("cpu", "1.5") == 1500
        # sub-milli rounds UP (quantity.go#MilliValue)
        assert canonical("cpu", "0.5m") == 1
        assert canonical("cpu", "100n") == 1

    def test_memory_bytes(self):
        assert canonical("memory", "1Gi") == 1024**3
        assert canonical("memory", "200M") == 200 * 10**6
        assert canonical("memory", "128974848") == 128974848
        # fractional bytes round UP (quantity.go#Value)
        assert canonical("memory", "1.5") == 2

    def test_pods_count(self):
        assert canonical("pods", "110") == 110

    def test_extended_resource(self):
        assert canonical("example.com/gpu", "4") == 4

    def test_saturation(self):
        assert canonical("memory", "100E") == MAX_INT64
        assert quantity_milli_value("10E") == MAX_INT64

    def test_requests_map(self):
        out = canonical_requests({"cpu": "250m", "memory": "64Mi"})
        assert out == {"cpu": 250, "memory": 64 * 1024**2}
        assert canonical_requests(None) == {}

    def test_format_round_trip(self):
        assert format_canonical("cpu", 250) == "250m"
        assert format_canonical("cpu", 2000) == "2"
        assert format_canonical("memory", 1024**3) == str(1024**3)
        assert canonical("cpu", format_canonical("cpu", 1234)) == 1234
        assert canonical("memory", format_canonical("memory", 999)) == 999


class TestHypothesis:
    def test_milli_value_ceiling_property(self):
        import pytest

        pytest.importorskip("hypothesis")
        from hypothesis import given, strategies as st

        @given(st.integers(min_value=0, max_value=10**12))
        def check(n):
            # n nano-cores -> milli is ceil(n/1e6)
            s = f"{n}n"
            expect = -(-n // 10**6)
            assert quantity_milli_value(s) == expect

        check()

    def test_value_vs_int_strings(self):
        import pytest

        pytest.importorskip("hypothesis")
        from hypothesis import given, strategies as st

        @given(st.integers(min_value=0, max_value=2**62))
        def check(n):
            assert quantity_value(str(n)) == n

        check()
