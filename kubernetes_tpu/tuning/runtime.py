"""TuningRuntime: the per-knob controllers wired to the scheduler.

One runtime per Scheduler (``SchedulerConfig.tuning``). Every applied
batch funnels through ``observe_batch`` (called from the scheduler's
metrics-recording chokepoint, which all four dispatch paths — sync,
pipelined, streaming, backlog drain — already share): it takes one
``CounterWindow`` sample, feeds the active controllers the throughput
objective, applies any accepted/reverted value, and journals the move.

Knobs and their application discipline:

- ``stream_depth`` — writes ``SchedulerConfig.stream_depth``; the
  streaming loop re-reads it ONLY at ring-drain boundaries (an
  in-flight ring keeps the depth it was dispatched under), so a depth
  change can never strand or orphan a dispatched slot.
- ``pipeline_split`` — the runtime owns the split value;
  ``Scheduler._choose_split`` consults it (and falls back to the
  window's EWMA rule when tuning is off — both read the SAME
  ``CounterWindow``, the satellite's anti-fighting contract).
- ``backlog_chunk`` — active only inside a ``drain_backlog`` pass;
  every candidate passes the HBM budget model
  (``solver/budget.estimate`` + the index-headroom audit) BEFORE it is
  applied, so a tuner-proposed chunk can never raise ``BudgetExceeded``
  from the dispatch path — that is the "guardrail breach" the metrics
  and the bench ladder pin at zero.
- ``fleet_flush`` — the write-behind flush batch of the fleet's remote
  occupancy exchange (``RemoteOccupancyExchange``); applied through
  ``FleetRuntime.set_flush_batch``, a no-op for in-process hubs.

Every adjustment is journaled three ways: the ``scheduler_tuning_*``
metric family (adjustments by knob+action, live knob values, settled
flags, guardrail rejections), a ``tuning`` obs span carrying
decision/trigger/old->new (so ``obs explain``-style attribution works
for knob moves too), and an in-memory decision history the sim footer
and the tuned-profile emitter read.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import metrics
from .controllers import Decision, HillClimber
from .window import CounterWindow

KNOB_STREAM_DEPTH = "stream_depth"
KNOB_SPLIT = "pipeline_split"
KNOB_CHUNK = "backlog_chunk"
KNOB_FLUSH = "fleet_flush"
ALL_KNOBS = (KNOB_CHUNK, KNOB_STREAM_DEPTH, KNOB_SPLIT, KNOB_FLUSH)


@dataclass(frozen=True)
class TuningConfig:
    """Knob-independent controller tuning. ``knobs`` lists what the
    runtime may touch — to pin a knob statically, set its config value
    and drop it from this tuple (README "Auto-tuning")."""

    # batches per evaluation window (scored as the window's ratio of
    # sums — pods over wall seconds, i.e. true window throughput)
    eval_batches: int = 6
    # a probe must beat the incumbent by this relative margin
    hysteresis: float = 0.05
    # consecutive both-directions-failed rounds before a knob settles
    settle_after: int = 2
    # probe budget per episode (construction/unsettle -> settle): the
    # hard termination bound a noisy objective cannot defeat
    max_probes: int = 16
    # relative change in the window's arrival-rate signature (pods per
    # wall-second — CounterWindow.rate; or an absolute change in the
    # hard-shape fraction above 0.35) that re-opens settled controllers
    shift_threshold: float = 0.75
    knobs: tuple = ALL_KNOBS
    # bounds per knob (lo, hi); chunk's upper bound additionally obeys
    # the HBM guardrail, and its lower bound the group alignment
    stream_depth_bounds: tuple = (1, 16)
    split_bounds: tuple = (1, 8)
    flush_bounds: tuple = (16, 4096)
    chunk_growth_cap: int = 16  # chunk hi = initial chunk * cap

    def validate(self) -> None:
        # the range checks live in ONE place — config/types.py's pure
        # validate_tuning_params — shared with the YAML loader so a
        # bound change cannot land in one and not the other
        from ..config.types import validate_tuning_params

        validate_tuning_params(
            self.eval_batches,
            self.hysteresis,
            self.settle_after,
            self.max_probes,
            self.shift_threshold,
            self.knobs,
        )


class TuningRuntime:
    def __init__(
        self, config: TuningConfig, window: CounterWindow, clock
    ) -> None:
        config.validate()
        self.config = config
        self.window = window
        self.clock = clock
        import logging

        self._log = logging.getLogger("kubernetes_tpu.tuning")
        self.controllers: dict[str, HillClimber] = {}
        self.decisions: list[Decision] = []
        # guardrail BREACHES: a tuner-applied value failing its guard at
        # apply time. Proposals are guarded BEFORE application, so this
        # stays 0 — the counter exists to prove it (the bench ladder and
        # the sim invariant both pin it).
        self.guardrail_breaches = 0
        self.shifts = 0
        # window.batches when every active controller first settled
        # (re-recorded after each unsettle; the bench ladder hoists the
        # first value as tuning_convergence_batches)
        self.convergence_batches: int | None = None
        # frozen: ticks become no-ops. The sim harness sets this at
        # quiescence — once churn stops, the draining tail is teardown,
        # not a workload, and letting shift detection fire on it would
        # unsettle controllers with no batches left to re-converge on.
        # Production never freezes (there is no "end of workload").
        self.frozen = False
        # the always-on controllers are attached on the first tick (the
        # scheduler's config is final by then); a flag, not a
        # controllers-empty check — the drain-chunk controller can
        # register FIRST via on_drain_start, and an emptiness check
        # would then silently skip the others forever
        self._attached = False
        self._settled_signature: tuple | None = None
        # while the signature window still contains samples from before
        # the settle point, keep refreshing the baseline instead of
        # comparing against it (the transition's own residual drift is
        # not a NEW shift) — frozen once the window has fully turned
        # over past this batch count
        self._signature_fresh_until = 0
        # consecutive over-threshold observations before a shift fires:
        # one window's rate can spike transiently (a burst of requeued
        # pods popping intra-cycle inflates pods/wall), but a real
        # regime change PERSISTS — requiring the signal on consecutive
        # ticks filters the burst without dulling genuine detection
        self._shift_streak = 0
        # window.batches at the most recent unsettle (0 = construction):
        # "still unsettled" is only a convergence FAILURE when the tuner
        # has since been given at least its structural settle bound of
        # batches — a shift detected near the end of a drive leaves it
        # legitimately mid-convergence (the sim invariant reads both)
        self._last_unsettle_batches = 0
        self._drain_budget_bytes = 0
        self._final_chunk: int | None = None
        # controllers retired from active duty (the drain-chunk climber
        # at drain end): their probe/move/guard counters must survive
        # into summary(), or a drain's guardrail activity vanishes from
        # the very report that pins it
        self._retired: list[HillClimber] = []

    # -- controller construction --

    def _add(self, climber: HillClimber) -> None:
        self.controllers[climber.knob] = climber
        metrics.tuning_knob_value.labels(climber.knob).set(climber.value)
        metrics.tuning_settled.labels(climber.knob).set(0)

    def attach(self, scheduler) -> None:
        """Build the always-on controllers from the scheduler's current
        config (the tuned arm starts exactly where the static arm is, so
        revert-on-regression makes 'tuned >= static' structural)."""
        c = self.config
        if KNOB_STREAM_DEPTH in c.knobs:
            lo, hi = c.stream_depth_bounds
            self._add(
                HillClimber(
                    KNOB_STREAM_DEPTH,
                    min(max(scheduler.config.stream_depth, lo), hi),
                    lo,
                    hi,
                    hysteresis=c.hysteresis,
                    settle_after=c.settle_after,
                    eval_batches=c.eval_batches,
                    max_probes=c.max_probes,
                )
            )
        if KNOB_SPLIT in c.knobs and scheduler.config.pipeline_split == 0:
            # a fixed config split (>= 1) is a static pin: adaptive and
            # tuned split both yield to it in _choose_split. Until the
            # controller's first probe, split_override() TRACKS the
            # adaptive window rule — the governed scheduler dispatches
            # exactly as the static arm would, so "tuned starts where
            # static is" holds for this knob too; the initial value
            # here is only the pre-first-batch placeholder.
            lo, hi = c.split_bounds
            self._add(
                HillClimber(
                    KNOB_SPLIT,
                    lo,
                    lo,
                    hi,
                    hysteresis=c.hysteresis,
                    settle_after=c.settle_after,
                    eval_batches=c.eval_batches,
                    max_probes=c.max_probes,
                )
            )
        if (
            KNOB_FLUSH in c.knobs
            and scheduler.fleet is not None
            and scheduler.fleet.flush_batch() is not None
        ):
            lo, hi = c.flush_bounds
            self._add(
                HillClimber(
                    KNOB_FLUSH,
                    min(max(scheduler.fleet.flush_batch(), lo), hi),
                    lo,
                    hi,
                    hysteresis=c.hysteresis,
                    settle_after=c.settle_after,
                    eval_batches=c.eval_batches,
                    max_probes=c.max_probes,
                )
            )

    # -- drain-chunk lifecycle (drain_backlog brackets a pass) --

    def on_drain_start(
        self, scheduler, chunk: int, budget_bytes: int
    ) -> None:
        """Arm the chunk controller for one backlog drain. The guard is
        the HBM budget model: a candidate chunk's per-device estimate
        (with the index-headroom audit) must fit ``budget_bytes`` or the
        candidate is never applied."""
        if KNOB_CHUNK not in self.config.knobs:
            return
        from ..solver import budget as hbm

        group = max(scheduler.solver.config.group_size, 1)
        self._drain_budget_bytes = budget_bytes

        def guard(candidate: int) -> bool:
            shape = scheduler.drain_shape(candidate)
            est = hbm.estimate(shape)
            ok = est.per_device_bytes <= budget_bytes
            if ok:
                try:
                    hbm.assert_index_headroom(
                        est.pod_pad, est.node_pad, d_pad=shape.d_pad,
                        group=group,
                    )
                except hbm.IndexWidthError:
                    ok = False
            if not ok:
                # BOTH rejection kinds (budget excess and index-width)
                # tick the counter, matching the climber's own
                # guard_rejections tally in the run summary
                metrics.tuning_guardrail_rejections_total.labels(
                    KNOB_CHUNK
                ).inc()
            return ok

        lo = min(group, chunk)
        hi = max(chunk * self.config.chunk_growth_cap, chunk)
        # group alignment keeps the grouped fast path's exact pod-axis
        # bucket — but only meaningful once the chunk spans whole
        # groups; below that every aligned candidate would snap to the
        # floor and the controller could never probe at all
        align = group if chunk >= group and chunk % group == 0 else 1
        self._add(
            HillClimber(
                KNOB_CHUNK,
                chunk,
                lo,
                hi,
                align=align,
                hysteresis=self.config.hysteresis,
                settle_after=self.config.settle_after,
                eval_batches=self.config.eval_batches,
                guard=guard,
                max_probes=self.config.max_probes,
            )
        )
        self._final_chunk = chunk

    def on_drain_end(self, scheduler) -> None:
        climber = self.controllers.pop(KNOB_CHUNK, None)
        if climber is not None:
            self._retired.append(climber)
            self._final_chunk = climber._incumbent
            metrics.tuning_knob_value.labels(KNOB_CHUNK).set(
                self._final_chunk
            )

    # -- the per-batch tick --

    def _active(self, scheduler, knob: str) -> bool:
        if knob == KNOB_CHUNK:
            return scheduler._backlog_drain_active
        if knob == KNOB_STREAM_DEPTH:
            return scheduler._streaming_active
        return True

    def observe_batch(
        self, scheduler, res, n_pods: int, occ_sensitive: bool = False
    ) -> None:
        """One applied batch: sample the window, drive the active
        controllers, apply + journal any decision. Driver thread only
        (the one thread every dispatch loop applies on)."""
        if self.frozen:
            return
        chained_total = float(
            sum(
                s.dispatch_counts.get("stream_chained", 0)
                for s in scheduler.solvers.values()
            )
        )
        sample = self.window.note_batch(
            pods=n_pods,
            solve_s=res.solve_seconds,
            chained_total=chained_total,
            occ_sensitive=occ_sensitive,
        )
        if not self._attached:
            self._attached = True
            self.attach(scheduler)
            # WARM batch: this first sample's wall delta spans from
            # scheduler construction — setup plus the first solve's
            # JIT compile — so its pods/wall score is garbage (a
            # deflated incumbent baseline would let the first probe
            # win unconditionally). The sample re-anchored the window
            # clock and counter baselines; feed no controller.
            return
        trigger = {
            "pods": n_pods,
            "unhidden_reads": sample.deltas.get("unhidden_reads", 0),
            "slot_discards": sample.deltas.get("slot_discards", 0),
            "chained": sample.chained,
            "h2d_bytes": int(sample.deltas.get("h2d_bytes", 0)),
            "cas_conflicts": sample.deltas.get("cas_conflicts", 0),
        }
        self._maybe_shift(scheduler, trigger)
        for knob, climber in list(self.controllers.items()):
            if not self._active(scheduler, knob):
                continue
            decision = climber.observe(
                n_pods, sample.wall_s, trigger
            )
            if decision is not None:
                self._apply(scheduler, climber, decision)
        if self.settled() and self._settled_signature is None:
            self._settled_signature = self.window.signature(
                self._signature_window()
            )
            self._signature_fresh_until = (
                self.window.batches + self._signature_window()
            )
            if self.convergence_batches is None:
                self.convergence_batches = self.window.batches

    def _signature_window(self) -> int:
        """Samples the workload fingerprint averages over: wider than
        one evaluation window so pop-boundary noise washes out, but
        short enough that a real regime change dominates it within a
        few cycles (a long window both lags detection and stretches the
        post-settle grace period during which shifts are absorbed as
        transition residue)."""
        return max(2 * self.config.eval_batches, 4)

    def _maybe_shift(self, scheduler, trigger: dict) -> None:
        """Workload-shift detection: when every controller is settled,
        a large move in the window signature re-opens tuning (the
        settled point was chosen for a workload that no longer
        exists)."""
        if self._settled_signature is None:
            return
        cur = self.window.signature(self._signature_window())
        if self.window.batches <= self._signature_fresh_until:
            # the window still spans the settle transition: its drift
            # is the old regime washing out, not a new shift — track it
            # as the baseline until the window has fully turned over
            self._settled_signature = cur
            return
        base_pods, base_hard = self._settled_signature
        cur_pods, cur_hard = cur
        rel = abs(cur_pods - base_pods) / max(base_pods, 1.0)
        if rel <= self.config.shift_threshold and abs(
            cur_hard - base_hard
        ) <= 0.35:
            self._shift_streak = 0
            return
        self._shift_streak += 1
        if self._shift_streak < 2:
            return  # a one-tick spike is a burst, not a regime
        self._shift_streak = 0
        self.shifts += 1
        self._settled_signature = None
        self._last_unsettle_batches = self.window.batches
        metrics.tuning_workload_shifts_total.inc()
        shift_trigger = dict(
            trigger,
            shift_rate=round(cur_pods, 3),
            settled_rate=round(base_pods, 3),
        )
        for climber in self.controllers.values():
            if climber.settled:
                d = climber.unsettle(shift_trigger)
                self._journal(scheduler, climber, d)
        self._log.info(
            "tuning: workload shift detected (rate %0.1f -> %0.1f "
            "pods/s); controllers re-opened",
            base_pods, cur_pods, extra={"step": scheduler._trace_step},
        )

    # -- application + journaling --

    def _apply(self, scheduler, climber: HillClimber, d: Decision) -> None:
        knob, value = climber.knob, climber.value
        if knob == KNOB_STREAM_DEPTH:
            # the streaming loop re-reads config.stream_depth ONLY at
            # ring-drain boundaries (run_streaming): an in-flight ring
            # keeps the depth it was dispatched under
            scheduler.config.stream_depth = value
        elif knob == KNOB_CHUNK:
            # apply-time guardrail re-check for NEWLY-proposed values
            # (probe transitions): the proposal already passed the
            # budget model in the same tick, so a failure here is a
            # genuine breach — counted, never applied. Accepts keep the
            # probe's value (live since the probe applied it) and
            # reverts/settles restore the incumbent the drain is
            # already running — re-checking either would count the
            # estimate's own mid-drain drift (vocab growth, queue
            # shape) as a breach of a shape that is live regardless.
            if (
                d.action == "probe"
                and climber.guard is not None
                and not climber.guard(value)
            ):
                self.guardrail_breaches += 1
                # the candidate was never applied: the climber must not
                # keep holding it (its next windows would score the
                # still-running incumbent under the candidate's name,
                # and an accept would install the rejected value past
                # the guard — review-caught)
                climber.abort_probe()
                return
            scheduler.config.batch_size = value
            self._final_chunk = value
        elif knob == KNOB_FLUSH:
            scheduler.fleet.set_flush_batch(value)
        # KNOB_SPLIT needs no push: _choose_split pulls split_override()
        self._journal(scheduler, climber, d)

    def _journal(self, scheduler, climber: HillClimber, d: Decision) -> None:
        metrics.tuning_adjustments_total.labels(d.knob, d.action).inc()
        metrics.tuning_knob_value.labels(d.knob).set(climber.value)
        metrics.tuning_settled.labels(d.knob).set(
            1 if climber.settled else 0
        )
        self.decisions.append(d)
        with scheduler.obs.span(
            "tuning",
            trace_id=scheduler._trace_step,
            knob=d.knob,
            action=d.action,
            old=d.old,
            new=d.new,
            objective=round(d.objective, 6),
            baseline=round(d.baseline, 6),
            **{
                k: v
                for k, v in d.trigger.items()
                if k in ("pods", "unhidden_reads", "slot_discards")
            },
        ):
            pass
        if d.action in ("accept", "settle", "unsettle"):
            self._log.info(
                "tuning: %s %s %d -> %d (objective %0.3f vs baseline "
                "%0.3f)",
                d.knob, d.action, d.old, d.new, d.objective, d.baseline,
                extra={"step": scheduler._trace_step},
            )

    # -- the scheduler-facing knob reads --

    def split_override(self, n_pods: int = 0) -> int | None:
        """The split controller's current value, or None when the knob
        is not governed (the adaptive window rule applies then).
        Until the controller's FIRST probe, it TRACKS the adaptive
        rule's pick for this batch — the governed scheduler dispatches
        exactly as the static arm would, and the baseline the climb
        later compares against was measured at that same value (the
        "tuned starts where static is" guarantee, review-caught: a
        floor-seeded controller silently overrode a warmed adaptive
        rule on high-RTT workloads)."""
        climber = self.controllers.get(KNOB_SPLIT)
        if climber is None:
            return None
        from .controllers import _MEASURE

        if (
            climber.probes == 0
            and not climber.settled
            and climber._phase == _MEASURE
            and n_pods > 0
        ):
            est = min(
                max(
                    self.window.split_estimate(n_pods, climber.hi),
                    climber.lo,
                ),
                climber.hi,
            )
            climber.value = est
            climber._incumbent = est
            return est
        return climber.value

    # -- reporting --

    def knob_values(self) -> dict:
        out = {
            knob: climber.value
            for knob, climber in sorted(self.controllers.items())
        }
        if self._final_chunk is not None and KNOB_CHUNK not in out:
            out[KNOB_CHUNK] = self._final_chunk
        return out

    def settled(self) -> bool:
        """Every controller that ever RAN has settled. Never-ticked
        controllers (a knob whose dispatch mode never engaged — e.g.
        stream_depth on a pipelined drive) are excluded: they were
        never given a batch to evaluate, which is idleness, not a
        convergence failure."""
        engaged = [
            c for c in self.controllers.values() if c.ticks > 0
        ]
        return bool(engaged) and all(c.settled for c in engaged)

    def summary(self) -> dict:
        """Deterministic run summary (the sim footer / bench row): all
        python-side counters, so same-seed sim runs stay
        byte-identical. Retired climbers (a finished drain's chunk
        controller) keep contributing their counters; ``settled``
        reflects the ACTIVE controllers only."""
        climbers = list(self.controllers.values()) + self._retired
        return {
            "adjustments": sum(len(c.history) for c in climbers),
            "probes": sum(c.probes for c in climbers),
            "moves": sum(c.moves for c in climbers),
            "max_knob_moves": max(
                (c.moves for c in climbers), default=0
            ),
            "guardrail_rejections": sum(
                c.guard_rejections for c in climbers
            ),
            "guardrail_breaches": self.guardrail_breaches,
            "shifts": self.shifts,
            "settled": 1 if self.settled() else 0,
            "convergence_batches": self.convergence_batches,
            # convergence-opportunity accounting: how many batches the
            # tuner has seen since its last unsettle, vs the structural
            # bound an episode needs (probe budget x windows + slack) —
            # "unsettled" is only a failure when opportunity >= bound
            "batches_since_unsettle": (
                self.window.batches - self._last_unsettle_batches
            ),
            "settle_bound": self.config.eval_batches
            * (2 * self.config.max_probes + 4),
            "knobs": self.knob_values(),
        }
