"""Nominated-pod parity for the spread/interpod tensorizers
(RunFilterPluginsWithNominatedPods): an unbound pod whose
``status.nominatedNodeName`` resolved to a live slot must fold into
the occupancy state EXACTLY like a placed pod at that slot — and a
batch pod must never count its OWN nomination as a standing peer
(the scheduler's nom_peers self-exclusion)."""

import numpy as np

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
from kubernetes_tpu.tensorize.plugins import build_static_tensors
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)
from kubernetes_tpu.tensorize.spread import build_spread_tensors


def _zone_nodes(n=4, zones=2):
    return [
        MakeNode()
        .name(f"node-{i:03}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "50"})
        .label("zone", f"z{i % zones}")
        .label("kubernetes.io/hostname", f"node-{i:03}")
        .obj()
        for i in range(n)
    ]


def _spread_pod(name):
    return (
        MakePod()
        .name(name)
        .label("app", "web")
        .req({"cpu": "100m"})
        .spread_constraint(1, "zone", "DoNotSchedule", match_labels={"app": "web"})
        .obj()
    )


def _build(builder, nodes, pods, peer, slot, as_nominated):
    vocab = ResourceVocab.build(pods + [peer], nodes)
    nbatch = build_node_batch(nodes, {}, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    placed_by_slot = {} if as_nominated else {slot: [peer]}
    nominated = [(peer, slot)] if as_nominated else []
    return builder(
        pods, static.reps, pbatch, slot_nodes,
        placed_by_slot, nbatch.padded, static.c_pad,
        nominated=nominated,
    )


def test_spread_counts_nominated_peer_like_placed():
    nodes = _zone_nodes()
    pods = [_spread_pod("p0")]
    peer = _spread_pod("peer")
    placed = _build(build_spread_tensors, nodes, pods, peer, 0, False)
    nom = _build(build_spread_tensors, nodes, pods, peer, 0, True)
    assert np.array_equal(placed.cnt0, nom.cnt0)
    assert placed.cnt0[:, 0].max() == 1  # the peer actually counted


def test_spread_ignores_nominated_peer_at_dead_slot():
    nodes = _zone_nodes()
    pods = [_spread_pod("p0")]
    peer = _spread_pod("peer")
    nom = _build(build_spread_tensors, nodes, pods, peer, 999, True)
    assert nom.cnt0.max() == 0


def _anti_pod(name):
    return (
        MakePod()
        .name(name)
        .label("app", "anti")
        .req({"cpu": "100m"})
        .pod_anti_affinity("kubernetes.io/hostname", {"app": "anti"})
        .obj()
    )


def test_interpod_counts_nominated_peer_like_placed():
    nodes = _zone_nodes()
    pods = [_anti_pod("p0")]
    peer = _anti_pod("peer")
    placed = _build(build_interpod_tensors, nodes, pods, peer, 1, False)
    nom = _build(build_interpod_tensors, nodes, pods, peer, 1, True)
    # the nominated peer feeds both directions exactly like a placed
    # one: the incoming count state AND the existing-side term owners
    assert np.array_equal(placed.in_cnt0, nom.in_cnt0)
    assert np.array_equal(placed.ex_cnt0, nom.ex_cnt0)
    assert placed.in_cnt0[:, 1].max() == 1


def test_batch_pod_does_not_see_its_own_nomination():
    """A hard-anti pod nominated to a node is itself IN the batch: if
    its nomination counted as a standing peer it would anti-affine
    against itself and park forever. The scheduler's nom_peers
    filtering must let it land on its nominated node."""
    cs = ClusterState()
    for n in _zone_nodes(2):
        cs.create_node(n)
    pod = (
        MakePod()
        .name("self")
        .label("app", "anti")
        .req({"cpu": "100m"})
        .pod_anti_affinity("kubernetes.io/hostname", {"app": "anti"})
        .nominated_node_name("node-000")
        .obj()
    )
    cs.create_pod(pod)
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=16, solver=ExactSolverConfig(tie_break="first")
        ),
    )
    sched.run_until_settled()
    assert cs.get_pod("default", "self").node_name == "node-000"


def test_nominated_peer_blocks_spread_slot_like_placed_peer():
    """End to end: an unbound nominated spread peer must steer a
    same-cohort batch pod away from its zone exactly as a bound peer
    would (host-side fold, device-side filter)."""
    cs = ClusterState()
    for n in _zone_nodes(2, zones=2):  # node-000 -> z0, node-001 -> z1
        cs.create_node(n)
    # the nominated peer occupies z0 without being bound: a FOREIGN
    # scheduler's pod, so it is pure nomination state here — never
    # popped into our batch, never bound by us
    cs.create_pod(
        MakePod()
        .name("peer")
        .label("app", "web")
        .req({"cpu": "100m"})
        .priority(10)
        .scheduler_name("other-scheduler")
        .nominated_node_name("node-000")
        .obj()
    )
    cs.create_pod(_spread_pod("mover"))
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=16, solver=ExactSolverConfig(tie_break="first")
        ),
    )
    sched.schedule_batch()
    mover = cs.get_pod("default", "mover")
    # z0 holds the nominated peer (count 1), z1 empty: maxSkew=1 lets
    # either zone pass, but the spread SCORE prefers the empty domain
    assert mover.node_name == "node-001"
