#!/usr/bin/env bash
# CI gate: the correctness/perf layers in order of cost —
#   1. static analysis (full Analyzer v2: per-module TPU001..MET001 plus
#      the project rules LOCK002/FENCE001/RETRY001/TPU004/MET002, the
#      suppression-debt ratchet, and the lock-order artifact drift
#      check; findings uploaded as SARIF + JSON artifacts; budgeted at
#      < 10 s wall so the gate stays instant)
#   2. tier-1 tests   (ROADMAP.md invocation, minus the soak marker)
#   3. sim smokes     (one fixed-seed run per scenario profile, plus a
#      determinism self-check on the flagship churn profile)
#   4. obs smoke      (journaled fixed-seed sim -> JSONL schema check ->
#      explain one pod from the recorded trace)
#
# Usage: scripts/ci.sh            # everything
#        SKIP_TESTS=1 scripts/ci.sh   # lint + sim only (fast local loop)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint: static analyzer (project rules + ratchet + lock-order) =="
# one invocation does everything: findings as JSON (stdout -> artifact),
# SARIF artifact, suppression-debt ratchet, lock-order drift check.
# Wall-time budget: the analyzer must stay under 10 s or it stops being
# the gate everyone runs first.
mkdir -p artifacts
SECONDS=0
python scripts/lint.py --json --sarif artifacts/analysis.sarif \
    --ratchet --check-lock-order > artifacts/analysis.json
lint_elapsed=$SECONDS
echo "-- analyzer wall time: ${lint_elapsed}s (budget 10s) --"
if [ "$lint_elapsed" -ge 10 ]; then
    echo "LINT BUDGET: analyzer took ${lint_elapsed}s (>= 10s)"
    exit 1
fi

if [ -z "${SKIP_TESTS:-}" ]; then
    echo "== tier-1 tests =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly
fi

echo "== sim smokes (fixed seed, every profile) =="
for profile in churn_heavy bind_storms node_flaps preemption_pressure \
               extender_flaky permit_stalls; do
    echo "-- $profile --"
    python -m kubernetes_tpu.sim --seed 0 --cycles 6 --profile "$profile"
done

echo "== sim determinism self-check =="
python -m kubernetes_tpu.sim --seed 0 --cycles 6 --profile churn_heavy \
    --selfcheck

echo "== pipelined hard-shape sim smoke =="
# churn_heavy now generates spread/anti/ports arrivals, so this fixed-seed
# run drives the occupancy-carrying pipelined path (hard shapes no longer
# drain to the synchronous loop) under delete/label churn; --selfcheck
# re-runs it and asserts byte-identical traces + journal digest. The
# preemption_pressure run covers the pipelined loop under PostFilter/
# nominated-pod traffic the same way.
python -m kubernetes_tpu.sim --seed 1 --cycles 8 --profile churn_heavy \
    --selfcheck
python -m kubernetes_tpu.sim --seed 1 --cycles 8 \
    --profile preemption_pressure --selfcheck

echo "== streaming dispatcher smoke =="
# sustained_stream: the high-arrival profile driving run_streaming —
# the device-resident solve loop with cross-batch occupancy chaining,
# per-slot fence epochs, and the completion thread; --selfcheck proves
# the whole loop byte-deterministic (the completion thread only warms
# transfers). churn_heavy re-driven through --dispatcher streaming
# covers slot discards + the livelock backstop under delete/label
# churn, and its trace digest is byte-compared at --mesh-devices 8 vs
# 1 (the PR 5 device-count-invariance convention, now through the
# chained stream dispatch). Greps pin the discard machinery within
# bounds: sustained_stream must never engage the livelock backstop
# (fallbacks=0 — the backstop is a last resort, not the steady state),
# and the churn run must actually exercise per-slot discards
# (stream_discards >= 1) while staying fallback-bounded (single
# digit). solver_flaky / crash_restart / fleet_mixed re-drive through
# the streaming dispatcher so degraded mode, restart recovery, and the
# fleet tier are proven to survive the refactor.
stream_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 8 \
    --profile sustained_stream --selfcheck)
echo "$stream_out"
echo "$stream_out" | grep -qE "fallbacks=0 " \
    || { echo "STREAM SMOKE: sustained_stream engaged the livelock backstop"; exit 1; }
churn_stream=$(python -m kubernetes_tpu.sim --seed 1 --cycles 8 \
    --profile churn_heavy --dispatcher streaming --selfcheck)
echo "$churn_stream"
echo "$churn_stream" | grep -qE "stream_discards=[1-9][0-9]* " \
    || { echo "STREAM SMOKE: churn never discarded a stream slot (vacuous fences)"; exit 1; }
echo "$churn_stream" | grep -qE "fallbacks=[0-9] " \
    || { echo "STREAM SMOKE: churn backstop out of bounds"; exit 1; }
stream_mesh_digest=$(python -m kubernetes_tpu.sim --seed 0 --cycles 6 \
    --profile sustained_stream --mesh-devices 8 | grep -o 'trace_digest=[0-9a-f]*')
stream_one_digest=$(python -m kubernetes_tpu.sim --seed 0 --cycles 6 \
    --profile sustained_stream | grep -o 'trace_digest=[0-9a-f]*')
if [ "$stream_mesh_digest" != "$stream_one_digest" ] || [ -z "$stream_mesh_digest" ]; then
    echo "STREAM MULTICHIP DIVERGENCE: mesh=$stream_mesh_digest vs 1-device=$stream_one_digest"
    exit 1
fi
echo "-- streaming mesh-vs-1-device trace digests identical: $stream_mesh_digest --"
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile solver_flaky \
    --dispatcher streaming --selfcheck
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile crash_restart \
    --dispatcher streaming --selfcheck
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile fleet_mixed \
    --fleet 2 --dispatcher streaming --selfcheck

echo "== backlog drain smoke: HBM-budget-planned chunked streaming =="
# backlog_drain: a seeded mega-backlog (sim-relative) drained at cycle 0
# through Scheduler.drain_backlog — chunk size planned by the HBM budget
# model (solver/budget.py), chunks streamed down the ring with cross-
# batch occupancy chaining, then delete churn + fresh arrivals. The
# profile forces the budget planner to auto-split (budget one byte
# below the base chunk's own estimate), so the grep pins the split
# path engaging non-vacuously (budget_splits >= 1); the drain must
# never trip the livelock backstop (fallbacks=0). --selfcheck proves
# the whole budget-plan -> chunk -> chain pipeline byte-deterministic.
backlog_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 4 \
    --profile backlog_drain --selfcheck)
echo "$backlog_out"
echo "$backlog_out" | grep -qE "budget_splits=[1-9]" \
    || { echo "BACKLOG SMOKE: the budget auto-split never engaged"; exit 1; }
echo "$backlog_out" | grep -qE "fallbacks=0 " \
    || { echo "BACKLOG SMOKE: the drain engaged the livelock backstop"; exit 1; }
echo "$backlog_out" | grep -qE "stream_chained=[0-9]+" \
    || { echo "BACKLOG SMOKE: no chain accounting in the footer"; exit 1; }

echo "== megaplan smoke: convex-relaxation warm-started drain =="
# megaplan: the backlog drain warm-starts — one relaxed global solve
# (solver/relax.py: dual ascent + deterministic rounding) ranks the
# whole active queue before the first chunk pops — and the harness's
# probe replays the relax+repair plan against the sequential oracle.
# check_megaplan asserts engagement, feasibility, and the objective-
# ratio floor; the greps pin each leg non-vacuously off the footer so
# a silently-disconnected warm-start (ranked=0) or a never-iterating
# relaxation cannot pass. --selfcheck proves the probe + warm-start +
# drain pipeline byte-deterministic (counts and rounded ratios only
# ride the footer).
mega_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 4 \
    --profile megaplan --selfcheck)
echo "$mega_out"
echo "$mega_out" | grep -qE "megaplan: pods=[1-9].* ranked=[1-9]" \
    || { echo "MEGAPLAN SMOKE: warm-start ranked no backlog pods"; exit 1; }
echo "$mega_out" | grep -qE "megaplan: .*iterations=[1-9]" \
    || { echo "MEGAPLAN SMOKE: the relaxation never iterated"; exit 1; }
echo "$mega_out" | grep -qE "megaplan: .*plan_valid=True" \
    || { echo "MEGAPLAN SMOKE: relaxed plan failed oracle feasibility"; exit 1; }

echo "== tuning smoke: closed-loop auto-tuning convergence =="
# tuning_convergence: the hill-climb controllers (stream_depth /
# pipeline_split, sim-sized evaluation windows) must probe both
# directions, settle, detect the mid-drive workload shift (arrivals
# roughly double at cycle 12), and re-settle — all under the tuning
# invariant (engaged / settled / zero guardrail breaches / bounded
# moves / shift detected). The greps pin settled=1 and
# guardrail_breaches=0 non-vacuously; --selfcheck proves the whole
# controller stack byte-deterministic (pure host python over the
# virtual clock). The backlog_drain --tuning run exercises the
# drain-chunk controller under the HBM budget guardrail.
tune_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 24 \
    --profile tuning_convergence --selfcheck)
echo "$tune_out"
echo "$tune_out" | grep -qE "settled=1 " \
    || { echo "TUNING SMOKE: controllers never settled"; exit 1; }
echo "$tune_out" | grep -qE "guardrail_breaches=0 " \
    || { echo "TUNING SMOKE: a tuner-applied value breached its guardrail"; exit 1; }
echo "$tune_out" | grep -qE "shifts=[1-9]" \
    || { echo "TUNING SMOKE: the workload shift was never detected"; exit 1; }
python -m kubernetes_tpu.sim --seed 0 --cycles 16 --profile backlog_drain \
    --tuning --selfcheck

echo "== chaos smoke: solver fallback ladder + poison quarantine =="
# solver_flaky: every device-tier solve fails during the fault window
# (virtual t in [2,5)), then heals. The run's resilience invariant
# asserts the fallback ladder engaged (breaker tripped, batches kept
# binding at degraded tiers down to the pure-host greedy), zero pods
# were lost (lost-pod + journal-completeness invariants), and the
# breaker RE-CLOSED to the top tier after the window — the footer's
# breaker-state summary is the assertion target. poison_pods: a
# fraction of arrivals deterministically break the solve at EVERY
# tier; the bisection must isolate exactly them into terminal
# quarantine while the rest of each batch proceeds. --selfcheck
# re-runs each drive and byte-compares traces + journal digest.
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile solver_flaky \
    --selfcheck
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile poison_pods \
    --selfcheck

echo "== crash smoke: restart recovery + partition-safe fencing =="
# crash_restart: the scheduler is killed mid-batch (pods assumed +
# approved, nothing bound) and a fresh incarnation recovers on the
# same ClusterState. The run's invariants assert zero lost pods
# (bounded recovery runs the lost-pod check the moment the new
# incarnation constructs), cross-incarnation journal completeness
# (terminal `recovered` records close the dead incarnation's dangling
# histories), and zero double-binds; --selfcheck proves the whole
# crash/restart boundary byte-deterministic. The greps pin the faults
# actually engaging — a run that never crashed or never recovered
# would pass the invariants vacuously.
crash_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 8 \
    --profile crash_restart --selfcheck)
echo "$crash_out"
echo "$crash_out" | grep -q "incarnations=2 crashes=1" \
    || { echo "CRASH SMOKE: the mid-batch kill never fired"; exit 1; }
echo "$crash_out" | grep -qE "recovered_records=[1-9]" \
    || { echo "CRASH SMOKE: recovery journaled no recovered records"; exit 1; }
# hub_partition: the last replica is partitioned from the occupancy
# hub with its lease observed stale — survivors revoke its commit
# fence and 100% of the zombie's bind attempts must reject with
# Conflict (the all-zombie-commits-fenced invariant), while
# conservative admission under aged-out rows rejects cross-shard-risky
# placements instead of risking overcommit. The grep pins >= 1 fenced
# zombie commit (and zero landed).
part_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 8 \
    --profile hub_partition --fleet 2 --selfcheck)
echo "$part_out"
echo "$part_out" | grep -qE "fenced_commits=[1-9][0-9]* zombie_binds_while_fenced=0" \
    || { echo "CRASH SMOKE: no fenced zombie commit (or one landed)"; exit 1; }

echo "== rebalance smoke: fragmentation profile =="
# fragmentation: heavy plain arrivals + heavy deletes carve the cluster
# into a sparse scatter; the idle-cycle rebalancer must detect it, plan
# through the pack-objective auction, and migrate pods through the REAL
# evict -> requeue -> re-bind path under the churn budget and the PDB
# gate. The run's rebalance invariant asserts budget-never-exceeded,
# zero PDB overruns, and packing-non-regressing across passes;
# --selfcheck proves the whole loop byte-deterministic. The greps pin
# the loop actually engaging — a run with no migrations would pass the
# invariants vacuously.
reb_out=$(python -m kubernetes_tpu.sim --seed 1234 --profile fragmentation \
    --selfcheck)
echo "$reb_out"
echo "$reb_out" | grep -qE "migrations_completed=[1-9]" \
    || { echo "REBALANCE SMOKE: no completed migration"; exit 1; }
echo "$reb_out" | grep -qE "over_budget=0" \
    || { echo "REBALANCE SMOKE: a cycle exceeded the churn budget"; exit 1; }
echo "$reb_out" | grep -qE "pdb_overruns=0" \
    || { echo "REBALANCE SMOKE: an eviction violated a PDB"; exit 1; }

echo "== gang smoke: all-or-nothing pod groups + heterogeneity =="
# the gang profile mixes pod-group arrivals (sizes 2-3, heterogeneous
# accelerator/workload classes feeding the effective-throughput
# objective) with one deliberately SHORT gang (min-member one above
# what ever arrives) under delete churn. The run's invariant layer
# asserts no pod group is EVER partially bound (check_no_partial_gangs
# after every drive) plus journal completeness through the
# gang_incomplete/quarantined outcomes; the greps pin the machinery
# engaging non-vacuously — >= 1 atomic gang commit, zero partial
# gangs at finish, and the short gang quarantined as a unit.
# --selfcheck proves the whole gate/round/commit pipeline
# byte-deterministic. gang_crash kills the scheduler at the exact
# assumed+staged-but-uncommitted window (crash between stage and
# commit): the fresh incarnation's rollback must reassemble
# half-staged gangs with zero partial binds. gang_replica_loss drives
# the same arrivals through a 2-replica fleet (every member stages
# through the fenced hub CAS) and kills one replica mid-drive — the
# survivor re-owns the shard with the partial-gang invariant still
# fleet-wide.
gang_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 12 \
    --profile gang --selfcheck)
echo "$gang_out"
echo "$gang_out" | grep -qE "gang: commits=[1-9]" \
    || { echo "GANG SMOKE: no atomic gang commit ever landed"; exit 1; }
echo "$gang_out" | grep -qE "partial_gangs=0 " \
    || { echo "GANG SMOKE: a pod group was partially bound"; exit 1; }
echo "$gang_out" | grep -qE "quarantined_gangs=[1-9]" \
    || { echo "GANG SMOKE: the short gang was never quarantined"; exit 1; }
python -m kubernetes_tpu.sim --seed 0 --cycles 12 --profile gang_crash \
    --selfcheck
gang_fleet=$(python -m kubernetes_tpu.sim --seed 0 --cycles 12 \
    --profile gang_replica_loss --fleet 2 --selfcheck)
echo "$gang_fleet"
echo "$gang_fleet" | grep -qE "partial_gangs=0 " \
    || { echo "GANG SMOKE: fleet replica loss left a partial gang"; exit 1; }

echo "== telemetry smoke: anomaly storm -> capture -> offline replay =="
# anomaly_storm: healthy warmup cycles, then a solver-fault window
# trips the breaker and collapses pods/s — the sentinel must fire
# (edge + regression rules), every fire must capture a replay bundle,
# and each carry-clean bundle must re-execute offline to BIT-IDENTICAL
# assignments (the run's telemetry invariant loads + replays every
# written bundle). --selfcheck re-runs WITHOUT the bundle dir and
# byte-compares summaries: capture EVENTS are part of the
# deterministic record, bundle writing is a pure side effect. The
# greps pin the loop engaging non-vacuously off the footer line; the
# explicit `obs replay` exercises the operator CLI end-to-end.
tele_dir=$(mktemp -d)
tele_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 12 \
    --profile anomaly_storm --bundle-dir "$tele_dir" --selfcheck)
echo "$tele_out"
echo "$tele_out" | grep -qE "telemetry: anomalies=[1-9]" \
    || { echo "TELEMETRY SMOKE: the sentinel never fired"; exit 1; }
echo "$tele_out" | grep -qE "bundles_captured=[1-9]" \
    || { echo "TELEMETRY SMOKE: no anomaly captured a bundle"; exit 1; }
tele_bundle=$(ls -d "$tele_dir"/bundle-* | head -1)
replay_out=$(python -m kubernetes_tpu.obs replay "$tele_bundle")
echo "$replay_out"
echo "$replay_out" | grep -q "assignments bit-identical" \
    || { echo "TELEMETRY SMOKE: offline replay diverged"; exit 1; }
rm -rf "$tele_dir"

echo "== fleet smoke: 2-replica sharded drive =="
# two active replicas sharding one cluster (shard-filtered watches,
# cross-shard occupancy exchange, handoff protocol) under the
# fleet_mixed hard-shape churn, with the no-global-overcommit and
# fleet journal-completeness invariants enabled; --selfcheck re-runs
# the drive and byte-compares the per-replica journal digests. The
# replica_loss run kills one replica mid-drive and requires its shard
# re-owned with every orphaned pod reaching a terminal outcome.
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile fleet_mixed \
    --fleet 2 --selfcheck
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile replica_loss \
    --fleet 2

echo "== fleet drain smoke: hub-coordinated backlog drain (ISSUE 20) =="
# fleet_backlog_drain: a seeded backlog partitioned by the coordinator's
# global relax plan into per-replica drain leases (hub ledger), drained
# by a 3-replica fleet with ONE replica killed mid-drain at cycle 1 —
# its outstanding lease must RETURN to the ledger (retire runs
# return_leases) and be re-claimed by a survivor, so no backlog pod
# drains twice and none is lost. The greps pin the fault engaging
# non-vacuously off the `fleet_drain:` footer line (the header's
# lost= field is the killed REPLICA, so every grep anchors on the
# footer key): >= 1 lease reassigned, zero pods lost fleet-wide, zero
# double-binds. --selfcheck proves the whole coordinator -> lease ->
# drain -> kill -> reassign pipeline byte-deterministic.
fdrain_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 12 \
    --profile fleet_backlog_drain --fleet 3 --selfcheck)
echo "$fdrain_out"
echo "$fdrain_out" | grep -qE "fleet_drain:.* leases_reassigned=[1-9]" \
    || { echo "FLEET DRAIN SMOKE: the mid-drain kill never returned a lease"; exit 1; }
echo "$fdrain_out" | grep -qE "fleet_drain:.* lost=0" \
    || { echo "FLEET DRAIN SMOKE: a backlog pod was lost fleet-wide"; exit 1; }
echo "$fdrain_out" | grep -qE "fleet_drain:.* double_bind=0" \
    || { echo "FLEET DRAIN SMOKE: a pod drained through two leases"; exit 1; }
echo "$fdrain_out" | grep -qE "fleet_drain:.* residual=[1-9]" \
    || { echo "FLEET DRAIN SMOKE: the serialized residual cohort never engaged"; exit 1; }

echo "== fleet smoke: gRPC-backed occupancy hub =="
# the same fault profiles re-driven with the hub served behind a
# localhost bulk gRPC server (--hub-grpc): every stage / fenced
# compare-and-stage / view crosses a real socket with the tensorcodec
# wire framing and the typed status-code conflict mapping
# (ABORTED/FAILED_PRECONDITION never retried). replica_loss proves
# shard re-owning + orphan adoption survive the wire; hub_partition
# re-pins the PR 8 contract over it — 100% of the fenced zombie's
# commits reject (zombie_binds_while_fenced=0) AND conservative
# admission under aged-out rows engages (stale_rejections >= 1).
# --selfcheck byte-compares per-replica journals across two runs (RPC
# wall time never enters the virtual clock; the write-behind row
# buffer re-times hub version bumps vs the in-process drive, so the
# cross-transport contract is invariants, not byte equality).
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile replica_loss \
    --fleet 2 --hub-grpc --selfcheck
part_grpc=$(python -m kubernetes_tpu.sim --seed 0 --cycles 8 \
    --profile hub_partition --fleet 2 --hub-grpc --selfcheck)
echo "$part_grpc"
echo "$part_grpc" | grep -qE "fenced_commits=[1-9][0-9]* zombie_binds_while_fenced=0" \
    || { echo "GRPC HUB SMOKE: no fenced zombie commit (or one landed)"; exit 1; }
echo "$part_grpc" | grep -qE "stale_rejections=[1-9]" \
    || { echo "GRPC HUB SMOKE: conservative admission never engaged"; exit 1; }

echo "== hub HA smoke: epoch-fenced failover chaos (ISSUE 15) =="
# the hub_failover profile kills the PRIMARY occupancy hub mid-drive:
# a standby replicated from the primary's op log must promote at the
# next lease epoch WITHOUT operator action, replicas must fail over
# (endpoint rotation + epoch-advance detection + forced wholesale
# republish), conservative admission must cover the blackout, and the
# resurrected OLD primary must keep serving reads while 100% of its
# replica-facing writes reject with the typed HubDeposed. A
# deterministic reply-loss-after-apply injection proves the idempotent
# flush dedup inside the chaos loop (the write-behind double-apply
# hazard's regression). Greps pin each fault engaging non-vacuously:
# failovers==1, stale-primary writes rejected >= 1, dedup hits >= 1,
# zero journal lines lost; zero lost rows/handoffs ride the run's own
# overcommit/lost-pod/journal invariants. Driven over the REAL gRPC
# hub pair; --selfcheck proves byte-determinism across runs.
ha_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 12 \
    --profile hub_failover --fleet 2 --hub-grpc --selfcheck)
echo "$ha_out"
echo "$ha_out" | grep -qE "hub_ha: failovers=1 epoch=2" \
    || { echo "HUB HA SMOKE: expected exactly one failover to epoch 2"; exit 1; }
echo "$ha_out" | grep -qE "stale_writes_rejected=[1-9]" \
    || { echo "HUB HA SMOKE: the deposed primary never rejected a write"; exit 1; }
echo "$ha_out" | grep -qE "dedup_hits=[1-9]" \
    || { echo "HUB HA SMOKE: the idempotent flush dedup never engaged"; exit 1; }
echo "$ha_out" | grep -qE "journal_missing=0" \
    || { echo "HUB HA SMOKE: the failover lost hub journal lines"; exit 1; }
echo "$ha_out" | grep -qE "stale_rejections=[1-9]" \
    || { echo "HUB HA SMOKE: conservative admission never covered the blackout"; exit 1; }

echo "== multichip: 8-device forced-host mesh smoke =="
# sharded-vs-unsharded exact-path equivalence on an 8-way virtual CPU
# mesh (conftest.py forces the device count before jax initializes):
# ExactSolver.solve(mesh=...) standalone + the full Scheduler session
# path must be bit-identical to the single-device solve, and padding
# rows must never take a binding.
XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
    python -m pytest tests/test_sharding.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
# one fixed-seed sim drive against the sharded solve, its trace digest
# byte-compared against the single-device run with identical flags —
# the device-count-invariance contract end to end through the sim
mesh_out=$(python -m kubernetes_tpu.sim --seed 0 --cycles 6 \
    --profile churn_heavy --mesh-devices 8)
echo "$mesh_out"
mesh_digest=$(echo "$mesh_out" | grep -o 'trace_digest=[0-9a-f]*')
one_digest=$(python -m kubernetes_tpu.sim --seed 0 --cycles 6 \
    --profile churn_heavy | grep -o 'trace_digest=[0-9a-f]*')
if [ "$mesh_digest" != "$one_digest" ] || [ -z "$mesh_digest" ]; then
    echo "MULTICHIP DIVERGENCE: mesh=$mesh_digest vs 1-device=$one_digest"
    exit 1
fi
echo "-- mesh-vs-1-device trace digests identical: $mesh_digest --"

echo "== obs smoke: journaled sim -> schema check -> explain =="
obs_journal=$(mktemp /tmp/ktpu_obs_journal.XXXXXX.jsonl)
python -m kubernetes_tpu.sim --seed 0 --cycles 6 --profile churn_heavy \
    --journal "$obs_journal"
python -m kubernetes_tpu.obs validate "$obs_journal"
obs_pod=$(python -c "import json,sys; print(json.loads(open(sys.argv[1]).readline())['pod'])" "$obs_journal")
python -m kubernetes_tpu.obs explain "$obs_pod" --trace "$obs_journal"
rm -f "$obs_journal"

echo "== obs fleet smoke: cross-replica explain over the gRPC hub =="
# the handoff-FORCING fleet profile drives a 2-replica fleet against
# the gRPC-served occupancy hub: replicas ship bounded journal
# segments to the hub's aggregation surface piggybacked on their
# write-behind flushes, handoff rows carry each pod's journey trace
# across the wire, and `obs explain --fleet` reconstructs the full
# enqueue→handoff→re-admit→bind chain with the PR 8 merge rules.
# --selfcheck proves the hub-aggregated journal (and therefore the
# explain output, a pure function of it) byte-identical across runs.
# The greps pin the tentpole non-vacuously: a handed-off pod must
# exist, its history must span >= 2 replicas under ONE journey trace,
# and it must reach a terminal outcome.
fleet_journal=$(mktemp /tmp/ktpu_fleet_journal.XXXXXX.jsonl)
python -m kubernetes_tpu.sim --seed 0 --cycles 8 --profile fleet_handoff \
    --fleet 2 --hub-grpc --journal "$fleet_journal" --selfcheck
python -m kubernetes_tpu.obs validate "$fleet_journal"
handoff_pod=$(python - "$fleet_journal" <<'PYEOF'
import collections, json, sys
by_pod = collections.defaultdict(set)
for ln in open(sys.argv[1]):
    rec = json.loads(ln)
    by_pod[rec["pod"]].add(rec.get("replica"))
crossed = sorted(p for p, reps in by_pod.items() if len(reps) > 1)
if not crossed:
    sys.exit("OBS FLEET SMOKE: no pod was handed off between replicas")
print(crossed[0])
PYEOF
)
explain_out=$(python -m kubernetes_tpu.obs explain "$handoff_pod" \
    --fleet --trace "$fleet_journal")
echo "$explain_out"
echo "$explain_out" | grep -qE "replicas: r[0-9]+ -> r[0-9]+" \
    || { echo "OBS FLEET SMOKE: history does not span >= 2 replicas"; exit 1; }
echo "$explain_out" | grep -q "one journey trace" \
    || { echo "OBS FLEET SMOKE: the journey shattered into multiple traces"; exit 1; }
echo "$explain_out" | grep -q "terminal outcome:" \
    || { echo "OBS FLEET SMOKE: the handed-off pod never reached a terminal outcome"; exit 1; }
rm -f "$fleet_journal" "$fleet_journal".r*

echo "== metrics doc drift gate =="
python -m kubernetes_tpu.metrics --check

echo "CI gate: OK"
