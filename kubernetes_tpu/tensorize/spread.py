"""PodTopologySpread tensorizer: compile each pod class's spread constraints
into "constraint instances" evaluated on-device with segment reductions.

Per instance j (one (class, constraint) pair, hard or soft):
- dom[j, n]   : domain id of node n under the instance's topologyKey
                (-1 = node lacks the key). Ids are per-topologyKey vocabs.
- elig[j, n]  : counting eligibility (common.go#calPreFilterState — node has
                ALL the class's keys + nodeAffinityPolicy/nodeTaintsPolicy).
- max_skew[j], min_domains[j] (-1 = nil), self_match[j], is_hostname[j].

The per-node match counts cnt[j, n] are SOLVE STATE: they start from the
already-placed pods and are incremented in-scan when a batch pod lands on a
node and matches instance j's selector+namespace (placed_match[p, j],
precompiled host-side). Domain aggregation (counts per domain, min over
registered domains, #domains) runs on device per step as segment sums over
the node axis — the tensor equivalent of the reference's
TpPairToMatchNum/criticalPaths bookkeeping (filtering.go#preFilterState).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from ..api.objects import Node, Pod
from ..ops.oracle import spread as osp
from .schema import PodBatch, bucket_pow2

INST_PAD = 8  # instance-axis quantum
DOM_PAD = 8


@dataclass
class SpreadTensors:
    num_instances: int
    d_pad: int  # static segment count for domain reductions
    # per-instance tables
    dom: np.ndarray  # [Jp, Np] int32, -1 = key missing
    elig: np.ndarray  # [Jp, Np] bool
    max_skew: np.ndarray  # [Jp] int32
    min_domains: np.ndarray  # [Jp] int32, -1 = nil
    self_match: np.ndarray  # [Jp] bool
    is_hostname: np.ndarray  # [Jp] bool
    # class -> instance tables (-1 pad)
    hard: np.ndarray  # [Cp, Sh] int32
    soft: np.ndarray  # [Cp, Ss] int32
    # state + per-pod
    cnt0: np.ndarray  # [Jp, Np] int32 — matching placed pods per node
    placed_match: np.ndarray  # [Pp, Jp] bool

    @property
    def empty(self) -> bool:
        return self.num_instances == 0

    @property
    def has_soft(self) -> bool:
        """False when no class has a soft constraint: soft_scores is
        statically zero and the scan can skip it."""
        return bool((self.soft >= 0).any())


def trivial_spread_tensors(pbatch: PodBatch, padded_n: int, c_pad: int) -> SpreadTensors:
    z = np.zeros((INST_PAD, padded_n), dtype=np.int32)
    return SpreadTensors(
        num_instances=0,
        d_pad=DOM_PAD,
        dom=z - 1,
        elig=np.zeros((INST_PAD, padded_n), dtype=bool),
        max_skew=np.ones(INST_PAD, dtype=np.int32),
        min_domains=np.full(INST_PAD, -1, dtype=np.int32),
        self_match=np.zeros(INST_PAD, dtype=bool),
        is_hostname=np.zeros(INST_PAD, dtype=bool),
        hard=np.full((c_pad, 1), -1, dtype=np.int32),
        soft=np.full((c_pad, 1), -1, dtype=np.int32),
        cnt0=z.copy(),
        placed_match=np.zeros((pbatch.padded, INST_PAD), dtype=bool),
    )


def build_spread_tensors(
    pods: Sequence[Pod],
    class_reps: Sequence[Pod],
    pbatch: PodBatch,
    slot_nodes: Sequence[Node | None],
    placed_by_slot: Mapping[int, Sequence[Pod]],
    padded_n: int,
    c_pad: int,
    services: Sequence | None = None,
    defaulting: str = "System",
    nominated: Sequence[tuple[Pod, int]] = (),
) -> SpreadTensors:
    """class_reps comes from the static tensorizer so all per-class tables
    share one class id space (xs carries class_of for the gather).

    ``services`` + ``defaulting`` feed PodTopologySpreadArgs.defaultingType
    =System: classes with no explicit constraints get the soft
    zone/hostname system defaults when a service selects them.

    ``nominated`` carries (pod, node slot) pairs for unbound pods whose
    ``status.nominatedNodeName`` resolved to a live slot: they count in
    ``cnt0`` exactly like placed pods (the
    RunFilterPluginsWithNominatedPods convention the synchronous filter
    path already applies via the ports tensorizer) so a spread
    constraint sees a nominated peer as occupying its slot."""
    # collect instances per class
    per_class: list[tuple[list, list]] = []  # (hard ECs, soft ECs)
    insts: list[tuple[int, osp.EffectiveConstraint, bool, Pod]] = []
    for c, rep in enumerate(class_reps):
        defaults = (
            osp.system_default_constraints(rep, services)
            if defaulting == "System" and services
            else ()
        )
        hard = osp.effective_constraints(rep, hard=True)
        soft = osp.effective_constraints(rep, hard=False, defaults=defaults)
        per_class.append((hard, soft))
        for ec in hard:
            insts.append((c, ec, True, rep))
        for ec in soft:
            insts.append((c, ec, False, rep))

    if not insts:
        return trivial_spread_tensors(pbatch, padded_n, c_pad)

    j_pad = bucket_pow2(len(insts), floor=INST_PAD)
    sh = max(max((len(h) for h, _ in per_class), default=0), 1)
    ss = max(max((len(s) for _, s in per_class), default=0), 1)
    hard_tbl = np.full((c_pad, sh), -1, dtype=np.int32)
    soft_tbl = np.full((c_pad, ss), -1, dtype=np.int32)

    # domain vocab per topology key (over all live nodes)
    all_keys = {ec.topology_key for _, ec, _, _ in insts}
    key_vocab: dict[str, dict[str, int]] = {k: {} for k in all_keys}
    for node in slot_nodes:
        if node is None:
            continue
        for key in all_keys:
            v = node.labels.get(key)
            if v is not None:
                vocab = key_vocab[key]
                vocab.setdefault(v, len(vocab))
    max_domains = max((len(v) for v in key_vocab.values()), default=1)
    d_pad = bucket_pow2(max_domains, floor=DOM_PAD)

    dom = np.full((j_pad, padded_n), -1, dtype=np.int32)
    elig = np.zeros((j_pad, padded_n), dtype=bool)
    max_skew = np.ones(j_pad, dtype=np.int32)
    min_domains = np.full(j_pad, -1, dtype=np.int32)
    self_match = np.zeros(j_pad, dtype=bool)
    is_hostname = np.zeros(j_pad, dtype=bool)
    cnt0 = np.zeros((j_pad, padded_n), dtype=np.int32)
    placed_match = np.zeros((pbatch.padded, j_pad), dtype=bool)

    # counting eligibility is shared by every instance of one (class,
    # hardness) bucket (upstream counts one node set per bucket) — compute
    # each bucket's [N] row once, not once per instance
    elig_cache: dict[tuple[int, bool], np.ndarray] = {}

    def bucket_elig(c: int, is_hard: bool) -> np.ndarray:
        row = elig_cache.get((c, is_hard))
        if row is None:
            bucket = per_class[c][0] if is_hard else per_class[c][1]
            rep = class_reps[c]
            row = np.zeros(padded_n, dtype=bool)
            for n_i, node in enumerate(slot_nodes):
                if node is not None and n_i < padded_n:
                    row[n_i] = osp._node_counted(rep, node, bucket)
            elig_cache[(c, is_hard)] = row
        return row

    hard_fill: dict[int, int] = {}
    soft_fill: dict[int, int] = {}
    for j, (c, ec, is_hard, rep) in enumerate(insts):
        tbl, fill = (hard_tbl, hard_fill) if is_hard else (soft_tbl, soft_fill)
        s = fill.get(c, 0)
        tbl[c, s] = j
        fill[c] = s + 1

        max_skew[j] = ec.max_skew
        if ec.min_domains is not None:
            min_domains[j] = ec.min_domains
        self_match[j] = osp._sel_matches(ec.selector, rep.labels)
        is_hostname[j] = ec.topology_key == osp.HOSTNAME_KEY
        elig[j] = bucket_elig(c, is_hard)

        vocab = key_vocab.get(ec.topology_key, {})
        for n_i, node in enumerate(slot_nodes):
            if node is None or n_i >= padded_n:
                continue
            v = node.labels.get(ec.topology_key)
            if v is not None:
                dom[j, n_i] = vocab[v]
        for n_i, placed in placed_by_slot.items():
            if n_i >= padded_n:
                continue
            cnt0[j, n_i] = sum(
                1
                for p in placed
                if p.namespace == rep.namespace
                and osp._sel_matches(ec.selector, p.labels)
            )
        for p, n_i in nominated:
            # nominated-pod parity: count a matching nominated pod at
            # its slot exactly like a placed pod
            if 0 <= n_i < padded_n and (
                p.namespace == rep.namespace
                and osp._sel_matches(ec.selector, p.labels)
            ):
                cnt0[j, n_i] += 1

        for p_i, pod in enumerate(pods):
            placed_match[p_i, j] = pod.namespace == rep.namespace and (
                osp._sel_matches(ec.selector, pod.labels)
            )

    return SpreadTensors(
        num_instances=len(insts),
        d_pad=d_pad,
        dom=dom,
        elig=elig,
        max_skew=max_skew,
        min_domains=min_domains,
        self_match=self_match,
        is_hostname=is_hostname,
        hard=hard_tbl,
        soft=soft_tbl,
        cnt0=cnt0,
        placed_match=placed_match,
    )
