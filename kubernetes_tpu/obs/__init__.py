"""kubernetes_tpu.obs — the end-to-end scheduling trace layer.

Three cooperating pieces, all zero-dep and virtual-time-clean:

- **spans** (``span.py``): OTel-shaped host-side spans threaded through
  both scheduler loops (enqueue → snapshot → tensorize → fold/extender
  → dispatch → fence → apply → bind) and the extender server's
  micro-batcher; exported as JSONL and into the flight recorder.
- **per-pod decision journal** (``journal.py``): one record per pod per
  solved batch — outcome plus per-plugin filter attribution pulled from
  the host-materialized solve tensors, so "why is pod X pending" has a
  concrete answer ("NodeResourcesFit rejected 14/16 nodes, ...").
- **flight recorder** (``recorder.py``): bounded ring of recent spans +
  decisions, dumped on crash, on sim invariant violation, and on demand
  via ``GET /debug/flightrecorder`` / ``/debug/spans``.

``python -m kubernetes_tpu.obs explain <pod> [--trace FILE | --url U]``
reconstructs a pod's history from any of those sources (``explain.py``).

Everything is OFF by default: ``build_obs(None, clock)`` returns a
disabled tracer and no journal/recorder, and the scheduler's hot path
then pays one attribute check per would-be span — no allocation, no
host↔device syncs (TPU001 stays clean; verified by the analyzer gate).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.clock import Clock
from .explain import (
    Explanation,
    explain_pod,
    merge_fleet_records,
    parse_stream,
)
from .journal import (
    OUTCOMES,
    TERMINAL_OUTCOMES,
    PodDecisionJournal,
    attribute_failure,
    fleet_merge_key,
    summarize_plugins,
    validate_line,
    validate_lines,
)
from .recorder import FlightRecorder, canonical
from .slo import SloConfig, SloEngine
from .span import Span, Tracer

__all__ = [
    "ObsConfig",
    "build_obs",
    "Tracer",
    "Span",
    "PodDecisionJournal",
    "FlightRecorder",
    "Explanation",
    "SloConfig",
    "SloEngine",
    "explain_pod",
    "merge_fleet_records",
    "parse_stream",
    "attribute_failure",
    "fleet_merge_key",
    "summarize_plugins",
    "validate_line",
    "validate_lines",
    "canonical",
    "OUTCOMES",
    "TERMINAL_OUTCOMES",
]


@dataclass
class ObsConfig:
    """Observability knobs carried on SchedulerConfig.obs (None = all
    off, the production default)."""

    spans: bool = False  # emit spans from the scheduler loops
    journal: bool = False  # per-pod decision journal
    span_capacity: int = 4096  # flight-recorder ring sizes
    decision_capacity: int = 8192
    # in-memory journal line retention: None = unbounded (the sim needs
    # the full history); serve passes a bound and streams to
    # journal_path for durability
    journal_capacity: int | None = None
    # streaming JSONL sinks (append-mode files); None = in-memory only
    spans_path: str | None = None
    journal_path: str | None = None
    # crash / invariant-violation dump target for the flight recorder
    dump_path: str | None = None
    # live SLO engine (obs/slo.py): an SloConfig enabling the sliding-
    # window p50/p99 latency, bind throughput, and multi-window error-
    # budget burn computation (scheduler_slo_* metrics + GET
    # /debug/slo + the degraded-health signal). None = off. Independent
    # of spans/journal — the engine reads only BatchResult numbers the
    # loops already compute.
    slo: SloConfig | None = None
    # deterministic 1-in-N sampling for the PER-WATCH-EVENT enqueue
    # span — the one span family whose volume scales with event rate
    # (tens of thousands/s at sustained-stream scale) rather than with
    # batches. The first event is always sampled and the counter is
    # deterministic, so same-seed sim runs stay byte-identical. 1 =
    # span every event (the PR 3 behavior). Batch-level spans
    # (schedule_batch/dispatch/apply/bind/...) are never sampled: they
    # are the trace's structure. The shipped default keeps the whole
    # obs layer inside the <= 5% sustained-throughput budget bench
    # ladder #13 asserts.
    enqueue_span_sample_n: int = 64
    # deterministic 1-in-N sampling for the PER-POD bind span (the
    # other per-pod-volume family). The decision JOURNAL stays
    # complete — one record per pod per batch, never sampled; the bind
    # span only adds the commit's wall duration, which N-sampling
    # preserves statistically. First bind always sampled; 1 = every
    # bind (PR 3 behavior).
    bind_span_sample_n: int = 8


class _FileSink:
    """Append-mode JSONL line writer (flushed per line: a crash must
    not lose the records explaining it)."""

    def __init__(self, path: str) -> None:
        self._f = open(path, "a")

    def __call__(self, rec: dict) -> None:
        self._f.write(canonical(rec) + "\n")
        self._f.flush()


def build_obs(
    cfg: ObsConfig | None, clock: Clock | None = None
) -> tuple[Tracer, PodDecisionJournal | None, FlightRecorder | None]:
    """(tracer, journal, flight recorder) for one Scheduler. With cfg
    None or everything disabled: a disabled Tracer and two Nones."""
    if cfg is None or not (cfg.spans or cfg.journal):
        return Tracer(clock=clock, enabled=False), None, None
    recorder = FlightRecorder(
        span_capacity=cfg.span_capacity,
        decision_capacity=cfg.decision_capacity,
        dump_path=cfg.dump_path,
    )
    tracer = Tracer(
        clock=clock,
        enabled=cfg.spans,
        recorder=recorder,
        sink=_FileSink(cfg.spans_path) if cfg.spans_path else None,
    )
    journal = None
    if cfg.journal:
        journal = PodDecisionJournal(
            clock=clock,
            recorder=recorder,
            sink=_FileSink(cfg.journal_path) if cfg.journal_path else None,
            capacity=cfg.journal_capacity,
        )
    return tracer, journal, recorder
