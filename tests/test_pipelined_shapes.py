"""The pipelined hard path (ISSUE 4 tentpole): batches with
ports/spread/interpod/volumes/DRA terms — and multi-profile / extender /
out-of-tree configs — schedule through Scheduler.run_pipelined instead
of draining to the synchronous loop. These tests pin:

1. no-drain regression — hard-shape batches take the occupancy-carrying
   ``carry`` mode (scheduler_pipeline_mode_total), never the sync
   fallback, and the chained sub-batch split actually dispatches;
2. per-shape binding equivalence — with tie_break="first", pipelined
   bindings (including split>1 chains) are identical to the synchronous
   loop's, per shape;
3. the occupancy fence — one discard test per newly-carried event kind
   (assigned-pod delete for ports/interpod, assigned-pod label change
   for spread, external ResourceClaim writes for DRA), plus the
   selectivity half: plain fit solves must NOT discard on those events
   (delete-churn degrading the plain pipeline was the original reason
   hard shapes were excluded).
"""

import time

import numpy as np

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def mk_cluster(n_nodes=6, cpu="8"):
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": "110"})
            .label(ZONE, f"z{i % 3}")
            .label(HOST, f"n{i}")
            .obj()
        )
    return cs


def mk_sched(cs, batch=16, group=8, split=0, **cfg):
    return Scheduler(
        cs,
        SchedulerConfig(
            batch_size=batch,
            pipeline_split=split,
            solver=ExactSolverConfig(tie_break="first", group_size=group),
            **cfg,
        ),
    )


def shape_pod(i: int, kind: str):
    b = MakePod().name(f"{kind}{i:03}").req({"cpu": "100m", "memory": "256Mi"})
    if kind == "spread":
        b = b.label("app", "spread").spread_constraint(
            1, ZONE, "DoNotSchedule", {"app": "spread"}
        )
    elif kind == "anti":
        b = b.label("app", "anti").pod_anti_affinity(HOST, {"app": "anti"})
    elif kind == "ports":
        b = b.host_port(8000 + i % 3)
    return b.obj()


def bindings(cs):
    return sorted((p.name, p.node_name) for p in cs.list_pods())


def mode_delta():
    return {
        m: metrics.pipeline_mode_total.labels(m)._value.get()
        for m in ("overlap", "carry", "sync")
    }


# -- 1. no-drain regression -------------------------------------------------


def test_hard_shapes_take_carry_mode_not_sync():
    """ports/spread/interpod batches must run the pipelined carry path:
    deferred dispatch with the chained sub-batch split — zero sync-mode
    batches (the old behavior drained every hard batch to _run_popped)."""
    for kind in ("ports", "spread", "anti"):
        cs = mk_cluster()
        s = mk_sched(cs, split=2)
        for i in range(20):
            cs.create_pod(shape_pod(i, kind))
        before = mode_delta()
        sub0 = metrics.pipeline_subbatches_total._value.get()
        results = s.run_pipelined()
        after = mode_delta()
        assert after["carry"] > before["carry"], kind
        assert after["sync"] == before["sync"], kind
        assert after["overlap"] == before["overlap"], kind
        assert metrics.pipeline_subbatches_total._value.get() > sub0, kind
        # every pod reached a terminal outcome through the carry path
        # (ports/anti overflow capacity by design: the surplus must land
        # as unschedulable, not vanish)
        outcomes = sum(
            len(r.scheduled) + len(r.unschedulable) for r in results
        )
        assert outcomes >= 20, kind
        assert sum(len(r.scheduled) for r in results) > 0, kind


def test_plain_batches_still_overlap():
    cs = mk_cluster()
    s = mk_sched(cs)
    for i in range(20):
        cs.create_pod(shape_pod(i, "plain"))
    before = mode_delta()
    s.run_pipelined()
    after = mode_delta()
    assert after["overlap"] > before["overlap"]
    assert after["carry"] == before["carry"]


# -- 2. per-shape pipelined-vs-sync equivalence -----------------------------


def _equivalence(kind, n_pods=30, split=0, n_nodes=6):
    cs1 = mk_cluster(n_nodes)
    s1 = mk_sched(cs1)
    for i in range(n_pods):
        cs1.create_pod(shape_pod(i, kind))
    s1.run_until_settled()
    cs2 = mk_cluster(n_nodes)
    s2 = mk_sched(cs2, split=split)
    for i in range(n_pods):
        cs2.create_pod(shape_pod(i, kind))
    s2.run_pipelined()
    assert bindings(cs1) == bindings(cs2), kind
    return cs2, s2


def test_ports_pipelined_matches_sync():
    cs, _ = _equivalence("ports", split=2)
    # hostPort exclusivity held under the pipelined path
    per = {}
    for p in cs.list_pods():
        if p.node_name:
            for port in p.host_ports():
                key = (p.node_name, port)
                assert key not in per, f"hostPort clash on {key}"
                per[key] = p.name


def test_spread_pipelined_matches_sync():
    cs, _ = _equivalence("spread", split=2)
    from collections import Counter

    zones = Counter()
    node_zone = {n.name: n.labels[ZONE] for n in cs.list_nodes()}
    for p in cs.list_pods():
        if p.node_name and p.name.startswith("spread"):
            zones[node_zone[p.node_name]] += 1
    assert max(zones.values()) - min(zones.values()) <= 1


def test_interpod_pipelined_matches_sync():
    cs, _ = _equivalence("anti", n_pods=6, split=2)
    anti_nodes = [
        p.node_name
        for p in cs.list_pods()
        if p.node_name and p.name.startswith("anti")
    ]
    assert len(set(anti_nodes)) == len(anti_nodes)  # one per node


def test_split_chain_matches_unsplit():
    """The RTT-hiding batch split is semantics-free: split=4 chains
    produce bit-identical bindings to split=1 (tie_break='first'), for
    both a plain and a hard shape."""
    for kind in ("plain", "spread"):
        cs1 = mk_cluster()
        s1 = mk_sched(cs1, split=1)
        for i in range(32):
            cs1.create_pod(shape_pod(i, kind))
        s1.run_pipelined()
        cs2 = mk_cluster()
        s2 = mk_sched(cs2, split=4)
        for i in range(32):
            cs2.create_pod(shape_pod(i, kind))
        sub0 = metrics.pipeline_subbatches_total._value.get()
        s2.run_pipelined()
        assert bindings(cs1) == bindings(cs2), kind
        assert metrics.pipeline_subbatches_total._value.get() > sub0


def test_multi_profile_pipelined_matches_sync():
    from kubernetes_tpu.api.objects import DEFAULT_SCHEDULER_NAME

    def mk(pipelined):
        cs = mk_cluster(4)
        s = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=8,
                profiles={
                    DEFAULT_SCHEDULER_NAME: ExactSolverConfig(
                        tie_break="first", group_size=4
                    ),
                    "alt": ExactSolverConfig(
                        tie_break="first", group_size=4
                    ),
                },
            ),
        )
        for i in range(6):
            cs.create_pod(
                MakePod().name(f"a{i}").req({"cpu": "500m"}).obj()
            )
            cs.create_pod(
                MakePod()
                .name(f"b{i}")
                .scheduler_name("alt")
                .req({"cpu": "500m"})
                .obj()
            )
        return cs, s

    cs1, s1 = mk(False)
    s1.run_until_settled()
    cs2, s2 = mk(True)
    before = mode_delta()
    s2.run_pipelined()
    after = mode_delta()
    assert bindings(cs1) == bindings(cs2)
    # multi-profile no longer bails to run_until_settled: its groups
    # ride the carry path
    assert after["carry"] > before["carry"]


def test_multi_profile_cross_profile_batches_do_not_overcommit():
    """Consecutive PLAIN batches of different profiles must not
    overlap: profile X's unapplied placements live only in X's device
    session, so dispatching profile Y before X applies would double-book
    the capacity X claimed. The loop drains on profile change; with
    capacity exactly equal to demand, any double-booking shows up as a
    capacity violation or a binding divergence."""
    from kubernetes_tpu.api.objects import DEFAULT_SCHEDULER_NAME

    def mk():
        cs = ClusterState()
        for i in range(2):
            cs.create_node(
                MakeNode()
                .name(f"n{i}")
                .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
                .label(HOST, f"n{i}")
                .obj()
            )
        s = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=8,
                profiles={
                    DEFAULT_SCHEDULER_NAME: ExactSolverConfig(
                        tie_break="first", group_size=4
                    ),
                    "alt": ExactSolverConfig(
                        tie_break="first", group_size=4
                    ),
                },
            ),
        )
        # 8 default-profile pods, then 8 alt-profile pods: pop order
        # yields one all-X batch followed by one all-Y batch, both plain
        for i in range(8):
            cs.create_pod(MakePod().name(f"x{i}").req({"cpu": "1"}).obj())
        for i in range(8):
            cs.create_pod(
                MakePod()
                .name(f"y{i}")
                .scheduler_name("alt")
                .req({"cpu": "1"})
                .obj()
            )
        return cs, s

    cs1, s1 = mk()
    s1.run_until_settled()
    cs2, s2 = mk()
    s2.run_pipelined()
    assert bindings(cs1) == bindings(cs2)
    per_node: dict = {}
    for p in cs2.list_pods():
        assert p.node_name  # demand == capacity: everything places
        per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
    assert all(v <= 8 for v in per_node.values())


def test_out_of_tree_filter_pipelines_as_prefold():
    """A Filter plugin config used to force the whole call into
    run_until_settled; the fold is now a pre-dispatch host stage and
    plain batches keep overlapping."""
    from kubernetes_tpu.framework.interface import FilterPlugin, Status

    class VetoN0(FilterPlugin):
        def name(self):
            return "veto-n0"

        def filter(self, state, pod, node, placed=()):
            return (
                Status.unschedulable("no n0")
                if node.name == "n0"
                else Status.success()
            )

    def mk(pipelined_cfg):
        cs = mk_cluster(4)
        s = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=8,
                solver=ExactSolverConfig(tie_break="first", group_size=4),
                out_of_tree_plugins=(VetoN0(),),
            ),
        )
        for i in range(12):
            cs.create_pod(
                MakePod().name(f"p{i:02}").req({"cpu": "500m"}).obj()
            )
        return cs, s

    cs1, s1 = mk(False)
    s1.run_until_settled()
    cs2, s2 = mk(True)
    before = mode_delta()
    s2.run_pipelined()
    after = mode_delta()
    assert bindings(cs1) == bindings(cs2)
    assert after["overlap"] > before["overlap"]
    assert not any(
        p.node_name == "n0" for p in cs2.list_pods() if p.node_name
    )


# -- 3. occupancy-fence discards per newly-carried event kind ---------------


def _flight(s, expect_pods):
    t0 = time.perf_counter()
    with s.cluster.lock:
        infos = s.queue.pop_batch(s.config.batch_size)
        base = s.queue.scheduling_cycle - len(infos)
        for i in infos:
            s._in_flight[i.key] = i
    assert len(infos) == expect_pods
    prep = s._tensorize_group(
        next(iter(s.solvers)), infos, list(range(len(infos))), base, t0
    )
    s._fold_group(prep)
    return s._dispatch_group(prep, defer=True, allow_heal=True)


def _assert_discards(s, flight, discarded=True):
    before = metrics.solves_discarded_total._value.get()
    res = s._apply_flight(flight)
    n = metrics.solves_discarded_total._value.get() - before
    if discarded:
        assert n == 1 and not res.scheduled
    else:
        assert n == 0
    return res


def test_ports_flight_discards_on_assigned_pod_delete():
    """An assigned-pod delete frees its hostPorts: a ports-carrying
    deferred solve that counted them must discard."""
    cs = mk_cluster(2)
    s = mk_sched(cs, batch=4)
    cs.create_pod(MakePod().name("old").req({"cpu": "1"}).host_port(8000).obj())
    cs.bind("default", "old", "n0")
    for i in range(2):
        cs.create_pod(shape_pod(i * 3, "ports"))  # both want port 8000
    flight = _flight(s, 2)
    assert flight.prep.occ_sensitive
    cs.delete_pod("default", "old")
    _assert_discards(s, flight)
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())


def test_spread_flight_discards_on_assigned_pod_label_change():
    """A placed pod's label change re-keys spread domain counts: a
    spread-carrying deferred solve must discard (a pure label flap on a
    running pod is NOT a _conflict_seq event, so only the occupancy
    fence catches it)."""
    import dataclasses

    cs = mk_cluster()
    s = mk_sched(cs)
    cs.create_pod(
        MakePod().name("old").label("app", "spread").req({"cpu": "1"}).obj()
    )
    cs.bind("default", "old", "n0")
    for i in range(4):
        cs.create_pod(shape_pod(i, "spread"))
    flight = _flight(s, 4)
    assert flight.prep.occ_sensitive
    old = cs.get_pod("default", "old")
    relabeled = dataclasses.replace(old, labels={"app": "other"})
    cs.update_pod(relabeled)
    _assert_discards(s, flight)
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())


def test_interpod_flight_discards_on_assigned_pod_delete():
    cs = mk_cluster()
    s = mk_sched(cs)
    cs.create_pod(
        MakePod().name("old").label("app", "anti").req({"cpu": "1"}).obj()
    )
    cs.bind("default", "old", "n0")
    for i in range(3):
        cs.create_pod(shape_pod(i, "anti"))
    flight = _flight(s, 3)
    assert flight.prep.occ_sensitive
    cs.delete_pod("default", "old")
    _assert_discards(s, flight)
    s.run_until_settled()
    anti_nodes = [
        p.node_name for p in cs.list_pods() if p.node_name
    ]
    assert len(set(anti_nodes)) == len(anti_nodes)


def test_dra_flight_discards_on_external_claim_write():
    from kubernetes_tpu.api.dra import (
        Device,
        DeviceClass,
        DeviceRequest,
        ResourceClaim,
        ResourceSlice,
    )
    from kubernetes_tpu.utils.featuregate import FeatureGates

    cs = ClusterState()
    for i in range(2):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
            .obj()
        )
        cs.create_resource_slice(
            ResourceSlice(
                name=f"slice-n{i}",
                node_name=f"n{i}",
                driver="gpu.example.com",
                devices=(Device(name="gpu-0"),),
            )
        )
    cs.create_device_class(
        DeviceClass(name="gpu", driver="gpu.example.com")
    )
    cs.create_resource_claim(
        ResourceClaim(
            name="c0",
            namespace="default",
            requests=(DeviceRequest(name="r0", device_class_name="gpu"),),
        )
    )
    # the claim the external writer will touch mid-flight
    cs.create_resource_claim(
        ResourceClaim(
            name="other",
            namespace="default",
            requests=(DeviceRequest(name="r0", device_class_name="gpu"),),
        )
    )
    s = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=4,
            solver=ExactSolverConfig(tie_break="first", group_size=1),
            feature_gates=FeatureGates.parse(
                "DynamicResourceAllocation=true"
            ),
        ),
    )
    cs.create_pod(
        MakePod().name("p0").req({"cpu": "1"}).resource_claim("c0").obj()
    )
    flight = _flight(s, 1)
    assert flight.prep.occ_sensitive
    # external claim write (not this scheduler's allocator): occ fence
    other = cs.get_resource_claim("default", "other")
    cs.update_resource_claim(other)
    _assert_discards(s, flight)
    s.run_until_settled()
    assert cs.get_pod("default", "p0").node_name


def test_plain_flight_survives_occupancy_events():
    """Selectivity: the occupancy fence must NOT discard plain fit
    solves — an assigned-pod delete or label flap mid-flight leaves the
    plain pipeline untouched (its device carry absorbs frees
    conservatively)."""
    import dataclasses

    cs = mk_cluster(2)
    s = mk_sched(cs, batch=4)
    cs.create_pod(
        MakePod().name("old").label("app", "x").req({"cpu": "1"}).obj()
    )
    cs.bind("default", "old", "n0")
    for i in range(3):
        cs.create_pod(shape_pod(i, "plain"))
    flight = _flight(s, 3)
    assert not flight.prep.occ_sensitive
    old = cs.get_pod("default", "old")
    cs.update_pod(dataclasses.replace(old, labels={"app": "y"}))
    cs.delete_pod("default", "old")
    res = _assert_discards(s, flight, discarded=False)
    assert len(res.scheduled) == 3


def test_mid_chain_occupancy_event_discards_remaining_subflights():
    """A chain of K sub-flights shares one occupancy fence: an event
    between sub-applies discards every remaining sub-flight, and the
    retry schedules everything against post-event truth."""
    cs = mk_cluster()
    s = mk_sched(cs, batch=16, split=4)
    cs.create_pod(
        MakePod().name("old").label("app", "spread").req({"cpu": "1"}).obj()
    )
    cs.bind("default", "old", "n0")
    for i in range(16):
        cs.create_pod(shape_pod(i, "spread"))
    t0 = time.perf_counter()
    with s.cluster.lock:
        infos = s.queue.pop_batch(16)
        base = s.queue.scheduling_cycle - len(infos)
        for i in infos:
            s._in_flight[i.key] = i
    prep = s._tensorize_group(
        next(iter(s.solvers)), infos, list(range(len(infos))), base, t0
    )
    flights = s._dispatch_group(prep, defer=True, allow_heal=True, split=4)
    assert isinstance(flights, list) and len(flights) >= 2
    # first sub-flight applies cleanly...
    r0 = s._apply_flight(flights[0])
    assert r0.scheduled
    # ...then the event lands: every remaining sub-flight discards
    cs.delete_pod("default", "old")
    before = metrics.solves_discarded_total._value.get()
    for f in flights[1:]:
        s._apply_flight(f)
    assert (
        metrics.solves_discarded_total._value.get() - before
        == len(flights) - 1
    )
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())
