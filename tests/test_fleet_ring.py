"""Hash-ring property tests (ISSUE 6 satellite): the fleet's node
partition must be deterministic, zone-contiguous, balance-capped, and
bounded-remap under single join/leave — the structural guarantees the
active-active tier's correctness and blast-radius story ride on.

Property tests run via tests/_hypothesis_compat.py (they skip
individually when hypothesis is absent); every property also has a
concrete deterministic twin so the contract stays enforced either way.
"""

import math

from _hypothesis_compat import given, settings, st

from kubernetes_tpu.fleet.ring import HashRing, RingNode, ring_nodes_from


def mk_nodes(k: int, zones: int) -> list[RingNode]:
    return [
        RingNode(f"n{i:03}", zone=f"z{i % zones}" if zones else "")
        for i in range(k)
    ]


def universe(n: int) -> list[str]:
    return [f"replica-{i}" for i in range(n)]


# -- determinism --


def test_assignment_is_order_and_construction_independent():
    nodes = mk_nodes(37, 4)
    reps = universe(3)
    a = HashRing(reps).assign(nodes)
    b = HashRing(list(reversed(reps))).assign(list(reversed(nodes)))
    assert a == b
    assert set(a) == {n.name for n in nodes}


def test_route_is_deterministic_and_total():
    ring = HashRing(universe(3))
    for key in ("default/p1", "default/p2", "ns/other"):
        assert ring.route(key) == ring.route(key)
        assert ring.route(key) in ring.alive


def test_ring_nodes_from_reads_zone_label():
    class N:
        def __init__(self, name, labels):
            self.name, self.labels = name, labels

    rn = ring_nodes_from(
        [
            N("a", {"topology.kubernetes.io/zone": "z1"}),
            N("b", {}),
        ]
    )
    assert rn[0].zone == "z1" and rn[1].zone == ""


# -- balance --


def test_balance_cap_holds_concrete():
    for k, n in ((37, 3), (8, 5), (100, 4), (7, 7), (3, 2)):
        nodes = mk_nodes(k, 4)
        asg = HashRing(universe(n)).assign(nodes)
        cap = math.ceil(k / n)
        loads: dict = {}
        for r in asg.values():
            loads[r] = loads.get(r, 0) + 1
        assert max(loads.values()) <= cap, (k, n, loads)
        assert len(asg) == k  # every node owned


def test_balance_cap_holds_with_dead_replicas():
    nodes = mk_nodes(30, 3)
    full = HashRing(universe(4))
    asg = full.with_alive(universe(4)[:2]).assign(nodes)
    cap = math.ceil(30 / 2)
    loads: dict = {}
    for r in asg.values():
        loads[r] = loads.get(r, 0) + 1
    assert set(loads) <= set(universe(4)[:2])
    assert max(loads.values()) <= cap
    assert len(asg) == 30


# -- zone affinity / contiguity --


def test_zone_contiguity_of_canonical_order():
    """Nodes sharing a zone are adjacent in the canonical fill order —
    the property that lets the balance cap split a zone across the
    MINIMAL number of replicas instead of striping it."""
    nodes = mk_nodes(24, 4)
    order = HashRing.canonical_order(nodes)
    seen: list = []
    for n in order:
        if not seen or seen[-1] != n.zone:
            seen.append(n.zone)
    assert len(seen) == len(set(seen))  # each zone appears as ONE run


def test_zone_keyed_affinity_minimizes_split():
    """With balance permitting (zones <= cap), every zone lands on
    exactly one replica."""
    # 3 zones x 4 nodes, 3 replicas: cap = 4 — each zone CAN fit
    nodes = mk_nodes(12, 3)
    asg = HashRing(universe(3)).assign(nodes)
    by_zone: dict = {}
    for n in nodes:
        by_zone.setdefault(n.zone, set()).add(asg[n.name])
    # zones are whole-zone assigned whenever the cap allows; a zone
    # never spans more than 2 replicas at this shape
    assert all(len(s) <= 2 for s in by_zone.values())


# -- bounded remap --


def _moved(a: dict, b: dict) -> int:
    return sum(1 for k in a if a[k] != b.get(k))


def test_single_leave_remaps_at_most_ceil_k_over_n():
    for k, n, zones in ((40, 4, 5), (17, 3, 2), (9, 2, 3), (50, 5, 8)):
        nodes = mk_nodes(k, zones)
        full = HashRing(universe(n))
        before = full.assign(nodes)
        bound = math.ceil(k / (n - 1))
        for gone in universe(n):
            survivors = [r for r in universe(n) if r != gone]
            after = full.with_alive(survivors).assign(nodes)
            moved = _moved(before, after)
            assert moved <= bound, (k, n, gone, moved, bound)
            # monotone: only the leaver's nodes move
            for name, owner in before.items():
                if owner != gone:
                    assert after[name] == owner


def test_single_rejoin_remaps_at_most_ceil_k_over_n():
    for k, n, zones in ((40, 4, 5), (17, 3, 2), (9, 2, 3)):
        nodes = mk_nodes(k, zones)
        full = HashRing(universe(n))
        before_full = full.assign(nodes)
        bound = math.ceil(k / (n - 1))
        for gone in universe(n):
            survivors = [r for r in universe(n) if r != gone]
            degraded = full.with_alive(survivors).assign(nodes)
            rejoined = full.assign(nodes)
            # rejoin restores the base partition exactly: the moved
            # set is precisely the redistributed orphans
            assert rejoined == before_full
            assert _moved(degraded, rejoined) <= bound


# -- the same three properties, hypothesis-driven --


@settings(max_examples=60, deadline=None)
@given(
    k=st.integers(min_value=2, max_value=60),
    n=st.integers(min_value=2, max_value=6),
    zones=st.integers(min_value=1, max_value=8),
    leaver=st.integers(min_value=0, max_value=5),
)
def test_property_partition_contract(k, n, zones, leaver):
    nodes = mk_nodes(k, zones)
    reps = universe(n)
    full = HashRing(reps)
    before = full.assign(nodes)
    # deterministic
    assert before == HashRing(list(reversed(reps))).assign(
        list(reversed(nodes))
    )
    # balanced
    loads: dict = {}
    for r in before.values():
        loads[r] = loads.get(r, 0) + 1
    assert max(loads.values()) <= math.ceil(k / n)
    # bounded remap on one leave + its rejoin
    gone = reps[leaver % n]
    survivors = [r for r in reps if r != gone]
    after = full.with_alive(survivors).assign(nodes)
    bound = math.ceil(k / (n - 1))
    assert _moved(before, after) <= bound
    assert _moved(after, full.assign(nodes)) <= bound
    # alive-balance
    loads2: dict = {}
    for r in after.values():
        loads2[r] = loads2.get(r, 0) + 1
    assert max(loads2.values()) <= math.ceil(k / (n - 1))


# -- input validation --


def test_empty_universe_rejected():
    import pytest

    with pytest.raises(ValueError):
        HashRing([])
    with pytest.raises(ValueError):
        HashRing(["a"]).with_alive([])


def test_alive_restricted_to_universe():
    ring = HashRing(["a", "b"]).with_alive(["b", "ghost"])
    assert ring.alive == ("b",)
