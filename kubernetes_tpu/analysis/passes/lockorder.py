"""LOCK002 — lock-order deadlock detection (project-wide).

Builds the *acquired-while-holding* graph over every lock the project
declares (``self.<attr> = threading.Lock()/RLock()/Condition()``): an
edge A→B means some code path acquires B while holding A — either a
lexically nested ``with``, or a call made under A to a function that
(transitively, through the cross-module call graph) acquires B. Two
findings fall out:

- a **cycle** among distinct locks (A→B and B→A reachable): two threads
  taking the locks in opposite orders can deadlock;
- a **self-acquisition** of a non-reentrant ``threading.Lock`` — a
  function called with the lock held takes it again and blocks forever
  (an RLock self-edge is reentrant and ignored).

``# ktpu: holds(expr)`` annotations participate: a function annotated
as running under ``self.cluster.lock`` contributes edges for the locks
it acquires inside. Unresolvable ``with`` subjects (e.g. a foreign
library's internal lock) contribute nothing — every edge comes from a
positive resolution.

When the graph is acyclic the proven total order is emitted as a
committed artifact (``docs/LOCK_ORDER.md``, regenerated via
``python -m kubernetes_tpu.analysis --write-lock-order`` and pinned by
``--check-lock-order`` plus a tier-1 test).
"""

from __future__ import annotations

import ast

from ..core import AnalysisContext, Finding
from ..project import ProjectGraph, ProjectPass

_JITTER_NONE = frozenset()


class LockOrderAnalysis:
    """One full lock-order computation; shared by the pass (findings)
    and the artifact writer (markdown)."""

    def __init__(self, project: ProjectGraph):
        self.project = project
        self.locks = {}  # lock_id -> LockDecl
        self._attr_index: dict[str, list] = {}
        for key in sorted(project.classes):
            cinfo = project.classes[key]
            for attr in sorted(cinfo.locks):
                decl = cinfo.locks[attr]
                self.locks[decl.lock_id] = decl
                self._attr_index.setdefault(attr, []).append(decl)
        # (a, b) -> (rel, line, kind) — first (sorted) example site;
        # kind is "with" for a lexical nesting, "call" for an edge
        # discovered through the call graph
        self.edges: dict[tuple, tuple] = {}
        self.self_deadlocks: list = []  # (lock_id, rel, line, via)
        self._acq_direct: dict[tuple, set] = {}  # node -> {lock_id}
        self._held_calls: list = []  # (held tuple, call node ids, rel, line)
        self._walk_project()
        self._close_over_calls()

    # -- per-function lexical walk -----------------------------------------

    def _walk_project(self) -> None:
        p = self.project
        for rel in sorted(p.graphs):
            graph = p.graphs[rel]
            m = p.modules[rel]
            for qual in sorted(graph.functions):
                finfo = graph.functions[qual]
                env = p.local_env(rel, finfo)
                cinfo = (
                    p.classes.get((rel, finfo.cls)) if finfo.cls else None
                )
                held0: tuple = ()
                holds = m.holds_lock(finfo.node)
                if holds:
                    decl = self._resolve_holds(holds, rel, finfo, env, cinfo)
                    if decl is not None:
                        held0 = (decl.lock_id,)
                self._walk(
                    finfo.node.body, held0, rel, qual, finfo, env, cinfo
                )

    def _resolve_holds(self, text, rel, finfo, env, cinfo):
        """holds(cluster.lock) means self.cluster.lock (LOCK001 grammar)."""
        try:
            expr = ast.parse(f"self.{text.strip()}", mode="eval").body
        except SyntaxError:
            return None
        return self._resolve_lock(expr, rel, finfo, env, cinfo)

    def _resolve_lock(self, expr, rel, finfo, env, cinfo):
        if not isinstance(expr, ast.Attribute):
            return None
        types = self.project.expr_types(expr.value, rel, env, cinfo)
        for t in sorted(types):
            decl = self._lock_on_class(t, expr.attr)
            if decl is not None:
                return decl
        # a lock attribute name used by exactly ONE class project-wide
        # resolves even when the receiver cannot be typed ("cluster.lock"
        # on an unannotated local): precision holds because ambiguous
        # names stay unresolved
        decls = self._attr_index.get(expr.attr, ())
        if len(decls) == 1:
            return decls[0]
        return None

    def _lock_on_class(self, ctype, attr):
        seen, work = set(), [ctype]
        while work:
            cur = work.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            cinfo = self.project.classes.get(cur)
            if cinfo is None:
                continue
            if attr in cinfo.locks:
                return cinfo.locks[attr]
            work.extend(cinfo.bases)
        return None

    def _walk(self, stmts, held, rel, qual, finfo, env, cinfo) -> None:
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # nested defs are separate entries
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in stmt.items:
                    self._scan_calls(
                        item.context_expr, held, rel, qual, finfo, env
                    )
                    decl = self._resolve_lock(
                        item.context_expr, rel, finfo, env, cinfo
                    )
                    if decl is None:
                        continue
                    self._acquire(
                        decl, new_held, rel, qual, stmt.lineno
                    )
                    if decl.lock_id not in new_held:
                        new_held = new_held + (decl.lock_id,)
                self._walk(
                    stmt.body, new_held, rel, qual, finfo, env, cinfo
                )
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_calls(child, held, rel, qual, finfo, env)
                elif isinstance(child, ast.stmt):
                    self._walk(
                        [child], held, rel, qual, finfo, env, cinfo
                    )
                elif isinstance(child, (ast.ExceptHandler, ast.match_case)):
                    self._walk(
                        child.body, held, rel, qual, finfo, env, cinfo
                    )

    def _acquire(self, decl, held, rel, qual, line) -> None:
        self._acq_direct.setdefault((rel, qual), set()).add(decl.lock_id)
        if decl.lock_id in held:
            if not decl.reentrant:
                self.self_deadlocks.append(
                    (decl.lock_id, rel, line, "with")
                )
            return
        for h in held:
            self._note_edge(h, decl.lock_id, rel, line, "with")

    def _note_edge(self, a, b, rel, line, kind) -> None:
        site = (rel, line, kind)
        prev = self.edges.get((a, b))
        if prev is None or site[:2] < prev[:2]:
            self.edges[(a, b)] = site

    def _scan_calls(self, expr, held, rel, qual, finfo, env) -> None:
        if not held:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                targets = self.project.call_targets(rel, finfo, node, env)
                if targets:
                    self._held_calls.append(
                        (held, tuple(sorted(targets)), rel, node.lineno)
                    )

    # -- interprocedural closure -------------------------------------------

    def _close_over_calls(self) -> None:
        # acquires*(n): locks a call to n may take, transitively
        acq = {n: set(s) for n, s in self._acq_direct.items()}
        edges = self.project.edges
        changed = True
        while changed:
            changed = False
            for n in edges:
                cur = acq.get(n)
                add = set()
                for c in edges[n]:
                    add |= acq.get(c, _JITTER_NONE)
                if add and (cur is None or not add <= cur):
                    acq.setdefault(n, set()).update(add)
                    changed = True
        self.acquires_star = acq
        for held, targets, rel, line in self._held_calls:
            reach = set()
            for t in targets:
                reach |= acq.get(t, _JITTER_NONE)
            for h in held:
                for lock_id in reach:
                    if lock_id == h:
                        if not self.locks[lock_id].reentrant:
                            self.self_deadlocks.append(
                                (lock_id, rel, line, "call")
                            )
                        continue
                    self._note_edge(h, lock_id, rel, line, "call")

    # -- cycles + order ----------------------------------------------------

    def cycles(self) -> list:
        """Strongly connected components with more than one lock, as
        sorted lock-id tuples (deterministic)."""
        graph: dict[str, set] = {k: set() for k in self.locks}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set = set()
        stack: list = []
        out: list = []
        counter = [0]

        def strongconnect(v):  # iterative Tarjan
            work = [(v, iter(sorted(graph.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph.get(w, ())))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        out.append(tuple(sorted(comp)))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return sorted(out)

    def order(self) -> list:
        """Deterministic topological order (Kahn, sorted ties) over ALL
        declared locks; meaningful only when cycles() is empty."""
        indeg = {k: 0 for k in self.locks}
        succ: dict[str, set] = {k: set() for k in self.locks}
        for (a, b) in self.edges:
            if b not in succ.get(a, set()):
                succ.setdefault(a, set()).add(b)
                indeg[b] = indeg.get(b, 0) + 1
        ready = sorted(k for k, d in indeg.items() if d == 0)
        out = []
        while ready:
            cur = ready.pop(0)
            out.append(cur)
            for nxt in sorted(succ.get(cur, ())):
                indeg[nxt] -= 1
                if indeg[nxt] == 0:
                    ready.append(nxt)
            ready.sort()
        return out


def get_analysis(project: ProjectGraph) -> LockOrderAnalysis:
    """Memoized on the project — the pass and the artifact writer run
    in the same CLI invocation and the walk is the expensive part."""
    cached = getattr(project, "_lock_order_cache", None)
    if cached is None:
        cached = LockOrderAnalysis(project)
        project._lock_order_cache = cached
    return cached


class LockOrderPass(ProjectPass):
    rule = "LOCK002"
    title = "lock-order deadlock detection"

    def run_project(
        self, project: ProjectGraph, ctx: AnalysisContext
    ) -> list:
        analysis = get_analysis(project)
        findings: list[Finding] = []
        for lock_id, rel, line, via in sorted(
            set(analysis.self_deadlocks)
        ):
            decl = analysis.locks[lock_id]
            how = (
                "re-enters it with a nested 'with'"
                if via == "with"
                else "calls a function that re-acquires it"
            )
            findings.append(
                Finding(
                    rule=self.rule,
                    path=project.modules[rel].path,
                    line=line,
                    message=(
                        f"non-reentrant lock '{lock_id}' ({decl.kind}) is "
                        f"already held here and this {how} — guaranteed "
                        "self-deadlock"
                    ),
                    hint=(
                        "hoist the inner acquisition out, add a _locked "
                        "variant of the callee, or make the lock an RLock "
                        "as a design decision"
                    ),
                )
            )
        for comp in analysis.cycles():
            # one example edge per hop, for an actionable message
            hops = []
            ordered = list(comp) + [comp[0]]
            for a, b in zip(ordered, ordered[1:]):
                site = analysis.edges.get((a, b))
                where = f" ({site[0]}:{site[1]})" if site else ""
                hops.append(f"{a} -> {b}{where}")
            anchor = min(
                (
                    analysis.edges[(a, b)]
                    for (a, b) in analysis.edges
                    if a in comp and b in comp
                ),
                default=("", 1, ""),
            )
            rel = anchor[0] or next(iter(sorted(project.modules)))
            findings.append(
                Finding(
                    rule=self.rule,
                    path=project.modules[rel].path,
                    line=anchor[1],
                    message=(
                        "lock-order cycle (potential deadlock): "
                        + "; ".join(hops)
                    ),
                    hint=(
                        "pick one global order (docs/LOCK_ORDER.md) and "
                        "restructure the later acquisition to happen "
                        "outside the held region"
                    ),
                )
            )
        return findings


def lock_order_markdown(project: ProjectGraph) -> str:
    """The committed artifact: every declared lock in its proven
    acquisition order, plus the observed acquired-while-holding edges
    with one example site each."""
    analysis = get_analysis(project)
    cycles = analysis.cycles()
    lines = [
        "# Lock acquisition order",
        "",
        "Generated by `python -m kubernetes_tpu.analysis "
        "--write-lock-order`; CI re-derives it and fails on drift "
        "(`--check-lock-order`). Acquire locks strictly in the order "
        "below — LOCK002 proves the observed acquired-while-holding "
        "graph is cycle-free against this file.",
        "",
        "## Order",
        "",
        "| # | lock | kind | declared at |",
        "|---|------|------|-------------|",
    ]
    if cycles:
        lines.append("")
        lines.append(
            "**CYCLE DETECTED** — no valid order exists: "
            + "; ".join(" <-> ".join(c) for c in cycles)
        )
    else:
        for i, lock_id in enumerate(analysis.order(), 1):
            d = analysis.locks[lock_id]
            lines.append(
                f"| {i} | `{lock_id}` | {d.kind} | `{d.rel}:{d.line}` |"
            )
    lines += [
        "",
        "## Observed acquired-while-holding edges",
        "",
        "| held | then acquired | example site |",
        "|------|---------------|--------------|",
    ]
    for (a, b) in sorted(analysis.edges):
        rel, line, kind = analysis.edges[(a, b)]
        lines.append(
            f"| `{a}` | `{b}` | `{rel}:{line}` ({kind}) |"
        )
    lines.append("")
    return "\n".join(lines)
