"""Feature gates — the component-base/featuregate analog (SURVEY §3.3:
[BOUNDARY] — a simple known-gate map with the reference's flag syntax).

Reference behavior mirrored (component-base/featuregate/feature_gate.go):
- gates parse from one ``--feature-gates`` string: "A=true,B=false";
- unknown gate names are an error (Set returns err upstream);
- each gate has a default; the map is queried, not scattered booleans.

Gates wired to real behavior in this framework:
- SchedulerQueueingHints (default on, upstream beta-on): when off, cluster
  events move every parked pod (the pre-hints reference behavior) instead
  of consulting the fit-gated isPodWorthRequeuing predicates.
- PodSchedulingReadiness (default on, upstream GA): when off,
  .spec.schedulingGates are ignored and gated pods enqueue normally
  (pre-1.26 behavior).
- DynamicResourceAllocation (default off, matching the upstream beta
  gate): when on, pods referencing ResourceClaims are filtered to nodes
  whose ResourceSlices satisfy the claims, devices are allocated at
  Reserve, and allocation + reservedFor are written at PreBind
  (api/dra.py, ops/oracle/dra.py, state/claim_allocator.py — scope and
  divergences documented there).
"""

from __future__ import annotations

from dataclasses import dataclass, field

KNOWN_GATES: dict[str, bool] = {
    "SchedulerQueueingHints": True,
    "PodSchedulingReadiness": True,
    "DynamicResourceAllocation": False,
}


@dataclass
class FeatureGates:
    overrides: dict[str, bool] = field(default_factory=dict)
    warnings: list[str] = field(default_factory=list)

    def enabled(self, name: str) -> bool:
        if name not in KNOWN_GATES:
            raise KeyError(f"unknown feature gate {name!r}")
        return self.overrides.get(name, KNOWN_GATES[name])

    @staticmethod
    def parse(spec: str | None) -> "FeatureGates":
        """Parse "A=true,B=false" (the --feature-gates flag syntax).
        Unknown names raise ValueError, like the reference's Set()."""
        fg = FeatureGates()
        if not spec:
            return fg
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"feature gate {part!r}: expected name=bool"
                )
            name, _, val = part.partition("=")
            name = name.strip()
            if name not in KNOWN_GATES:
                raise ValueError(f"unknown feature gate {name!r}")
            lv = val.strip().lower()
            if lv not in ("true", "false"):
                raise ValueError(
                    f"feature gate {name}: invalid value {val!r}"
                )
            fg.overrides[name] = lv == "true"
        return fg
