"""LOCK001 — lock discipline for annotated shared attributes.

The pipelined loop shares mutable scheduler state (conflict fence,
session staleness, in-flight bookkeeping) between the drain thread and
watch-event ingest. The discipline is declared, not inferred: an
attribute assignment in ``__init__`` carrying ``# ktpu:
guarded-by(cluster.lock)`` registers the attribute, and every other
read or write of ``self.<attr>`` in the class must then sit lexically
inside ``with self.cluster.lock:`` (any alias spelled exactly
``self.<lockexpr>``) or in a function annotated ``# ktpu:
holds(cluster.lock)`` (asserting every caller already holds it — watch
callbacks fire under the cluster lock, for example).

The check is lexical: a nested function defined outside a ``with`` but
only ever *called* inside one needs a ``holds`` annotation (that is the
documentation the rule exists to force). ``__init__`` itself is exempt
(no concurrent readers before construction completes).
"""

from __future__ import annotations

import ast

from ..core import Finding, Pass, SourceModule


class LockDisciplinePass(Pass):
    rule = "LOCK001"
    title = "guarded attribute accessed without its lock"

    def run(self, module, ctx):
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node, module, findings)
        return findings

    def _check_class(
        self, cls: ast.ClassDef, module: SourceModule, findings: list
    ) -> None:
        guarded = self._collect_guarded(cls, module)
        if not guarded:
            return
        for stmt in cls.body:
            if (
                isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name != "__init__"
            ):
                held = set()
                h = module.holds_lock(stmt)
                if h:
                    held.add(h)
                for sub in ast.iter_child_nodes(stmt):
                    self._visit(
                        sub, guarded, held, module, findings, stmt.name
                    )

    def _collect_guarded(
        self, cls: ast.ClassDef, module: SourceModule
    ) -> dict[str, str]:
        guarded: dict[str, str] = {}
        init = next(
            (
                s
                for s in cls.body
                if isinstance(s, ast.FunctionDef) and s.name == "__init__"
            ),
            None,
        )
        if init is None:
            return guarded
        for stmt in ast.walk(init):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            lock = module.guarded_by(stmt)
            if lock is None:
                continue
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    guarded[t.attr] = lock
        return guarded

    def _visit(
        self, node, guarded, held, module, findings, funcname
    ) -> None:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in guarded
            and guarded[node.attr] not in held
        ):
            lock = guarded[node.attr]
            findings.append(
                Finding(
                    self.rule, module.path, node.lineno,
                    f"'{node.attr}' is guarded by '{lock}' but accessed "
                    f"outside 'with self.{lock}' in '{funcname}'",
                    hint=f"wrap the access in 'with self.{lock}:', or "
                    f"annotate the function '# ktpu: holds({lock})' if "
                    "every caller already holds it",
                )
            )
            return
        if isinstance(node, ast.With):
            added = set()
            locks = set(guarded.values())
            for item in node.items:
                self._visit(
                    item.context_expr, guarded, held, module, findings,
                    funcname,
                )
                expr = ast.unparse(item.context_expr)
                for lock in locks:
                    if expr in (f"self.{lock}", lock):
                        added.add(lock)
            for sub in node.body:
                self._visit(
                    sub, guarded, held | added, module, findings, funcname
                )
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            h = module.holds_lock(node)
            inner = held | ({h} if h else set())
            for sub in ast.iter_child_nodes(node):
                self._visit(sub, guarded, inner, module, findings, node.name)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, guarded, held, module, findings, funcname)
