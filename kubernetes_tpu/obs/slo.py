"""Live SLO engine: are we meeting latency SLOs *right now*, answered
from counters the scheduling loops already tick — no bench ladder run,
no new device syncs (the PR 13 ``CounterWindow`` sampling discipline:
host-side reads of numbers the apply path already materialized).

One ``SloEngine`` per Scheduler, ticked from ``_record_metrics`` (the
chokepoint every dispatch loop — sync, pipelined, streaming, drain —
funnels applied batches through):

- **sliding-window pod latency** — p50/p99 of first-enqueue→bind (the
  ladder's sustained-latency definition, ``BatchResult.e2e_latencies``,
  already computed per batch) over a bounded sample pool;
- **bind throughput** — pods bound per wall second over the window;
- **multi-window error-budget burn rate** — the SRE burn-rate form:
  (observed bad fraction) / (allowed bad fraction), where an event is
  *bad* when a bound pod missed the latency objective or a binding
  failed. A burn of 1.0 consumes the budget exactly at the sustainable
  rate; the short window catches fast burns, the long window slow ones;
- **degraded-health signal** — ``healthy`` flips false while the short
  window burns faster than ``degraded_burn`` (with a minimum event
  count so an idle scheduler's first hiccup cannot flip it). Consumers:
  the fleet tier publishes it through the occupancy exchange so handoff
  chains route refugees to healthy replicas (the breaker's degraded
  flag discipline), and the resilience layer defers half-open breaker
  probes while it is set (don't re-probe a suspect top tier while the
  error budget is already burning).

Exported as the ``scheduler_slo_*`` metric family and served as one
JSON document at ``GET /debug/slo``.

Everything is driver-thread-only host arithmetic off the injectable
``Clock`` — a FakeClock sim drive produces deterministic SLO output.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .. import metrics


@dataclass
class SloConfig:
    """Carried on ``ObsConfig.slo`` (None = engine off)."""

    # per-pod latency objective, first queue entry -> bind commit
    latency_objective_s: float = 30.0
    # target fraction of events meeting the objective; the error budget
    # is (1 - target)
    availability_target: float = 0.99
    # sliding window backing p50/p99 + throughput
    window_s: float = 300.0
    # multi-window burn rates, shortest first (the shortest also drives
    # the degraded-health signal)
    burn_windows: tuple = (60.0, 300.0, 3600.0)
    # short-window burn rate beyond which health reads degraded
    degraded_burn: float = 2.0
    # minimum events in the short window before health may flip (an
    # idle scheduler's only pod failing must not read as an outage)
    min_events: int = 20
    # bounded latency sample pool (memory cap; the window prune usually
    # bounds it first)
    sample_capacity: int = 4096
    # minimum seconds between quantile/throughput gauge recomputations:
    # the percentile sort over the sample pool is the engine's one
    # non-O(1) step, and re-sorting per batch at sustained-stream batch
    # rates is measurable against the obs-overhead budget. Health/burn
    # still evaluate every observe (cheap bucket loop). 0 = every
    # observe (tests).
    export_interval_s: float = 1.0

    def validate(self) -> None:
        if self.latency_objective_s <= 0:
            raise ValueError("slo.latency_objective_s must be > 0")
        if not 0.0 < self.availability_target < 1.0:
            raise ValueError("slo.availability_target must be in (0, 1)")
        if not self.burn_windows or any(
            w <= 0 for w in self.burn_windows
        ):
            raise ValueError("slo.burn_windows must be positive")


def _quantile(sorted_vals: list, q: float) -> float:
    """Nearest-rank quantile over an already-sorted list (the ladder's
    p99 formula: index 0.99 * (n - 1))."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[int(q * (len(sorted_vals) - 1))]


@dataclass
class _Bucket:
    """One observed batch: timestamp + good/bad event counts + bound
    pods (throughput numerator)."""

    t: float
    good: int
    bad: int
    bound: int


class SloEngine:
    """Driver-thread-only; every mutation happens inside the scheduler's
    metrics-recording chokepoint."""

    def __init__(self, config: SloConfig | None, clock) -> None:
        self.config = config or SloConfig()
        self.config.validate()
        self.clock = clock
        # (t, latency) samples inside the sliding window
        self._latencies: deque = deque(
            maxlen=self.config.sample_capacity
        )
        # per-batch event buckets, pruned to the LONGEST burn window
        self._buckets: deque[_Bucket] = deque()
        # incremental short-window accounting (the per-observe health
        # check must be O(1), not a bucket scan — an hour-long horizon
        # holds ~1e5 buckets at sustained-stream batch rates): a
        # second deque over the SHORT window only, with running sums
        self._short: deque[_Bucket] = deque()
        self._short_good = 0
        self._short_bad = 0
        self.healthy = True
        self.degraded_flips = 0  # python-side counter (sim footers)
        self._last_export = float("-inf")
        # callbacks fired with the new health bool on every flip (the
        # scheduler wires the fleet degraded flag + resilience here)
        self.on_health_change: list = []
        self._burn_gauges = {
            w: metrics.slo_error_budget_burn.labels(f"{int(w)}s")
            for w in self.config.burn_windows
        }
        metrics.slo_healthy.set(1)

    # -- ingest --

    def observe_batch(self, res) -> None:
        """Fold one applied ``BatchResult`` in: bound pods' e2e
        latencies, bind failures as budget-burning events."""
        now = self.clock.now()
        cfg = self.config
        bad = sum(
            1 for x in res.e2e_latencies if x > cfg.latency_objective_s
        )
        bad += len(res.bind_failures)
        good = len(res.e2e_latencies) - (bad - len(res.bind_failures))
        bound = len(res.scheduled)
        for x in res.e2e_latencies:
            self._latencies.append((now, x))
        if good or bad or bound:
            bucket = _Bucket(now, good, bad, bound)
            self._buckets.append(bucket)
            self._short.append(bucket)
            self._short_good += good
            self._short_bad += bad
        self._prune(now)
        self._export(now)

    def _prune(self, now: float) -> None:
        w = self.config.window_s
        while self._latencies and now - self._latencies[0][0] > w:
            self._latencies.popleft()
        horizon = max(self.config.burn_windows)
        while self._buckets and now - self._buckets[0].t > horizon:
            self._buckets.popleft()
        short = self.config.burn_windows[0]
        while self._short and now - self._short[0].t > short:
            b = self._short.popleft()
            self._short_good -= b.good
            self._short_bad -= b.bad

    # -- the numbers --

    def latency_quantiles(self) -> tuple[float, float]:
        vals = sorted(x for _, x in self._latencies)
        return _quantile(vals, 0.5), _quantile(vals, 0.99)

    def throughput(self, now: float | None = None) -> float:
        """Pods bound per second over the sliding window (ratio of
        sums — the CounterWindow.rate discipline). 0.0 until the
        window spans any time at all: the first batch's bucket is
        stamped with the same clock reading `now` carries, and
        dividing by that near-zero span would export an absurd
        pods/nanosecond gauge (review-caught)."""
        now = self.clock.now() if now is None else now
        w = self.config.window_s
        bound = sum(b.bound for b in self._buckets if now - b.t <= w)
        if not bound:
            return 0.0
        ts = [b.t for b in self._buckets if now - b.t <= w]
        span = now - min(ts)
        if span <= 1e-3:
            return 0.0  # one instant is not a rate
        return bound / span

    def burn_rate(self, window_s: float, now: float | None = None) -> float:
        """Error-budget burn over the trailing ``window_s``: observed
        bad fraction / allowed bad fraction. 0.0 with no events."""
        now = self.clock.now() if now is None else now
        good = bad = 0
        for b in self._buckets:
            if now - b.t <= window_s:
                good += b.good
                bad += b.bad
        total = good + bad
        if not total:
            return 0.0
        budget = 1.0 - self.config.availability_target
        return (bad / total) / max(budget, 1e-9)

    def window_events(self, window_s: float, now: float | None = None) -> int:
        now = self.clock.now() if now is None else now
        return sum(
            b.good + b.bad for b in self._buckets if now - b.t <= window_s
        )

    # -- export + health --

    def _export(self, now: float) -> None:
        if now - self._last_export >= self.config.export_interval_s:
            self._last_export = now
            p50, p99 = self.latency_quantiles()
            metrics.slo_p50_pod_latency_seconds.set(p50)
            metrics.slo_p99_pod_latency_seconds.set(p99)
            metrics.slo_bind_throughput.set(self.throughput(now))
            for w, gauge in self._burn_gauges.items():
                gauge.set(self.burn_rate(w, now))
        self._eval_health()

    def _eval_health(self) -> None:
        # O(1) health check off the incremental short-window sums
        short_events = self._short_good + self._short_bad
        budget = 1.0 - self.config.availability_target
        short_burn = (
            (self._short_bad / short_events) / max(budget, 1e-9)
            if short_events
            else 0.0
        )
        healthy = not (
            short_events >= self.config.min_events
            and short_burn > self.config.degraded_burn
        )
        if healthy != self.healthy:
            self.healthy = healthy
            self.degraded_flips += 1
            metrics.slo_healthy.set(1 if healthy else 0)
            for cb in self.on_health_change:
                cb(healthy)

    def tick(self) -> None:
        """Time-only re-evaluation: prune aged buckets and re-check
        health WITHOUT a new batch. Without this, a degraded flip
        would latch forever once traffic stops — the bad events age
        out of the short window arithmetically, but observe_batch
        (the only other evaluation point) never runs on an idle
        scheduler, and the degraded flag routing work away can make
        the idleness self-sustaining (review-caught). Called from
        ``snapshot`` (any /debug read heals) and the scheduler's
        ``pending`` poll (the serve drain loop's idle heartbeat)."""
        self._prune(self.clock.now())
        self._eval_health()

    def snapshot(self) -> dict:
        """The ``GET /debug/slo`` body: one consistent host-side cut
        (also a time-only health re-evaluation point — see tick)."""
        self.tick()
        now = self.clock.now()
        p50, p99 = self.latency_quantiles()
        return {
            "healthy": self.healthy,
            "latency_objective_s": self.config.latency_objective_s,
            "availability_target": self.config.availability_target,
            "window_s": self.config.window_s,
            "p50_pod_latency_s": round(p50, 6),
            "p99_pod_latency_s": round(p99, 6),
            "bind_throughput_pods_per_sec": round(
                self.throughput(now), 3
            ),
            "burn_rates": {
                f"{int(w)}s": round(self.burn_rate(w, now), 4)
                for w in self.config.burn_windows
            },
            "window_events": self.window_events(
                max(self.config.burn_windows), now
            ),
            "degraded_flips": self.degraded_flips,
        }
