"""Device snapshot: the double-buffer between the scheduler cache and the
solver's HBM tensors.

Reference: pkg/scheduler/backend/cache/snapshot.go#Snapshot +
cache.go#UpdateSnapshot — the incremental O(changed-nodes) contract. Here
"copying a NodeInfo" becomes rewriting one column of the [K, N] arrays
(a dirty-column scatter); node add/remove manages slots (removed nodes leave
invalid slots that are reused) so node indices stay stable between updates —
important because the solver returns node *indices* and compiled shapes only
change when capacity grows (pow2 growth to bound XLA recompiles).
"""

from __future__ import annotations

import numpy as np

from ..api.objects import RESOURCE_PODS, Node
from ..tensorize.schema import LANE, NodeBatch, ResourceVocab, bucket_pow2
from .cache import SchedulerCache


class Snapshot:
    def __init__(self) -> None:
        self.batch: NodeBatch | None = None
        # node-padding multiple beyond the LANE/pow2 bucket: the mesh
        # device count when the solve is sharded over the node axis (a
        # NamedSharding needs the trailing axis evenly divisible). Set by
        # the Scheduler from SchedulerConfig.mesh_devices before the
        # first update; padding columns stay valid=False/schedulable=
        # False so they are masked out of every filter/score/argmax path.
        self.pad_multiple = 1
        self.names: list[str] = []  # slot -> node name ("" = free)
        self._slot_of: dict[str, int] = {}
        self._free: list[int] = []
        self._last_generation = -1
        # per-column write versions: every column (re)write bumps its entry
        # from a monotonic counter. Device-resident solver sessions compare
        # against the version they last uploaded and re-heal only columns
        # written since — the device-side analog of the generation-based
        # incremental UpdateSnapshot contract.
        self.col_versions: np.ndarray = np.zeros(0, dtype=np.int64)
        self._col_counter = 0

    def _bump_col(self, i: int) -> None:
        self._col_counter += 1
        self.col_versions[i] = self._col_counter

    def touch(self, slot: int) -> None:
        """Force-mark a column dirty for device sessions. Used when host-side
        bookkeeping for a solver-made placement failed (e.g. assume rejected)
        so the device state may hold a placement the cache never saw."""
        self._bump_col(slot)

    def slot_of(self, name: str) -> int:
        return self._slot_of[name]

    def name_of(self, slot: int) -> str:
        return self.names[slot]

    # -- internals --

    def _ensure_capacity(self, n: int, vocab: ResourceVocab) -> None:
        cap = 0 if self.batch is None else self.batch.padded
        if n <= cap and self.batch is not None and tuple(vocab.names) == tuple(
            self.batch.vocab.names
        ):
            return
        # never shrink: existing slot indices must remain valid
        new_cap = bucket_pow2(max(n, cap, LANE))
        if self.pad_multiple > 1:
            # keep LANE alignment AND device-count divisibility (the
            # sharded node axis): round up to lcm(LANE, devices). For
            # power-of-two device counts <= LANE this is a no-op.
            import math

            q = math.lcm(LANE, self.pad_multiple)
            new_cap = ((new_cap + q - 1) // q) * q
        k = len(vocab)
        old = self.batch
        b = NodeBatch(
            vocab=vocab,
            names=[],
            num_nodes=0,
            padded=new_cap,
            allocatable=np.zeros((k, new_cap), dtype=np.int64),
            used=np.zeros((k, new_cap), dtype=np.int64),
            nonzero_used=np.zeros((2, new_cap), dtype=np.int64),
            pod_count=np.zeros(new_cap, dtype=np.int32),
            max_pods=np.zeros(new_cap, dtype=np.int32),
            valid=np.zeros(new_cap, dtype=bool),
            schedulable=np.zeros(new_cap, dtype=bool),
        )
        if old is not None and tuple(vocab.names) == tuple(old.vocab.names):
            c = old.padded
            b.allocatable[:, :c] = old.allocatable
            b.used[:, :c] = old.used
            b.nonzero_used[:, :c] = old.nonzero_used
            b.pod_count[:c] = old.pod_count
            b.max_pods[:c] = old.max_pods
            b.valid[:c] = old.valid
            b.schedulable[:c] = old.schedulable
            self.batch = b
        else:
            self.batch = b
            if old is not None:
                # vocab changed: every occupied column must be rewritten
                self._last_generation = -1
        self.names.extend([""] * (new_cap - len(self.names)))
        if len(self.col_versions) < new_cap:
            grown = np.zeros(new_cap, dtype=np.int64)
            grown[: len(self.col_versions)] = self.col_versions
            self.col_versions = grown

    def _required_vocab(self, cache: SchedulerCache) -> ResourceVocab:
        cur = self.batch.vocab if self.batch is not None else None
        needed: set[str] = set()
        for info in cache.nodes.values():
            if info.node is not None:
                needed.update(info.node.allocatable.keys())
            needed.update(k for k, v in info.used.items() if v)
        needed.discard(RESOURCE_PODS)
        if cur is not None and needed.issubset(cur.names):
            return cur
        from ..tensorize.schema import BASE_RESOURCES

        extended = sorted(needed - set(BASE_RESOURCES))
        return ResourceVocab(BASE_RESOURCES + tuple(extended))

    def _write_column(self, i: int, info, vocab: ResourceVocab) -> None:
        b = self.batch
        node = info.node
        b.allocatable[:, i] = vocab.vectorize(node.allocatable)
        b.used[:, i] = vocab.vectorize(info.used)
        b.nonzero_used[0, i] = info.nonzero_cpu
        b.nonzero_used[1, i] = info.nonzero_mem
        b.pod_count[i] = len(info.pods)
        b.max_pods[i] = node.allocatable.get(RESOURCE_PODS, 0)
        b.valid[i] = True
        b.schedulable[i] = not node.unschedulable
        self._bump_col(i)

    # -- the public incremental update --

    def update(self, cache: SchedulerCache) -> NodeBatch:
        """cache.go#UpdateSnapshot: refresh only what changed."""
        vocab = self._required_vocab(cache)
        live = {
            name: info
            for name, info in cache.nodes.items()
            if info.node is not None
        }
        new_count = sum(1 for name in live if name not in self._slot_of)
        self._ensure_capacity(len(self._slot_of) + new_count, vocab)
        b = self.batch

        # removals: slots whose node vanished (or became pod-only ghost)
        for name in list(self._slot_of):
            if name not in live:
                i = self._slot_of.pop(name)
                self.names[i] = ""
                b.valid[i] = False
                b.schedulable[i] = False
                self._free.append(i)
                self._bump_col(i)

        # additions + dirty rewrites. Fresh slots must dodge EVERY taken
        # slot, not just count up from the pre-add maximum: a removal can
        # free a HIGH slot in this same update, and once _free hands it
        # out, a max+1 counter sitting below it would walk back up and
        # assign the same slot twice — two nodes sharing one column, the
        # second _write_column silently erasing the first node's usage
        # (device tables then understate and the solver overcommits;
        # caught by the sim harness's capacity invariant under node-churn
        # profiles).
        taken = set(self._slot_of.values())
        next_slot = 0
        for name, info in live.items():
            i = self._slot_of.get(name)
            if i is None:
                if self._free:
                    i = self._free.pop()
                else:
                    while next_slot in taken:
                        next_slot += 1
                    i = next_slot
                taken.add(i)
                self._slot_of[name] = i
                self.names[i] = name
                self._write_column(i, info, vocab)
            elif info.generation > self._last_generation:
                self._write_column(i, info, vocab)

        self._last_generation = cache.generation
        b.num_nodes = len(self._slot_of)
        b.names = [n for n in self.names if n]
        return b
