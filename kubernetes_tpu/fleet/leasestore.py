"""File-backed hub lease: the ``HubLease`` surface over SQLite.

``fleet/ha.py``'s in-memory ``HubLease`` coordinates hubs within one
process tree; its scope note promises the same interface over a real
coordination store for multi-host deployments. ``SqliteHubLease`` is
that store, provable OFFLINE: one row in one SQLite file (stdlib
``sqlite3`` — nothing to install), every transition a ``BEGIN
IMMEDIATE`` transaction so two hub processes racing on the same file
serialize at the database lock, and the epoch — the fencing token —
PERSISTED, so a restarted coordination store can never hand out a
reused epoch (monotone gaps are harmless, a reused epoch is not).

Semantics mirror ``HubLease`` exactly — the failover suite runs
against both backends:

- ``try_acquire`` by the incumbent is a renewal (no epoch bump); a new
  holder only acquires after the incumbent's lease EXPIRED, and every
  ownership change bumps the epoch;
- ``renew`` refuses a non-holder and an already-expired holder;
- ``release`` expires the lease without rewinding the epoch.

The injectable clock keeps the failover sim fully virtual-time; wall
time never touches the stored state (``renewed_at`` is whatever the
clock said, compared against the same clock later).
"""

from __future__ import annotations

import contextlib
import sqlite3
import threading

__all__ = ["SqliteHubLease"]

_SCHEMA = """
CREATE TABLE IF NOT EXISTS hub_lease (
    id INTEGER PRIMARY KEY CHECK (id = 0),
    holder TEXT,
    epoch INTEGER NOT NULL,
    renewed_at REAL
)
"""

# renewed_at NULL encodes "never renewed" (float('-inf') in the
# in-memory lease): IEEE infinities don't survive every SQLite
# round-trip, NULL does
_SEED = (
    "INSERT OR IGNORE INTO hub_lease (id, holder, epoch, renewed_at) "
    "VALUES (0, NULL, 0, NULL)"
)


class SqliteHubLease:
    """``HubLease`` over one SQLite file. Safe across threads AND
    processes: each call opens its own connection (no shared handle to
    trip ``check_same_thread``) and mutates inside ``BEGIN
    IMMEDIATE``, so concurrent acquirers serialize at the file lock
    exactly like the in-memory lease serializes at its mutex."""

    def __init__(
        self, path, clock=None, duration_s: float = 10.0
    ) -> None:
        from ..utils.clock import Clock

        self._clock = clock or Clock()
        self.duration_s = float(duration_s)
        self._path = str(path)
        # local serialization for same-process callers: cheaper than
        # colliding on SQLITE_BUSY, and mirrors HubLease's mutex
        self._lock = threading.Lock()
        with self._connect() as db:
            db.execute(_SCHEMA)
            db.execute(_SEED)
            db.commit()

    def _connect(self):
        return contextlib.closing(
            sqlite3.connect(
                self._path, timeout=5.0, isolation_level=None
            )
        )

    @staticmethod
    def _row(db):
        holder, epoch, renewed_at = db.execute(
            "SELECT holder, epoch, renewed_at FROM hub_lease "
            "WHERE id = 0"
        ).fetchone()
        renewed = (
            float("-inf") if renewed_at is None else float(renewed_at)
        )
        return holder, int(epoch), renewed

    @property
    def epoch(self) -> int:
        with self._lock, self._connect() as db:
            return self._row(db)[1]

    @property
    def holder(self) -> str | None:
        with self._lock, self._connect() as db:
            return self._row(db)[0]

    def try_acquire(self, holder: str) -> int | None:
        """Grant (or re-confirm) the lease — the in-memory contract,
        transactional: takeover only after the incumbent expired,
        ownership changes bump the PERSISTED epoch, the incumbent
        re-acquiring is a renewal at its current epoch."""
        with self._lock, self._connect() as db:
            now = self._clock.now()
            db.execute("BEGIN IMMEDIATE")
            try:
                cur, epoch, renewed = self._row(db)
                if cur == holder:
                    db.execute(
                        "UPDATE hub_lease SET renewed_at = ? "
                        "WHERE id = 0",
                        (now,),
                    )
                    db.execute("COMMIT")
                    return epoch
                if cur is None or now - renewed > self.duration_s:
                    db.execute(
                        "UPDATE hub_lease SET holder = ?, "
                        "epoch = epoch + 1, renewed_at = ? "
                        "WHERE id = 0",
                        (holder, now),
                    )
                    db.execute("COMMIT")
                    return epoch + 1
                db.execute("COMMIT")
                return None
            except BaseException:
                db.execute("ROLLBACK")
                raise

    def renew(self, holder: str) -> bool:
        with self._lock, self._connect() as db:
            now = self._clock.now()
            db.execute("BEGIN IMMEDIATE")
            try:
                cur, _epoch, renewed = self._row(db)
                if cur != holder or now - renewed > self.duration_s:
                    db.execute("COMMIT")
                    return False
                db.execute(
                    "UPDATE hub_lease SET renewed_at = ? WHERE id = 0",
                    (now,),
                )
                db.execute("COMMIT")
                return True
            except BaseException:
                db.execute("ROLLBACK")
                raise

    def valid(self, holder: str) -> bool:
        with self._lock, self._connect() as db:
            cur, _epoch, renewed = self._row(db)
            return (
                cur == holder
                and self._clock.now() - renewed <= self.duration_s
            )

    def release(self, holder: str) -> None:
        """Expire without waiting out the duration; the epoch is NOT
        rewound (the in-memory lease's rule, now durable)."""
        with self._lock, self._connect() as db:
            db.execute("BEGIN IMMEDIATE")
            try:
                cur, _epoch, _renewed = self._row(db)
                if cur == holder:
                    db.execute(
                        "UPDATE hub_lease SET renewed_at = NULL "
                        "WHERE id = 0"
                    )
                db.execute("COMMIT")
            except BaseException:
                db.execute("ROLLBACK")
                raise
