"""CLI: ``python -m kubernetes_tpu.analysis [--json] [paths...]``.

Exit status 0 when every finding is suppressed (with a reason), 1
otherwise — scripts/lint.py and the tier-1 gate both key on this.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import ALL_PASSES, run_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="Tracer-safety & lock-discipline static analyzer.",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories (default: the kubernetes_tpu package)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings (including suppressed) as a JSON array",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings in text mode",
    )
    args = parser.parse_args(argv)

    try:
        findings = run_paths(args.paths or None)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.as_json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        shown = findings if args.show_suppressed else active
        for f in shown:
            print(f.render())
        rules = ", ".join(c.rule for c in ALL_PASSES)
        print(
            f"{len(active)} finding(s), {len(suppressed)} suppressed "
            f"(passes: {rules})"
        )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
