"""Fixture-builder DSL for tests, modeled on
pkg/scheduler/testing/wrappers.go#MakePod / #MakeNode.

Upstream tests read like::

    st.MakePod().Name("p").Req(map[...]{cpu: "100m"}).NodeAffinityIn(...).Obj()

Ours::

    MakePod().name("p").req({"cpu": "100m"}).node_affinity_in("k", ["v"]).obj()
"""

from __future__ import annotations

from typing import Mapping, Sequence

from .labels import (
    IN,
    NOT_IN,
    Requirement,
    Selector,
    selector_from_match_labels,
)
from .objects import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from .quantity import canonical_requests


class MakePod:
    def __init__(self) -> None:
        self._pod = Pod()
        self._containers: list[Container] = []
        self._init_containers: list[Container] = []

    # -- metadata --
    def name(self, n: str) -> "MakePod":
        self._pod.name = n
        return self

    def namespace(self, ns: str) -> "MakePod":
        self._pod.namespace = ns
        return self

    def uid(self, u: str) -> "MakePod":
        self._pod.uid = u
        return self

    def label(self, k: str, v: str) -> "MakePod":
        self._pod.labels[k] = v
        return self

    def labels(self, m: Mapping[str, str]) -> "MakePod":
        self._pod.labels.update(m)
        return self

    def annotation(self, k: str, v: str) -> "MakePod":
        self._pod.annotations[k] = v
        return self

    # -- spec --
    def node(self, n: str) -> "MakePod":
        self._pod.node_name = n
        return self

    def scheduler_name(self, n: str) -> "MakePod":
        self._pod.scheduler_name = n
        return self

    def priority(self, p: int) -> "MakePod":
        self._pod.priority = p
        return self

    def preemption_policy(self, p: str) -> "MakePod":
        self._pod.preemption_policy = p
        return self

    def scheduling_gates(self, gates: Sequence[str]) -> "MakePod":
        self._pod.scheduling_gates = tuple(gates)
        return self

    def node_selector(self, sel: Mapping[str, str]) -> "MakePod":
        self._pod.node_selector.update(sel)
        return self

    def req(self, requests: Mapping[str, str | int]) -> "MakePod":
        """Add a container with the given resource requests (wrappers.go#Req)."""
        self._containers.append(
            Container(
                name=f"con{len(self._containers)}",
                requests=canonical_requests(dict(requests)),
            )
        )
        return self

    def init_req(
        self, requests: Mapping[str, str | int], restart_policy: str = ""
    ) -> "MakePod":
        self._init_containers.append(
            Container(
                name=f"init{len(self._init_containers)}",
                requests=canonical_requests(dict(requests)),
                restart_policy=restart_policy,
            )
        )
        return self

    def container_image(self, image: str, requests: Mapping[str, str | int] | None = None) -> "MakePod":
        self._containers.append(
            Container(
                name=f"con{len(self._containers)}",
                requests=canonical_requests(dict(requests or {})),
                images=(image,),
            )
        )
        return self

    def host_port(self, port: int, protocol: str = "TCP", host_ip: str = "") -> "MakePod":
        self._containers.append(
            Container(
                name=f"con{len(self._containers)}",
                ports=(ContainerPort(host_port=port, protocol=protocol, host_ip=host_ip),),
            )
        )
        return self

    def overhead(self, requests: Mapping[str, str | int]) -> "MakePod":
        self._pod.overhead = canonical_requests(dict(requests))
        return self

    def toleration(
        self, key: str = "", value: str = "", operator: str = "Equal", effect: str = ""
    ) -> "MakePod":
        self._pod.tolerations = self._pod.tolerations + (
            Toleration(key=key, operator=operator, value=value, effect=effect),
        )
        return self

    def _node_affinity(self) -> NodeAffinity:
        aff = self._pod.affinity or Affinity()
        na = aff.node_affinity or NodeAffinity()
        return na

    def _set_node_affinity(self, na: NodeAffinity) -> None:
        aff = self._pod.affinity or Affinity()
        self._pod.affinity = Affinity(
            node_affinity=na,
            pod_affinity=aff.pod_affinity,
            pod_anti_affinity=aff.pod_anti_affinity,
        )

    def node_affinity_in(self, key: str, vals: Sequence[str]) -> "MakePod":
        """Required node affinity: key In vals (wrappers.go#NodeAffinityIn)."""

        na = self._node_affinity()
        term = NodeSelectorTerm(
            match_expressions=Selector((Requirement(key, IN, tuple(vals)),)),
            empty=False,
        )
        self._set_node_affinity(
            NodeAffinity(required=(na.required or ()) + (term,), preferred=na.preferred)
        )
        return self

    def node_affinity_not_in(self, key: str, vals: Sequence[str]) -> "MakePod":

        na = self._node_affinity()
        term = NodeSelectorTerm(
            match_expressions=Selector((Requirement(key, NOT_IN, tuple(vals)),)),
            empty=False,
        )
        self._set_node_affinity(
            NodeAffinity(required=(na.required or ()) + (term,), preferred=na.preferred)
        )
        return self

    def preferred_node_affinity(self, weight: int, key: str, vals: Sequence[str]) -> "MakePod":

        na = self._node_affinity()
        term = PreferredSchedulingTerm(
            weight=weight,
            preference=NodeSelectorTerm(
                match_expressions=Selector((Requirement(key, IN, tuple(vals)),)),
                empty=False,
            ),
        )
        self._set_node_affinity(
            NodeAffinity(required=na.required, preferred=na.preferred + (term,))
        )
        return self

    def _pod_affinity_parts(self) -> tuple[PodAffinity, PodAffinity]:
        aff = self._pod.affinity or Affinity()
        return (aff.pod_affinity or PodAffinity(), aff.pod_anti_affinity or PodAffinity())

    def _set_pod_affinity(self, pa: PodAffinity, anti: PodAffinity) -> None:
        aff = self._pod.affinity or Affinity()
        self._pod.affinity = Affinity(
            node_affinity=aff.node_affinity,
            pod_affinity=pa if (pa.required or pa.preferred) else None,
            pod_anti_affinity=anti if (anti.required or anti.preferred) else None,
        )

    def pod_affinity(
        self, topology_key: str, match_labels: Mapping[str, str], anti: bool = False
    ) -> "MakePod":
        """Required pod (anti-)affinity with a matchLabels selector
        (wrappers.go#PodAffinityExists-style helpers)."""

        term = PodAffinityTerm(
            label_selector=selector_from_match_labels(dict(match_labels)),
            topology_key=topology_key,
        )
        pa, paa = self._pod_affinity_parts()
        if anti:
            paa = PodAffinity(required=paa.required + (term,), preferred=paa.preferred)
        else:
            pa = PodAffinity(required=pa.required + (term,), preferred=pa.preferred)
        self._set_pod_affinity(pa, paa)
        return self

    def pod_anti_affinity(self, topology_key: str, match_labels: Mapping[str, str]) -> "MakePod":
        return self.pod_affinity(topology_key, match_labels, anti=True)

    def preferred_pod_affinity(
        self,
        weight: int,
        topology_key: str,
        match_labels: Mapping[str, str],
        anti: bool = False,
    ) -> "MakePod":

        wterm = WeightedPodAffinityTerm(
            weight=weight,
            term=PodAffinityTerm(
                label_selector=selector_from_match_labels(dict(match_labels)),
                topology_key=topology_key,
            ),
        )
        pa, paa = self._pod_affinity_parts()
        if anti:
            paa = PodAffinity(required=paa.required, preferred=paa.preferred + (wterm,))
        else:
            pa = PodAffinity(required=pa.required, preferred=pa.preferred + (wterm,))
        self._set_pod_affinity(pa, paa)
        return self

    def spread_constraint(
        self,
        max_skew: int,
        topology_key: str,
        when_unsatisfiable: str = "DoNotSchedule",
        match_labels: Mapping[str, str] | None = None,
        min_domains: int | None = None,
    ) -> "MakePod":

        sel = (
            selector_from_match_labels(dict(match_labels))
            if match_labels is not None
            else None
        )
        self._pod.topology_spread_constraints = self._pod.topology_spread_constraints + (
            TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topology_key,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=sel,
                min_domains=min_domains,
            ),
        )
        return self

    def pvc(self, claim_name: str) -> "MakePod":
        self._pod.pvc_names = self._pod.pvc_names + (claim_name,)
        return self

    def resource_claim(self, claim_name: str) -> "MakePod":
        """spec.resourceClaims[].resourceClaimName reference (DRA)."""
        self._pod.resource_claim_names = self._pod.resource_claim_names + (
            claim_name,
        )
        return self

    def nominated_node_name(self, n: str) -> "MakePod":
        self._pod.nominated_node_name = n
        return self

    def start_time(self, t: float) -> "MakePod":
        self._pod.start_time = t
        return self

    def obj(self) -> Pod:
        self._pod.containers = tuple(self._containers) or (Container(name="con0"),)
        self._pod.init_containers = tuple(self._init_containers)
        return self._pod


class MakeNode:
    def __init__(self) -> None:
        self._node = Node()

    def name(self, n: str) -> "MakeNode":
        self._node.name = n
        if "kubernetes.io/hostname" not in self._node.labels:
            self._node.labels["kubernetes.io/hostname"] = n
        return self

    def label(self, k: str, v: str) -> "MakeNode":
        self._node.labels[k] = v
        return self

    def capacity(self, res: Mapping[str, str | int]) -> "MakeNode":
        """Sets both capacity and allocatable (wrappers.go#Capacity)."""
        c = canonical_requests(dict(res))
        self._node.capacity = dict(c)
        self._node.allocatable = dict(c)
        return self

    def allocatable(self, res: Mapping[str, str | int]) -> "MakeNode":
        self._node.allocatable = canonical_requests(dict(res))
        return self

    def taint(self, key: str, value: str = "", effect: str = "NoSchedule") -> "MakeNode":
        self._node.taints = self._node.taints + (Taint(key, value, effect),)
        return self

    def unschedulable(self, v: bool = True) -> "MakeNode":
        self._node.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "MakeNode":
        self._node.images = self._node.images + (
            ContainerImage(names=(name,), size_bytes=size_bytes),
        )
        return self

    def obj(self) -> Node:
        return self._node
