"""Fleet sim drive (sim/fleet.py): determinism, fleet-vs-single
binding equivalence, replica loss, and known-bad fixtures for the new
no-global-overcommit and fleet journal-completeness invariants."""

from kubernetes_tpu.sim.fleet import FleetSimHarness, run_fleet_sim
from kubernetes_tpu.sim.invariants import (
    check_fleet_journal_completeness,
    check_no_global_overcommit,
)
from kubernetes_tpu.sim.generators import make_node, make_pod
from kubernetes_tpu.state.cluster import ClusterState


def test_fleet_drive_clean_and_deterministic():
    a = run_fleet_sim("fleet_mixed", seed=0, cycles=6)
    assert a.ok, [v.as_dict() for v in a.violations]
    assert a.replicas == 2
    assert a.summary["unbound"] == 0
    b = run_fleet_sim("fleet_mixed", seed=0, cycles=6)
    assert a.journal_digests == b.journal_digests
    assert a.bindings == b.bindings
    # a different seed takes a different path (the digests actually
    # carry information)
    c = run_fleet_sim("fleet_mixed", seed=1, cycles=6)
    assert c.journal_digests != a.journal_digests


def test_fleet_bindings_equivalent_to_single_modulo_ownership():
    """ISSUE 6 acceptance: the fleet-of-2 drive binds exactly the pod
    set the single-scheduler drive binds (nodes may differ — that IS
    the shard ownership). Holds because fleet profiles generate an
    identical event stream either way (no external binds / shrinks)."""
    from kubernetes_tpu.sim.harness import run_sim

    fleet = run_fleet_sim("fleet_mixed", seed=1, cycles=8)
    single = run_sim("fleet_mixed", seed=1, cycles=8)
    assert fleet.ok, [v.as_dict() for v in fleet.violations]
    assert single.ok
    assert set(fleet.bindings) == set(single.bindings)


def test_replica_loss_reowns_shard_and_completes_journals():
    res = run_fleet_sim("replica_loss", seed=0, cycles=8)
    assert res.ok, [v.as_dict() for v in res.violations]
    assert res.summary["lost_replica"] == "r1"
    assert res.summary["alive"] == 1
    # the survivor owns the whole cluster after the loss
    h = FleetSimHarness("replica_loss", seed=0, cycles=8)
    res2 = h.run()
    assert res2.ok
    survivor = h.schedulers["r0"]
    with h.cluster.lock:
        assert all(
            r == "r0" for r in survivor.fleet._assignment.values()
        )
    # every node in the cluster is in the survivor's cache
    live = {n.name for n in h.cluster.list_nodes()}
    cached = {
        n
        for n, info in survivor.cache.nodes.items()
        if info.node is not None
    }
    assert live == cached
    # both replicas actually bound work before/after the loss
    assert all(v > 0 for v in res.summary["binds_by_replica"].values())


def test_fleet_drive_exercises_cross_shard_machinery():
    """The fleet_mixed profile must actually drive the exchange (rows
    staged/committed) — otherwise the reconcile path is dead code in
    the smoke."""
    from kubernetes_tpu import metrics

    def rows(op):
        return metrics.fleet_occupancy_rows_total.labels(op)._value.get()

    staged0, committed0 = rows("staged"), rows("committed")
    res = run_fleet_sim("fleet_mixed", seed=2, cycles=6)
    assert res.ok
    assert rows("staged") > staged0
    assert rows("committed") > committed0


# -- known-bad fixtures --


def _tiny_cluster():
    cs = ClusterState()
    cs.create_node(make_node("n0", "2", "4Gi"))
    cs.create_node(make_node("n1", "2", "4Gi"))
    return cs


def test_no_global_overcommit_flags_foreign_bind():
    """Ownership fixture: a bind reported by a replica that does NOT
    own the node must violate, even with capacity intact."""
    cs = _tiny_cluster()
    cs.create_pod(make_pod("p0", "1"))
    cs.bind("default", "p0", "n0")
    violations: list = []
    check_no_global_overcommit(
        cs, 0, violations,
        binds=[("r1", "default/p0", "n0")],
        owners={"n0": "r0", "n1": "r1"},
    )
    assert any(
        v.invariant == "global_overcommit" and "r1" in v.detail
        for v in violations
    )


def test_no_global_overcommit_flags_capacity_breach():
    """Capacity fixture: two replicas double-booking one node trips
    the global capacity half regardless of ownership claims."""
    cs = _tiny_cluster()
    for i in range(3):
        cs.create_pod(make_pod(f"p{i}", "1"))
        cs.bind("default", f"p{i}", "n0")  # 3 cpu onto a 2-cpu node
    violations: list = []
    check_no_global_overcommit(
        cs, 0, violations,
        binds=[
            ("r0", "default/p0", "n0"),
            ("r0", "default/p1", "n0"),
            ("r1", "default/p2", "n0"),
        ],
        owners={"n0": "r0", "n1": "r1"},
    )
    kinds = {v.invariant for v in violations}
    assert "capacity" in kinds  # the overcommit itself
    assert "global_overcommit" in kinds  # r1's foreign bind


def test_no_global_overcommit_clean_case_passes():
    cs = _tiny_cluster()
    cs.create_pod(make_pod("p0", "1"))
    cs.bind("default", "p0", "n0")
    violations: list = []
    check_no_global_overcommit(
        cs, 0, violations,
        binds=[("r0", "default/p0", "n0")],
        owners={"n0": "r0", "n1": "r1"},
    )
    assert violations == []


class _JournalStub:
    def __init__(self, lines):
        self.lines = lines


class _SchedStub:
    def __init__(self, lines, solvers=("default-scheduler",)):
        self.journal = _JournalStub(lines)
        self.solvers = {name: None for name in solvers}

        class _Q:
            @staticmethod
            def entries():
                return {}

        self.queue = _Q()


def _dec(pod, outcome, t, step=1, replica="r0"):
    import json

    return json.dumps(
        {
            "k": "dec", "v": 1, "step": step, "cycle": 1, "pod": pod,
            "uid": "", "outcome": outcome, "t": t, "replica": replica,
        },
        sort_keys=True,
    )


def test_fleet_journal_completeness_merges_across_replicas():
    """A pod handed off (non-terminal 'discarded' on r0) and then
    bound by r1 is COMPLETE fleet-wide; the single-replica view alone
    would flag it."""
    cs = _tiny_cluster()
    cs.create_pod(make_pod("p0", "1"))
    cs.bind("default", "p0", "n1")
    r0 = _SchedStub([_dec("default/p0", "discarded", 1.0, replica="r0")])
    r1 = _SchedStub([_dec("default/p0", "bound", 2.0, replica="r1")])
    violations: list = []
    check_fleet_journal_completeness(
        cs, [r0, r1], 0, violations, {"default/p0"}
    )
    assert violations == []


def test_fleet_journal_completeness_flags_orphaned_pod():
    """Known-bad: an unbound pod whose merged history ends
    non-terminal (the replica-loss blind spot this invariant exists
    to close)."""
    cs = _tiny_cluster()
    cs.create_pod(make_pod("p0", "1"))  # never bound
    r0 = _SchedStub([_dec("default/p0", "discarded", 1.0)])
    r1 = _SchedStub([])
    violations: list = []
    check_fleet_journal_completeness(cs, [r0, r1], 0, violations, set())
    assert any(
        v.invariant == "journal" and "non-terminal" in v.detail
        for v in violations
    )
    # ...and one that never journaled anywhere
    cs.create_pod(make_pod("p1", "1"))
    violations2: list = []
    check_fleet_journal_completeness(cs, [r0, r1], 0, violations2, set())
    assert any(
        "never appeared" in v.detail for v in violations2
    )


def test_fleet_journal_completeness_flags_unjournaled_bind():
    cs = _tiny_cluster()
    cs.create_pod(make_pod("p0", "1"))
    cs.bind("default", "p0", "n0")
    r0 = _SchedStub([])
    violations: list = []
    check_fleet_journal_completeness(
        cs, [r0], 0, violations, {"default/p0"}
    )
    assert any(
        v.invariant == "journal" and "bound" in v.detail
        for v in violations
    )


def test_fleet_harness_rejects_unsound_profiles():
    import pytest

    with pytest.raises(ValueError, match="prompt delivery"):
        FleetSimHarness("churn_heavy", seed=0, cycles=2)


def test_hub_partition_zombie_fenced_and_conservative():
    """The ISSUE-8 partition scenario: the last replica is cut off
    from the occupancy hub with its lease observed stale. 100% of its
    bind attempts while fenced must reject with Conflict (the
    commit-fence invariant), conservative admission must reject
    cross-shard-risky placements while rows are aged out, and after
    the heal the fleet settles clean."""
    res = run_fleet_sim("hub_partition", seed=0, cycles=8)
    assert res.violations == []
    assert res.settled
    s = res.summary
    assert s["zombie"] == "r1"
    assert s["fenced_commits"]["r1"] >= 1  # the zombie really tried
    assert s["zombie_binds_while_fenced"] == 0  # ...and never landed one
    assert s["stale_rejections"] >= 1  # conservative admission engaged
    # determinism across the partition/heal boundary
    res2 = run_fleet_sim("hub_partition", seed=0, cycles=8)
    assert res.journal_digests == res2.journal_digests
