"""Consistent node-partition ring for the fleet tier: a deterministic,
zone-affine, balance-capped assignment of cluster nodes to scheduler
replicas.

Requirements (ISSUE 6 / ROADMAP open item #1):

- **deterministic** — the partition is a pure function of (node set,
  configured replica universe, alive subset): blake2b-keyed hashing and
  sorted iteration everywhere, no dependence on insertion order or
  PYTHONHASHSEED, so every replica computes the identical partition
  independently with no coordination;
- **zone-keyed affinity** — nodes sharing a topology zone share one
  replica-preference chain and are laid out contiguously in the
  canonical order, so a zone lands on as few replicas as balance
  allows (cross-shard ``PodTopologySpread`` domains — the constraint
  family the reconciliation round exists for — are minimized at the
  partitioning layer);
- **balanced** — no replica owns more than ``ceil(K / N_alive)``
  nodes, so a replica's shard (and therefore its per-batch solve cost)
  is bounded by construction, and losing one replica orphans at most a
  1/N-ish slice of the cluster (blast-radius isolation);
- **bounded remap** — one replica joining or leaving remaps at most
  ``ceil(K / N)`` nodes (tests/test_fleet_ring.py).

The bound is structural, not probabilistic. A membership change in a
lease-based fleet is an *availability* change against a configured
universe (a replica's per-shard lease expires, or a restarted replica
re-acquires it), so the partition is two-layered:

1. **base partition** — a greedy capacity-capped rendezvous fill of
   all nodes over the full configured universe, in canonical zone
   order. Fixed for a fixed universe: it never moves at runtime.
2. **orphan redistribution** — nodes whose base owner is dead are
   re-dealt over the alive replicas (zone-keyed rendezvous chains,
   capacity ``ceil(K / N_alive)``). Alive replicas always keep their
   base nodes (base load ``<= ceil(K / N_universe) <=`` any alive cap),
   so a single leave moves exactly the leaver's owned nodes and a
   single rejoin moves exactly the nodes that had been redistributed —
   both ``<= ceil(K / N)``.

Growing the universe itself (scale-out from N to N+1 *configured*
replicas) recomputes the base partition and is a deploy-time
repartition, not a runtime membership event; the remap bound applies
to runtime join/leave only.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Iterable, Mapping

ZONE_LABEL = "topology.kubernetes.io/zone"


def _h(*parts: str) -> int:
    """Stable 64-bit hash of joined parts (PYTHONHASHSEED-immune)."""
    d = hashlib.blake2b(
        "\x1f".join(parts).encode(), digest_size=8
    ).digest()
    return int.from_bytes(d, "little")


@dataclass(frozen=True)
class RingNode:
    """One placeable node as the ring sees it: name + zone key (empty
    when the node carries no zone label — such nodes get per-node
    preference chains instead of a shared zone chain)."""

    name: str
    zone: str = ""


class HashRing:
    """The fleet's node partitioner. Stateless: ``assign`` recomputes
    the full partition from (universe, alive) membership; callers diff
    the result against their previous view to find the (bounded)
    remap set."""

    def __init__(
        self, universe: Iterable[str], alive: Iterable[str] | None = None
    ) -> None:
        self.universe = tuple(sorted(set(universe)))
        if not self.universe:
            raise ValueError("ring needs at least one configured replica")
        self.alive = (
            self.universe
            if alive is None
            else tuple(sorted(set(alive) & set(self.universe)))
        )
        if not self.alive:
            raise ValueError("ring needs at least one alive replica")

    def with_alive(self, alive: Iterable[str]) -> "HashRing":
        return HashRing(self.universe, alive)

    # -- preference chains --

    @staticmethod
    def _prefs(key: str, replicas: tuple[str, ...]) -> list[str]:
        """Rendezvous ranking of ``replicas`` for one zone (or zoneless
        node) key: highest blake2b(key, replica) wins. Stable under
        membership change: restricting the replica set drops entries
        from the chain without reordering the rest."""
        return sorted(replicas, key=lambda r: (-_h(key, r), r))

    @staticmethod
    def _chain_key(node: RingNode) -> str:
        return node.zone if node.zone else f"\x00node\x1f{node.name}"

    @staticmethod
    def canonical_order(nodes: Iterable[RingNode]) -> list[RingNode]:
        """Zone-contiguous canonical order: zones sort by hash (so the
        fill order is uncorrelated with zone naming), nodes within a
        zone by hash-then-name. Every replica iterates nodes in exactly
        this order, which is what makes the greedy capped fill a pure
        function of membership."""
        return sorted(
            nodes,
            key=lambda n: (
                _h("zone", n.zone), n.zone, _h("node", n.name), n.name,
            ),
        )

    def _fill(
        self,
        ordered: list[RingNode],
        replicas: tuple[str, ...],
        cap: int,
        load: dict[str, int],
        out: dict[str, str],
    ) -> None:
        """Greedy capacity-capped rendezvous fill of ``ordered`` over
        ``replicas``: each node goes to the first replica in its
        zone-keyed preference chain with remaining capacity."""
        pref_cache: dict[str, list[str]] = {}
        for node in ordered:
            key = self._chain_key(node)
            prefs = pref_cache.get(key)
            if prefs is None:
                prefs = self._prefs(key, replicas)
                pref_cache[key] = prefs
            for r in prefs:
                if load[r] < cap:
                    load[r] += 1
                    out[node.name] = r
                    break

    # -- the partition --

    def assign(self, nodes: Iterable[RingNode]) -> dict[str, str]:
        """node name -> alive replica id for the full node set."""
        ordered = self.canonical_order(nodes)
        k = len(ordered)
        if k == 0:
            return {}
        # layer 1: the base partition over the full universe (cap
        # ceil(K / N_universe)); total capacity >= K, the fill always
        # succeeds
        base: dict[str, str] = {}
        base_load = {r: 0 for r in self.universe}
        self._fill(
            ordered, self.universe, -(-k // len(self.universe)),
            base_load, base,
        )
        if self.alive == self.universe:
            return base
        # layer 2: redistribute orphans (nodes whose base owner is
        # dead) over the alive set. Alive base assignments are kept
        # verbatim — base load <= ceil(K/N_universe) <= alive cap, so
        # they can never be displaced — which is exactly what bounds a
        # single leave/rejoin to the departed replica's own share.
        alive = set(self.alive)
        cap = -(-k // len(self.alive))
        out: dict[str, str] = {}
        load = {r: 0 for r in self.alive}
        orphans: list[RingNode] = []
        for node in ordered:
            owner = base[node.name]
            if owner in alive:
                out[node.name] = owner
                load[owner] += 1
            else:
                orphans.append(node)
        self._fill(orphans, self.alive, cap, load, out)
        # a pathological chain restriction could leave an orphan's
        # whole chain at cap when zones are few and lopsided; total
        # capacity still covers K, so sweep into any remaining room
        for node in orphans:
            if node.name not in out:
                r = min(
                    (r for r in self.alive if load[r] < cap),
                    key=lambda r: (-_h(self._chain_key(node), r), r),
                )
                load[r] += 1
                out[node.name] = r
        return out

    def owner(self, assignment: Mapping[str, str], name: str) -> str | None:
        return assignment.get(name)

    # -- pod routing (the queue partition) --

    def route(self, pod_key: str) -> str:
        """Unbound-pod routing: rendezvous over the pod key and the
        ALIVE set, no capacity cap (pods are transient queue entries,
        not owned state). Every replica computes the same route, so
        exactly one alive replica enqueues each pending pod."""
        return max(self.alive, key=lambda r: (_h("pod", pod_key, r), r))


def ring_nodes_from(nodes: Iterable) -> list[RingNode]:
    """Adapt api.objects.Node instances (anything with ``name`` and
    ``labels``) to RingNodes, zone-keyed on the well-known label."""
    return [
        RingNode(name=n.name, zone=n.labels.get(ZONE_LABEL, ""))
        for n in nodes
    ]
