"""End-to-end slice: create nodes + pods in the cluster-state service, run the
batched TPU scheduler, verify all bindings land and match the sequential
oracle — the integration-test tier of SURVEY.md §5 (real scheduler + in-proc
'apiserver', bare Node objects, no kubelets)."""

import numpy as np

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle import scheduler as osched
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ApiError, ClusterState


def mk_cluster(n_nodes, cpu="4", mem="8Gi", pods="110"):
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode().name(f"node-{i:04}").capacity({"cpu": cpu, "memory": mem, "pods": pods}).obj()
        )
    return cs


def first_tiebreak_config(batch=1024):
    return SchedulerConfig(
        batch_size=batch,
        solver=ExactSolverConfig(tie_break="first", balanced_fdtype="float64"),
    )


class TestEndToEnd:
    def test_all_pods_bound(self):
        cs = mk_cluster(8)
        sched = Scheduler(cs, first_tiebreak_config())
        for i in range(40):
            cs.create_pod(MakePod().name(f"p{i:03}").req({"cpu": "200m", "memory": "256Mi"}).obj())
        results = sched.run_until_settled()
        scheduled = [x for r in results for x in r.scheduled]
        assert len(scheduled) == 40
        assert all(p.node_name for p in cs.list_pods())
        assert sched.pending == 0

    def test_bindings_match_sequential_oracle(self):
        cs = mk_cluster(5)
        node_objs = cs.list_nodes()
        pods = [
            MakePod().name(f"p{i:03}").req({"cpu": f"{100 + 70 * (i % 7)}m", "memory": f"{256 + 128 * (i % 3)}Mi"}).obj()
            for i in range(30)
        ]
        sched = Scheduler(cs, first_tiebreak_config())
        for p in pods:
            cs.create_pod(p)
        sched.run_until_settled()
        # oracle replay in creation order (same as queue order: equal
        # priority, FIFO timestamps)
        oracle = osched.schedule(pods, osched.make_node_states(node_objs))
        name_by_idx = [n.name for n in node_objs]
        want = {
            p.key: (name_by_idx[a] if a >= 0 else None)
            for p, a in zip(pods, oracle.assignments)
        }
        got = {p.key: (p.node_name or None) for p in cs.list_pods()}
        assert got == want

    def test_infeasible_pods_parked_then_rescued_by_node_add(self):
        cs = mk_cluster(1, cpu="1")
        sched = Scheduler(cs, first_tiebreak_config())
        cs.create_pod(MakePod().name("big").req({"cpu": "3"}).obj())
        results = sched.run_until_settled()
        assert results[0].unschedulable == ["default/big"]
        assert cs.get_pod("default", "big").node_name == ""
        # a big node appears -> queue moves the pod back (after backoff)
        cs.create_node(MakeNode().name("big-node").capacity({"cpu": "8", "memory": "8Gi", "pods": "10"}).obj())
        import time as _t

        deadline = _t.monotonic() + 5
        bound = False
        while _t.monotonic() < deadline:
            sched.queue.flush_backoff_completed()
            rs = sched.run_until_settled()
            if any(r.scheduled for r in rs):
                bound = True
                break
            _t.sleep(0.2)
        assert bound
        assert cs.get_pod("default", "big").node_name == "big-node"

    def test_bind_conflict_forgets_and_requeues(self):
        cs = mk_cluster(2)
        sched = Scheduler(cs, first_tiebreak_config())
        fail_once = {"n": 1}

        def fault(pod, node_name):
            if fail_once["n"]:
                fail_once["n"] -= 1
                raise ApiError("Conflict", "injected bind conflict")

        cs.bind_fault = fault
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())
        r1 = sched.run_until_settled()
        assert any(bf for r in r1 for bf in r.bind_failures)
        # cache must hold no leaked assumption
        assert sched.cache.nodes["node-0000"].used.get("cpu", 0) == 0
        assert sched.cache.nodes["node-0001"].used.get("cpu", 0) == 0
        # retry succeeds after backoff
        sched.queue.move_all_to_active_or_backoff("test")
        import time as _t

        _t.sleep(1.1)
        sched.queue.flush_backoff_completed()
        r2 = sched.run_until_settled()
        assert any(r.scheduled for r in r2)
        assert cs.get_pod("default", "p").node_name != ""

    def test_priority_order_across_batches(self):
        # higher-priority pods must be placed first even when created later
        cs = mk_cluster(1, cpu="1", pods="2")
        sched = Scheduler(cs, first_tiebreak_config(batch=16))
        cs.create_pod(MakePod().name("low-a").priority(1).req({"cpu": "400m"}).obj())
        cs.create_pod(MakePod().name("low-b").priority(1).req({"cpu": "400m"}).obj())
        cs.create_pod(MakePod().name("high").priority(100).req({"cpu": "800m"}).obj())
        sched.run_until_settled()
        assert cs.get_pod("default", "high").node_name != ""
        bound_lows = [
            n for n in ("low-a", "low-b") if cs.get_pod("default", n).node_name
        ]
        assert len(bound_lows) == 0  # 800m + 400m > 1 cpu; pods cap=2 anyway

    def test_two_deployment_waves(self):
        cs = mk_cluster(4)
        sched = Scheduler(cs, first_tiebreak_config())
        for i in range(10):
            cs.create_pod(MakePod().name(f"a{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        for i in range(10):
            cs.create_pod(MakePod().name(f"b{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert sum(1 for p in cs.list_pods() if p.node_name) == 20
        # cache bookkeeping matches cluster truth
        per_node = {}
        for p in cs.list_pods():
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        for name, info in sched.cache.nodes.items():
            assert len(info.pods) == per_node.get(name, 0)


class TestEventsRecorder:
    """SURVEY §6.5 events row (VERDICT r3 #4): per-pod scheduling history
    through the events.k8s.io-shaped recorder, listable and watchable."""

    def test_scheduled_event_for_bound_pod(self):
        cs = mk_cluster(3)
        sched = Scheduler(cs, first_tiebreak_config())
        cs.create_pod(MakePod().name("ok").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        evs = cs.list_events(regarding_name="ok")
        assert [e.reason for e in evs] == ["Scheduled"]
        e = evs[0]
        assert e.type == "Normal" and e.regarding_kind == "Pod"
        node = cs.get_pod("default", "ok").node_name
        assert node and node in e.note
        # wire shape round-trips the events.k8s.io/v1 fields
        d = e.to_dict()
        assert d["kind"] == "Event" and d["regarding"]["name"] == "ok"

    def test_failed_scheduling_event_dedups_across_retries(self):
        from kubernetes_tpu.utils.clock import FakeClock

        cs = mk_cluster(2)
        sched = Scheduler(cs, first_tiebreak_config(), clock=FakeClock())
        cs.create_pod(MakePod().name("big").req({"cpu": "64"}).obj())
        sched.schedule_batch()
        # forced leftover flush -> second attempt -> same (reason, note)
        sched.clock.advance(301.0)
        sched.schedule_batch()
        evs = cs.list_events(regarding_name="big")
        assert [e.reason for e in evs] == ["FailedScheduling"]
        assert evs[0].count == 2  # correlator dedup, not two records
        assert evs[0].type == "Warning"
        assert "0/2 nodes are available" in evs[0].note

    def test_preemption_emits_victim_and_nominee_events(self):
        cs = mk_cluster(1, cpu="2")
        sched = Scheduler(cs, first_tiebreak_config())
        cs.create_pod(
            MakePod().name("victim").priority(0).req({"cpu": "2"}).obj()
        )
        sched.run_until_settled()
        cs.create_pod(
            MakePod().name("vip").priority(100).req({"cpu": "2"}).obj()
        )
        r = sched.schedule_batch()
        assert r.preemptions, "preemption must fire"
        v_evs = cs.list_events(regarding_name="victim")
        assert any(
            e.reason == "Preempted" and "default/vip" in e.note
            for e in v_evs
        )
        vip_evs = [e.reason for e in cs.list_events(regarding_name="vip")]
        assert "Nominated" in vip_evs and "FailedScheduling" in vip_evs

    def test_events_are_watchable(self):
        cs = mk_cluster(2)
        seen = []
        cs.subscribe(
            lambda ev: seen.append(ev) if ev.kind == "Event" else None
        )
        sched = Scheduler(cs, first_tiebreak_config())
        cs.create_pod(MakePod().name("w").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        assert any(
            ev.type == "ADDED" and ev.obj.reason == "Scheduled"
            for ev in seen
        )


def test_fit_error_reference_shaped_message():
    """An unschedulable pod's FailedScheduling event carries the
    reference's aggregated FitError diagnosis (schedule_one.go#FitError):
    per-reason node counts, not a generic rejection."""
    from kubernetes_tpu.api.wrappers import MakeNode, MakePod
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.state.cluster import ClusterState

    cs = ClusterState()
    # two nodes too small for the pod, one tainted
    for i in range(2):
        cs.create_node(
            MakeNode().name(f"small-{i}").capacity(
                {"cpu": "1", "memory": "1Gi", "pods": "10"}
            ).obj()
        )
    cs.create_node(
        MakeNode().name("tainted").capacity(
            {"cpu": "32", "memory": "64Gi", "pods": "10"}
        ).taint("dedicated", "gpu", "NoSchedule").obj()
    )
    sched = Scheduler(cs, SchedulerConfig(batch_size=8))
    cs.create_pod(
        MakePod().name("big").req({"cpu": "8", "memory": "2Gi"}).obj()
    )
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/big"]
    notes = [
        e.note
        for e in cs.list_events(regarding_name="big")
        if e.reason == "FailedScheduling"
    ]
    assert notes, "no FailedScheduling event"
    note = notes[-1]
    assert note.startswith("0/3 nodes are available:"), note
    assert "2 Insufficient cpu" in note, note
    assert "1 node(s) had untolerated taint(s)" in note, note
