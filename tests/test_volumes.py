"""Volume plugin family: oracle unit tests + solver parity + e2e."""

from kubernetes_tpu.api.objects import (
    NodeAffinity,
    PersistentVolume,
    PersistentVolumeClaim,
)
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
from kubernetes_tpu.ops.oracle.volumes import (
    VolumeContext,
    csi_limit_key,
    volume_filter,
)
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.tensorize.plugins import build_static_tensors
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)

GB = 1024**3


def zone_node(name, zone):
    return (
        MakeNode().name(name)
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
        .label("topology.kubernetes.io/zone", zone)
        .obj()
    )


def pv(name, zone=None, size=10 * GB, claim_ref="", sc="", driver="", modes=("ReadWriteOnce",)):
    labels = {"topology.kubernetes.io/zone": zone} if zone else {}
    return PersistentVolume(
        name=name, labels=labels, capacity_bytes=size, claim_ref=claim_ref,
        storage_class=sc, csi_driver=driver, access_modes=tuple(modes),
    )


def pvc(name, volume="", size=5 * GB, sc="", wffc=False, ns="default"):
    return PersistentVolumeClaim(
        name=name, namespace=ns, volume_name=volume, request_bytes=size,
        storage_class=sc, wait_for_first_consumer=wffc,
    )


# -- oracle unit tests ------------------------------------------------------


def test_bound_claim_zone_check():
    ctx = VolumeContext.build([pv("pv1", zone="z0")], [pvc("c1", volume="pv1")], {})
    pod = MakePod().name("p").pvc("c1").obj()
    assert volume_filter(pod, zone_node("a", "z0"), ctx)
    assert not volume_filter(pod, zone_node("b", "z1"), ctx)


def test_missing_claim_or_pv_fails():
    ctx = VolumeContext.build([], [], {})
    pod = MakePod().name("p").pvc("ghost").obj()
    assert not volume_filter(pod, zone_node("a", "z0"), ctx)
    ctx2 = VolumeContext.build([], [pvc("c1", volume="gone")], {})
    pod2 = MakePod().name("p").pvc("c1").obj()
    assert not volume_filter(pod2, zone_node("a", "z0"), ctx2)


def test_wait_for_first_consumer_defers():
    ctx = VolumeContext.build([], [pvc("c1", wffc=True)], {})
    pod = MakePod().name("p").pvc("c1").obj()
    assert volume_filter(pod, zone_node("a", "z0"), ctx)


def test_unbound_immediate_needs_matching_pv():
    # available PV only in z0, big enough, same class
    ctx = VolumeContext.build(
        [pv("pv1", zone="z0", size=10 * GB, sc="fast")],
        [pvc("c1", size=5 * GB, sc="fast")],
        {},
    )
    pod = MakePod().name("p").pvc("c1").obj()
    assert volume_filter(pod, zone_node("a", "z0"), ctx)
    assert not volume_filter(pod, zone_node("b", "z1"), ctx)
    # too-small PV fails
    ctx2 = VolumeContext.build(
        [pv("pv1", zone="z0", size=1 * GB, sc="fast")],
        [pvc("c1", size=5 * GB, sc="fast")],
        {},
    )
    assert not volume_filter(pod, zone_node("a", "z0"), ctx2)


def test_rwo_follows_holder():
    holder = MakePod().name("holder").node("a").pvc("c1").obj()
    ctx = VolumeContext.build(
        [pv("pv1")], [pvc("c1", volume="pv1")], {"a": [holder]}
    )
    pod = MakePod().name("p").pvc("c1").obj()
    assert volume_filter(pod, zone_node("a", "z0"), ctx)
    assert not volume_filter(pod, zone_node("b", "z0"), ctx)


def test_csi_volume_limits():
    n = (
        MakeNode().name("a")
        .capacity({
            "cpu": "8", "memory": "32Gi", "pods": "20",
            csi_limit_key("ebs.csi.aws.com"): "2",
        })
        .obj()
    )
    attached = [
        MakePod().name(f"e{i}").node("a").pvc(f"c{i}").obj() for i in range(2)
    ]
    pvs = [pv(f"pv{i}", driver="ebs.csi.aws.com") for i in range(3)]
    pvcs = [pvc(f"c{i}", volume=f"pv{i}") for i in range(3)]
    ctx = VolumeContext.build(pvs, pvcs, {"a": attached})
    pod = MakePod().name("p").pvc("c2").obj()
    assert not volume_filter(pod, n, ctx)  # 2 attached + 1 new > limit 2
    # node without the limit key accepts
    free = MakeNode().name("b").capacity({"cpu": "8", "pods": "20"}).obj()
    ctx2 = VolumeContext.build(pvs, pvcs, {})
    assert volume_filter(pod, free, ctx2)


def test_csi_limit_counts_unique_volumes():
    """Two pods sharing one PV consume ONE attachment slot (upstream counts
    distinct volume handles), and a pod referencing an already-attached
    volume adds no new slot."""
    n = (
        MakeNode().name("a")
        .capacity({
            "cpu": "8", "memory": "32Gi", "pods": "20",
            csi_limit_key("ebs.csi.aws.com"): "2",
        })
        .obj()
    )
    shared_pv = pv("pv-shared", driver="ebs.csi.aws.com", modes=("ReadWriteMany",))
    other_pv = pv("pv-other", driver="ebs.csi.aws.com")
    pvcs = [
        pvc("c-shared", volume="pv-shared"),
        pvc("c-shared2", volume="pv-shared"),
        pvc("c-other", volume="pv-other"),
    ]
    # two pods both using the shared PV: unique count on the node is 1
    attached = [
        MakePod().name("e0").node("a").pvc("c-shared").obj(),
        MakePod().name("e1").node("a").pvc("c-shared2").obj(),
    ]
    ctx = VolumeContext.build([shared_pv, other_pv], pvcs, {"a": attached})
    assert ctx.csi_count("a", "ebs.csi.aws.com") == 1
    # a new pod with a second distinct volume fits: 1 + 1 <= 2
    pod = MakePod().name("p").pvc("c-other").obj()
    assert volume_filter(pod, n, ctx)
    # a new pod re-referencing the ALREADY-ATTACHED volume adds nothing
    pod2 = MakePod().name("p2").pvc("c-shared").obj()
    assert volume_filter(pod2, n, ctx)


# -- solver parity ----------------------------------------------------------


def test_solver_parity_with_volumes():
    nodes = [zone_node(f"n{i}", f"z{i % 2}") for i in range(4)]
    pvs = [pv("pv-a", zone="z0"), pv("pv-b", zone="z1")]
    pvcs = [pvc("claim-a", volume="pv-a"), pvc("claim-b", volume="pv-b")]
    pods = [
        MakePod().name("pa").pvc("claim-a").req({"cpu": "1"}).obj(),
        MakePod().name("pb").pvc("claim-b").req({"cpu": "1"}).obj(),
        MakePod().name("free").req({"cpu": "1"}).obj(),
    ]
    ctx = VolumeContext.build(pvs, pvcs, {})
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - 4)
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded, ctx)
    a = ExactSolver(ExactSolverConfig(tie_break="first")).solve(
        nbatch, pbatch, static
    )
    assert int(a[0]) % 2 == 0  # z0
    assert int(a[1]) % 2 == 1  # z1
    oracle = FullOracle(make_oracle_nodes(nodes), volume_ctx=ctx)
    names = [nbatch.names[x] if x >= 0 else None for x in a]
    errors = oracle.validate_assignments(pods, list(a), names=names)
    assert not errors, errors[:3]


# -- e2e --------------------------------------------------------------------


def test_e2e_zonal_volume_scheduling():
    cs = ClusterState()
    for i in range(4):
        cs.create_node(zone_node(f"node-{i}", f"z{i % 2}"))
    cs.create_pv(pv("data-pv", zone="z1", size=20 * GB))
    cs.create_pvc(pvc("data", volume="data-pv"))
    sched = Scheduler(
        cs, SchedulerConfig(batch_size=8, solver=ExactSolverConfig(tie_break="first"))
    )
    cs.create_pod(MakePod().name("db").pvc("data").req({"cpu": "2"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 1
    _, node = r.scheduled[0]
    assert int(node.split("-")[1]) % 2 == 1  # z1 only
