"""kubernetes_tpu/obs/slo.py — the live SLO engine: sliding-window
latency quantiles, bind throughput, multi-window error-budget burn,
the degraded-health signal and its consumers (fleet degraded flag,
resilience probe deferral), and the /debug/slo snapshot."""

import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.obs import ObsConfig, SloConfig, SloEngine
from kubernetes_tpu.scheduler import BatchResult, Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock


def _batch(scheduled=0, latencies=(), bind_failures=0):
    res = BatchResult()
    res.scheduled = [(f"default/p{i}", "n0") for i in range(scheduled)]
    res.e2e_latencies = list(latencies)
    res.bind_failures = [
        (f"default/f{i}", "boom") for i in range(bind_failures)
    ]
    return res


def mk_engine(**kw):
    clock = FakeClock()
    cfg = SloConfig(
        latency_objective_s=kw.pop("objective", 1.0),
        availability_target=kw.pop("target", 0.9),
        window_s=kw.pop("window", 100.0),
        burn_windows=kw.pop("burn_windows", (10.0, 100.0)),
        degraded_burn=kw.pop("degraded_burn", 2.0),
        min_events=kw.pop("min_events", 4),
    )
    return SloEngine(cfg, clock), clock


class TestSloEngine:
    def test_quantiles_over_sliding_window(self):
        eng, clock = mk_engine()
        eng.observe_batch(
            _batch(scheduled=5, latencies=[0.1, 0.2, 0.3, 0.4, 0.5])
        )
        p50, p99 = eng.latency_quantiles()
        assert p50 == 0.3
        # nearest-rank (the ladder's formula): index int(0.99 * 4) = 3
        assert p99 == 0.4
        # samples age out of the window
        clock.advance(200.0)
        eng.observe_batch(_batch(scheduled=1, latencies=[0.9]))
        p50, p99 = eng.latency_quantiles()
        assert p50 == p99 == 0.9

    def test_first_batch_throughput_is_zero_not_absurd(self):
        """Review-caught: the first bucket's timestamp equals `now`,
        and dividing by that zero span exported pods/nanosecond."""
        eng, _ = mk_engine()
        eng.observe_batch(_batch(scheduled=256, latencies=[0.1] * 256))
        assert eng.throughput() == 0.0

    def test_tick_heals_degraded_health_without_traffic(self):
        """Review-caught: a degraded flip must not latch forever once
        traffic stops — the time-only tick re-evaluates after the bad
        events age out of the short window."""
        eng, clock = mk_engine(min_events=4)
        flips = []
        eng.on_health_change.append(flips.append)
        eng.observe_batch(_batch(scheduled=6, latencies=[5.0] * 6))
        assert not eng.healthy
        clock.advance(20.0)  # past the 10s short window; NO new batch
        eng.tick()
        assert eng.healthy
        assert flips == [False, True]

    def test_snapshot_is_a_tick_point(self):
        eng, clock = mk_engine(min_events=4)
        eng.observe_batch(_batch(scheduled=6, latencies=[5.0] * 6))
        assert not eng.healthy
        clock.advance(20.0)
        assert eng.snapshot()["healthy"] is True

    def test_throughput_is_ratio_of_sums(self):
        eng, clock = mk_engine()
        eng.observe_batch(_batch(scheduled=10, latencies=[0.1] * 10))
        clock.advance(5.0)
        eng.observe_batch(_batch(scheduled=10, latencies=[0.1] * 10))
        # 20 pods over the 5s span between first and latest bucket
        assert eng.throughput() == pytest.approx(4.0)

    def test_burn_rate_zero_when_meeting_objective(self):
        eng, _ = mk_engine()
        eng.observe_batch(_batch(scheduled=8, latencies=[0.2] * 8))
        assert eng.burn_rate(10.0) == 0.0
        assert eng.healthy

    def test_burn_rate_counts_latency_misses_and_bind_failures(self):
        eng, _ = mk_engine()
        # 4 good + 4 over-objective: bad fraction 0.5 vs budget 0.1
        eng.observe_batch(
            _batch(scheduled=8, latencies=[0.2] * 4 + [5.0] * 4)
        )
        assert eng.burn_rate(10.0) == pytest.approx(5.0)
        eng2, _ = mk_engine()
        eng2.observe_batch(_batch(scheduled=4, latencies=[0.1] * 4,
                                  bind_failures=4))
        assert eng2.burn_rate(10.0) == pytest.approx(5.0)

    def test_multi_window_burn_diverges(self):
        eng, clock = mk_engine()
        # old badness outside the short window, inside the long one
        eng.observe_batch(
            _batch(scheduled=4, latencies=[5.0] * 4)
        )
        clock.advance(50.0)
        eng.observe_batch(_batch(scheduled=4, latencies=[0.1] * 4))
        assert eng.burn_rate(10.0) == 0.0  # short window: clean
        assert eng.burn_rate(100.0) == pytest.approx(5.0)  # long: burning

    def test_health_flip_requires_min_events(self):
        eng, _ = mk_engine(min_events=10)
        eng.observe_batch(_batch(scheduled=4, latencies=[5.0] * 4))
        assert eng.healthy  # 4 events < min_events=10

    def test_health_flip_fires_callbacks_and_gauge(self):
        eng, clock = mk_engine(min_events=4)
        flips = []
        eng.on_health_change.append(flips.append)
        eng.observe_batch(_batch(scheduled=6, latencies=[5.0] * 6))
        assert not eng.healthy
        assert flips == [False]
        assert metrics.slo_healthy._value.get() == 0
        # the badness ages out of the short window -> health returns
        clock.advance(20.0)
        eng.observe_batch(_batch(scheduled=6, latencies=[0.1] * 6))
        assert eng.healthy
        assert flips == [False, True]
        assert eng.degraded_flips == 2

    def test_snapshot_shape(self):
        eng, _ = mk_engine()
        eng.observe_batch(_batch(scheduled=3, latencies=[0.1, 0.2, 0.3]))
        snap = eng.snapshot()
        assert snap["healthy"] is True
        # nearest-rank over 3 samples: index int(0.99 * 2) = 1
        assert snap["p99_pod_latency_s"] == 0.2
        assert set(snap["burn_rates"]) == {"10s", "100s"}
        assert snap["window_events"] == 3

    def test_config_validation(self):
        with pytest.raises(ValueError):
            SloConfig(latency_objective_s=0).validate()
        with pytest.raises(ValueError):
            SloConfig(availability_target=1.5).validate()
        with pytest.raises(ValueError):
            SloConfig(burn_windows=()).validate()


class TestSchedulerIntegration:
    def _cluster(self, n=3):
        cs = ClusterState()
        for i in range(n):
            cs.create_node(
                MakeNode()
                .name(f"n{i}")
                .capacity({"cpu": "4", "memory": "8Gi", "pods": "20"})
                .obj()
            )
        return cs

    def test_slo_engine_ticks_from_record_metrics(self):
        cs = self._cluster()
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=16,
                solver=ExactSolverConfig(tie_break="first"),
                obs=ObsConfig(slo=SloConfig(latency_objective_s=30.0)),
            ),
        )
        assert sched.slo is not None
        for i in range(4):
            cs.create_pod(
                MakePod().name(f"p{i}").namespace("default")
                .req({"cpu": "100m"}).obj()
            )
        res = sched.schedule_batch()
        assert len(res.scheduled) == 4
        # the tick runs post-commit, so the e2e latencies landed
        snap = sched.slo.snapshot()
        assert snap["window_events"] == 4
        assert snap["healthy"] is True
        assert len(sched.slo._latencies) == 4
        assert metrics.slo_p99_pod_latency_seconds._value.get() >= 0.0

    def test_no_slo_config_means_engine_off(self):
        cs = self._cluster()
        sched = Scheduler(
            cs,
            SchedulerConfig(obs=ObsConfig(spans=True, journal=True)),
        )
        assert sched.slo is None

    def test_slo_degradation_publishes_fleet_degraded_flag(self):
        """The degraded-health consumer the ISSUE names: an
        SLO-degraded replica publishes the exchange degraded flag so
        handoff chains route refugees elsewhere — and clears it when
        health returns, WITHOUT fighting the breaker's own flag."""
        from kubernetes_tpu.fleet import FleetConfig, OccupancyExchange

        cs = self._cluster()
        hub = OccupancyExchange()
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=16,
                solver=ExactSolverConfig(tie_break="first"),
                obs=ObsConfig(
                    slo=SloConfig(
                        latency_objective_s=0.001,  # everything misses
                        min_events=2,
                        burn_windows=(10.0, 100.0),
                    )
                ),
                fleet=FleetConfig(
                    replica="r0", replicas=("r0", "r1"), exchange=hub
                ),
            ),
            clock=FakeClock(),
        )
        res = _batch(scheduled=4, latencies=[5.0] * 4)
        sched._commit_all([], [], res)  # the post-commit SLO tick
        assert not sched.slo.healthy
        assert "r0" in hub.degraded_replicas()
        # breaker untouched: the flag clears when SLO health returns
        sched.clock.advance(20.0)
        sched._commit_all([], [], _batch(scheduled=4, latencies=[0.0] * 4))
        assert sched.slo.healthy
        assert "r0" not in hub.degraded_replicas()

    def test_debug_slo_endpoint(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from kubernetes_tpu.server.extender import ExtenderCore, make_app

        cs = self._cluster()
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=16,
                solver=ExactSolverConfig(tie_break="first"),
                obs=ObsConfig(slo=SloConfig(latency_objective_s=30.0)),
            ),
        )
        for i in range(3):
            cs.create_pod(
                MakePod().name(f"p{i}").namespace("default")
                .req({"cpu": "100m"}).obj()
            )
        sched.schedule_batch()
        core = ExtenderCore(cs, backend="oracle")
        app = make_app(core, slo=sched.slo)

        async def drive():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/debug/slo")
                assert r.status == 200
                doc = await r.json()
                assert doc["healthy"] is True
                assert doc["window_events"] == 3
                assert "burn_rates" in doc
            finally:
                await client.close()

        asyncio.new_event_loop().run_until_complete(drive())

    def test_debug_slo_404_when_disabled(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from kubernetes_tpu.server.extender import ExtenderCore, make_app

        cs = self._cluster()
        app = make_app(ExtenderCore(cs, backend="oracle"))

        async def drive():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/debug/slo")
                assert r.status == 404
            finally:
                await client.close()

        asyncio.new_event_loop().run_until_complete(drive())

    def test_slo_degradation_defers_breaker_probes(self):
        """Resilience consumption: a half-open probe whose fault
        window elapsed is DEFERRED while the SLO is degraded, and
        fires once health returns."""
        from kubernetes_tpu.resilience import SolveResilience, ResilienceConfig

        clock = FakeClock()
        r = SolveResilience(
            ResilienceConfig(trip_after=1, open_seconds=5.0),
            clock,
            ("mesh", "single", "cpu", "host"),
        )
        st = r._st("default")
        st.open_until[0] = clock.now() + 5.0
        clock.advance(10.0)  # window elapsed: probe due
        r.set_slo_degraded(True)
        idx, _tier = r.acquire("default")
        assert idx == 1  # probe deferred: serve at the next rung
        assert st.probing is None
        assert r.probes_deferred == 1
        r.set_slo_degraded(False)
        idx, _tier = r.acquire("default")
        assert idx == 0  # health returned: the probe fires
        assert st.probing == 0
