"""MET002 — two-way drift check: metrics registry vs docs/METRICS.md.

The registry (``kubernetes_tpu/metrics/__init__.py``) and the
documentation table are both hand-visible surfaces; PR 15 added four
metrics and the doc kept up only because a runtime gate
(``python -m kubernetes_tpu.metrics --check``) compares the RENDERED
document byte-for-byte. That gate needs a live prometheus import; this
pass is the analyzer-side equivalent — pure AST + text, so it runs in
the lint gate with zero runtime deps — and it is two-way:

- every metric registered in the module must appear in the doc table
  (finding anchored at the registration line);
- every ``| `name` |`` row in the doc must correspond to a registered
  metric (finding anchored at the doc row, path = the doc file).

Prometheus counters expose ``<name>_total`` even when registered
without the suffix; the comparison normalizes exactly like the doc
generator does.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from ..core import AnalysisContext, Finding
from ..project import ProjectGraph, ProjectPass

_METRIC_CLASSES = {"Counter", "Gauge", "Histogram", "Summary"}
_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")


def _registered(tree: ast.Module) -> list:
    """(exposed series name, line) per registry assignment."""
    out = []
    for stmt in tree.body:
        if not (
            isinstance(stmt, ast.Assign)
            and isinstance(stmt.value, ast.Call)
        ):
            continue
        f = stmt.value.func
        kind = (
            f.id
            if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else ""
        )
        if kind not in _METRIC_CLASSES or not stmt.value.args:
            continue
        arg = stmt.value.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            continue
        name = arg.value
        if kind == "Counter" and not name.endswith("_total"):
            name += "_total"
        out.append((name, stmt.lineno))
    return out


class MetricsDocPass(ProjectPass):
    rule = "MET002"
    title = "metrics registry <-> docs/METRICS.md drift"

    def run_project(
        self, project: ProjectGraph, ctx: AnalysisContext
    ) -> list:
        reg_rel = next(
            (
                rel
                for rel in sorted(project.modules)
                if rel.endswith(ctx.metrics_module_suffix)
            ),
            None,
        )
        if reg_rel is None:
            return []  # partial run (single file / fixtures without one)
        m = project.modules[reg_rel]
        registered = _registered(m.tree)

        doc_text = ctx.metrics_doc_text
        doc_label = "docs/METRICS.md"
        if doc_text is None:
            doc_path = (
                Path(m.path).resolve().parents[2] / "docs" / "METRICS.md"
            )
            doc_label = str(doc_path)
            if not doc_path.exists():
                return [
                    Finding(
                        rule=self.rule,
                        path=m.path,
                        line=1,
                        message="docs/METRICS.md not found",
                        hint=(
                            "generate it: python -m kubernetes_tpu."
                            "metrics --doc"
                        ),
                    )
                ]
            doc_text = doc_path.read_text()

        documented: dict[str, int] = {}
        for i, line in enumerate(doc_text.splitlines(), 1):
            row = _ROW_RE.match(line.strip())
            if row:
                documented.setdefault(row.group(1), i)

        findings: list[Finding] = []
        reg_names = {name for name, _ in registered}
        for name, line in registered:
            if name not in documented:
                findings.append(
                    Finding(
                        rule=self.rule,
                        path=m.path,
                        line=line,
                        message=(
                            f"metric '{name}' is registered but missing "
                            "from docs/METRICS.md"
                        ),
                        hint=(
                            "regenerate the table: python -m "
                            "kubernetes_tpu.metrics --doc"
                        ),
                    )
                )
        for name in sorted(documented):
            if name not in reg_names:
                findings.append(
                    Finding(
                        rule=self.rule,
                        path=doc_label,
                        line=documented[name],
                        message=(
                            f"documented metric '{name}' is not "
                            "registered in kubernetes_tpu/metrics"
                        ),
                        hint=(
                            "drop the stale row (or restore the metric): "
                            "python -m kubernetes_tpu.metrics --doc"
                        ),
                    )
                )
        return findings
