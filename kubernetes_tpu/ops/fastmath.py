"""Exact integer division on TPU without hardware int div.

TPU VPUs have no integer divide: XLA expands `//` into a long shift-subtract
sequence (~0.2 ms per [5k] vector on this box — measured, it dominated the
per-pod scan step). The reference's scoring math is integer division on
non-negative int64 (resource_allocation.go, scoring normalization), so the
kernels need EXACT floor division, not a float approximation.

`floor_div_exact` computes a float32 estimate (one multiply by the
reciprocal — cheap on the VPU) and then repairs it with a handful of
integer multiply-compare correction steps. Correction bound: for
quotients q < 2^23 the f32 estimate is within q·2^-23 + 1 < 3 of the true
floor, so 4 steps in each direction are provably enough; callers here all
have q <= ~10^6 (scores scaled by 100, counts). Each step is one int
multiply + compare — far cheaper than the division expansion.
"""

from __future__ import annotations

import jax.numpy as jnp

# correction radius: |f32_estimate - true_floor| < 1 + q * 2^-23; with
# q < 2^23 this is < 3, rounded up for safety
_STEPS = 4


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def floor_div_exact(num, den):
    """floor(num / den) for num >= 0, den >= 1 (int32/int64 arrays or
    scalars; shapes broadcast). Exact for quotients below 2^23.

    The float estimate may be off by a few units; the integer correction
    steps walk it to the exact floor: q is decremented while q*den > num
    and incremented while (q+1)*den <= num.
    """
    num = jnp.asarray(num)
    q = jnp.floor(
        num.astype(jnp.float32) / jnp.asarray(den).astype(jnp.float32)
    ).astype(num.dtype)
    q = jnp.maximum(q, 0)
    for _ in range(_STEPS):
        q = q - (q * den > num).astype(num.dtype)
    for _ in range(_STEPS):
        q = q + ((q + 1) * den <= num).astype(num.dtype)
    return q
