"""CLI: seeded simulator runs and trace replay.

    # fresh run (deterministic: same seed+profile => identical trace)
    python -m kubernetes_tpu.sim --seed 0 --profile churn_heavy
    python -m kubernetes_tpu.sim --seed 7 --cycles 20 --profile bind_storms \\
        --trace /tmp/storm.jsonl

    # reproduce a recorded run bit-for-bit
    python -m kubernetes_tpu.sim --replay /tmp/storm.jsonl

    # determinism self-check: run twice, compare trace digests
    python -m kubernetes_tpu.sim --seed 0 --profile node_flaps --selfcheck

Exit status: 0 clean; 1 invariant violations / failed settle / replay
divergence; 2 usage errors.
"""

from __future__ import annotations

import argparse
import sys


def _configure_jax(mesh_devices: int = 1) -> None:
    """Force CPU + 64-bit resource arithmetic BEFORE the solver imports
    jax (tests get this from tests/conftest.py; the CLI must do it
    itself — on this toolchain only jax.config.update is honored).
    ``mesh_devices > 1`` additionally forces that many virtual CPU
    devices (must land before the backend initializes) so the sim can
    drive the node-axis-sharded solve path."""
    import os

    if mesh_devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={mesh_devices}"
            ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def _print_result(res) -> None:
    s = res.summary
    dispatcher = (
        "streaming"
        if s.get("streaming")
        else ("pipelined" if s["pipelined"] else "sync")
    )
    print(
        f"profile={res.profile} seed={res.seed} cycles={res.cycles} "
        f"pipelined={s['pipelined']} dispatcher={dispatcher}"
    )
    print(
        f"  events={s['events']} bound={s['bound']} unbound={s['unbound']} "
        f"settled={s['settled']}"
    )
    print(
        f"  faults: bind={s['bind_faults']} "
        f"watch_delivered={s['watch_delivered']} "
        f"dup={s['watch_duplicated']} extender_aborts={s['extender_aborts']} "
        f"permit_stalls={s['permit_stalls']}"
    )
    print(
        f"  pipeline: discards={s['discards']:.0f} "
        f"fallbacks={s['pipeline_fallbacks']:.0f} "
        f"stream_discards={s.get('stream_discards', 0):.0f} "
        f"preemptions={s['preemptions']:.0f}"
    )
    resil = s.get("resilience")
    if resil is not None and (
        s.get("solver_faults") or s.get("poison_hits") or resil["trips"]
    ):
        tiers = {
            name: p["tier"] for name, p in resil["profiles"].items()
        }
        print(
            f"  resilience: faults={s['solver_faults']} "
            f"poison={s['poison_hits']} trips={resil['trips']} "
            f"recloses={resil['recloses']} "
            f"quarantined={len(s['quarantined'])} tier={tiers}"
        )
    bl = s.get("backlog")
    if bl:
        print(
            f"  backlog: pods={bl['pods']} drained={bl['drained']} "
            f"chunks={bl['chunks']} chunk_pods={bl['chunk_pods']} "
            f"budget_splits={bl['budget_splits']} "
            f"stream_chained={bl['stream_chained']}"
        )
    tu = s.get("tuning")
    if tu:
        knobs = ",".join(f"{k}={v}" for k, v in sorted(tu["knobs"].items()))
        print(
            f"  tuning: probes={tu['probes']} moves={tu['moves']} "
            f"settled={tu['settled']} shifts={tu['shifts']} "
            f"guardrail_rejections={tu['guardrail_rejections']} "
            f"guardrail_breaches={tu['guardrail_breaches']} "
            f"convergence_batches={tu['convergence_batches']} "
            f"knobs[{knobs}]"
        )
    reb = s.get("rebalance")
    if reb:
        print(
            f"  rebalance: runs={reb['runs']} "
            f"evicted={reb['evicted']} "
            f"migrations_completed={reb['migrations_completed']} "
            f"max_cycle_evictions={reb['max_cycle_evictions']} "
            f"budget={reb['budget']} over_budget={reb['over_budget']} "
            f"pdb_blocked={reb['pdb_blocked']} "
            f"pdb_overruns={reb['pdb_overruns']} "
            f"final_packing={reb['final_packing']}"
        )
    g = s.get("gang")
    if g:
        print(
            f"  gang: commits={g['gang_commits']} "
            f"bound_pods={g['gang_bound_pods']} "
            f"incomplete_rounds={g['gang_incomplete_rounds']} "
            f"partial_gangs={g['partial_gangs']} "
            f"quarantined_gangs={g['quarantined_gangs']}"
        )
    mp = s.get("megaplan")
    if mp:
        # the CI megaplan smoke greps ranked/iterations/plan_valid/
        # objective_ratio off this line — keep the key=value shape
        print(
            f"  megaplan: pods={mp.get('pods', 0)} "
            f"ranked={mp.get('ranked', 0)} "
            f"iterations={mp.get('iterations', 0)} "
            f"repaired={mp.get('repaired', 0)} "
            f"relax_placed={mp.get('relax_placed', 0)} "
            f"exact_placed={mp.get('exact_placed', 0)} "
            f"objective_ratio={mp.get('objective_ratio', 0.0)} "
            f"plan_valid={mp.get('plan_valid', False)}"
        )
    tel = s.get("telemetry")
    if tel:
        # the CI telemetry smoke greps anomalies/bundles_captured
        # off this line — keep the key=value shape stable
        signals = ",".join(tel["anomaly_signals"]) or "-"
        triggers = (
            ",".join(
                f"{k}={v}" for k, v in sorted(tel["bundle_triggers"].items())
            )
            or "-"
        )
        print(
            f"  telemetry: anomalies={tel['anomalies']} "
            f"signals={signals} "
            f"bundles_captured={tel['bundles_captured']} "
            f"triggers={triggers}"
        )
    if s.get("crashes") or s.get("incarnations", 1) > 1:
        print(
            f"  lifecycle: incarnations={s['incarnations']} "
            f"crashes={s['crashes']} "
            f"recovered_records={s['recovered_records']}"
        )
    print(
        f"  journal: records={s['journal_records']} "
        f"digest={s['journal_digest'][:16]}"
    )
    print(f"  trace_digest={res.trace.digest()}")
    if res.flight_dump:
        print(f"  flight recorder dumped: {res.flight_dump}")
    if res.replay_divergence:
        print(f"  REPLAY DIVERGED: {res.replay_divergence}")
    elif res.violations:
        print(f"  {len(res.violations)} INVARIANT VIOLATION(S):")
        for v in res.violations[:20]:
            print(f"    [{v.invariant}] cycle {v.cycle}: {v.detail}")
    else:
        print("  invariants: OK")


def _print_fleet_result(res) -> None:
    s = res.summary
    print(
        f"profile={res.profile} seed={res.seed} cycles={res.cycles} "
        f"fleet={res.replicas} alive={s['alive']} "
        f"lost={s['lost_replica'] or '-'} "
        f"hub={s.get('hub', 'in-process')} "
        f"cas_conflicts={s.get('cas_conflicts', 0)}"
    )
    print(
        f"  events={s['events']} bound={s['bound']} "
        f"unbound={s['unbound']} settled={s['settled']} "
        f"binds_by_replica={s['binds_by_replica']}"
    )
    if s.get("zombie"):
        fenced = s["fenced_commits"].get(s["zombie"], 0)
        print(
            f"  partition: zombie={s['zombie']} "
            f"fenced_commits={fenced} "
            f"zombie_binds_while_fenced={s['zombie_binds_while_fenced']} "
            f"stale_rejections={s['stale_rejections']}"
        )
    ha = s.get("hub_ha")
    if ha:
        print(
            f"  hub_ha: failovers={ha['promotions']} "
            f"epoch={ha['epoch']} "
            f"blackout_cycles={ha['blackout_cycles']} "
            f"stale_writes_rejected={ha['deposed_write_rejections']} "
            f"dedup_hits={ha['flush_dedup_hits']} "
            f"client_failovers={ha['client_failovers']} "
            f"replicated_ops={ha['replication_ops']} "
            f"journal_missing={ha['hub_journal_missing']} "
            f"old_primary_reads_ok={ha['old_primary_reads_ok']} "
            f"stale_rejections={s['stale_rejections']}"
        )
    g = s.get("gang")
    if g:
        print(
            f"  gang: commits={g['gang_commits']} "
            f"bound_pods={g['gang_bound_pods']} "
            f"incomplete_rounds={g['gang_incomplete_rounds']} "
            f"partial_gangs={g['partial_gangs']} "
            f"quarantined_gangs={g['quarantined_gangs']}"
        )
    fd = s.get("fleet_drain")
    if fd:
        # the CI fleet-drain smoke greps leases_reassigned/lost/
        # double_bind off this line — keep the key=value shape
        print(
            f"  fleet_drain: pods={fd['pods']} "
            f"partitions={fd['partitions']} "
            f"residual={fd['residual']} drained={fd['drained']} "
            f"leases={fd['leases']} "
            f"leases_reassigned={fd['leases_reassigned']} "
            f"lost={fd['lost']} double_bind={fd['double_bind']}"
        )
    for rid in sorted(res.journal_digests):
        print(f"  journal[{rid}]={res.journal_digests[rid]}")
    print(
        f"  hub_journal: lines={s.get('hub_journal_lines', 0)} "
        f"digest={s.get('hub_journal_digest', '')[:16]}"
    )
    for path in sorted(res.flight_dumps):
        print(
            f"  flight recorder dumped [{res.flight_dumps[path]}]: {path}"
        )
    if res.violations:
        print(f"  {len(res.violations)} INVARIANT VIOLATION(S):")
        for v in res.violations[:20]:
            print(f"    [{v.invariant}] cycle {v.cycle}: {v.detail}")
    else:
        print("  invariants: OK")


def _run_fleet(args) -> int:
    from .fleet import run_fleet_sim

    pipelined = streaming = None
    if args.dispatcher is not None:
        pipelined = args.dispatcher == "pipelined"
        streaming = args.dispatcher == "streaming"
    try:
        res = run_fleet_sim(
            args.profile, seed=args.seed, cycles=args.cycles,
            replicas=args.fleet, pipelined=pipelined,
            streaming=streaming, grpc_hub=args.hub_grpc,
            flight_dump=args.flight_dump,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    _print_fleet_result(res)
    if args.journal:
        from pathlib import Path

        # the hub's aggregated journal (every replica's shipped
        # segments, one file) — the `obs explain --fleet` source
        Path(args.journal).write_text(
            "\n".join(res.hub_journal_lines) + "\n"
            if res.hub_journal_lines
            else ""
        )
        print(
            f"  hub journal written: {args.journal} "
            f"({len(res.hub_journal_lines)} lines)"
        )
        for rid, lines in sorted(res.journals.items()):
            path = f"{args.journal}.{rid}"
            Path(path).write_text("\n".join(lines) + "\n")
            print(f"  journal written: {path}")
    if args.selfcheck:
        res2 = run_fleet_sim(
            args.profile, seed=args.seed, cycles=args.cycles,
            replicas=args.fleet, pipelined=pipelined,
            streaming=streaming, grpc_hub=args.hub_grpc,
        )
        if res.journal_digests != res2.journal_digests:
            print(
                "NON-DETERMINISTIC: per-replica journal digests differ "
                f"({res.journal_digests} vs {res2.journal_digests})",
                file=sys.stderr,
            )
            return 1
        if res.hub_journal_lines != res2.hub_journal_lines:
            print(
                "NON-DETERMINISTIC: hub-aggregated journals differ "
                f"({len(res.hub_journal_lines)} vs "
                f"{len(res2.hub_journal_lines)} lines)",
                file=sys.stderr,
            )
            return 1
        if res.bindings != res2.bindings:
            print(
                "NON-DETERMINISTIC: final bindings differ",
                file=sys.stderr,
            )
            return 1
        print(
            "  selfcheck: two runs produced byte-identical per-replica "
            "journals (and hub aggregation)"
        )
    return 0 if res.ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.sim",
        description="Deterministic cluster simulator + fault injection.",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--cycles", type=int, default=10)
    parser.add_argument(
        "--profile", default="churn_heavy",
        help="scenario profile (see sim/README.md); --list-profiles",
    )
    parser.add_argument(
        "--sync", action="store_true",
        help="drive run_until_settled instead of the profile's default",
    )
    parser.add_argument(
        "--dispatcher", choices=("sync", "pipelined", "streaming"),
        default=None,
        help="override the profile's dispatch loop: sync "
        "(schedule_batch), pipelined (run_pipelined), streaming "
        "(run_streaming — the device-resident solve loop)",
    )
    parser.add_argument(
        "--trace", metavar="PATH", help="write the replayable trace here"
    )
    parser.add_argument(
        "--replay", metavar="PATH",
        help="re-execute a recorded trace instead of a fresh run",
    )
    parser.add_argument(
        "--journal", metavar="PATH",
        help="write the per-pod decision journal (kubernetes_tpu/obs "
        "JSONL; explain pods with `python -m kubernetes_tpu.obs "
        "explain <pod> --trace PATH`)",
    )
    parser.add_argument(
        "--flight-dump", metavar="PATH",
        help="dump the flight recorder here when an invariant fires",
    )
    parser.add_argument(
        "--bundle-dir", metavar="DIR",
        help="telemetry profiles (e.g. anomaly_storm): write capture-"
        "on-anomaly replay bundles into this directory; the telemetry "
        "invariant replays each one and asserts bit-identical "
        "assignments (`python -m kubernetes_tpu.obs replay <bundle>` "
        "does the same offline)",
    )
    parser.add_argument(
        "--tuning", action="store_true",
        help="enable the closed-loop auto-tuning runtime "
        "(kubernetes_tpu/tuning) on any profile: hill-climb "
        "controllers over stream_depth / pipeline_split / drain "
        "chunk with sim-sized evaluation windows; the footer's "
        "tuning line and the tuning invariant report convergence",
    )
    parser.add_argument(
        "--tuned-profile", metavar="PATH",
        help="after a --tuning run, write the converged knob values "
        "as a standard KubeSchedulerConfiguration YAML (tuned config "
        "in, standard config out)",
    )
    parser.add_argument(
        "--mesh-devices", type=int, default=1, metavar="N",
        help="shard the node-axis solve over N virtual CPU devices "
        "(SchedulerConfig.mesh_devices; forces the device count before "
        "jax initializes). Results are bit-exactly device-count "
        "invariant, so traces match the single-device run.",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="run twice and verify the traces are byte-identical",
    )
    parser.add_argument(
        "--fleet", type=int, default=0, metavar="N",
        help="drive N active scheduler replicas sharding the cluster "
        "(sim/fleet.py): shard-filtered watches, occupancy exchange, "
        "no-global-overcommit + fleet journal invariants. 0 = the "
        "single-scheduler drive; use with the fleet_mixed / "
        "replica_loss profiles. --selfcheck byte-compares per-replica "
        "journal digests across two runs.",
    )
    parser.add_argument(
        "--hub-grpc", action="store_true",
        help="fleet drives only: serve the occupancy hub behind a "
        "localhost bulk gRPC server (real wire framing, typed "
        "CAS-conflict status mapping) instead of the shared in-process "
        "object — the cross-process deployment shape on one box",
    )
    parser.add_argument("--list-profiles", action="store_true")
    args = parser.parse_args(argv)

    if args.list_profiles:
        from .profiles import PROFILES

        for name in sorted(PROFILES):
            p = PROFILES[name]
            line = f"{name}: pipelined={p.pipelined} nodes={p.nodes}"
            if p.gang_rate > 0 or p.gang_short_at >= 0:
                # gang profiles carry the pod-group workload knobs
                # (kubernetes_tpu/gang): surface them so the listing
                # says WHICH profiles drive the gang gate and how
                line += (
                    f" gang_rate={p.gang_rate}"
                    f" gang_sizes={p.gang_sizes}"
                    f" gang_short_at={p.gang_short_at}"
                    f" accel_classes={len(p.gang_accel_classes)}"
                )
            print(line)
        return 0

    _configure_jax(args.mesh_devices)
    if args.fleet:
        if args.tuning:
            # the multi-scheduler drive builds its own replica configs;
            # silently dropping the flag would misread as "tuned fleet"
            print(
                "error: --tuning is not supported on fleet drives "
                "(the fleet_flush knob is unit-tested; per-replica "
                "tuning is future work)",
                file=sys.stderr,
            )
            return 2
        return _run_fleet(args)
    from .harness import replay_trace, run_sim
    from .trace import TraceError

    if args.replay:
        try:
            res = replay_trace(args.replay)
        except TraceError as e:
            print(f"replay failed: {e}", file=sys.stderr)
            return 1
        _print_result(res)
        return 0 if res.ok else 1

    # --sync must override BOTH profile defaults: a streaming profile
    # (sustained_stream) would otherwise still drive run_streaming
    pipelined = False if args.sync else None
    streaming = False if args.sync else None
    if args.dispatcher is not None:
        pipelined = args.dispatcher == "pipelined"
        streaming = args.dispatcher == "streaming"
    tuning = True if args.tuning else None
    try:
        res = run_sim(
            args.profile, seed=args.seed, cycles=args.cycles,
            pipelined=pipelined, streaming=streaming,
            flight_dump=args.flight_dump,
            mesh_devices=args.mesh_devices,
            tuning=tuning,
            bundle_dir=args.bundle_dir,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    _print_result(res)
    if args.tuned_profile and res.tuned_profile is not None:
        from pathlib import Path

        from kubernetes_tpu.tuning.profile import dump_yaml

        Path(args.tuned_profile).write_text(
            dump_yaml(res.tuned_profile)
        )
        print(f"  tuned profile written: {args.tuned_profile}")
    if args.trace:
        res.trace.dump(args.trace)
        print(f"  trace written: {args.trace}")
    if args.journal:
        from pathlib import Path

        Path(args.journal).write_text(
            "\n".join(res.journal_lines) + "\n"
        )
        print(f"  journal written: {args.journal}")
    if args.selfcheck:
        res2 = run_sim(
            args.profile, seed=args.seed, cycles=args.cycles,
            pipelined=pipelined, streaming=streaming,
            mesh_devices=args.mesh_devices,
            tuning=tuning,
        )
        if res.journal_lines != res2.journal_lines:
            print(
                "NON-DETERMINISTIC: decision journals differ "
                f"({len(res.journal_lines)} vs {len(res2.journal_lines)} "
                "records)",
                file=sys.stderr,
            )
            return 1
        if res.trace.lines != res2.trace.lines:
            for i, (a, b) in enumerate(
                zip(res.trace.lines, res2.trace.lines)
            ):
                if a != b:
                    print(
                        f"NON-DETERMINISTIC at trace line {i + 1}:\n"
                        f"  run1: {a}\n  run2: {b}",
                        file=sys.stderr,
                    )
                    break
            else:
                print(
                    "NON-DETERMINISTIC: trace lengths differ "
                    f"({len(res.trace.lines)} vs {len(res2.trace.lines)})",
                    file=sys.stderr,
                )
            return 1
        print("  selfcheck: two runs produced byte-identical traces")
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
