"""CounterWindow: the tuning layer's measurement surface.

One bounded window of per-batch samples over the counters the
scheduling loops already tick — host-side reads of prometheus counter
cells and driver-side tallies, never a new device sync. Every number a
tuning controller (or the adaptive pipeline-split rule) consumes comes
from here, which is the anti-fighting contract of ISSUE 13's satellite:
two tuners reading two private estimates of the same signal can push a
knob in opposite directions forever; two tuners reading ONE window
cannot disagree about what was measured.

The window also owns the RTT / per-pod-solve EWMAs that used to live as
``Scheduler._rtt_ewma`` / ``_pod_solve_ewma``: ``note_read`` keeps the
exact update rule (only reads that actually BLOCKED the driver > 1 ms
carry signal — post-overlap reads are the overlap working, and folding
them in would drive the estimate to ~0), and ``split_estimate`` is the
adaptive batch-split rule moved verbatim so the scheduler and the split
controller evaluate the same formula over the same state.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .. import metrics


def _counter_value(counter) -> float:
    """Current value of an unlabeled prometheus counter cell (the
    test-style internal read every delta consumer in this repo uses)."""
    return counter._value.get()


def _labeled_total(counter) -> float:
    """Sum over every child of a labeled counter (e.g. the CAS-conflict
    counter's version/fenced kinds) without materializing new labels."""
    try:
        with counter._lock:
            children = list(counter._metrics.values())
    except AttributeError:
        return 0.0
    return float(sum(c._value.get() for c in children))


# the counter families one batch sample snapshots (name -> reader).
# All are driver-side totals the loops already maintain: deltas between
# consecutive samples are the per-batch signal.
_COUNTER_READERS = {
    "unhidden_reads": lambda: _counter_value(
        metrics.stream_unhidden_reads_total
    ),
    "slot_discards": lambda: _counter_value(
        metrics.stream_slot_discard_total
    ),
    "solve_discards": lambda: _counter_value(metrics.solves_discarded_total),
    "h2d_bytes": lambda: _counter_value(metrics.h2d_bytes_total),
    "d2h_bytes": lambda: _counter_value(metrics.d2h_bytes_total),
    "cas_conflicts": lambda: _labeled_total(
        metrics.fleet_admit_cas_conflict_total
    ),
}


@dataclass
class BatchSample:
    """One applied batch's measurements: absolute per-batch facts plus
    the counter deltas since the previous sample."""

    pods: int = 0
    wall_s: float = 0.0  # scheduler-clock seconds since the last sample
    solve_s: float = 0.0
    chained: int = 0  # stream_chained dispatch delta
    occ_sensitive: bool = False  # hard shape (ports/spread/interpod/...)
    deltas: dict = field(default_factory=dict)


class CounterWindow:
    """Bounded deque of ``BatchSample``s + the split-rule EWMAs."""

    def __init__(self, clock, capacity: int = 128) -> None:
        self.clock = clock
        self.samples: deque[BatchSample] = deque(maxlen=capacity)
        self._last_counters = {
            k: reader() for k, reader in _COUNTER_READERS.items()
        }
        self._last_chained = 0.0
        self._last_at = clock.perf()
        # RTT-hiding batch-split estimators (moved from Scheduler):
        # EWMAs of the blocking device-read wait (~ tunnel RTT +
        # residual solve) and of per-pod device time. Driver-thread
        # only, like every mutation on this object.
        self.rtt_ewma = 0.0
        self.pod_solve_ewma = 0.0
        self.batches = 0  # samples ever taken (not capped)

    # -- the split-rule estimators (ISSUE 13 satellite: ONE home) --

    def note_read(
        self, read_seconds: float, dispatch_seconds: float, n_pods: int
    ) -> None:
        """Feed the estimators from an applied (or read-then-discarded)
        flight. Only reads that actually BLOCKED (> 1 ms) carry signal:
        they approximate residual solve + tunnel RTT, an upper bound on
        the RTT. Post-overlap reads (~0.2 ms) are the overlap WORKING
        and say nothing about the RTT — folding them in would drive the
        estimate to ~0 and make the adaptive rule split every batch to
        the max. EWMAs, not running extrema, so the estimates track
        tunnel mood both ways."""
        if read_seconds < 1e-3 or n_pods <= 0:
            return
        self.rtt_ewma = (
            read_seconds
            if self.rtt_ewma <= 0
            else 0.7 * self.rtt_ewma + 0.3 * read_seconds
        )
        per_pod = (dispatch_seconds + read_seconds) / n_pods
        self.pod_solve_ewma = (
            per_pod
            if self.pod_solve_ewma <= 0
            else 0.7 * self.pod_solve_ewma + 0.3 * per_pod
        )

    def split_estimate(self, n_pods: int, max_split: int) -> int:
        """The adaptive pipeline-split rule (formerly
        ``Scheduler._choose_split``'s private-EWMA branch): split once
        the estimated device solve time for the batch exceeds the
        estimated read round trip, so the assignment read of sub-batch
        i can overlap the solve of i+1."""
        if self.rtt_ewma <= 0 or self.pod_solve_ewma <= 0:
            return 1
        est_solve = n_pods * self.pod_solve_ewma
        if est_solve <= 2 * self.rtt_ewma:
            return 1
        return max(2, min(int(est_solve / self.rtt_ewma), max_split))

    # -- per-batch sampling --

    def note_batch(
        self,
        *,
        pods: int,
        solve_s: float = 0.0,
        chained_total: float | None = None,
        occ_sensitive: bool = False,
    ) -> BatchSample:
        """Record one applied batch: absolute facts passed in by the
        scheduler, counter deltas read here. Called once per applied
        batch from the metrics-recording chokepoint every dispatch loop
        (sync, pipelined, streaming, drain) already funnels through."""
        now = self.clock.perf()
        deltas = {}
        for k, reader in _COUNTER_READERS.items():
            v = reader()
            deltas[k] = v - self._last_counters[k]
            self._last_counters[k] = v
        chained = 0
        if chained_total is not None:
            chained = int(chained_total - self._last_chained)
            self._last_chained = chained_total
        sample = BatchSample(
            pods=pods,
            wall_s=max(now - self._last_at, 0.0),
            solve_s=solve_s,
            chained=chained,
            occ_sensitive=occ_sensitive,
            deltas=deltas,
        )
        self._last_at = now
        self.samples.append(sample)
        self.batches += 1
        return sample

    # -- aggregates the controllers and the shift detector read --

    def recent(self, n: int) -> list[BatchSample]:
        if n <= 0:
            return []
        return list(self.samples)[-n:]

    def hard_fraction(self, n: int) -> float:
        recent = self.recent(n)
        if not recent:
            return 0.0
        return sum(1 for s in recent if s.occ_sensitive) / len(recent)

    def rate(self, n: int) -> float:
        """Pods per wall-second over the last ``n`` samples (ratio of
        sums — robust to how a cycle's arrivals happened to split into
        pops, which per-batch means are not)."""
        recent = self.recent(n)
        if not recent:
            return 0.0
        return sum(s.pods for s in recent) / max(
            sum(s.wall_s for s in recent), 1e-6
        )

    def signature(self, n: int) -> tuple[float, float]:
        """A compact workload fingerprint — (arrival-rate proxy,
        hard-shape fraction) — the shift detector compares across
        settle points. A large relative move in either component means
        the workload the tuned values were chosen for is gone. The
        rate, not the mean batch size: a 15-pod cycle pops as one
        15-pod batch or a 16-cap batch plus a remainder depending on
        timing, which whipsaws a per-batch mean while leaving the rate
        untouched."""
        return (self.rate(n), self.hard_fraction(n))
