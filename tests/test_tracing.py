"""utils/tracing.py (the jax-profiler step-trace wrapper) — previously
the only untested module in utils/: lazy session start on first step,
stop() as a no-op when never started, and the disabled path yielding
without importing jax."""

import sys

import pytest

from kubernetes_tpu.utils import tracing


@pytest.fixture(autouse=True)
def reset_tracing_state():
    """The module is global-state by design (one profiler session per
    process); isolate each test."""
    old_dir, old_started = tracing._trace_dir, tracing._started
    tracing._trace_dir = None
    tracing._started = False
    yield
    tracing._trace_dir, tracing._started = old_dir, old_started


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, d):
        self.calls.append(("start", d))

    def stop_trace(self):
        self.calls.append(("stop",))

    class StepTraceAnnotation:
        def __init__(self, name, step_num=0):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False


def test_disabled_path_yields_without_importing_jax(monkeypatch):
    # a poisoned jax module would explode on any attribute access: the
    # disabled path must never get that far
    class _Poison:
        def __getattr__(self, name):
            raise AssertionError(f"disabled tracing touched jax.{name}")

    monkeypatch.setitem(sys.modules, "jax", _Poison())
    assert not tracing.enabled()
    ran = False
    with tracing.step("batch", 1):
        ran = True
    assert ran
    tracing.stop()  # still a no-op: never started


def test_stop_is_noop_when_never_started(monkeypatch):
    class _Poison:
        def __getattr__(self, name):
            raise AssertionError("stop() touched jax without a session")

    monkeypatch.setitem(sys.modules, "jax", _Poison())
    tracing.enable("/tmp/traces")
    assert tracing.enabled()
    tracing.stop()  # enabled but no step ran: must not import/stop jax


def test_lazy_start_on_first_step_and_stop_flushes(monkeypatch):
    import types

    prof = _FakeProfiler()
    fake_jax = types.SimpleNamespace(profiler=prof)
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    tracing.enable("/tmp/traces")
    assert prof.calls == []  # enable alone starts nothing
    with tracing.step("batch", 1):
        pass
    assert prof.calls == [("start", "/tmp/traces")]
    with tracing.step("batch", 2):
        pass
    assert prof.calls == [("start", "/tmp/traces")]  # started once
    tracing.stop()
    assert prof.calls[-1] == ("stop",)
    tracing.stop()  # idempotent after flush
    assert prof.calls.count(("stop",)) == 1
