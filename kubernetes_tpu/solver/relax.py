"""Convex-relaxation mega-planner — fractional assignment by mirror
descent + dual ascent, TPU-native (ISSUE 19, ROADMAP item #3).

The single-shot auction prices capacity through SEQUENTIAL rounds:
top-T bids, segmented admission, price escalation on rejection. Each
round is dense, but the round chain is inherently serial and the top-T
window caps how much of the price surface one round can explore — at
1M+ pods the plan solve stops fitting a planning cycle. The CvxCluster
line of work (PAPERS.md) shows the road past it: RELAX the integral
assignment to a fractional one, solve the relaxation with first-order
iterations that are pure matmul + softmax — natively batched, node-axis
mesh-shardable, exactly the arithmetic the TPU is built for — then
round and repair the integrality gap.

The relaxation, in request-class space (never [P, N] — the same memory
move that makes the auction fit, `single_shot.request_classes`):

  maximize   sum_{rc,n} score[rc,n] * x[rc,n]  +  temp * H(x)
  s.t.       sum_rc x[rc,n] * req[rc,k] <= free[k,n]     (lam[k,n])
             sum_rc x[rc,n]             <= cnt_free[n]   (mu[n])
             sum_n  x[rc,n]              = mass[rc]
             x >= 0,  x[rc,n] = 0 where statically infeasible

H is the entropy regularizer that makes the primal step closed-form:
holding the duals fixed, the optimal x is a temperature-``temp``
softmax over (score - penalty) per class, scaled to the class mass —
one [RC,K]x[K,N] matmul for the penalty, one softmax. The duals then
take a projected ascent step on the normalized overcommit
(load/capacity - 1). Iterations run in one jitted
``lax.while_loop`` with residual-based early exit: converged solves
stop paying for the remaining iteration budget.

Rounding is deterministic and device-side: per-class quotas
(round-to-nearest of x, clamped per node against remaining integer
capacity by a scan over the small RC axis, mass-clamped per class),
then pods map to quota slots by priority rank through one
searchsorted over the flattened [RC*N] quota prefix — higher-priority
pods take the quota slots, the tail stays unassigned. The tail then
repairs through the EXISTING single-shot auction (scarcity repair and
all), so end states carry the auction's feasibility guarantees and
pass ``validate_assignments``: the relaxation proposes, the auction
disposes.

The converged duals are exported as PRICES: ``lam[k, n]`` is the
marginal score cost of one normalized unit of resource k on node n
(``mu`` the pod-slot analog) — aggregated per node group they are the
cost signal ROADMAP item #2's autoscaler consumes: a group whose price
stays pinned at zero has slack; a group whose price climbs is worth
growing.

Scope mirrors the auction: NodeResourcesFit + folded static plugin
masks + headroom scoring, ``"spread"``/``"pack"`` objectives with the
same integer base score. HBM discipline: ``solver/budget.py``'s
``relax_estimate`` byte model + ``assert_index_headroom`` (with the
relaxation's own flattened-index lanes audited) run before dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..tensorize.plugins import StaticPluginTensors, trivial_static_tensors
from ..tensorize.schema import CPU_IDX, MEM_IDX, NodeBatch, PodBatch
from .single_shot import (
    SingleShotConfig,
    _cumsum0,
    _segmented_prefix,
    _single_shot_jit,
    request_classes,
)

NEG_F = jnp.float32(-1e30)


@dataclass(frozen=True)
class RelaxConfig:
    # iteration budget for the dual-ascent loop; the residual early
    # exit means converged shapes pay only what they use
    max_iters: int = 128
    # convergence tolerance on the relative overcommit residual:
    # max over (k, n) of load/capacity - 1, clipped at 0. 0.01 = the
    # fractional plan overcommits no node by more than 1% before
    # rounding (rounding itself is exact — the clamp admits only
    # integer quotas that fit).
    tol: float = 0.01
    # softmax temperature in score points: lower = harder argmax
    # (faster commitment, worse exploration), higher = smoother mass
    # spreading. Score range is 0..100; 8 measured a good balance.
    temp: float = 8.0
    # dual ascent step in score points per unit of relative overcommit
    step: float = 4.0
    # "spread" = prefer high-headroom nodes; "pack" = prefer full
    # nodes (the planner posture) — same integer base score as the
    # auction, so objectives are directly comparable
    objective: str = "spread"


def _relax(
    alloc,  # [K, N] int
    used0,  # [K, N] int
    pod_count0,  # [N] int32
    max_pods,  # [N] int32
    node_valid,  # [N] bool
    static_mask,  # [C, N] bool
    rc_req,  # [RC, K] int — request per request-class
    rc_static,  # [RC] int32 — static-plugin class of the request-class
    rc_of,  # [P] int32
    priority,  # [P] int32
    pod_valid,  # [P] bool
    tol,  # f32 scalar
    temp,  # f32 scalar
    step,  # f32 scalar
    *,
    max_iters: int,
    pack: bool = False,
):
    p = rc_of.shape[0]
    n = alloc.shape[1]
    rc = rc_req.shape[0]

    pod_idx = jnp.arange(p, dtype=jnp.int32)
    mass = jax.ops.segment_sum(
        pod_valid.astype(jnp.float32), rc_of, num_segments=rc
    )  # [RC] valid pods per class

    # -- capacities and the static feasibility mask (fixed across
    # iterations: the relaxation prices the SNAPSHOT, like one auction
    # solve) --
    free_i = jnp.maximum(alloc - used0, 0)  # [K, N] int64
    cnt_free_i = jnp.maximum(
        (max_pods - pod_count0).astype(jnp.int32), 0
    )  # [N] int32
    free_f = free_i.astype(jnp.float32)
    cnt_free_f = cnt_free_i.astype(jnp.float32)
    req_f = rc_req.astype(jnp.float32)  # [RC, K]

    # single-pod fit at snapshot free capacity + folded static masks:
    # a cell that cannot host even one pod of the class carries no
    # fractional mass, ever
    fit = jnp.all(rc_req[:, :, None] <= free_i[None, :, :], axis=1)
    ok = (
        fit
        & static_mask[rc_static]
        & node_valid[None, :]
        & (cnt_free_i >= 1)[None, :]
    )  # [RC, N]
    feas_any = jnp.any(ok, axis=1)  # [RC]

    # same integer base score as the auction (headroom at snapshot,
    # pack flips the sense) so relax-vs-auction objectives compare
    alloc2 = alloc[: MEM_IDX + 1].astype(jnp.float32)
    used2 = used0[: MEM_IDX + 1].astype(jnp.float32)
    free_frac = jnp.where(
        alloc2 > 0, (alloc2 - used2) / jnp.maximum(alloc2, 1.0), 0.0
    )
    headroom = (
        100.0 * (free_frac[CPU_IDX] + free_frac[MEM_IDX]) / 2.0
    ).astype(jnp.int32)
    base_score = (jnp.int32(100) - headroom) if pack else headroom
    score_f = base_score.astype(jnp.float32)  # [N]

    inv_free = 1.0 / jnp.maximum(free_f, 1.0)  # [K, N]
    inv_cnt = 1.0 / jnp.maximum(cnt_free_f, 1.0)  # [N]

    def primal(lam, mu):
        """Closed-form entropic primal: x = mass * softmax over the
        penalized score. Penalty = the duals paired with the
        NORMALIZED constraint coefficients req/free — one matmul."""
        pen = req_f @ (lam * inv_free)  # [RC, N]
        logits = (score_f[None, :] - pen - (mu * inv_cnt)[None, :]) / temp
        logits = jnp.where(ok, logits, NEG_F)
        m = jnp.max(logits, axis=1, keepdims=True)
        z = jnp.where(ok, jnp.exp(logits - m), 0.0)
        denom = jnp.maximum(jnp.sum(z, axis=1, keepdims=True), 1e-30)
        x = mass[:, None] * z / denom
        return jnp.where(feas_any[:, None], x, 0.0)

    def residual_of(x):
        load = req_f.T @ x  # [K, N]
        over_res = jnp.max(
            jnp.where(node_valid[None, :], load * inv_free - 1.0, 0.0)
        )
        cnt_load = jnp.sum(x, axis=0)
        over_cnt = jnp.max(
            jnp.where(node_valid, cnt_load * inv_cnt - 1.0, 0.0)
        )
        return jnp.maximum(jnp.maximum(over_res, over_cnt), 0.0)

    def cond(state):
        it, _, _, res = state
        return (it < max_iters) & (res > tol)

    def body(state):
        it, lam, mu, _ = state
        x = primal(lam, mu)
        load = req_f.T @ x  # [K, N]
        cnt_load = jnp.sum(x, axis=0)  # [N]
        # projected dual ascent on relative overcommit: prices rise
        # where the fractional plan overbooks, decay toward 0 where it
        # leaves slack — the converged lam/mu ARE the exported prices
        lam = jnp.maximum(lam + step * (load * inv_free - 1.0), 0.0)
        mu = jnp.maximum(mu + step * (cnt_load * inv_cnt - 1.0), 0.0)
        return it + 1, lam, mu, residual_of(primal(lam, mu))

    k = alloc.shape[0]
    lam0 = jnp.zeros((k, n), dtype=jnp.float32)
    mu0 = jnp.zeros(n, dtype=jnp.float32)
    iters, lam, mu, res = jax.lax.while_loop(
        cond, body, (jnp.int32(0), lam0, mu0, jnp.float32(jnp.inf))
    )
    x = primal(lam, mu)

    # -- deterministic rounding: fractional mass -> integer per-class
    # quotas, clamped against remaining integer capacity (scan over the
    # small RC axis — the only sequential chain, length RC not P) --
    q_des = jnp.floor(x + 0.5).astype(jnp.int32)  # [RC, N]
    mass_i = mass.astype(jnp.int32)

    def round_class(carry, inp):
        free_c, cnt_c = carry  # [K, N] int64, [N] int32
        qd, req_row, ok_row, m_rc = inp
        safe_req = jnp.maximum(req_row, 1)  # [K]
        cap_k = free_c // safe_req[:, None]  # [K, N] int64
        cap_k = jnp.where(req_row[:, None] > 0, cap_k, jnp.int64(1 << 31))
        # per-node admissible count for this class, bounded by the pod
        # axis (mass <= P < 2^31) so the narrowing below cannot wrap
        cap = jnp.minimum(
            jnp.min(cap_k, axis=0), cnt_c.astype(jnp.int64)
        )
        cap = jnp.clip(cap, 0, jnp.int64(m_rc)).astype(jnp.int32)
        q = jnp.where(ok_row, jnp.minimum(qd, cap), 0)
        # mass clamp: cumulative quota along the node axis never
        # exceeds the class's pod count (round-to-nearest can
        # overshoot). The prefix accumulates in int64 — N * per-node
        # quota passes 2^31 at mega shapes — then narrows: the clamped
        # value is bounded by q (int32) by construction.
        q64 = q.astype(jnp.int64)
        cq = jnp.cumsum(q64)
        q = jnp.clip(
            m_rc.astype(jnp.int64) - (cq - q64), 0, q64
        ).astype(jnp.int32)
        free_c = free_c - q.astype(jnp.int64)[None, :] * req_row[:, None]
        cnt_c = cnt_c - q
        return (free_c, cnt_c), q

    (_, _), quotas = jax.lax.scan(
        round_class,
        (free_i, cnt_free_i),
        (q_des, rc_req, ok, mass_i),
    )  # quotas [RC, N] int32

    # -- pods -> quota slots by priority rank within their class --
    inv_prio = jnp.int64((1 << 31) - 1) - priority.astype(jnp.int64)
    key = jnp.where(
        pod_valid,
        rc_of.astype(jnp.int64) * (1 << 32) + inv_prio,
        jnp.int64(1) << 62,
    )
    order = jnp.argsort(key)  # stable: pod index is the final tiebreak
    rc_sorted = rc_of[order]
    # ranks only matter for valid pods (invalid all sort to the tail
    # under the 2^62 key and are masked out of `placed` below)
    seg_start = jnp.concatenate(
        [
            jnp.array([True], dtype=jnp.bool_),
            rc_sorted[1:] != rc_sorted[:-1],
        ]
    )
    seg_id = _cumsum0(seg_start.astype(jnp.int32)) - 1
    rank_sorted = (
        _segmented_prefix(
            jnp.ones(p, dtype=jnp.int32), seg_start, seg_id, p
        )
        - 1
    )
    rank = jnp.zeros(p, dtype=jnp.int32).at[order].set(rank_sorted)

    flat_q = quotas.reshape(-1).astype(jnp.int64)  # [RC * N]
    gcum = jnp.cumsum(flat_q)  # monotone quota prefix over flat cells
    gcum0 = jnp.concatenate([jnp.zeros(1, dtype=jnp.int64), gcum])
    # class offsets into the flat prefix: int64 product — rc * N can
    # pass 2^31 at mega shapes (the audited relax flat-cell lane)
    cell_base = rc_of.astype(jnp.int64) * n
    offs = gcum0[cell_base]
    tot = quotas.sum(axis=1).astype(jnp.int64)  # [RC] placed per class
    placed = pod_valid & (rank.astype(jnp.int64) < tot[rc_of])
    g = jnp.where(placed, offs + rank.astype(jnp.int64), jnp.int64(0))
    flat_cell = jnp.searchsorted(gcum, g, side="right")
    # node id within the class's row: bounded by the node pad (< 2^31)
    node64 = flat_cell.astype(jnp.int64) - cell_base
    assigned_to = jnp.where(placed, node64, -1).astype(jnp.int32)

    req_add = jnp.where(placed[:, None], rc_req[rc_of], 0)
    park = jnp.where(placed, assigned_to, n)
    used = used0 + jax.ops.segment_sum(
        req_add, park, num_segments=n + 1
    )[:n].T
    pod_count = pod_count0 + jax.ops.segment_sum(
        placed.astype(jnp.int32), park, num_segments=n + 1
    )[:n]
    placed_total = jnp.sum(placed.astype(jnp.int32))

    return assigned_to, used, pod_count, placed_total, lam, mu, iters, res


_relax_jit = jax.jit(
    _relax,
    static_argnames=("max_iters", "pack"),
    donate_argnums=(1, 2),
)


@dataclass
class RelaxStats:
    """Host-side record of the last RelaxSolver.solve, the source for
    the ``scheduler_relax_*`` metric family and the sim footer."""

    iterations: int = 0
    residual: float = 0.0
    placed_relaxed: int = 0  # pods the rounded relaxation seated
    placed_total: int = 0  # after the auction tail repair
    repaired_pods: int = 0  # tail size handed to the auction
    repair_rounds: int = 0  # auction rounds the repair actually ran
    # per-node aggregate dual price (sum_k lam[k, n] + mu[n]), score
    # points per normalized capacity unit — 0 on uncontended nodes
    node_prices: np.ndarray | None = None


class RelaxSolver:
    """Host wrapper mirroring ``SingleShotSolver.solve``'s contract
    (fit + static mask scope, mutates nodes.used/pod_count, returns the
    per-pod assignment), with the relaxation as the engine and the
    auction as the integrality-tail repair."""

    def __init__(
        self,
        config: RelaxConfig | None = None,
        repair: SingleShotConfig | None = None,
    ):
        self.config = config or RelaxConfig()
        # the tail repair runs the EXISTING auction at the same
        # objective; None disables (planning callers that simply drop
        # the unplaced tail pass repair=None and keep the narrow plan)
        self.repair = repair
        self.last = RelaxStats()
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)

    def solve(
        self,
        nodes: NodeBatch,
        pods: PodBatch,
        static: StaticPluginTensors | None = None,
        mesh=None,
    ) -> np.ndarray:
        """``mesh``: optional jax.sharding.Mesh with a "nodes" axis —
        node-resident arrays shard over their trailing node axis,
        class/pod arrays replicate, and GSPMD inserts the collectives
        the matmul/softmax iterations need (the same contract as
        ``SingleShotSolver.solve``)."""
        if static is None:
            static = trivial_static_tensors(
                pods, nodes.padded, nodes.schedulable
            )
        from .budget import assert_index_headroom

        rc_req, rc_static, rc_of = request_classes(pods, static)
        # index-dtype audit including the relaxation's own flat-cell
        # lane (rc * node_pad quota prefix) — typed failure at dispatch
        assert_index_headroom(
            pods.padded, nodes.padded, rc_pad=rc_req.shape[0]
        )
        args = [
            nodes.allocatable,
            nodes.used,
            nodes.pod_count,
            nodes.max_pods,
            nodes.valid,
            static.mask,
            rc_req,
            rc_static,
            rc_of,
            pods.priority,
            pods.valid & pods.feasible_static,
        ]
        if mesh is not None:
            from ..parallel.sharding import node_sharding, replicated

            node_axis_args = {0, 1, 2, 3, 4, 5}  # node-resident inputs
            args = [
                jax.device_put(
                    jnp.asarray(a),
                    node_sharding(mesh, np.ndim(a))
                    if i in node_axis_args
                    else replicated(mesh),
                )
                for i, a in enumerate(args)
            ]
        else:
            args = [jnp.asarray(a) for a in args]
        cfg = self.config
        pod_valid = args[10]
        assigned, used, pod_count, placed, lam, mu, iters, res = _relax_jit(
            *args,
            jnp.float32(cfg.tol),
            jnp.float32(cfg.temp),
            jnp.float32(cfg.step),
            max_iters=cfg.max_iters,
            pack=cfg.objective == "pack",
        )
        stats = RelaxStats(
            iterations=int(iters),
            residual=float(res),
            placed_relaxed=int(placed),
            placed_total=int(placed),
            node_prices=np.asarray(
                jnp.sum(lam, axis=0) + mu, dtype=np.float32
            ),
        )

        tail = np.asarray(pod_valid & (np.asarray(assigned) < 0))
        n_tail = int(tail.sum())
        if self.repair is not None and n_tail > 0:
            # the integrality tail repairs through the EXISTING auction
            # against the post-rounding occupancy: only the still-
            # unassigned pods bid, everything the rounding seated is
            # fixed load. End states inherit the auction's feasibility.
            rep = self.repair
            rep_assigned, used, pod_count, _, rounds = _single_shot_jit(
                args[0],
                used,
                pod_count,
                args[3],
                args[4],
                args[5],
                args[6],
                args[7],
                args[8],
                args[9],
                jnp.asarray(tail),
                max_rounds=rep.max_rounds,
                price_step=rep.price_step,
                top_t=rep.top_t,
                repair_rounds=rep.repair_rounds,
                pack=rep.objective == "pack",
            )
            assigned = jnp.where(
                jnp.asarray(tail), rep_assigned, assigned
            )
            stats.repaired_pods = n_tail
            stats.repair_rounds = int(rounds)
            stats.placed_total = int(
                jnp.sum((assigned >= 0) & jnp.asarray(pod_valid))
            )
        self.last = stats
        nodes.used = np.array(used)
        nodes.pod_count = np.array(pod_count)
        return np.asarray(assigned)[: pods.num_pods]


def group_prices(
    stats: RelaxStats,
    node_groups: list[str],
    valid: np.ndarray | None = None,
) -> dict[str, float]:
    """Aggregate the per-node dual prices into per-node-group means —
    the autoscaler-facing cost signal (ROADMAP item #2): a group priced
    at 0 has slack at the converged plan; a rising price is demand the
    group cannot absorb. ``node_groups`` names a group per UNPADDED
    node slot (e.g. the zone label); padded slots never contribute."""
    if stats.node_prices is None:
        return {}
    out: dict[str, list[float]] = {}
    for i, grp in enumerate(node_groups):
        if valid is not None and not bool(valid[i]):
            continue
        out.setdefault(grp, []).append(float(stats.node_prices[i]))
    return {g: float(np.mean(v)) for g, v in sorted(out.items())}
