"""Device kernels for PodTopologySpread (the in-scan pieces).

Domain bookkeeping that the reference keeps in hash maps
(podtopologyspread/filtering.go: TpPairToMatchNum, TpKeyToCriticalPaths) is
recomputed per scan step as segment reductions over the node axis: counts
per domain = segment_sum of per-node match counts keyed by domain id, the
"critical path" minimum = masked min over registered domains. This is the
TPU-shaped tradeoff — O(N) fused vector work per constraint per step beats
maintaining device-side sorted structures, and the node axis is already
lane-resident.

Sentinel: INF_COUNT stands in for the reference's math.MaxInt32 initial
criticalPaths value — an empty domain set means the constraint cannot be
violated (skew is hugely negative), matching filtering.go#minMatchNum.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import ops as jops

MAX_NODE_SCORE = 100
INF_COUNT = jnp.int32(2**30)


def _domain_aggregate(dom_row, elig_row, cnt_row, d_pad: int):
    """Returns (per-node domain count, #registered domains, min over
    registered domains). dom_row: [N] int32 (-1 missing), elig_row: [N] bool,
    cnt_row: [N] int32 per-node match counts."""
    hk = dom_row >= 0
    dd = jnp.where(hk, dom_row, 0)
    counted = elig_row & hk
    dom_counts = jops.segment_sum(
        jnp.where(counted, cnt_row, 0), dd, num_segments=d_pad
    )
    dom_present = (
        jops.segment_sum(counted.astype(jnp.int32), dd, num_segments=d_pad) > 0
    )
    n_dom = jnp.sum(dom_present.astype(jnp.int32))
    min_match = jnp.min(jnp.where(dom_present, dom_counts, INF_COUNT))
    node_cnt = dom_counts[dd]  # [N]
    return node_cnt, n_dom, min_match, hk


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def hard_violations(spr, cnt, cls, d_pad: int):
    """[N] bool — any hard spread constraint of class ``cls`` violated.

    spr: dict of spread tables (dom, elig, max_skew, min_domains, self_match,
    hard [C, Sh]); cnt: [J, N] carried per-node match counts.
    """
    n = spr["dom"].shape[1]
    viol = jnp.zeros(n, dtype=bool)
    sh = spr["hard"].shape[1]
    for s in range(sh):  # static unroll over the class's constraint slots
        j = spr["hard"][cls, s]
        active = j >= 0
        jj = jnp.maximum(j, 0)
        node_cnt, n_dom, min_match, hk = _domain_aggregate(
            spr["dom"][jj], spr["elig"][jj], cnt[jj], d_pad
        )
        md = spr["min_domains"][jj]
        min_match = jnp.where((md >= 0) & (n_dom < md), 0, min_match)
        skew = node_cnt + spr["self_match"][jj].astype(jnp.int32) - min_match
        v = (~hk) | (skew > spr["max_skew"][jj])
        viol = viol | (v & active)
    return viol


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def soft_scores(spr, cnt, cls, mask, d_pad: int, fdtype=jnp.float32):
    """[N] int32 — normalized 0-100 PodTopologySpread score over the
    feasible set ``mask`` (scoring.go#Score + #NormalizeScore).

    ``fdtype`` mirrors the solver's balanced_fdtype knob: float64 matches the
    oracle's Go-float64 math bit-for-bit in CPU parity tests."""
    n = spr["dom"].shape[1]
    ss = spr["soft"].shape[1]
    raw = jnp.zeros(n, dtype=fdtype)
    ignored = jnp.zeros(n, dtype=bool)
    has_soft = spr["soft"][cls, 0] >= 0
    n_feasible = jnp.sum(mask.astype(jnp.int32))
    for s in range(ss):
        j = spr["soft"][cls, s]
        active = j >= 0
        jj = jnp.maximum(j, 0)
        node_cnt, n_dom, _, hk = _domain_aggregate(
            spr["dom"][jj], spr["elig"][jj], cnt[jj], d_pad
        )
        hostname = spr["is_hostname"][jj]
        c = jnp.where(hostname, cnt[jj], node_cnt).astype(fdtype)
        size = jnp.where(hostname, n_feasible, n_dom).astype(fdtype)
        contrib = c * jnp.log(size + 2.0) + (
            spr["max_skew"][jj].astype(fdtype) - 1.0
        )
        raw = raw + jnp.where(active & hk, contrib, 0.0)
        ignored = ignored | (active & ~hk)
    raw_i = jnp.round(raw).astype(jnp.int32)

    considered = mask & ~ignored
    mx = jnp.max(jnp.where(considered, raw_i, -INF_COUNT))
    mn = jnp.min(jnp.where(considered, raw_i, INF_COUNT))
    any_considered = jnp.any(considered)
    norm = MAX_NODE_SCORE * (mx + mn - raw_i) // jnp.maximum(mx, 1)
    norm = jnp.where(mx == 0, MAX_NODE_SCORE, norm)
    out = jnp.where(considered & any_considered, norm, 0)
    return jnp.where(has_soft, out, 0)
