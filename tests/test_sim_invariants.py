"""Known-bad fixtures for the sim's invariant checkers — the
fixture-per-rule pattern of tests/test_static_analysis.py: a checker
that never fires gates nothing, so each one is fed a crafted violation
it MUST flag (and a clean state it must not)."""

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.sim.invariants import (
    BindTransitionTracker,
    MonotonicCounters,
    check_capacity,
    check_constraints,
    check_lost_pods,
)
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock


def _cluster(n_nodes=2, cpu="4"):
    cs = ClusterState(clock=FakeClock())
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": "10"})
            .obj()
        )
    return cs


def _pod(name, cpu="1"):
    return MakePod().name(name).req({"cpu": cpu, "memory": "1Gi"}).obj()


# -- double_bind ------------------------------------------------------------


def test_double_bind_flags_node_transition():
    cs = _cluster()
    tracker = BindTransitionTracker(cs)
    cs.create_pod(_pod("a"))
    cs.bind("default", "a", "n0")
    violations = []
    tracker.drain(0, violations)
    assert violations == []  # a first bind is fine
    # the state service's binding subresource refuses rebinds, so forge
    # the A->B transition the way a buggy writer would: update_pod
    pod = cs.get_pod("default", "a")
    pod.node_name = "n1"
    cs.update_pod(pod)
    tracker.drain(1, violations)
    assert len(violations) == 1
    assert violations[0].invariant == "double_bind"
    assert "rebound n0 -> n1" in violations[0].detail


def test_double_bind_flags_duplicate_scheduler_result():
    cs = _cluster()
    tracker = BindTransitionTracker(cs)
    tracker.record_results([("default/a", "n0")])
    tracker.record_results([("default/a", "n1")])
    violations = []
    tracker.drain(0, violations)
    assert [v.invariant for v in violations] == ["double_bind"]


def test_double_bind_allows_delete_then_recreate():
    cs = _cluster()
    tracker = BindTransitionTracker(cs)
    cs.create_pod(_pod("a"))
    cs.bind("default", "a", "n0")
    cs.delete_pod("default", "a")
    cs.create_pod(_pod("a"))
    cs.bind("default", "a", "n1")
    violations = []
    tracker.drain(0, violations)
    assert violations == []


# -- capacity ---------------------------------------------------------------


def test_capacity_flags_overflow():
    cs = _cluster(n_nodes=1, cpu="2")
    # the binding subresource doesn't check capacity (neither does the
    # apiserver) — overflowing it is exactly the scheduler bug class
    # this checker exists to catch
    for i in range(3):
        cs.create_pod(_pod(f"p{i}", cpu="1"))
        cs.bind("default", f"p{i}", "n0")
    violations = []
    check_capacity(cs, 0, violations)
    assert [v.invariant for v in violations] == ["capacity"]
    assert "cpu used 3000 > allocatable 2000" in violations[0].detail


def test_capacity_clean_at_exact_fit():
    cs = _cluster(n_nodes=1, cpu="2")
    for i in range(2):
        cs.create_pod(_pod(f"p{i}", cpu="1"))
        cs.bind("default", f"p{i}", "n0")
    violations = []
    check_capacity(cs, 0, violations)
    assert violations == []


def test_capacity_flags_pod_count_overflow():
    cs = _cluster(n_nodes=1, cpu="64")
    # pods allocatable is 10; bind 11 near-free pods
    for i in range(11):
        cs.create_pod(_pod(f"p{i}", cpu="100m"))
        cs.bind("default", f"p{i}", "n0")
    violations = []
    check_capacity(cs, 0, violations)
    assert any("pods > allowed" in v.detail for v in violations)


# -- constraint (hard-shape placements) -------------------------------------


def test_constraint_flags_hostport_clash():
    cs = _cluster(n_nodes=1, cpu="8")
    for i in range(2):
        cs.create_pod(
            MakePod()
            .name(f"p{i}")
            .req({"cpu": "1"})
            .host_port(8080)
            .obj()
        )
        cs.bind("default", f"p{i}", "n0")
    violations = []
    check_constraints(cs, 0, violations)
    assert [v.invariant for v in violations] == ["constraint"]
    assert "hostPort" in violations[0].detail


def test_constraint_flags_anti_affinity_coresidence():
    cs = _cluster(n_nodes=1, cpu="8")
    for i in range(2):
        cs.create_pod(
            MakePod()
            .name(f"a{i}")
            .label("app", "anti")
            .req({"cpu": "1"})
            .pod_anti_affinity("kubernetes.io/hostname", {"app": "anti"})
            .obj()
        )
        cs.bind("default", f"a{i}", "n0")
    violations = []
    check_constraints(cs, 0, violations)
    assert violations and all(
        v.invariant == "constraint" for v in violations
    )
    assert "anti-affinity" in violations[0].detail


def test_constraint_clean_on_separate_nodes():
    cs = _cluster(n_nodes=2, cpu="8")
    for i in range(2):
        cs.create_pod(
            MakePod()
            .name(f"a{i}")
            .label("app", "anti")
            .req({"cpu": "1"})
            .pod_anti_affinity("kubernetes.io/hostname", {"app": "anti"})
            .host_port(8080)
            .obj()
        )
        cs.bind("default", f"a{i}", f"n{i}")
    violations = []
    check_constraints(cs, 0, violations)
    assert violations == []


# -- lost_pod ---------------------------------------------------------------


def _sched(cs):
    return Scheduler(
        cs,
        SchedulerConfig(
            batch_size=8,
            solver=ExactSolverConfig(tie_break="first", group_size=4),
        ),
        clock=FakeClock(),
    )


def test_lost_pod_flags_dropped_bookkeeping():
    cs = _cluster()
    s = _sched(cs)
    cs.create_pod(_pod("a"))
    violations = []
    check_lost_pods(cs, s, 0, violations)
    assert violations == []  # queued: accounted for
    # simulate the bug class: the pod falls out of every structure
    s.queue.delete("default/a")
    check_lost_pods(cs, s, 1, violations)
    assert [v.invariant for v in violations] == ["lost_pod"]
    assert "default/a" in violations[0].detail


def test_lost_pod_accepts_undelivered_watch_add():
    cs = _cluster()
    s = _sched(cs)
    cs.unsubscribe(s._on_event)  # the delayed-bus interposition shape
    cs.create_pod(_pod("a"))  # scheduler never saw the ADDED event
    violations = []
    check_lost_pods(
        cs, s, 0, violations, undelivered=lambda: {"default/a"}
    )
    assert violations == []
    check_lost_pods(cs, s, 1, violations)  # no undelivered claim -> lost
    assert [v.invariant for v in violations] == ["lost_pod"]


def test_lost_pod_ignores_foreign_scheduler_pods():
    cs = _cluster()
    s = _sched(cs)
    pod = MakePod().name("x").scheduler_name("other").req({"cpu": "1"}).obj()
    cs.create_pod(pod)
    violations = []
    check_lost_pods(cs, s, 0, violations)
    assert violations == []


# -- monotonic --------------------------------------------------------------


def test_monotonic_flags_regressing_counter():
    series = {"scheduler_schedule_attempts_total": 5.0}
    mono = MonotonicCounters(sample=lambda: dict(series))
    violations = []
    mono.observe(0, violations)
    assert violations == []
    series["scheduler_schedule_attempts_total"] = 3.0  # regression
    mono.observe(1, violations)
    assert [v.invariant for v in violations] == ["monotonic"]
    assert "went backwards" in violations[0].detail


def test_monotonic_clean_on_growth():
    series = {"scheduler_schedule_attempts_total": 5.0}
    mono = MonotonicCounters(sample=lambda: dict(series))
    violations = []
    mono.observe(0, violations)
    series["scheduler_schedule_attempts_total"] = 9.0
    mono.observe(1, violations)
    assert violations == []


# -- progress (the settle loop's violation) ---------------------------------


def test_progress_violation_on_unsettled_harness():
    """A harness whose scheduler never drains must emit a progress
    violation instead of looping forever — pin it with a queue-stuffed
    settle check rather than a real livelock (the real one is what the
    pipelined backstop prevents, test_pipelined covers it)."""
    from kubernetes_tpu.sim.harness import SimHarness

    h = SimHarness("node_flaps", seed=0, cycles=0, max_settle_rounds=3)
    # park a pod the scheduler will never see an event for, then gut the
    # drive so nothing ever drains it
    cs = h.cluster
    cs.create_pod(_pod("stuck"))
    h.bus.pump_all()
    h.scheduler.run_until_settled = lambda max_batches=0: []
    h.scheduler.run_pipelined = lambda max_batches=0: []
    res = h.run()
    assert not res.settled
    assert any(v.invariant == "progress" for v in res.violations)


# -- recovery (crash_restart) -----------------------------------------------


def test_recovery_flags_crash_that_never_fired():
    from kubernetes_tpu.sim.invariants import check_recovery

    violations = []
    check_recovery(
        0, violations, crash_expected=True, crashes=0, incarnations=1,
        orphans_at_restart=0, recovered_records=0,
    )
    assert [v.invariant for v in violations] == ["recovery"]
    assert "never engaged" in violations[0].detail


def test_recovery_flags_missing_restart():
    from kubernetes_tpu.sim.invariants import check_recovery

    violations = []
    check_recovery(
        0, violations, crash_expected=True, crashes=1, incarnations=1,
        orphans_at_restart=0, recovered_records=0,
    )
    assert [v.invariant for v in violations] == ["recovery"]
    assert "restart never happened" in violations[0].detail


def test_recovery_flags_unjournaled_orphans():
    from kubernetes_tpu.sim.invariants import check_recovery

    violations = []
    check_recovery(
        0, violations, crash_expected=True, crashes=1, incarnations=2,
        orphans_at_restart=3, recovered_records=0,
    )
    assert [v.invariant for v in violations] == ["recovery"]
    assert "recovered" in violations[0].detail


def test_recovery_clean_on_good_run():
    from kubernetes_tpu.sim.invariants import check_recovery

    violations = []
    check_recovery(
        0, violations, crash_expected=True, crashes=1, incarnations=2,
        orphans_at_restart=3, recovered_records=3,
    )
    assert violations == []


# -- fencing (hub_partition / zombie) ---------------------------------------


def test_fencing_flags_vacuous_zombie():
    from kubernetes_tpu.sim.invariants import check_hub_partition

    violations = []
    check_hub_partition(
        0, violations, fenced_commits=0, zombie_binds_while_fenced=0,
        stale_rejections=2,
    )
    assert [v.invariant for v in violations] == ["fencing"]
    assert "never engaged" in violations[0].detail


def test_fencing_flags_leaked_zombie_bind():
    from kubernetes_tpu.sim.invariants import check_hub_partition

    violations = []
    check_hub_partition(
        0, violations, fenced_commits=2, zombie_binds_while_fenced=1,
        stale_rejections=2,
    )
    assert [v.invariant for v in violations] == ["fencing"]
    assert "LANDED" in violations[0].detail


def test_fencing_flags_missing_conservative_admission():
    from kubernetes_tpu.sim.invariants import check_hub_partition

    violations = []
    check_hub_partition(
        0, violations, fenced_commits=2, zombie_binds_while_fenced=0,
        stale_rejections=0,
    )
    assert [v.invariant for v in violations] == ["fencing"]
    assert "conservative" in violations[0].detail


def test_fencing_clean_on_good_partition_run():
    from kubernetes_tpu.sim.invariants import check_hub_partition

    violations = []
    check_hub_partition(
        0, violations, fenced_commits=2, zombie_binds_while_fenced=0,
        stale_rejections=3,
    )
    assert violations == []


# -- rebalance (fragmentation profile) --------------------------------------


def _run_record(t=10.0, packing=0.4, evicted=2, **kw):
    from kubernetes_tpu.rebalance.runtime import RunRecord

    return RunRecord(
        t=t, packing_before=packing, stranded_before=0.5,
        planned=kw.get("planned", evicted),
        selected=kw.get("selected", evicted),
        evicted=evicted, pdb_blocked=kw.get("pdb_blocked", 0),
        plan_solve_s=0.01,
    )


def _check_rebalance(violations, history, **kw):
    from kubernetes_tpu.sim.invariants import check_rebalance

    defaults = dict(
        budget=4, pdb_overruns=0, migrations_completed=1,
        churn_end_t=9.0, final_packing=0.5,
    )
    defaults.update(kw)
    check_rebalance(0, violations, history=history, **defaults)


def test_rebalance_flags_budget_exceeded_plan():
    violations = []
    _check_rebalance(violations, [_run_record(evicted=9)], budget=4)
    assert [v.invariant for v in violations] == ["rebalance"]
    assert "churn budget" in violations[0].detail


def test_rebalance_flags_pdb_violating_eviction():
    violations = []
    _check_rebalance(violations, [_run_record()], pdb_overruns=1)
    assert [v.invariant for v in violations] == ["rebalance"]
    assert "PDB" in violations[0].detail


def test_rebalance_flags_utilization_regression():
    violations = []
    _check_rebalance(
        violations,
        [
            _run_record(t=10.0, packing=0.5),
            _run_record(t=21.0, packing=0.3),  # settle-phase regression
        ],
    )
    assert any(
        v.invariant == "rebalance" and "regressed" in v.detail
        for v in violations
    )


def test_rebalance_flags_final_packing_regression():
    violations = []
    _check_rebalance(
        violations, [_run_record(t=10.0, packing=0.5)], final_packing=0.2,
    )
    assert [v.invariant for v in violations] == ["rebalance"]
    assert "final packed utilization" in violations[0].detail


def test_rebalance_flags_stranded_evictions():
    violations = []
    _check_rebalance(
        violations, [_run_record(evicted=3)], migrations_completed=0,
    )
    assert [v.invariant for v in violations] == ["rebalance"]
    assert "strands" in violations[0].detail


def test_rebalance_flags_never_engaged():
    violations = []
    _check_rebalance(violations, [])
    assert [v.invariant for v in violations] == ["rebalance"]
    assert "never engaged" in violations[0].detail


def test_rebalance_churn_phase_regression_exempt():
    # packing moving both ways DURING churn is legitimate: only
    # settle-phase passes are held to monotonicity
    violations = []
    _check_rebalance(
        violations,
        [
            _run_record(t=3.0, packing=0.6),  # churn phase
            _run_record(t=5.0, packing=0.3),  # churn phase
            _run_record(t=10.0, packing=0.4),
            _run_record(t=21.0, packing=0.45),
        ],
    )
    assert violations == []


def test_rebalance_clean_on_good_run():
    violations = []
    _check_rebalance(
        violations,
        [_run_record(t=10.0, packing=0.4), _run_record(t=21.0, packing=0.55)],
        final_packing=0.6,
    )
    assert violations == []


def test_rebalance_tracker_counts_evictions_and_pdb_overruns():
    """The tracker's independent allowance mirror must flag an eviction
    that the enforcement code (hypothetically buggy) let through."""
    from kubernetes_tpu.api.labels import (
        Selector,
        requirements_from_match_labels,
    )
    from kubernetes_tpu.api.objects import PodDisruptionBudget
    from kubernetes_tpu.sim.invariants import RebalanceTracker

    cs = _cluster()
    cs.create_pdb(
        PodDisruptionBudget(
            name="guard", namespace="default",
            selector=Selector(
                requirements=requirements_from_match_labels({"app": "g"})
            ),
            disruptions_allowed=1,
        )
    )
    tracker = RebalanceTracker(cs)
    for name in ("a", "b"):
        pod = MakePod().name(name).label("app", "g").req(
            {"cpu": "1", "memory": "1Gi"}
        ).obj()
        cs.create_pod(pod)
        cs.bind("default", name, "n0")
    # first eviction consumes the allowance; force the second past the
    # subresource's own gate by resetting the LIVE allowance — the
    # tracker's mirror (seeded at construction) must still flag it
    cs.evict("default", "a")
    assert tracker.evictions == 1 and tracker.pdb_overruns == 0
    cs.list_pdbs()[0].disruptions_allowed = 1
    cs.evict("default", "b")
    assert tracker.evictions == 2
    assert tracker.pdb_overruns == 1
    assert tracker.evicted_keys == ["default/a", "default/b"]


def test_double_bind_evict_then_rebind_is_legitimate():
    """An evict-and-rebind inside one drive delivers its DELETED before
    the bind report drains: the banked bound-delete credit keeps the
    tracker from misreading the migration as a double-bind — while a
    genuine double-report still flags."""
    cs = _cluster()
    tracker = BindTransitionTracker(cs)
    cs.create_pod(_pod("a"))
    cs.bind("default", "a", "n0")
    cs.evict("default", "a")
    cs.bind("default", "a", "n1")
    violations = []
    # both binds report at drive end, after the eviction's DELETED
    tracker.record_results([("default/a", "n0"), ("default/a", "n1")])
    tracker.drain(0, violations)
    assert violations == []
    # a THIRD report with no delete in between is still a double-bind
    tracker.record_results([("default/a", "n1")])
    tracker.drain(1, violations)
    assert [v.invariant for v in violations] == ["double_bind"]


def test_double_bind_plain_delete_banks_no_credit():
    """Only EVICTIONS bank re-bind credits (keyed on the subresource's
    Evicted event): a plain bound-pod delete racing the bind report
    must NOT absorb a masked double-report of the dead pod's key —
    that is exactly the scheduler bug the check exists to catch."""
    cs = _cluster()
    tracker = BindTransitionTracker(cs)
    cs.create_pod(_pod("a"))
    cs.bind("default", "a", "n0")
    cs.delete_pod("default", "a")  # churn delete, no Evicted record
    violations = []
    tracker.record_results([("default/a", "n0"), ("default/a", "n0")])
    tracker.drain(0, violations)
    assert [v.invariant for v in violations] == ["double_bind"]


# -- cross-incarnation journal merge ----------------------------------------


def test_merged_last_outcomes_last_incarnation_wins():
    from kubernetes_tpu.sim.invariants import merged_last_outcomes

    inc1 = [
        '{"outcome":"permit_wait","pod":"default/a","step":1,"t":1.0}',
        '{"outcome":"bound","pod":"default/b","step":1,"t":1.0}',
    ]
    inc2 = [
        '{"outcome":"recovered","pod":"default/a","step":0,"t":4.0}',
    ]
    merged = merged_last_outcomes([inc1, inc2])
    assert merged["default/a"]["outcome"] == "recovered"
    assert merged["default/b"]["outcome"] == "bound"


# -- gang (no partial binds) -------------------------------------------------


def _gang_pod(name, group="train", min_member=3):
    from kubernetes_tpu.gang import GANG_LABEL, MIN_MEMBER_ANNOTATION

    return (
        MakePod()
        .name(name)
        .req({"cpu": "1", "memory": "1Gi"})
        .label(GANG_LABEL, group)
        .annotation(MIN_MEMBER_ANNOTATION, str(min_member))
        .obj()
    )


def test_gang_flags_partially_bound_group():
    from kubernetes_tpu.sim.invariants import check_no_partial_gangs

    cs = _cluster()
    for n in ("m0", "m1", "m2"):
        cs.create_pod(_gang_pod(n))
    # forge the wreck a non-atomic commit would leave: 1/3 bound
    cs.bind("default", "m0", "n0")
    violations = []
    check_no_partial_gangs(cs, 3, violations)
    assert [v.invariant for v in violations] == ["gang"]
    assert "default/train" in violations[0].detail
    assert "default/m0" in violations[0].detail  # names the bound side
    assert "default/m1" in violations[0].detail  # and the pending side


def test_gang_clean_when_fully_bound_or_fully_pending():
    from kubernetes_tpu.sim.invariants import check_no_partial_gangs

    cs = _cluster()
    violations = []
    # all pending: fine (mid-assembly)
    for n in ("m0", "m1", "m2"):
        cs.create_pod(_gang_pod(n))
    check_no_partial_gangs(cs, 0, violations)
    assert violations == []
    # all bound: fine (the atomic commit landed)
    for i, n in enumerate(("m0", "m1", "m2")):
        cs.bind("default", n, f"n{i % 2}")
    check_no_partial_gangs(cs, 1, violations)
    assert violations == []
    # two independent gangs, each internally consistent: still clean
    for n in ("x0", "x1"):
        cs.create_pod(_gang_pod(n, group="other", min_member=2))
    check_no_partial_gangs(cs, 2, violations)
    assert violations == []


def test_gang_delete_churn_cannot_fake_violation():
    from kubernetes_tpu.sim.invariants import check_no_partial_gangs

    cs = _cluster()
    for n in ("m0", "m1"):
        cs.create_pod(_gang_pod(n, min_member=2))
    cs.bind("default", "m0", "n0")
    cs.bind("default", "m1", "n1")
    # delete churn removes one bound member: the survivor is all-bound,
    # not a partial gang
    cs.delete_pod("default", "m0")
    violations = []
    check_no_partial_gangs(cs, 5, violations)
    assert violations == []


# -- telemetry --------------------------------------------------------------


def _tel_summary(anomalies=1, captures=1):
    return {"anomalies": anomalies, "bundles_captured": captures}


def test_telemetry_flags_silent_sentinel():
    from kubernetes_tpu.sim.invariants import check_telemetry

    v = []
    check_telemetry(5, v, summary=_tel_summary(anomalies=0))
    assert [x.invariant for x in v] == ["telemetry"]
    assert "sentinel never fired" in v[0].detail


def test_telemetry_flags_disconnected_capture_seam():
    from kubernetes_tpu.sim.invariants import check_telemetry

    v = []
    check_telemetry(5, v, summary=_tel_summary(captures=0))
    assert [x.invariant for x in v] == ["telemetry"]
    assert "capture seam is disconnected" in v[0].detail


def test_telemetry_flags_configured_dir_with_no_bundles(tmp_path):
    from kubernetes_tpu.sim.invariants import check_telemetry

    v = []
    check_telemetry(
        5, v, summary=_tel_summary(), bundle_dir=str(tmp_path)
    )
    assert [x.invariant for x in v] == ["telemetry"]
    assert "no bundle was written" in v[0].detail


def test_telemetry_flags_unloadable_bundle(tmp_path):
    from kubernetes_tpu.sim.invariants import check_telemetry

    # a bundle directory with no manifest: load must fail and the
    # checker must surface it (a truncated capture is itself a finding)
    (tmp_path / "bundle-00000-sentinel").mkdir()
    v = []
    check_telemetry(
        5, v, summary=_tel_summary(), bundle_dir=str(tmp_path)
    )
    details = [x.detail for x in v]
    assert any("failed to load/replay" in d for d in details)
    # ... and with every bundle broken, the loop never closed
    assert any("none replayed bit-identical" in d for d in details)


def test_telemetry_clean_without_bundle_dir():
    from kubernetes_tpu.sim.invariants import check_telemetry

    v = []
    check_telemetry(5, v, summary=_tel_summary())
    assert v == []


# -- megaplan ---------------------------------------------------------------


def _mp_summary(**kw):
    base = {
        "pods": 120,
        "ranked": 90,
        "iterations": 64,
        "plan_valid": True,
        "plan_errors": 0,
        "objective_ratio": 1.02,
        "relax_placed": 95,
        "exact_placed": 93,
    }
    base.update(kw)
    return base


def test_megaplan_flags_missing_probe():
    from kubernetes_tpu.sim.invariants import check_megaplan

    v = []
    check_megaplan(5, v, summary=None)
    assert [x.invariant for x in v] == ["megaplan"]


def test_megaplan_flags_never_iterated():
    from kubernetes_tpu.sim.invariants import check_megaplan

    v = []
    check_megaplan(5, v, summary=_mp_summary(iterations=0))
    assert [x.invariant for x in v] == ["megaplan"]
    assert "never iterated" in v[0].detail


def test_megaplan_flags_disconnected_reorder_seam():
    from kubernetes_tpu.sim.invariants import check_megaplan

    v = []
    check_megaplan(5, v, summary=_mp_summary(ranked=0))
    assert [x.invariant for x in v] == ["megaplan"]
    assert "re-ranked zero" in v[0].detail


def test_megaplan_flags_infeasible_plan():
    from kubernetes_tpu.sim.invariants import check_megaplan

    v = []
    check_megaplan(
        5, v, summary=_mp_summary(plan_valid=False, plan_errors=3)
    )
    assert [x.invariant for x in v] == ["megaplan"]
    assert "feasibility replay" in v[0].detail


def test_megaplan_flags_ratio_below_floor():
    from kubernetes_tpu.sim.invariants import check_megaplan

    v = []
    check_megaplan(5, v, summary=_mp_summary(objective_ratio=0.5))
    assert [x.invariant for x in v] == ["megaplan"]
    assert "floor" in v[0].detail


def test_megaplan_clean_on_good_summary():
    from kubernetes_tpu.sim.invariants import check_megaplan

    v = []
    check_megaplan(5, v, summary=_mp_summary())
    assert v == []


# -- fleet backlog drain -----------------------------------------------------


def _fd_kwargs(**kw):
    base = {
        "backlog": 120,
        "drained": 118,
        "double_binds": 0,
        "lost": 0,
        "leases_reassigned": 1,
        "expect_reassign": True,
    }
    base.update(kw)
    return base


def test_fleet_drain_clean_on_good_summary():
    from kubernetes_tpu.sim.invariants import check_fleet_drain

    v = []
    check_fleet_drain(5, v, **_fd_kwargs())
    assert v == []


def test_fleet_drain_flags_empty_backlog_as_vacuous():
    from kubernetes_tpu.sim.invariants import check_fleet_drain

    v = []
    check_fleet_drain(5, v, **_fd_kwargs(backlog=0))
    assert [x.invariant for x in v] == ["fleet_drain"]
    assert "vacuous" in v[0].detail


def test_fleet_drain_flags_disengaged_ledger():
    from kubernetes_tpu.sim.invariants import check_fleet_drain

    v = []
    check_fleet_drain(5, v, **_fd_kwargs(drained=0))
    assert [x.invariant for x in v] == ["fleet_drain"]
    assert "never engaged" in v[0].detail


def test_fleet_drain_flags_lost_pods():
    from kubernetes_tpu.sim.invariants import check_fleet_drain

    v = []
    check_fleet_drain(5, v, **_fd_kwargs(lost=3))
    assert [x.invariant for x in v] == ["fleet_drain"]
    assert "lost work" in v[0].detail


def test_fleet_drain_flags_double_binds():
    from kubernetes_tpu.sim.invariants import check_fleet_drain

    v = []
    check_fleet_drain(5, v, **_fd_kwargs(double_binds=2))
    assert [x.invariant for x in v] == ["fleet_drain"]
    assert "two drain leases" in v[0].detail


def test_fleet_drain_flags_disconnected_reassignment_seam():
    from kubernetes_tpu.sim.invariants import check_fleet_drain

    v = []
    check_fleet_drain(5, v, **_fd_kwargs(leases_reassigned=0))
    assert [x.invariant for x in v] == ["fleet_drain"]
    assert "return-on-retire" in v[0].detail


def test_fleet_drain_reassign_clause_scoped_to_kill_profiles():
    from kubernetes_tpu.sim.invariants import check_fleet_drain

    v = []
    check_fleet_drain(
        5, v,
        **_fd_kwargs(leases_reassigned=0, expect_reassign=False),
    )
    assert v == []
