"""Continuous rebalancer (kubernetes_tpu/rebalance): fragmentation
detection over snapshot tensors, the pack-objective auction plan and its
budget/gain/feasibility/PDB bounding, and the runtime loop end to end
through the REAL Scheduler — evict (fenced, PDB-gated, Conflict-on-
stale) -> requeue with a nominated hint -> re-bind through the ordinary
commit path. The sim's `fragmentation` profile proves the same loop
under churn; these are the direct unit/integration tiers."""

import numpy as np
import pytest

from kubernetes_tpu.api.labels import (
    Selector,
    requirements_from_match_labels,
)
from kubernetes_tpu.api.objects import PodDisruptionBudget
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.rebalance.detector import (
    detect,
    packing_score,
)
from kubernetes_tpu.rebalance.planner import select_moves
from kubernetes_tpu.rebalance.runtime import RebalanceConfig, Rebalancer
from kubernetes_tpu.scheduler import BatchResult, Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.state.snapshot import Snapshot
from kubernetes_tpu.tensorize.schema import CPU_IDX
from kubernetes_tpu.utils.clock import FakeClock


def node(name, cpu="8", mem="16Gi", pods="110"):
    return (
        MakeNode()
        .name(name)
        .capacity({"cpu": cpu, "memory": mem, "pods": pods})
        .obj()
    )


def pod(name, cpu="1", mem="1Gi", prio=0, labels=None):
    mp = MakePod().name(name).req({"cpu": cpu, "memory": mem})
    if prio:
        mp = mp.priority(prio)
    for k, v in (labels or {}).items():
        mp = mp.label(k, v)
    return mp.obj()


def batch_of(placements, node_cpu="8", node_mem="16Gi"):
    """NodeBatch via the production cache+snapshot path:
    ``placements`` maps node name -> list of (pod_name, cpu)."""
    c = SchedulerCache(FakeClock())
    for name in placements:
        c.add_node(node(name, cpu=node_cpu, mem=node_mem))
    for name, pods_here in placements.items():
        for pname, cpu in pods_here:
            p = pod(pname, cpu=cpu)
            p.node_name = name
            c.add_pod(p)
    snap = Snapshot()
    return snap.update(c), snap


# -- detector ---------------------------------------------------------------


def test_detect_flags_sparse_scatter_as_fragmented():
    # 12 cpu of load thinly spread over 6 of 6 nodes: packed 0.25,
    # bin-packing lower bound 2 -> fragmented at the 0.7 bar
    b, _ = batch_of(
        {f"n{i}": [(f"p{i}a", "1"), (f"p{i}b", "1")] for i in range(6)}
    )
    r = detect(b, min_packing=0.7)
    assert r.nodes_in_use == 6
    assert r.ideal_nodes == 2
    assert r.packed_utilization == pytest.approx(12 / 48)
    assert r.fragmented


def test_detect_unconsolidatable_sparse_cluster_exempt():
    # one near-node-sized pod per node: packed is low-ish but the load
    # provably cannot fit on fewer nodes -> never fragmented (would
    # trigger pointless plan solves every interval otherwise)
    b, _ = batch_of({f"n{i}": [(f"p{i}", "5")] for i in range(2)})
    r = detect(b, min_packing=0.7)
    assert r.packed_utilization == pytest.approx(10 / 16)  # below bar
    assert r.nodes_in_use == 2
    assert r.ideal_nodes == 2  # ceil(10 / 8): no consolidation exists
    assert not r.fragmented


def test_detect_well_packed_cluster_not_fragmented():
    b, _ = batch_of(
        {
            "n0": [(f"p{i}", "1") for i in range(7)],
            "n1": [(f"q{i}", "1") for i in range(7)],
            "n2": [],
            "n3": [],
        }
    )
    r = detect(b, min_packing=0.7)
    assert r.packed_utilization == pytest.approx(14 / 16)
    assert not r.fragmented


def test_detect_empty_cluster_is_trivially_packed():
    b, _ = batch_of({"n0": [], "n1": []})
    r = detect(b)
    assert r.nodes_in_use == 0
    assert r.packed_utilization == 1.0
    assert not r.fragmented


def test_packing_score_dominant_resource_and_extra_used():
    b, snap = batch_of({"n0": [("p0", "4")], "n1": []})
    s0 = snap.slot_of("n0")
    assert packing_score(b, s0) == 50  # 4/8 cpu dominates 1Gi/16Gi
    assert packing_score(b, snap.slot_of("n1")) == 0
    # minus the pod's own request: the source side of a move's gain
    req = np.asarray(
        b.vocab.vectorize(pod("x", cpu="4").resource_request()),
        dtype=np.int64,
    )
    assert packing_score(b, s0, extra_used=-req) < 50


# -- planner ----------------------------------------------------------------


def _raw_moves(b, snap, specs):
    """[(pod, src_slot, dst_slot)] from (pod, src_name, dst_name)."""
    return [
        (p, snap.slot_of(src), snap.slot_of(dst))
        for p, src, dst in specs
    ]


def test_plan_moves_consolidates_off_drained_sources():
    from kubernetes_tpu.rebalance.planner import plan_moves

    b, snap = batch_of(
        {
            "n0": [("p0", "1")],
            "n1": [("p1", "2")],
            "n2": [(f"q{i}", "1") for i in range(5)],  # the anchor
            "n3": [],
        }
    )
    slot_names = list(snap.names)
    movable = []
    fixed_used = b.used.copy()
    fixed_cnt = b.pod_count.copy()
    drain = set()
    # two DISTINCT request classes: each class's rank-0 pod bids on its
    # own best node, so both must pick the fullest (the same-class case
    # round-robins across the window by design — select_moves prunes
    # the scattered tail by strict gain)
    for pname, cpu, nname in (("p0", "1", "n0"), ("p1", "2", "n1")):
        slot = snap.slot_of(nname)
        p = pod(pname, cpu=cpu)
        movable.append((p, slot))
        req = np.asarray(
            b.vocab.vectorize(p.resource_request()), dtype=np.int64
        )
        fixed_used[:, slot] -= req
        fixed_cnt[slot] -= 1
        drain.add(slot)
    raw = plan_moves(
        b, movable, fixed_used, fixed_cnt, frozenset(drain)
    )
    # the pack auction lands both candidates on the fullest node —
    # never back on a drained source
    assert len(raw) == 2
    for _p, src, dst in raw:
        assert dst not in drain
        assert slot_names[dst] == "n2"


def test_select_moves_respects_budget():
    b, snap = batch_of(
        {
            "n0": [(f"p{i}", "1") for i in range(4)],
            "n1": [(f"q{i}", "1") for i in range(6)],
        }
    )
    raw = _raw_moves(
        b, snap, [(pod(f"p{i}"), "n0", "n1") for i in range(4)]
    )
    plan = select_moves(
        b, list(snap.names), raw, [], budget=2, min_gain=1
    )
    assert plan.planned == 4
    assert len(plan.moves) == 2


def test_select_moves_priority_order_least_important_first():
    b, snap = batch_of(
        {
            "n0": [("lo", "1"), ("hi", "1")],
            "n1": [(f"q{i}", "1") for i in range(6)],
        }
    )
    raw = _raw_moves(
        b,
        snap,
        [
            (pod("hi", prio=100), "n0", "n1"),
            (pod("lo", prio=1), "n0", "n1"),
        ],
    )
    plan = select_moves(
        b, list(snap.names), raw, [], budget=1, min_gain=1
    )
    assert [m.pod.name for m in plan.moves] == ["lo"]


def test_select_moves_gain_first_within_a_priority():
    # same priority class, budget 1: the HIGHER-gain move wins even
    # when the lower-gain pod started more recently (start_time is
    # near-unique, so sorting it before gain would make gain dead)
    b, snap = batch_of(
        {
            "n0": [("lowgain", "1"), ("highgain", "1")],
            "n1": [("q0", "1"), ("q1", "1")],
            "n2": [(f"r{i}", "1") for i in range(6)],
        }
    )
    lo = pod("lowgain")
    lo.start_time = 100.0  # newest
    hi = pod("highgain")
    hi.start_time = 1.0
    raw = _raw_moves(
        b, snap, [(lo, "n0", "n1"), (hi, "n0", "n2")]
    )
    plan = select_moves(
        b, list(snap.names), raw, [], budget=1, min_gain=1
    )
    assert [m.pod.name for m in plan.moves] == ["highgain"]


def test_select_moves_drops_non_strict_gains():
    # n1 (the target) is EMPTIER than n0 without the pod: gain < 1 —
    # the move cannot strictly improve packing and must not be kept
    b, snap = batch_of(
        {
            "n0": [(f"p{i}", "1") for i in range(4)],
            "n1": [("q0", "1")],
        }
    )
    raw = _raw_moves(b, snap, [(pod("p0"), "n0", "n1")])
    plan = select_moves(
        b, list(snap.names), raw, [], budget=8, min_gain=1
    )
    assert plan.planned == 1
    assert plan.moves == []


def test_select_moves_skips_targets_without_live_capacity():
    # the plan's hypothetical target has no room in current truth: the
    # joint-feasibility pass must skip it (execution would just strand)
    b, snap = batch_of(
        {
            "n0": [("p0", "2")],
            "n1": [(f"q{i}", "1") for i in range(7)],  # 7/8 cpu used
        }
    )
    raw = _raw_moves(b, snap, [(pod("p0", cpu="2"), "n0", "n1")])
    plan = select_moves(
        b, list(snap.names), raw, [], budget=8, min_gain=1
    )
    assert plan.moves == []


def test_select_moves_pdb_gate_blocks_exhausted_cohort():
    b, snap = batch_of(
        {
            "n0": [("guarded", "1"), ("free", "1")],
            "n1": [(f"q{i}", "1") for i in range(6)],
        }
    )
    pdb = PodDisruptionBudget(
        name="guard",
        selector=Selector(
            requirements=requirements_from_match_labels({"app": "db"})
        ),
        disruptions_allowed=0,
    )
    raw = _raw_moves(
        b,
        snap,
        [
            (pod("guarded", labels={"app": "db"}), "n0", "n1"),
            (pod("free"), "n0", "n1"),
        ],
    )
    plan = select_moves(
        b, list(snap.names), raw, [pdb], budget=8, min_gain=1
    )
    assert plan.pdb_blocked == 1
    assert [m.pod.name for m in plan.moves] == ["free"]


def test_select_moves_pdb_allowance_decrements_across_plan():
    # two cohort pods, one disruption allowed: exactly one move
    # survives — the gate decrements per candidate like
    # filterPodsWithPDBViolation, not per PDB object
    b, snap = batch_of(
        {
            "n0": [("a", "1"), ("b", "1")],
            "n1": [(f"q{i}", "1") for i in range(6)],
        }
    )
    pdb = PodDisruptionBudget(
        name="guard",
        selector=Selector(
            requirements=requirements_from_match_labels({"app": "db"})
        ),
        disruptions_allowed=1,
    )
    raw = _raw_moves(
        b,
        snap,
        [
            (pod("a", labels={"app": "db"}), "n0", "n1"),
            (pod("b", labels={"app": "db"}), "n0", "n1"),
        ],
    )
    plan = select_moves(
        b, list(snap.names), raw, [pdb], budget=8, min_gain=1
    )
    assert plan.pdb_blocked == 1
    assert len(plan.moves) == 1


# -- runtime: the loop through the real Scheduler ---------------------------


def _fragmented(n_nodes=6, per_node=2, clock=None, rebalance=None,
                labels=None, fence_role=None):
    """6 nodes x 2 small pods each, bound through the state service:
    packed utilization 0.25 against the 0.7 bar."""
    from kubernetes_tpu.obs import ObsConfig

    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(node(f"n{i}"))
    for i in range(n_nodes):
        for j in range(per_node):
            name = f"p{i}{j}"
            cs.create_pod(pod(name, labels=labels))
            cs.bind("default", name, f"n{i}")
    cfg = SchedulerConfig(
        solver=ExactSolverConfig(tie_break="first"),
        rebalance=rebalance
        or RebalanceConfig(
            interval_s=1.0, max_moves_per_cycle=4, min_packing=0.7
        ),
        obs=ObsConfig(journal=True),
        fence_role=fence_role,
    )
    sched = Scheduler(cs, cfg, clock=clock or FakeClock())
    return cs, sched


def _packing(sched):
    return detect(
        sched.snapshot.update(sched.cache),
        min_packing=sched.rebalancer.config.min_packing,
    )


def test_rebalancer_consolidates_within_budget_every_cycle():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock)
    before = _packing(sched)
    assert before.fragmented
    for _ in range(12):
        clock.advance(1.5)
        sched.run_until_settled()
        if not _packing(sched).fragmented:
            break
    after = _packing(sched)
    # converged above the bar in a bounded number of cycles, never
    # exceeding the churn budget, and every eviction re-bound (the
    # migration completed through the ordinary scheduling path)
    assert not after.fragmented
    assert after.packed_utilization > before.packed_utilization
    assert after.nodes_in_use < before.nodes_in_use
    stats = sched.rebalancer.stats()
    assert stats["runs"] >= 1
    assert stats["evicted"] >= 1
    assert stats["max_cycle_evictions"] <= 4
    assert stats["over_budget"] == 0
    sched.rebalancer.reconcile(cs)
    assert sched.rebalancer.stats()["migrations_completed"] >= 1
    assert sched.rebalancer.pending_migrations == {}
    assert all(p.node_name for p in cs.list_pods())  # nobody stranded
    assert sched.pending == 0


def test_rebalancer_journals_evictions_with_nominated_target():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock)
    clock.advance(1.5)
    sched.run_until_settled()
    import json

    recs = [
        r
        for r in map(json.loads, sched.journal.lines)
        if r.get("outcome") == "evicted_for_rebalance"
    ]
    assert recs, "no eviction journaled"
    for r in recs:
        assert r["node"]  # the source
        assert r["nominated"]  # the auction's target hint
        assert r["nominated"] != r["node"]


def test_rebalancer_interval_gates_passes():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock)
    clock.advance(1.5)
    sched.run_until_settled()
    runs = len(sched.rebalancer.history)
    assert runs >= 1
    # interval not yet elapsed: another settle adds no pass
    clock.advance(0.2)
    sched.run_until_settled()
    assert len(sched.rebalancer.history) == runs


def test_rebalancer_waits_for_idle_queues():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock)
    cs.create_pod(pod("newcomer"))  # real scheduling work pending
    clock.advance(1.5)
    res = BatchResult()
    assert sched.rebalancer.maybe_run(sched, res) == 0
    assert sched.rebalancer.history == []
    assert res.rebalance_evictions == []


def test_rebalancer_fenced_zombie_moves_nothing():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock, fence_role="leader")
    placement = {p.key: p.node_name for p in cs.list_pods()}
    cs.grant_fence("leader")  # supersede: sched is now a zombie
    clock.advance(1.5)
    res = BatchResult()
    assert sched.rebalancer.maybe_run(sched, res) == 0
    assert sched.rebalancer.history == []
    assert {p.key: p.node_name for p in cs.list_pods()} == placement


def test_rebalancer_refenced_incarnation_resumes():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock, fence_role="leader")
    cs.grant_fence("leader")
    clock.advance(1.5)
    assert sched.rebalancer.maybe_run(sched, BatchResult()) == 0
    # the incarnation re-acquires its lease: passes resume
    sched.reacquire_fence()
    clock.advance(1.5)
    sched.run_until_settled()
    assert sched.rebalancer.stats()["evicted"] >= 1


def test_rebalancer_never_moves_pdb_guarded_pods():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock, labels={"app": "db"})
    cs.create_pdb(
        PodDisruptionBudget(
            name="guard",
            selector=Selector(
                requirements=requirements_from_match_labels(
                    {"app": "db"}
                )
            ),
            disruptions_allowed=0,
        )
    )
    placement = {p.key: p.node_name for p in cs.list_pods()}
    for _ in range(4):
        clock.advance(1.5)
        sched.run_until_settled()
    stats = sched.rebalancer.stats()
    # the gate engaged non-vacuously (the plan WANTED to move cohort
    # pods) and not one of them moved
    assert stats["pdb_blocked"] >= 1
    assert stats["evicted"] == 0
    assert {p.key: p.node_name for p in cs.list_pods()} == placement


def test_rebalancer_respects_node_selectors():
    """A nodeSelector-constrained pod is only ever planned toward (and
    migrated to) a matching node: the plan auction folds the static
    plugin masks through the production builder, so an infeasible
    target can never be nominated — evicting toward one would bounce
    the pod right back and churn it every interval."""
    from kubernetes_tpu.obs import ObsConfig

    clock = FakeClock()
    cs = ClusterState()
    # two pool-labeled nodes (n0 sparse source, n1 loaded target) and
    # four unlabeled nodes that are fuller — the tempting-but-illegal
    # consolidation targets
    for i in range(2):
        n = MakeNode().name(f"n{i}").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "110"}
        ).label("pool", "gold").obj()
        cs.create_node(n)
    for i in range(2, 6):
        cs.create_node(node(f"n{i}"))
    cs.create_pod(
        MakePod().name("sel").req({"cpu": "1", "memory": "1Gi"})
        .node_selector({"pool": "gold"}).obj()
    )
    cs.bind("default", "sel", "n0")
    for j in range(4):
        cs.create_pod(pod(f"t{j}"))
        cs.bind("default", f"t{j}", "n1")
    for i in range(2, 6):
        for j in range(3):
            cs.create_pod(pod(f"f{i}{j}"))
            cs.bind("default", f"f{i}{j}", f"n{i}")
    sched = Scheduler(
        cs,
        SchedulerConfig(
            solver=ExactSolverConfig(tie_break="first"),
            rebalance=RebalanceConfig(
                interval_s=1.0, max_moves_per_cycle=8, min_packing=0.7
            ),
            obs=ObsConfig(journal=True),
        ),
        clock=clock,
    )
    for _ in range(8):
        clock.advance(1.5)
        sched.run_until_settled()
    p = cs.get_pod("default", "sel")
    assert p.node_name in ("n0", "n1"), (
        "constrained pod migrated off its selector's pool"
    )
    assert sched.pending == 0


def test_rebalancer_skips_hard_shaped_pods():
    clock = FakeClock()
    cs, sched = _fragmented(clock=clock)
    hard = [
        MakePod().name("ports").req({"cpu": "1"}).host_port(8080).obj(),
        MakePod()
        .name("spread")
        .req({"cpu": "1"})
        .label("app", "s")
        .spread_constraint(1, "zone", "DoNotSchedule", {"app": "s"})
        .obj(),
        MakePod()
        .name("anti")
        .req({"cpu": "1"})
        .pod_anti_affinity("kubernetes.io/hostname", {"app": "s"})
        .obj(),
        MakePod().name("pvc").req({"cpu": "1"}).pvc("claim0").obj(),
    ]
    for p in hard:
        assert not Rebalancer._movable(sched, p), p.name
    assert Rebalancer._movable(sched, cs.get_pod("default", "p00"))


def test_rebalancer_not_fragmented_cluster_untouched():
    clock = FakeClock()
    cs = ClusterState()
    for i in range(2):
        cs.create_node(node(f"n{i}"))
    for i in range(7):
        cs.create_pod(pod(f"p{i}"))
        cs.bind("default", f"p{i}", "n0")
    from kubernetes_tpu.obs import ObsConfig

    sched = Scheduler(
        cs,
        SchedulerConfig(
            solver=ExactSolverConfig(tie_break="first"),
            rebalance=RebalanceConfig(interval_s=1.0),
            obs=ObsConfig(journal=True),
        ),
        clock=clock,
    )
    placement = {p.key: p.node_name for p in cs.list_pods()}
    clock.advance(1.5)
    sched.run_until_settled()
    assert sched.rebalancer.stats()["evicted"] == 0
    assert {p.key: p.node_name for p in cs.list_pods()} == placement


def test_config_layer_builds_rebalance_section():
    from kubernetes_tpu.config.types import load, scheduler_config

    cfg = load(
        {
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "rebalance": {
                "enabled": True,
                "intervalSeconds": 30,
                "maxMovesPerCycle": 16,
                "minPackingUtilization": 0.6,
                "minGainPoints": 2,
                "nominate": False,
            },
        }
    )
    sc = scheduler_config(cfg)
    assert sc.rebalance is not None
    assert sc.rebalance.interval_s == 30.0
    assert sc.rebalance.max_moves_per_cycle == 16
    assert sc.rebalance.min_packing == 0.6
    assert sc.rebalance.min_gain == 2
    assert sc.rebalance.nominate is False
    # disabled = no rebalancer constructed at all
    off = load(
        {
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
        }
    )
    assert scheduler_config(off).rebalance is None


def test_config_rejects_bad_rebalance_values():
    from kubernetes_tpu.config.types import load

    for bad in (
        {"maxMovesPerCycle": -1},
        {"minPackingUtilization": 0.0},
        {"intervalSeconds": 0},
        {"intervalSeconds": -5},
        # min_gain >= 1 is the strict-improvement termination argument
        {"minGainPoints": 0},
    ):
        with pytest.raises(ValueError):
            load(
                {
                    "apiVersion": "kubescheduler.config.k8s.io/v1",
                    "kind": "KubeSchedulerConfiguration",
                    "rebalance": bad,
                }
            )


def test_config_explicit_nulls_default():
    # a YAML key left blank ("intervalSeconds:") parses as None: it
    # must take the default, not TypeError out of int()/float()
    from kubernetes_tpu.config.types import load

    cfg = load(
        {
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "tpuSolver": {"singleShot": {"repairRounds": None}},
            "rebalance": {
                "enabled": True,
                "intervalSeconds": None,
                "maxMovesPerCycle": None,
                "minPackingUtilization": None,
                "minGainPoints": None,
                "nominate": None,
            },
        }
    )
    assert cfg.tpu_solver.single_shot.repair_rounds == 16
    assert cfg.rebalance.interval_seconds == 60.0
    assert cfg.rebalance.max_moves_per_cycle == 512
    assert cfg.rebalance.min_packing_utilization == 0.7
    assert cfg.rebalance.min_gain_points == 1
    assert cfg.rebalance.nominate is True
