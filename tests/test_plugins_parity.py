"""End-to-end parity: ExactSolver with static plugin tensors vs the
FullOracle sequential pipeline (SURVEY.md §8.6 — the oracle is the
sanitizer). Every solver pick must land in the oracle's tie set given
identical history."""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
)
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)

MB = 1024 * 1024
GB = 1024 * MB


def run_solver(nodes, pods, tie_break="first"):
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    solver = ExactSolver(ExactSolverConfig(tie_break=tie_break))
    return solver.solve(nbatch, pbatch, static, ports), nbatch


def assert_parity(nodes, pods, tie_break="first"):
    assignments, nbatch = run_solver(nodes, pods, tie_break)
    oracle = FullOracle(make_oracle_nodes(nodes))
    names = [
        nbatch.names[a] if a >= 0 else "" for a in assignments
    ]
    errors = oracle.validate_assignments(
        pods, list(assignments), names=[n or None for n in names]
    )
    assert not errors, "\n".join(errors[:5])
    return assignments


def mk_nodes(n, taint_every=0, zone_count=0, unsched_every=0, image_every=0):
    nodes = []
    for i in range(n):
        b = (
            MakeNode()
            .name(f"node-{i:03}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "50"})
        )
        if zone_count:
            b = b.label("zone", f"z{i % zone_count}")
        if taint_every and i % taint_every == 0:
            b = b.taint("dedicated", "gpu", "NoSchedule")
        if unsched_every and i % unsched_every == 0:
            b = b.unschedulable()
        if image_every and i % image_every == 0:
            b = b.image("app:latest", 800 * MB)
        nodes.append(b.obj())
    return nodes


def test_taints_steer_placement():
    nodes = mk_nodes(8, taint_every=2)
    pods = [
        MakePod().name(f"p{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
        for i in range(10)
    ]
    a = assert_parity(nodes, pods)
    # untolerated pods must avoid tainted (even) nodes
    assert all(x % 2 == 1 for x in a if x >= 0)


def test_toleration_opens_tainted_nodes():
    nodes = mk_nodes(4, taint_every=1)
    pods = [
        MakePod()
        .name(f"p{i}")
        .req({"cpu": "100m"})
        .toleration(key="dedicated", value="gpu", effect="NoSchedule")
        .obj()
        for i in range(4)
    ]
    a = assert_parity(nodes, pods)
    assert all(x >= 0 for x in a)


def test_node_selector_and_required_affinity():
    nodes = mk_nodes(9, zone_count=3)
    pods = [
        MakePod().name(f"sel{i}").node_selector({"zone": "z1"}).req({"cpu": "100m"}).obj()
        for i in range(3)
    ] + [
        MakePod().name(f"aff{i}").node_affinity_in("zone", ["z2"]).req({"cpu": "100m"}).obj()
        for i in range(3)
    ]
    a = assert_parity(nodes, pods)
    assert all(x % 3 == 1 for x in a[:3])  # z1 nodes
    assert all(x % 3 == 2 for x in a[3:])  # z2 nodes


def test_preferred_affinity_scores():
    nodes = mk_nodes(6, zone_count=2)
    pods = [
        MakePod()
        .name(f"p{i}")
        .req({"cpu": "100m"})
        .preferred_node_affinity(50, "zone", ["z0"])
        .obj()
        for i in range(4)
    ]
    a = assert_parity(nodes, pods)
    assert all(x % 2 == 0 for x in a if x >= 0)  # prefers z0


def test_unschedulable_and_nodename():
    nodes = mk_nodes(4, unsched_every=2)
    pods = [
        MakePod().name("pinned").node("node-002").req({"cpu": "100m"}).obj(),
        MakePod().name("free").req({"cpu": "100m"}).obj(),
        MakePod()
        .name("tolerates-unsched")
        .toleration(key="node.kubernetes.io/unschedulable", operator="Exists",
                    effect="NoSchedule")
        .req({"cpu": "100m"})
        .obj(),
    ]
    a = assert_parity(nodes, pods)
    # pinned to an unschedulable node -> fails (node-002 is unschedulable)
    assert a[0] == -1
    assert a[1] in (1, 3)


def test_host_ports_exclude_and_serialize():
    nodes = mk_nodes(2)
    pods = [
        MakePod().name(f"web{i}").host_port(80).req({"cpu": "100m"}).obj()
        for i in range(3)
    ]
    a = assert_parity(nodes, pods)
    # only 2 nodes => only 2 pods with hostPort 80 can land
    placed = [x for x in a if x >= 0]
    assert sorted(placed) == [0, 1]
    assert list(a).count(-1) == 1


def test_host_ports_against_placed_pods():
    # a pod already on node-000 holds port 80; the new pod must go elsewhere
    nodes = mk_nodes(2)
    placed = MakePod().name("old").node("node-000").host_port(80).obj()
    pods = [MakePod().name("new").host_port(80).req({"cpu": "100m"}).obj()]

    vocab = ResourceVocab.build(pods + [placed], nodes)
    nbatch = build_node_batch(nodes, {"node-000": [placed]}, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(
        pods, pbatch, slot_nodes, {0: [placed]}, nbatch.padded
    )
    solver = ExactSolver(ExactSolverConfig(tie_break="first"))
    a = solver.solve(nbatch, pbatch, static, ports)
    assert a[0] == 1


def test_image_locality_prefers_cached_nodes():
    nodes = mk_nodes(4, image_every=2)
    pods = [
        MakePod()
        .name(f"p{i}")
        .container_image("app:latest", {"cpu": "100m"})
        .obj()
        for i in range(2)
    ]
    a = assert_parity(nodes, pods)
    assert all(x % 2 == 0 for x in a)  # nodes 0,2 have the image


def test_randomized_cluster_parity():
    rng = np.random.default_rng(7)
    zones = 3
    nodes = []
    for i in range(24):
        b = (
            MakeNode()
            .name(f"node-{i:03}")
            .capacity(
                {
                    "cpu": f"{int(rng.integers(4, 17))}",
                    "memory": f"{int(rng.integers(8, 65))}Gi",
                    "pods": "30",
                }
            )
            .label("zone", f"z{i % zones}")
            .label("disk", "ssd" if i % 2 else "hdd")
        )
        if rng.random() < 0.25:
            b = b.taint("team", f"t{int(rng.integers(0, 2))}", "NoSchedule")
        if rng.random() < 0.2:
            b = b.taint("soft", "x", "PreferNoSchedule")
        if rng.random() < 0.1:
            b = b.unschedulable()
        if rng.random() < 0.3:
            b = b.image("cache:latest", int(rng.integers(100, 900)) * MB)
        nodes.append(b.obj())

    pods = []
    for i in range(60):
        b = (
            MakePod()
            .name(f"pod-{i:03}")
            .req(
                {
                    "cpu": f"{int(rng.integers(1, 20)) * 100}m",
                    "memory": f"{int(rng.integers(1, 8))}Gi",
                }
            )
        )
        r = rng.random()
        if r < 0.2:
            b = b.node_selector({"zone": f"z{int(rng.integers(0, zones))}"})
        elif r < 0.35:
            b = b.node_affinity_in("disk", ["ssd"])
        if rng.random() < 0.3:
            b = b.toleration(key="team", value=f"t{int(rng.integers(0, 2))}",
                             effect="NoSchedule")
        if rng.random() < 0.2:
            b = b.preferred_node_affinity(
                int(rng.integers(1, 100)), "zone", [f"z{int(rng.integers(0, zones))}"]
            )
        if rng.random() < 0.15:
            b = b.host_port(int(rng.integers(8000, 8004)))
        if rng.random() < 0.25:
            b = b.container_image("cache:latest", {"cpu": "100m"})
        pods.append(b.obj())

    assert_parity(nodes, pods)


def test_random_tiebreak_stays_in_tie_set():
    nodes = mk_nodes(8)
    pods = [MakePod().name(f"p{i}").req({"cpu": "100m"}).obj() for i in range(16)]
    assignments, nbatch = run_solver(nodes, pods, tie_break="random")
    oracle = FullOracle(make_oracle_nodes(nodes))
    names = [nbatch.names[a] if a >= 0 else None for a in assignments]
    errors = oracle.validate_assignments(pods, list(assignments), names=names)
    assert not errors, "\n".join(errors[:5])
