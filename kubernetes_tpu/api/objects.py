"""Pod / Node API objects — the subset of core/v1 the scheduler consumes.

Reference semantics:
- staging/src/k8s.io/api/core/v1/types.go#Pod, #PodSpec, #Node, #NodeStatus,
  #Affinity, #Toleration, #Taint, #TopologySpreadConstraint
- pkg/scheduler/framework/types.go#computePodResourceRequest /
  util/pod/resources (sum containers, max initContainers, + overhead)
- pkg/scheduler/util/non_zero.go#GetNonzeroRequests (100 mCPU / 200 MB
  defaults for zero-request pods, used only for scoring)

Objects parse from / serialize to the real v1 JSON wire shapes so the
extender webhook server (kubernetes_tpu/server) speaks byte-compatible
payloads. Resource quantities are canonicalized to int64 on parse
(cpu -> milli, memory/storage -> bytes) per kubernetes_tpu/api/quantity.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping

from .labels import (
    Selector,
    label_selector_to_dict,
    selector_from_label_selector,
    selector_from_node_selector_requirements,
)
from .quantity import canonical_requests, format_canonical

# Non-zero scoring defaults: pkg/scheduler/util/non_zero.go
DEFAULT_MILLI_CPU_REQUEST = 100  # 0.1 core
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200 MiB

RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_PODS = "pods"

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Taint effects: core/v1/types.go#TaintEffect
TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"


def _pod_requests(
    containers: list[dict[str, int]],
    init_containers: list[tuple[dict[str, int], bool]],
) -> dict[str, int]:
    """The PodRequests aggregation from k8s.io/component-helpers
    resource/helpers.go#PodRequests (order-sensitive sidecar semantics):

    - main requests = sum over containers, plus every restartable
      (sidecar) init container;
    - each non-sidecar init container's *effective* request is its own
      request plus the sidecar requests accumulated before it in declaration
      order (those sidecars are already running when it executes);
    - result = elementwise max(main, max over effective init requests).

    Overhead is added by the caller.
    """
    req: dict[str, int] = {}
    for c in containers:
        for k, v in c.items():
            req[k] = req.get(k, 0) + v
    sidecar_prefix: dict[str, int] = {}
    init_max: dict[str, int] = {}
    for c, is_sidecar in init_containers:
        if is_sidecar:
            for k, v in c.items():
                req[k] = req.get(k, 0) + v
                sidecar_prefix[k] = sidecar_prefix.get(k, 0) + v
            effective = dict(sidecar_prefix)
        else:
            effective = dict(sidecar_prefix)
            for k, v in c.items():
                effective[k] = effective.get(k, 0) + v
        for k, v in effective.items():
            if v > init_max.get(k, 0):
                init_max[k] = v
    for k, v in init_max.items():
        if v > req.get(k, 0):
            req[k] = v
    return req


# ---------------------------------------------------------------------------
# Leaf types
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ContainerPort:
    """core/v1#ContainerPort — only host ports matter to scheduling."""

    host_port: int = 0
    host_ip: str = ""
    protocol: str = "TCP"
    container_port: int = 0

    @staticmethod
    def from_dict(d: Mapping) -> "ContainerPort":
        return ContainerPort(
            host_port=int(d.get("hostPort") or 0),
            host_ip=d.get("hostIP") or "",
            protocol=d.get("protocol") or "TCP",
            container_port=int(d.get("containerPort") or 0),
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.container_port:
            out["containerPort"] = self.container_port
        if self.host_port:
            out["hostPort"] = self.host_port
        if self.host_ip:
            out["hostIP"] = self.host_ip
        if self.protocol != "TCP":
            out["protocol"] = self.protocol
        return out


@dataclass(frozen=True)
class Container:
    name: str = ""
    requests: Mapping[str, int] = field(default_factory=dict)  # canonical ints
    limits: Mapping[str, int] = field(default_factory=dict)
    ports: tuple[ContainerPort, ...] = ()
    images: tuple[str, ...] = ()  # image name(s) for ImageLocality
    restart_policy: str = ""  # "Always" on an initContainer => sidecar

    @staticmethod
    def from_dict(d: Mapping) -> "Container":
        res = d.get("resources") or {}
        image = d.get("image")
        return Container(
            name=d.get("name") or "",
            requests=canonical_requests(res.get("requests")),
            limits=canonical_requests(res.get("limits")),
            ports=tuple(ContainerPort.from_dict(p) for p in d.get("ports") or ()),
            images=(image,) if image else (),
            restart_policy=d.get("restartPolicy") or "",
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"name": self.name}
        if self.images:
            out["image"] = self.images[0]
        res: dict[str, Any] = {}
        if self.requests:
            res["requests"] = {
                k: format_canonical(k, v) for k, v in self.requests.items()
            }
        if self.limits:
            res["limits"] = {k: format_canonical(k, v) for k, v in self.limits.items()}
        if res:
            out["resources"] = res
        if self.ports:
            out["ports"] = [p.to_dict() for p in self.ports]
        if self.restart_policy:
            out["restartPolicy"] = self.restart_policy
        return out


@dataclass(frozen=True)
class Toleration:
    """core/v1#Toleration; match semantics in
    k8s.io/api/core/v1/toleration.go#ToleratesTaint."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty = all effects
    toleration_seconds: int | None = None

    def tolerates(self, taint: "Taint") -> bool:
        # toleration.go#ToleratesTaint: empty effect matches all effects;
        # empty key matches all keys (no restriction); then the operator
        # decides — Equal/"" compares values, Exists always matches.
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        if self.operator in ("Equal", ""):
            return self.value == taint.value
        return False

    @staticmethod
    def from_dict(d: Mapping) -> "Toleration":
        return Toleration(
            key=d.get("key") or "",
            operator=d.get("operator") or "Equal",
            value=d.get("value") or "",
            effect=d.get("effect") or "",
            toleration_seconds=d.get("tolerationSeconds"),
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.key:
            out["key"] = self.key
        if self.operator != "Equal":
            out["operator"] = self.operator
        if self.value:
            out["value"] = self.value
        if self.effect:
            out["effect"] = self.effect
        if self.toleration_seconds is not None:
            out["tolerationSeconds"] = self.toleration_seconds
        return out


@dataclass(frozen=True)
class Taint:
    key: str = ""
    value: str = ""
    effect: str = ""

    @staticmethod
    def from_dict(d: Mapping) -> "Taint":
        return Taint(d.get("key") or "", d.get("value") or "", d.get("effect") or "")

    def to_dict(self) -> dict:
        return {"key": self.key, "value": self.value, "effect": self.effect}


@dataclass(frozen=True)
class NodeSelectorTerm:
    """OR-term: AND of matchExpressions and matchFields."""

    match_expressions: Selector = field(default_factory=Selector)
    match_fields: Selector = field(default_factory=Selector)
    # A term with no expressions and no fields matches NOTHING
    # (nodeaffinity.go#nodeSelectorTermsMatch) — track emptiness explicitly.
    empty: bool = True

    @staticmethod
    def from_dict(d: Mapping) -> "NodeSelectorTerm":
        exprs = selector_from_node_selector_requirements(d.get("matchExpressions"))
        fields_ = selector_from_node_selector_requirements(d.get("matchFields"))
        return NodeSelectorTerm(
            match_expressions=exprs,
            match_fields=fields_,
            empty=not (d.get("matchExpressions") or d.get("matchFields")),
        )

    def matches(self, node_labels: Mapping[str, str], node_fields: Mapping[str, str]) -> bool:
        if self.empty:
            return False
        return self.match_expressions.matches(node_labels) and self.match_fields.matches(
            node_fields
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        d = label_selector_to_dict(self.match_expressions)
        if d and d.get("matchExpressions"):
            out["matchExpressions"] = d["matchExpressions"]
        f = label_selector_to_dict(self.match_fields)
        if f and f.get("matchExpressions"):
            out["matchFields"] = f["matchExpressions"]
        return out


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm

    @staticmethod
    def from_dict(d: Mapping) -> "PreferredSchedulingTerm":
        return PreferredSchedulingTerm(
            weight=int(d.get("weight") or 0),
            preference=NodeSelectorTerm.from_dict(d.get("preference") or {}),
        )

    def to_dict(self) -> dict:
        return {"weight": self.weight, "preference": self.preference.to_dict()}


@dataclass(frozen=True)
class NodeAffinity:
    """requiredDuringSchedulingIgnoredDuringExecution is an OR of terms."""

    required: tuple[NodeSelectorTerm, ...] | None = None  # None = no requirement
    preferred: tuple[PreferredSchedulingTerm, ...] = ()

    @staticmethod
    def from_dict(d: Mapping) -> "NodeAffinity":
        req = d.get("requiredDuringSchedulingIgnoredDuringExecution")
        required = None
        if req is not None:
            required = tuple(
                NodeSelectorTerm.from_dict(t) for t in req.get("nodeSelectorTerms") or ()
            )
        preferred = tuple(
            PreferredSchedulingTerm.from_dict(t)
            for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or ()
        )
        return NodeAffinity(required=required, preferred=preferred)

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.required is not None:
            out["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [t.to_dict() for t in self.required]
            }
        if self.preferred:
            out["preferredDuringSchedulingIgnoredDuringExecution"] = [
                t.to_dict() for t in self.preferred
            ]
        return out


@dataclass(frozen=True)
class PodAffinityTerm:
    """core/v1#PodAffinityTerm. label_selector=None matches no pods."""

    label_selector: Selector | None = None
    topology_key: str = ""
    namespaces: tuple[str, ...] = ()  # empty => pod's own namespace
    namespace_selector: Selector | None = None
    match_label_keys: tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: Mapping) -> "PodAffinityTerm":
        return PodAffinityTerm(
            label_selector=selector_from_label_selector(d.get("labelSelector")),
            topology_key=d.get("topologyKey") or "",
            namespaces=tuple(d.get("namespaces") or ()),
            namespace_selector=selector_from_label_selector(d.get("namespaceSelector")),
            match_label_keys=tuple(d.get("matchLabelKeys") or ()),
        )

    def matches_namespace(self, pod_namespace: str, target_ns: str,
                          target_ns_labels: Mapping[str, str] | None = None) -> bool:
        """Which namespaces the term selects, per
        framework/types.go#AffinityTerm.Matches."""
        if self.namespaces:
            if target_ns in self.namespaces:
                return True
        elif self.namespace_selector is None:
            # no namespaces and no selector => pod's own namespace
            return target_ns == pod_namespace
        if self.namespace_selector is not None:
            return self.namespace_selector.matches(target_ns_labels or {})
        return False

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"topologyKey": self.topology_key}
        if self.label_selector is not None:
            out["labelSelector"] = label_selector_to_dict(self.label_selector)
        if self.namespaces:
            out["namespaces"] = list(self.namespaces)
        if self.namespace_selector is not None:
            out["namespaceSelector"] = label_selector_to_dict(self.namespace_selector)
        if self.match_label_keys:
            out["matchLabelKeys"] = list(self.match_label_keys)
        return out


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm

    @staticmethod
    def from_dict(d: Mapping) -> "WeightedPodAffinityTerm":
        return WeightedPodAffinityTerm(
            weight=int(d.get("weight") or 0),
            term=PodAffinityTerm.from_dict(d.get("podAffinityTerm") or {}),
        )

    def to_dict(self) -> dict:
        return {"weight": self.weight, "podAffinityTerm": self.term.to_dict()}


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()

    @staticmethod
    def from_dict(d: Mapping) -> "PodAffinity":
        return PodAffinity(
            required=tuple(
                PodAffinityTerm.from_dict(t)
                for t in d.get("requiredDuringSchedulingIgnoredDuringExecution") or ()
            ),
            preferred=tuple(
                WeightedPodAffinityTerm.from_dict(t)
                for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or ()
            ),
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.required:
            out["requiredDuringSchedulingIgnoredDuringExecution"] = [
                t.to_dict() for t in self.required
            ]
        if self.preferred:
            out["preferredDuringSchedulingIgnoredDuringExecution"] = [
                t.to_dict() for t in self.preferred
            ]
        return out


@dataclass(frozen=True)
class Affinity:
    node_affinity: NodeAffinity | None = None
    pod_affinity: PodAffinity | None = None
    pod_anti_affinity: PodAffinity | None = None

    @staticmethod
    def from_dict(d: Mapping | None) -> "Affinity | None":
        if not d:
            return None
        na = d.get("nodeAffinity")
        pa = d.get("podAffinity")
        paa = d.get("podAntiAffinity")
        return Affinity(
            node_affinity=NodeAffinity.from_dict(na) if na else None,
            pod_affinity=PodAffinity.from_dict(pa) if pa else None,
            pod_anti_affinity=PodAffinity.from_dict(paa) if paa else None,
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.node_affinity:
            out["nodeAffinity"] = self.node_affinity.to_dict()
        if self.pod_affinity:
            out["podAffinity"] = self.pod_affinity.to_dict()
        if self.pod_anti_affinity:
            out["podAntiAffinity"] = self.pod_anti_affinity.to_dict()
        return out


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: Selector | None = None
    min_domains: int | None = None
    node_affinity_policy: str = "Honor"  # Honor | Ignore
    node_taints_policy: str = "Ignore"  # Honor | Ignore
    match_label_keys: tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: Mapping) -> "TopologySpreadConstraint":
        return TopologySpreadConstraint(
            max_skew=int(d.get("maxSkew") or 1),
            topology_key=d.get("topologyKey") or "",
            when_unsatisfiable=d.get("whenUnsatisfiable") or "DoNotSchedule",
            label_selector=selector_from_label_selector(d.get("labelSelector")),
            min_domains=d.get("minDomains"),
            node_affinity_policy=d.get("nodeAffinityPolicy") or "Honor",
            node_taints_policy=d.get("nodeTaintsPolicy") or "Ignore",
            match_label_keys=tuple(d.get("matchLabelKeys") or ()),
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {
            "maxSkew": self.max_skew,
            "topologyKey": self.topology_key,
            "whenUnsatisfiable": self.when_unsatisfiable,
        }
        if self.label_selector is not None:
            out["labelSelector"] = label_selector_to_dict(self.label_selector)
        if self.min_domains is not None:
            out["minDomains"] = self.min_domains
        if self.node_affinity_policy != "Honor":
            out["nodeAffinityPolicy"] = self.node_affinity_policy
        if self.node_taints_policy != "Ignore":
            out["nodeTaintsPolicy"] = self.node_taints_policy
        if self.match_label_keys:
            out["matchLabelKeys"] = list(self.match_label_keys)
        return out


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


@dataclass
class Pod:
    """Treat as immutable once scheduling sees it: resource accessors memoize
    (``_resource_request``/``_non_zero_request``), so mutating containers/
    overhead afterwards would serve stale totals. The state layer replaces Pod
    objects instead of mutating them (only queue/binding bookkeeping fields —
    node_name, nominated_node_name, resource_version — are ever written)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)

    # spec
    node_name: str = ""
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    priority: int | None = None
    priority_class_name: str = ""
    preemption_policy: str = ""  # "" => PreemptLowerPriority
    scheduling_gates: tuple[str, ...] = ()
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Affinity | None = None
    tolerations: tuple[Toleration, ...] = ()
    topology_spread_constraints: tuple[TopologySpreadConstraint, ...] = ()
    containers: tuple[Container, ...] = ()
    init_containers: tuple[Container, ...] = ()
    overhead: dict[str, int] = field(default_factory=dict)  # canonical ints
    host_network: bool = False
    # PVC names referenced by spec.volumes[].persistentVolumeClaim.claimName
    pvc_names: tuple[str, ...] = ()
    # ResourceClaim names referenced by spec.resourceClaims[].
    # resourceClaimName (DRA). Entries that carry only a
    # resourceClaimTemplateName (the claim is generated by a controller we
    # don't run) are kept in claim_template_names — the DRA path reports
    # such pods unschedulable with a clear reason, and to_dict preserves
    # the references. [BOUNDARY] per SURVEY §3.2 dynamicresources row.
    resource_claim_names: tuple[str, ...] = ()
    claim_template_names: tuple[str, ...] = ()

    # status
    phase: str = "Pending"
    nominated_node_name: str = ""
    # queue bookkeeping (not wire fields)
    creation_timestamp: float = 0.0
    resource_version: int = 0
    start_time: float = 0.0  # for preemption victim ordering

    # ---- derived, cached ----
    _resource_request: dict[str, int] | None = field(
        default=None, repr=False, compare=False
    )
    _non_zero_request: tuple[int, int] | None = field(
        default=None, repr=False, compare=False
    )

    @property
    def effective_priority(self) -> int:
        return self.priority if self.priority is not None else 0

    @property
    def claim_templates_unresolved(self) -> bool:
        """True when the pod references a ResourceClaim template whose
        generated claim we cannot resolve (DRA reports it unschedulable)."""
        return bool(self.claim_template_names)

    def resource_request(self) -> dict[str, int]:
        """computePodResourceRequest: sum(containers) elementwise-max'd with
        each initContainer, sidecars (restartPolicy=Always initContainers)
        added to the running sum, plus pod overhead.

        Ref: pkg/scheduler/framework/plugins/noderesources/fit.go
        #computePodResourceRequest and k8s.io/component-helpers resource.
        """
        if self._resource_request is not None:
            return self._resource_request
        req = _pod_requests(
            [dict(c.requests) for c in self.containers],
            [(dict(c.requests), c.restart_policy == "Always") for c in self.init_containers],
        )
        for k, v in self.overhead.items():
            req[k] = req.get(k, 0) + v
        self._resource_request = req
        return req

    def non_zero_request(self) -> tuple[int, int]:
        """(milliCPU, memoryBytes) with scoring defaults applied.

        Ref: pkg/scheduler/util/non_zero.go#GetNonzeroRequests — defaults are
        applied per *container* whose request for that resource is zero.
        """
        if self._non_zero_request is not None:
            return self._non_zero_request

        def defaulted(c: Container) -> dict[str, int]:
            return {
                RESOURCE_CPU: c.requests.get(RESOURCE_CPU, 0) or DEFAULT_MILLI_CPU_REQUEST,
                RESOURCE_MEMORY: c.requests.get(RESOURCE_MEMORY, 0) or DEFAULT_MEMORY_REQUEST,
            }

        req = _pod_requests(
            [defaulted(c) for c in self.containers],
            [(defaulted(c), c.restart_policy == "Always") for c in self.init_containers],
        )
        cpu = req.get(RESOURCE_CPU, 0) + self.overhead.get(RESOURCE_CPU, 0)
        mem = req.get(RESOURCE_MEMORY, 0) + self.overhead.get(RESOURCE_MEMORY, 0)
        self._non_zero_request = (cpu, mem)
        return self._non_zero_request

    def host_ports(self) -> tuple[tuple[str, str, int], ...]:
        """(hostIP, protocol, hostPort) triples requested by this pod.
        Ref: plugins/nodeports/node_ports.go#getContainerPorts."""
        out = []
        for c in self.containers:
            for p in c.ports:
                if p.host_port > 0:
                    out.append((p.host_ip or "0.0.0.0", p.protocol, p.host_port))
        return tuple(out)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Pod":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        aff = Affinity.from_dict(spec.get("affinity"))
        return Pod(
            name=meta.get("name") or "",
            namespace=meta.get("namespace") or "default",
            uid=meta.get("uid") or "",
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
            node_name=spec.get("nodeName") or "",
            scheduler_name=spec.get("schedulerName") or DEFAULT_SCHEDULER_NAME,
            priority=spec.get("priority"),
            priority_class_name=spec.get("priorityClassName") or "",
            preemption_policy=spec.get("preemptionPolicy") or "",
            scheduling_gates=tuple(
                g.get("name", "") for g in spec.get("schedulingGates") or ()
            ),
            node_selector=dict(spec.get("nodeSelector") or {}),
            affinity=aff,
            tolerations=tuple(Toleration.from_dict(t) for t in spec.get("tolerations") or ()),
            topology_spread_constraints=tuple(
                TopologySpreadConstraint.from_dict(t)
                for t in spec.get("topologySpreadConstraints") or ()
            ),
            containers=tuple(Container.from_dict(c) for c in spec.get("containers") or ()),
            init_containers=tuple(
                Container.from_dict(c) for c in spec.get("initContainers") or ()
            ),
            overhead=canonical_requests(spec.get("overhead")),
            host_network=bool(spec.get("hostNetwork") or False),
            pvc_names=tuple(
                v["persistentVolumeClaim"]["claimName"]
                for v in spec.get("volumes") or ()
                if v.get("persistentVolumeClaim", {}).get("claimName")
            ),
            resource_claim_names=tuple(
                rc["resourceClaimName"]
                for rc in spec.get("resourceClaims") or ()
                if rc.get("resourceClaimName")
            ),
            claim_template_names=tuple(
                rc["resourceClaimTemplateName"]
                for rc in spec.get("resourceClaims") or ()
                if rc.get("resourceClaimTemplateName")
                and not rc.get("resourceClaimName")
            ),
            phase=status.get("phase") or "Pending",
            nominated_node_name=status.get("nominatedNodeName") or "",
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        spec: dict[str, Any] = {}
        if self.node_name:
            spec["nodeName"] = self.node_name
        if self.scheduler_name != DEFAULT_SCHEDULER_NAME:
            spec["schedulerName"] = self.scheduler_name
        if self.priority is not None:
            spec["priority"] = self.priority
        if self.priority_class_name:
            spec["priorityClassName"] = self.priority_class_name
        if self.preemption_policy:
            spec["preemptionPolicy"] = self.preemption_policy
        if self.scheduling_gates:
            spec["schedulingGates"] = [{"name": g} for g in self.scheduling_gates]
        if self.node_selector:
            spec["nodeSelector"] = dict(self.node_selector)
        if self.affinity:
            spec["affinity"] = self.affinity.to_dict()
        if self.tolerations:
            spec["tolerations"] = [t.to_dict() for t in self.tolerations]
        if self.topology_spread_constraints:
            spec["topologySpreadConstraints"] = [
                t.to_dict() for t in self.topology_spread_constraints
            ]
        spec["containers"] = [c.to_dict() for c in self.containers]
        if self.init_containers:
            spec["initContainers"] = [c.to_dict() for c in self.init_containers]
        if self.overhead:
            spec["overhead"] = {
                k: format_canonical(k, v) for k, v in self.overhead.items()
            }
        if self.host_network:
            spec["hostNetwork"] = True
        if self.pvc_names:
            spec["volumes"] = [
                {
                    "name": f"vol{i}",
                    "persistentVolumeClaim": {"claimName": c},
                }
                for i, c in enumerate(self.pvc_names)
            ]
        if self.resource_claim_names or self.claim_template_names:
            spec["resourceClaims"] = [
                {"name": f"claim{i}", "resourceClaimName": c}
                for i, c in enumerate(self.resource_claim_names)
            ] + [
                {"name": f"claimtpl{i}", "resourceClaimTemplateName": t}
                for i, t in enumerate(self.claim_template_names)
            ]
        status: dict[str, Any] = {"phase": self.phase}
        if self.nominated_node_name:
            status["nominatedNodeName"] = self.nominated_node_name
        meta: dict[str, Any] = {"name": self.name, "namespace": self.namespace}
        if self.uid:
            meta["uid"] = self.uid
        if self.labels:
            meta["labels"] = dict(self.labels)
        if self.annotations:
            meta["annotations"] = dict(self.annotations)
        if self.resource_version:
            meta["resourceVersion"] = str(self.resource_version)
        return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec, "status": status}


# ---------------------------------------------------------------------------
# PersistentVolume / PersistentVolumeClaim — the slice the volume plugins
# read ([BOUNDARY], SURVEY.md §3.2: static F-stage checks; dynamic
# provisioning and the PV controller are out of scope)
# ---------------------------------------------------------------------------

ACCESS_RWO = "ReadWriteOnce"

ZONE_LABELS = ("topology.kubernetes.io/zone", "failure-domain.beta.kubernetes.io/zone")


@dataclass
class PersistentVolume:
    """core/v1#PersistentVolume: capacity, zone labels, node affinity, the
    CSI driver name (for nodevolumelimits counting), access modes."""

    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    capacity_bytes: int = 0
    access_modes: tuple[str, ...] = (ACCESS_RWO,)
    storage_class: str = ""
    csi_driver: str = ""
    claim_ref: str = ""  # ns/name of the bound PVC ("" = available)
    node_affinity: "NodeAffinity | None" = None  # required terms only
    resource_version: int = 0

    def matches_node(self, node: "Node") -> bool:
        """volume_zone.go + the PV nodeAffinity check in volumebinding:
        zone labels (if present) and spec.nodeAffinity must match."""
        for zl in ZONE_LABELS:
            want = self.labels.get(zl)
            if want is not None:
                # zone label values may be a __-separated set (GCE legacy)
                if node.labels.get(zl) not in want.split("__"):
                    return False
        if self.node_affinity is not None and self.node_affinity.required is not None:
            fields = node.field_labels()
            if not any(
                t.matches(node.labels, fields) for t in self.node_affinity.required
            ):
                return False
        return True

    @staticmethod
    def from_dict(d: Mapping) -> "PersistentVolume":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        cap = canonical_requests((spec.get("capacity") or {}))
        csi = spec.get("csi") or {}
        na = spec.get("nodeAffinity") or {}
        required = na.get("required")
        node_affinity = None
        if required is not None:
            node_affinity = NodeAffinity.from_dict(
                {"requiredDuringSchedulingIgnoredDuringExecution": required}
            )
        claim = spec.get("claimRef") or {}
        claim_ref = (
            f"{claim.get('namespace', 'default')}/{claim['name']}"
            if claim.get("name")
            else ""
        )
        return PersistentVolume(
            name=meta.get("name") or "",
            labels=dict(meta.get("labels") or {}),
            capacity_bytes=cap.get("storage", 0),
            access_modes=tuple(spec.get("accessModes") or (ACCESS_RWO,)),
            storage_class=spec.get("storageClassName") or "",
            csi_driver=csi.get("driver") or "",
            claim_ref=claim_ref,
            node_affinity=node_affinity,
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        spec: dict[str, Any] = {
            "capacity": {"storage": format_canonical("storage", self.capacity_bytes)},
            "accessModes": list(self.access_modes),
        }
        if self.storage_class:
            spec["storageClassName"] = self.storage_class
        if self.csi_driver:
            spec["csi"] = {"driver": self.csi_driver}
        if self.claim_ref:
            ns, name = self.claim_ref.split("/", 1)
            spec["claimRef"] = {"namespace": ns, "name": name}
        if self.node_affinity is not None:
            na = self.node_affinity.to_dict()
            req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
            if req:
                spec["nodeAffinity"] = {"required": req}
        meta: dict[str, Any] = {"name": self.name}
        if self.labels:
            meta["labels"] = dict(self.labels)
        return {
            "apiVersion": "v1",
            "kind": "PersistentVolume",
            "metadata": meta,
            "spec": spec,
        }


@dataclass
class PersistentVolumeClaim:
    """core/v1#PersistentVolumeClaim: the scheduler reads the bound volume
    name, requested size, class, and the binding mode of its class
    (WaitForFirstConsumer => defer to scheduling)."""

    name: str = ""
    namespace: str = "default"
    volume_name: str = ""  # bound PV ("" = unbound)
    storage_class: str = ""
    request_bytes: int = 0
    access_modes: tuple[str, ...] = (ACCESS_RWO,)
    # StorageClass.volumeBindingMode collapsed onto the claim [BOUNDARY]
    wait_for_first_consumer: bool = False
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "PersistentVolumeClaim":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        req = canonical_requests(
            ((spec.get("resources") or {}).get("requests") or {})
        )
        return PersistentVolumeClaim(
            name=meta.get("name") or "",
            namespace=meta.get("namespace") or "default",
            volume_name=spec.get("volumeName") or "",
            storage_class=spec.get("storageClassName") or "",
            request_bytes=req.get("storage", 0),
            access_modes=tuple(spec.get("accessModes") or (ACCESS_RWO,)),
            wait_for_first_consumer=bool(
                (d.get("metadata") or {})
                .get("annotations", {})
                .get("volume.kubernetes.io/wait-for-first-consumer")
            )
            or bool(spec.get("waitForFirstConsumer")),
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        spec: dict[str, Any] = {"accessModes": list(self.access_modes)}
        if self.volume_name:
            spec["volumeName"] = self.volume_name
        if self.storage_class:
            spec["storageClassName"] = self.storage_class
        if self.request_bytes:
            spec["resources"] = {
                "requests": {
                    "storage": format_canonical("storage", self.request_bytes)
                }
            }
        if self.wait_for_first_consumer:
            spec["waitForFirstConsumer"] = True
        return {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": spec,
        }


# ---------------------------------------------------------------------------
# PodDisruptionBudget (policy/v1) — the slice preemption reads
# ---------------------------------------------------------------------------


@dataclass
class Service:
    """[BOUNDARY] minimal core/v1 Service: name/namespace + spec.selector
    (plain label equality map). Consumed by PodTopologySpread's
    defaultingType=System path, where helper.DefaultSelector unions the
    selectors of services matching the pod (helper/spread.go#DefaultSelector;
    ReplicaSet/StatefulSet owner lookup is [CONTEXT] — documented out)."""

    name: str = ""
    namespace: str = "default"
    selector: dict = field(default_factory=dict)
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def selects(self, pod: "Pod") -> bool:
        return (
            pod.namespace == self.namespace
            and bool(self.selector)
            and all(pod.labels.get(k) == v for k, v in self.selector.items())
        )

    @staticmethod
    def from_dict(d: Mapping) -> "Service":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        return Service(
            name=meta.get("name") or "",
            namespace=meta.get("namespace") or "default",
            selector=dict(spec.get("selector") or {}),
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        return {
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "resourceVersion": str(self.resource_version),
            },
            "spec": {"selector": dict(self.selector)},
        }


@dataclass
class PodDisruptionBudget:
    """[BOUNDARY] minimal PDB: preemption dry-run reads selector matching
    and status.disruptionsAllowed (policy/v1#PodDisruptionBudget,
    preemption.go#filterPodsWithPDBViolation). The controller deriving
    disruptionsAllowed from minAvailable/maxUnavailable is out of scope —
    callers set the allowance directly (tests mirror how integration tests
    seed PDB status)."""

    name: str = ""
    namespace: str = "default"
    selector: Selector | None = None
    disruptions_allowed: int = 0
    min_available: int | str | None = None  # parsed but not enforced
    max_unavailable: int | str | None = None
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def matches(self, pod: "Pod") -> bool:
        return (
            pod.namespace == self.namespace
            and self.selector is not None
            and self.selector.matches(pod.labels)
        )

    @staticmethod
    def from_dict(d: Mapping) -> "PodDisruptionBudget":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return PodDisruptionBudget(
            name=meta.get("name") or "",
            namespace=meta.get("namespace") or "default",
            selector=selector_from_label_selector(spec.get("selector")),
            disruptions_allowed=int(status.get("disruptionsAllowed") or 0),
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        spec: dict[str, Any] = {}
        if self.selector is not None:
            spec["selector"] = label_selector_to_dict(self.selector)
        if self.min_available is not None:
            spec["minAvailable"] = self.min_available
        if self.max_unavailable is not None:
            spec["maxUnavailable"] = self.max_unavailable
        return {
            "apiVersion": "policy/v1",
            "kind": "PodDisruptionBudget",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": spec,
            "status": {"disruptionsAllowed": self.disruptions_allowed},
        }


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ContainerImage:
    names: tuple[str, ...] = ()
    size_bytes: int = 0

    @staticmethod
    def from_dict(d: Mapping) -> "ContainerImage":
        return ContainerImage(
            names=tuple(d.get("names") or ()), size_bytes=int(d.get("sizeBytes") or 0)
        )

    def to_dict(self) -> dict:
        return {"names": list(self.names), "sizeBytes": self.size_bytes}


@dataclass
class Node:
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    unschedulable: bool = False
    taints: tuple[Taint, ...] = ()
    allocatable: dict[str, int] = field(default_factory=dict)  # canonical ints
    capacity: dict[str, int] = field(default_factory=dict)
    images: tuple[ContainerImage, ...] = ()
    resource_version: int = 0

    @property
    def allowed_pod_number(self) -> int:
        return self.allocatable.get(RESOURCE_PODS, 0)

    def field_labels(self) -> dict[str, str]:
        """matchFields vocabulary — only metadata.name is supported upstream
        (nodeaffinity.go)."""
        return {"metadata.name": self.name}

    @staticmethod
    def from_dict(d: Mapping) -> "Node":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return Node(
            name=meta.get("name") or "",
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
            unschedulable=bool(spec.get("unschedulable") or False),
            taints=tuple(Taint.from_dict(t) for t in spec.get("taints") or ()),
            allocatable=canonical_requests(status.get("allocatable")),
            capacity=canonical_requests(status.get("capacity")),
            images=tuple(ContainerImage.from_dict(i) for i in status.get("images") or ()),
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        meta: dict[str, Any] = {"name": self.name}
        if self.labels:
            meta["labels"] = dict(self.labels)
        if self.annotations:
            meta["annotations"] = dict(self.annotations)
        if self.resource_version:
            meta["resourceVersion"] = str(self.resource_version)
        spec: dict[str, Any] = {}
        if self.unschedulable:
            spec["unschedulable"] = True
        if self.taints:
            spec["taints"] = [t.to_dict() for t in self.taints]
        status: dict[str, Any] = {}
        if self.allocatable:
            status["allocatable"] = {
                k: format_canonical(k, v) for k, v in self.allocatable.items()
            }
        if self.capacity:
            status["capacity"] = {
                k: format_canonical(k, v) for k, v in self.capacity.items()
            }
        if self.images:
            status["images"] = [i.to_dict() for i in self.images]
        return {"apiVersion": "v1", "kind": "Node", "metadata": meta, "spec": spec, "status": status}
