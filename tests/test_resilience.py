"""Degraded-mode solve resilience (kubernetes_tpu/resilience): the
fallback ladder + circuit breaker state machine, poison-batch bisection
quarantine, pre-apply output validation, and the fleet degraded flag.

The breaker property test drives seeded fault sequences through the
state machine and asserts the transition invariants
(closed→open→half-open→closed); the bisection fixtures are the ISSUE's
known-bad shapes — 1 and 2 poison pods in a 64-pod batch, and a poison
pod riding a CARRY-mode sub-chain through run_pipelined.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.resilience import (
    ACT_BISECT,
    ACT_DESCEND,
    ACT_REBUILD,
    TIER_HOST,
    ResilienceConfig,
    SolveResilience,
    SolverFaultError,
    build_ladder,
    validate_assignments,
)
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock

from _hypothesis_compat import given, settings, st

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def _build(n_nodes, batch=64, group=16, n_pods=0, clock=None, zones=0,
           resilience=None, split=0):
    cs = ClusterState()
    for i in range(n_nodes):
        b = (
            MakeNode()
            .name(f"n{i:03}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .label(HOST, f"n{i:03}")
        )
        if zones:
            b = b.label(ZONE, f"z{i % zones}")
        cs.create_node(b.obj())
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=batch,
            pipeline_split=split,
            # mesh_devices=1: the unsharded ladder ("single", "host") —
            # deterministic tier arithmetic under conftest's 8 virtual
            # devices
            mesh_devices=1,
            solver=ExactSolverConfig(tie_break="first", group_size=group),
            resilience=resilience,
        ),
        clock=clock,
    )
    for i in range(n_pods):
        cs.create_pod(
            MakePod().name(f"p{i:04}")
            .req({"cpu": "500m", "memory": "1Gi"}).obj()
        )
    return cs, sched


def _poison_hook(keys):
    keys = set(keys)

    def hook(pods, tier):
        hit = sorted(p.key for p in pods if p.key in keys)
        if hit:
            raise SolverFaultError(f"test: poison {hit}")

    return hook


# -- breaker state machine --


def test_ladder_shape():
    assert build_ladder(False)[-1] == TIER_HOST
    assert build_ladder(True)[0] == "mesh"
    assert TIER_HOST not in build_ladder(True)[:-1]


def test_breaker_closed_open_halfopen_closed():
    clock = FakeClock()
    r = SolveResilience(
        ResilienceConfig(open_seconds=10.0), clock, ("single", "host")
    )
    assert r.acquire("p") == (0, "single")
    # first failure: one session rebuild, same tier
    assert r.on_failure("p", 0) == ACT_REBUILD
    assert r.acquire("p") == (0, "single")
    # rebuilt retry fails: deterministic episode -> trip -> descend
    assert r.on_failure("p", 0) == ACT_DESCEND
    assert r.acquire("p") == (1, TIER_HOST)
    assert r.trips == 1
    # host keeps serving while the window runs
    clock.advance(9.0)
    assert r.acquire("p") == (1, TIER_HOST)
    # window elapsed: half-open probe at the tripped rung
    clock.advance(2.0)
    assert r.acquire("p") == (0, "single")
    assert r.probes == 1
    # probe success -> closed, back at the top
    r.on_success("p", 0)
    assert r.recloses == 1
    assert r.acquire("p") == (0, "single")
    assert not r.should_sync()


def test_breaker_probe_failure_reopens_with_backoff():
    clock = FakeClock()
    r = SolveResilience(
        ResilienceConfig(open_seconds=10.0, open_backoff=2.0),
        clock, ("single", "host"),
    )
    r.on_failure("p", 0)  # rebuild
    r.on_failure("p", 0)  # trip (window 10)
    clock.advance(11.0)
    assert r.acquire("p") == (0, "single")  # probe
    # probe fails: re-open with doubled window, no rebuild offered
    assert r.on_failure("p", 0) == ACT_DESCEND
    assert r.acquire("p") == (1, TIER_HOST)
    clock.advance(11.0)  # first window would have expired; doubled one not
    assert r.acquire("p") == (1, TIER_HOST)
    clock.advance(10.0)
    assert r.acquire("p") == (0, "single")  # 20s backoff window elapsed


def test_host_rung_failure_is_bisect_not_breaker():
    clock = FakeClock()
    r = SolveResilience(ResilienceConfig(), clock, ("single", "host"))
    assert r.on_failure("p", 1) == ACT_BISECT
    assert r.trips == 0


def test_force_tier_pins_ladder():
    clock = FakeClock()
    r = SolveResilience(
        ResilienceConfig(force_tier="host"), clock, ("single", "host")
    )
    assert r.acquire("p") == (1, TIER_HOST)
    assert r.should_sync()
    with pytest.raises(ValueError):
        SolveResilience(
            ResilienceConfig(force_tier="mesh"), clock, ("single", "host")
        )


def test_async_failure_routes_sync_until_success():
    clock = FakeClock()
    r = SolveResilience(ResilienceConfig(), clock, ("single", "host"))
    assert not r.should_sync()
    r.note_async_failure("p")
    assert r.should_sync()
    r.on_success("p", 0)
    assert not r.should_sync()


@settings(max_examples=60, deadline=None)
@given(st.integers(0, 2**32 - 1), st.integers(1, 60))
def test_breaker_transitions_property(seed, n_events):
    """Under any seeded fault/success/time sequence: the acquired tier
    is always a ladder index; a tier with an unexpired open window is
    never acquired EXCEPT as nothing (open tiers are skipped, expired
    ones probe); on_success at the probed tier always closes it; and
    the host rung never trips."""
    rng = random.Random(seed)
    clock = FakeClock()
    ladder = ("mesh", "single", "host")
    r = SolveResilience(
        ResilienceConfig(open_seconds=5.0), clock, ladder
    )
    for _ in range(n_events):
        idx, tier = r.acquire("p")
        assert 0 <= idx < len(ladder)
        assert ladder[idx] == tier
        st_ = r._st("p")
        until = st_.open_until.get(idx)
        # an acquired tier is closed or its window has elapsed (probe)
        assert until is None or clock.now() >= until
        ev = rng.random()
        if ev < 0.45:
            act = r.on_failure("p", idx)
            if tier == TIER_HOST:
                assert act == ACT_BISECT
            else:
                assert act in (ACT_REBUILD, ACT_DESCEND, "retry")
        elif ev < 0.8:
            r.on_success("p", idx)
            assert idx not in r._st("p").open_until
        else:
            clock.advance(rng.random() * 6.0)
        # invariant: the host rung never carries a breaker
        assert len(ladder) - 1 not in r._st("p").open_until


# -- pre-apply output validation --


def _prep_for(s, n_pods):
    import time

    with s.cluster.lock:
        infos = s.queue.pop_batch(s.config.batch_size)
        base = s.queue.scheduling_cycle - len(infos)
        for i in infos:
            s._in_flight[i.key] = i
    assert len(infos) == n_pods
    return s._tensorize_group(
        next(iter(s.solvers)), infos, list(range(len(infos))), base,
        time.perf_counter(),
    )


def test_validation_rejects_corrupt_vectors():
    cs, s = _build(4, n_pods=8)
    prep = _prep_for(s, 8)
    ok = np.zeros(8, dtype=np.int32)  # all on slot 0: 8 x 500m fits 8cpu
    assert validate_assignments(prep, 0, ok) is None
    prep.validated_usage = None
    bad_range = np.full(8, prep.batch.padded + 3, dtype=np.int32)
    assert "out of range" in validate_assignments(prep, 0, bad_range)
    prep.validated_usage = None
    bad_dtype = np.zeros(8, dtype=np.float32)
    assert "integer" in validate_assignments(prep, 0, bad_dtype)
    if prep.batch.padded > prep.batch.num_nodes:
        prep.validated_usage = None
        pad_slot = np.full(8, prep.batch.num_nodes, dtype=np.int32)
        why = validate_assignments(prep, 0, pad_slot)
        assert why is not None  # padding slots are not live targets


def test_validation_rejects_overcommit():
    cs, s = _build(2, n_pods=40)  # 2 nodes x 8cpu = 32 x 500m slots
    prep = _prep_for(s, 40)
    # a corrupt solve that piles all 40 pods (20 cpu) onto node 0
    corrupt = np.zeros(40, dtype=np.int32)
    why = validate_assignments(prep, 0, corrupt)
    assert why is not None and "overcommit" in why


def test_validation_accumulates_across_chained_flights():
    cs, s = _build(2, n_pods=32)
    prep = _prep_for(s, 32)
    half = np.zeros(16, dtype=np.int32)  # 16 x 500m = 8cpu: fills node 0
    assert validate_assignments(prep, 0, half) is None
    # the second sub-flight piling onto the same node must trip the
    # accumulated check even though it fits the tensorize-time snapshot
    why = validate_assignments(prep, 16, half)
    assert why is not None and "overcommit" in why


def test_validation_failure_does_not_pollute_retry():
    """Merge-on-success: a FAILED validation must not leave phantom
    usage in the prep accumulator — the ladder-rung retry of the same
    prep would otherwise falsely flag its correct output."""
    cs, s = _build(2, n_pods=40)
    prep = _prep_for(s, 40)
    corrupt = np.zeros(40, dtype=np.int32)  # 20cpu onto one 8cpu node
    assert "overcommit" in validate_assignments(prep, 0, corrupt)
    # a correct spread over both nodes (10cpu/node... still too much:
    # 20 pods x 500m = 10 > 8) — use a genuinely feasible vector
    ok = np.array([i % 2 for i in range(32)] + [-1] * 8, dtype=np.int32)
    # 16 pods x 500m = 8cpu per node: exactly fits — must validate
    assert validate_assignments(prep, 0, ok) is None


def test_force_tier_device_failure_terminates_via_quarantine():
    """A pinned device tier + a deterministically failing solve must
    NOT livelock: with no rung to descend to, the failure is treated
    as data-shaped after one rebuild (bisect → quarantine)."""
    cs, s = _build(
        4, batch=8,
        resilience=ResilienceConfig(force_tier="single"),
    )
    s._solve_fault = _poison_hook({"default/p0002"})
    for i in range(6):
        cs.create_pod(
            MakePod().name(f"p{i:04}")
            .req({"cpu": "500m", "memory": "1Gi"}).obj()
        )
    rs = s.run_until_settled()
    assert sum(len(r.scheduled) for r in rs) == 5
    assert sorted(s._quarantine) == ["default/p0002"]


def test_corrupt_solve_feeds_breaker_and_recovers():
    """A corrupt output is never applied: the batch retries through
    the ladder and lands clean."""
    cs, s = _build(4, n_pods=8)
    real_dispatch = s._dispatch_group
    corrupted = [0]

    def corrupting(prep, defer, allow_heal=True, split=1, tier=None):
        flight = real_dispatch(
            prep, defer, allow_heal=allow_heal, split=split, tier=tier
        )
        if corrupted[0] == 0 and not isinstance(flight, list):
            corrupted[0] = 1
            flight.handle = np.full(
                len(prep.pods), prep.batch.padded + 7, dtype=np.int32
            )
        return flight

    s._dispatch_group = corrupting
    before = metrics.batch_failure_total.labels("corrupt")._value.get()
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())
    assert (
        metrics.batch_failure_total.labels("corrupt")._value.get()
        > before
    )


# -- poison-batch bisection quarantine (the ISSUE's fixtures) --


def _outcomes(s):
    import json

    out = {}
    for line in s.journal.lines if s.journal is not None else []:
        rec = json.loads(line)
        out[rec["pod"]] = rec["outcome"]
    return out


def test_bisection_one_poison_in_64():
    from kubernetes_tpu.obs import ObsConfig

    cs = ClusterState()
    for i in range(8):
        cs.create_node(
            MakeNode().name(f"n{i}")
            .capacity({"cpu": "32", "memory": "64Gi", "pods": "110"})
            .label(HOST, f"n{i}").obj()
        )
    s = Scheduler(cs, SchedulerConfig(
        batch_size=64, mesh_devices=1,
        solver=ExactSolverConfig(tie_break="first", group_size=16),
        obs=ObsConfig(journal=True),
    ))
    s._solve_fault = _poison_hook({"default/p0037"})
    for i in range(64):
        cs.create_pod(
            MakePod().name(f"p{i:04}")
            .req({"cpu": "500m", "memory": "1Gi"}).obj()
        )
    rs = s.run_until_settled()
    assert sum(len(r.scheduled) for r in rs) == 63
    assert sorted(s._quarantine) == ["default/p0037"]
    assert _outcomes(s)["default/p0037"] == "quarantined"


def test_bisection_two_poison_in_64():
    cs, s = _build(8, batch=64)
    bad = {"default/p0007", "default/p0052"}
    s._solve_fault = _poison_hook(bad)
    for i in range(64):
        cs.create_pod(
            MakePod().name(f"p{i:04}")
            .req({"cpu": "250m", "memory": "1Gi"}).obj()
        )
    rs = s.run_until_settled()
    assert sum(len(r.scheduled) for r in rs) == 62
    assert set(s._quarantine) == bad
    # the healthy 62 actually bound
    assert sum(1 for p in cs.list_pods() if p.node_name) == 62


def test_bisection_poison_in_carry_mode_subchain():
    """Poison pod in a hard-shape (spread) batch driven through
    run_pipelined's CARRY mode with the sub-batch split engaged: the
    deferred dispatch failure must route the batch to the synchronous
    resilient path, which bisects at the host rung and quarantines
    exactly the poison pod while the spread cohort lands skew-legal."""
    cs, s = _build(6, batch=16, zones=3, split=4)
    s._solve_fault = _poison_hook({"default/s0005"})
    for i in range(12):
        cs.create_pod(
            MakePod().name(f"s{i:04}")
            .req({"cpu": "500m", "memory": "1Gi"})
            .label("app", "spread")
            .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "spread"})
            .obj()
        )
    rs = s.run_pipelined(max_batches=100)
    assert sum(len(r.scheduled) for r in rs) == 11
    assert sorted(s._quarantine) == ["default/s0005"]
    # skew still holds among the placed cohort
    zones = {}
    for p in cs.list_pods():
        if p.node_name:
            z = cs.get_node(p.node_name).labels[ZONE]
            zones[z] = zones.get(z, 0) + 1
    assert max(zones.values()) - min(zones.values()) <= 1


def test_quarantine_ttl_readmits_and_backs_off():
    clock = FakeClock()
    cs, s = _build(
        4, batch=8, clock=clock,
        resilience=ResilienceConfig(
            quarantine_ttl=30.0, quarantine_backoff=2.0,
            open_seconds=5.0,
        ),
    )
    poison_on = [True]

    def hook(pods, tier):
        if poison_on[0] and any(p.key == "default/p0003" for p in pods):
            raise SolverFaultError("test: poison")

    s._solve_fault = hook
    for i in range(6):
        cs.create_pod(
            MakePod().name(f"p{i:04}")
            .req({"cpu": "500m", "memory": "1Gi"}).obj()
        )
    s.run_until_settled()
    assert sorted(s._quarantine) == ["default/p0003"]
    assert s._quarantine_counts["default/p0003"] == 1
    # TTL not yet elapsed: stays quarantined
    clock.advance(10.0)
    s.run_until_settled()
    assert "default/p0003" in s._quarantine
    # TTL elapsed, still poison: re-admitted, re-quarantined, backoff x2
    clock.advance(31.0)
    s.run_until_settled()
    assert s._quarantine_counts["default/p0003"] == 2
    # poison cured: the next re-admit binds it
    poison_on[0] = False
    clock.advance(61.0)
    s.run_until_settled()
    assert not s._quarantine
    assert all(p.node_name for p in cs.list_pods())


# -- ladder end-to-end --


def test_forced_host_tier_matches_device_bindings():
    cs1, s1 = _build(6, n_pods=40)
    s1.run_until_settled()
    cs2, s2 = _build(
        6, n_pods=40, resilience=ResilienceConfig(force_tier="host")
    )
    s2.run_until_settled()
    placed1 = sum(1 for p in cs1.list_pods() if p.node_name)
    placed2 = sum(1 for p in cs2.list_pods() if p.node_name)
    assert placed1 == placed2 == 40
    # capacity respected on the host rung too
    per_node = {}
    for p in cs2.list_pods():
        per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
    assert all(v <= 16 for v in per_node.values())


def test_transient_fault_journals_solver_error_then_binds():
    from kubernetes_tpu.obs import ObsConfig
    import json

    cs = ClusterState()
    for i in range(4):
        cs.create_node(
            MakeNode().name(f"n{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .label(HOST, f"n{i}").obj()
        )
    s = Scheduler(cs, SchedulerConfig(
        batch_size=8, mesh_devices=1,
        obs=ObsConfig(journal=True),
    ))
    calls = [0]

    def once(pods, tier):
        calls[0] += 1
        if calls[0] == 1:
            raise SolverFaultError("test: one-off device error")

    s._solve_fault = once
    before = metrics.batch_failure_total.labels("dispatch")._value.get()
    for i in range(4):
        cs.create_pod(
            MakePod().name(f"p{i}")
            .req({"cpu": "1", "memory": "1Gi"}).obj()
        )
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())
    assert (
        metrics.batch_failure_total.labels("dispatch")._value.get()
        == before + 1
    )
    # retry history: a non-terminal solver_error precedes the bound
    history = [
        json.loads(line)["outcome"]
        for line in s.journal.lines
        if json.loads(line)["pod"] == "default/p0"
    ]
    assert history[0] == "solver_error"
    assert history[-1] == "bound"
    assert s.resilience.rebuilds == 1  # one session rebuild healed it


def test_device_outage_falls_to_host_and_probes_back():
    """A full device outage (every device-tier solve fails) must keep
    binding at the host rung, then climb back once the outage ends."""
    clock = FakeClock()
    cs, s = _build(
        4, batch=8, clock=clock,
        resilience=ResilienceConfig(open_seconds=5.0),
    )
    outage = [True]

    def hook(pods, tier):
        if outage[0] and tier != TIER_HOST:
            raise SolverFaultError("test: device outage")

    s._solve_fault = hook
    for i in range(6):
        cs.create_pod(
            MakePod().name(f"p{i}")
            .req({"cpu": "500m", "memory": "1Gi"}).obj()
        )
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())  # progress held
    assert s.resilience.trips >= 1
    assert s.resilience.tier_index(next(iter(s.solvers))) == 1
    outage[0] = False
    clock.advance(6.0)
    for i in range(6, 10):
        cs.create_pod(
            MakePod().name(f"p{i}")
            .req({"cpu": "500m", "memory": "1Gi"}).obj()
        )
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())
    assert s.resilience.recloses >= 1
    assert s.resilience.tier_index(next(iter(s.solvers))) == 0


# -- resilience invariant (known-bad fixtures) --


def test_check_resilience_flags_missing_trip_and_stuck_tier():
    from kubernetes_tpu.sim.invariants import check_resilience

    cs, s = _build(2, n_pods=0)
    violations = []
    # faults injected but no trips -> "never engaged"
    check_resilience(s, 0, violations, device_faults=3, poison_hits=0)
    assert any("never engaged" in v.detail for v in violations)
    # trip the breaker and leave it open -> "never re-closed"
    s.resilience.on_failure(next(iter(s.solvers)), 0)
    s.resilience.on_failure(next(iter(s.solvers)), 0)
    violations2 = []
    check_resilience(s, 0, violations2, device_faults=3, poison_hits=0)
    assert any("re-closed" in v.detail for v in violations2)
    # poison hits with no quarantine -> "never isolated"
    violations3 = []
    check_resilience(s, 0, violations3, device_faults=0, poison_hits=2)
    assert any("isolated" in v.detail for v in violations3)


# -- fleet degraded flag --


def test_fleet_degraded_flag_orders_handoff_chain_last():
    from kubernetes_tpu.fleet.occupancy import OccupancyExchange
    from kubernetes_tpu.fleet.ring import _h

    ex = OccupancyExchange()
    v0 = ex.version
    ex.set_degraded("r1", True)
    assert ex.degraded_replicas() == frozenset({"r1"})
    assert ex.version > v0  # peers' parked pods re-evaluate
    ex.set_degraded("r1", True)  # idempotent: no version churn
    assert ex.version == v0 + 1
    # the rendezvous chain used by maybe_hand_off puts degraded last
    alive = ["r0", "r1", "r2"]
    key = "default/pod-x"
    degraded = ex.degraded_replicas()
    chain = sorted(
        alive, key=lambda r: (r in degraded, -_h("pod", key, r), r)
    )
    assert chain[-1] == "r1"
    ex.set_degraded("r1", False)
    assert ex.degraded_replicas() == frozenset()
    ex.retire("r1")  # retiring a degraded replica clears the flag too
    ex.set_degraded("r2", True)
    ex.retire("r2")
    assert ex.degraded_replicas() == frozenset()


def test_scheduler_breaker_publishes_fleet_degraded():
    """A breaker trip publishes the replica's degraded flag through the
    occupancy exchange; the re-close clears it."""
    from kubernetes_tpu.fleet.occupancy import OccupancyExchange
    from kubernetes_tpu.fleet.runtime import FleetConfig

    clock = FakeClock()
    ex = OccupancyExchange()
    cs = ClusterState(clock=clock)
    for i in range(4):
        cs.create_node(
            MakeNode().name(f"n{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .label(HOST, f"n{i}").obj()
        )
    s = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=8, mesh_devices=1,
            resilience=ResilienceConfig(open_seconds=5.0),
            fleet=FleetConfig(replica="r0", replicas=("r0",), exchange=ex),
        ),
        clock=clock,
    )
    outage = [True]

    def hook(pods, tier):
        if outage[0] and tier != TIER_HOST:
            raise SolverFaultError("test: outage")

    s._solve_fault = hook
    for i in range(4):
        cs.create_pod(
            MakePod().name(f"p{i}")
            .req({"cpu": "1", "memory": "1Gi"}).obj()
        )
    s.run_until_settled()
    assert "r0" in ex.degraded_replicas()  # trip published the flag
    outage[0] = False
    clock.advance(6.0)
    for i in range(4, 6):
        cs.create_pod(
            MakePod().name(f"p{i}")
            .req({"cpu": "1", "memory": "1Gi"}).obj()
        )
    s.run_until_settled()
    assert "r0" not in ex.degraded_replicas()  # re-close cleared it
