"""Pass framework for the tracer-safety / lock-discipline analyzer.

Self-contained stdlib-only AST analysis (the sandbox is offline; no
third-party linter deps). A :class:`SourceModule` pairs the parsed tree
with the comment stream (``ast`` drops comments, so annotations like
``# ktpu: hot`` are recovered from ``tokenize``); passes walk the tree
and emit :class:`Finding`\\ s; the runner applies inline suppressions
(``# ktpu: ignore[RULE]: reason``) afterwards so suppressed findings
stay visible in ``--json`` output for auditing.

Annotation grammar (shared by all passes; see analysis/README.md):

- ``# ktpu: ignore[RULE]: reason``  — suppress RULE on this line or the
  line below. The reason is REQUIRED; a reasonless ignore is itself a
  finding (KTPU000).
- ``# ktpu: hot``         — register the function below/beside as a
  hot-path root for TPU001 (host-sync) scope propagation.
- ``# ktpu: cold``        — mark an error/diagnosis path: stops hot/jit
  scope propagation into this function.
- ``# ktpu: holds(expr)`` — the function below/beside runs with
  ``self.<expr>`` held by every caller (LOCK001, LOCK002).
- ``# ktpu: guarded-by(expr)`` — trailing an attribute assignment in
  ``__init__``: registers the attribute as guarded by ``self.<expr>``.
- ``# ktpu: replicated`` — trailing an attribute assignment in
  ``__init__``: the attribute is hub-replicated state; FENCE001
  requires every method touching it to run a fence check first.
- ``# ktpu: fence-check`` — the function below/beside IS the role/
  epoch fence check; reaching it (directly or through helpers)
  satisfies FENCE001.
- ``# ktpu: fence-exempt(reason)`` — the function below/beside
  deliberately skips the fence (replication path, harness bypass…).
  The reason is REQUIRED; a reasonless exemption is a finding.
- ``# ktpu: fenced-by-caller`` — private helper whose callers have
  already run the fence checks (the ``_locked`` suffix convention).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

# ignore is a directive and must lead the comment; the function/attribute
# marks may trail prose ("... always holds it: ktpu: holds(cluster.lock)")
_IGNORE_RE = re.compile(
    r"#\s*ktpu:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*:?\s*(.*)"
)
_HOT_RE = re.compile(r"#.*\bktpu:\s*hot\b")
_COLD_RE = re.compile(r"#.*\bktpu:\s*cold\b")
_HOLDS_RE = re.compile(r"#.*\bktpu:\s*holds\(([^)]+)\)")
_GUARDED_RE = re.compile(r"#.*\bktpu:\s*guarded-by\(([^)]+)\)")
_REPLICATED_RE = re.compile(r"#.*\bktpu:\s*replicated\b")
_FENCE_CHECK_RE = re.compile(r"#.*\bktpu:\s*fence-check\b")
_FENCE_EXEMPT_RE = re.compile(r"#.*\bktpu:\s*fence-exempt\(([^)]*)\)")
_FENCED_BY_CALLER_RE = re.compile(r"#.*\bktpu:\s*fenced-by-caller\b")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    hint: str = ""
    suppressed: bool = False
    suppress_reason: str = ""

    def render(self) -> str:
        tag = " [suppressed: %s]" % self.suppress_reason if self.suppressed else ""
        hint = " (hint: %s)" % self.hint if self.hint else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{hint}{tag}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }


@dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]
    reason: str
    used: bool = False


@dataclass
class SourceModule:
    """One parsed file plus its recovered comment/annotation stream."""

    path: str  # as given on the command line / API
    rel: str  # package-relative posix path ("kubernetes_tpu/scheduler.py")
    source: str
    tree: ast.Module
    comments: dict[int, str] = field(default_factory=dict)  # line -> text
    suppressions: list[Suppression] = field(default_factory=list)

    @classmethod
    def parse(cls, path: str | Path, source: str | None = None) -> "SourceModule":
        p = Path(path)
        if source is None:
            source = p.read_text()
        tree = ast.parse(source, filename=str(p))
        mod = cls(path=str(p), rel=_rel_path(p), source=source, tree=tree)
        mod._collect_comments()
        return mod

    def _collect_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in toks:
                if tok.type == tokenize.COMMENT:
                    line = tok.start[0]
                    # multiple comments per line are impossible; keep last
                    self.comments[line] = tok.string
        except tokenize.TokenizeError:  # pragma: no cover - parse succeeded
            pass
        for line, text in self.comments.items():
            m = _IGNORE_RE.search(text)
            if m:
                rules = tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                self.suppressions.append(
                    Suppression(line=line, rules=rules, reason=m.group(2).strip())
                )

    # -- annotation lookups ------------------------------------------------

    def _mark_lines(self, node: ast.AST) -> list[int]:
        """Lines where a function-level mark may sit: the def line, the
        line above it, and the line above the first decorator."""
        lines = [node.lineno, node.lineno - 1]
        deco = getattr(node, "decorator_list", None)
        if deco:
            lines.append(deco[0].lineno - 1)
        return lines

    def _match_mark(self, node: ast.AST, regex: re.Pattern) -> re.Match | None:
        for line in self._mark_lines(node):
            text = self.comments.get(line)
            if text:
                m = regex.search(text)
                if m:
                    return m
        return None

    def is_hot(self, func: ast.AST) -> bool:
        return self._match_mark(func, _HOT_RE) is not None

    def is_cold(self, func: ast.AST) -> bool:
        return self._match_mark(func, _COLD_RE) is not None

    def holds_lock(self, func: ast.AST) -> str | None:
        m = self._match_mark(func, _HOLDS_RE)
        return m.group(1).strip() if m else None

    def guarded_by(self, stmt: ast.stmt) -> str | None:
        """guarded-by mark trailing (or directly above) a statement."""
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for line in range(stmt.lineno - 1, end + 1):
            text = self.comments.get(line)
            if text:
                m = _GUARDED_RE.search(text)
                if m:
                    return m.group(1).strip()
        return None

    def replicated_mark(self, stmt: ast.stmt) -> bool:
        """``replicated`` mark trailing (or directly above) a statement.
        The line-above form only counts on a comment-ONLY line — a mark
        trailing the PREVIOUS statement must not bleed onto this one."""
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for line in range(stmt.lineno - 1, end + 1):
            text = self.comments.get(line)
            if text and _REPLICATED_RE.search(text):
                if line >= stmt.lineno:
                    return True
                src = self.source.splitlines()[line - 1]
                if src.lstrip().startswith("#"):
                    return True
        return False

    def is_fence_check(self, func: ast.AST) -> bool:
        return self._match_mark(func, _FENCE_CHECK_RE) is not None

    def fence_exempt(self, func: ast.AST) -> str | None:
        """The exemption reason, '' when the mark is present but empty
        (itself a finding), None when unmarked."""
        m = self._match_mark(func, _FENCE_EXEMPT_RE)
        return m.group(1).strip() if m else None

    def is_fenced_by_caller(self, func: ast.AST) -> bool:
        return self._match_mark(func, _FENCED_BY_CALLER_RE) is not None


def _rel_path(p: Path) -> str:
    """Path relative to the directory CONTAINING the kubernetes_tpu
    package, when the file lives inside one; else the bare filename (the
    fixture-test case)."""
    parts = p.resolve().parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == "kubernetes_tpu":
            return "/".join(parts[i:])
    return p.name


class Pass:
    """Base class: one rule, one AST walk."""

    rule = "KTPU999"
    title = ""

    def run(self, module: SourceModule, ctx: "AnalysisContext") -> list[Finding]:
        raise NotImplementedError


@dataclass
class AnalysisContext:
    """Cross-file configuration shared by all passes (defaults in
    registry.py; fixture tests inject overrides)."""

    # (rel-path suffix, dotted qualname) pairs where host sync is sanctioned
    sanctioned_sync: frozenset = frozenset()
    # rel-path prefixes where TPU003 dtype discipline applies
    dtype_paths: tuple = ()
    # rel-path prefixes where MET001 scans metric usage
    metric_scan_paths: tuple = ()
    # metric attribute -> prometheus name (None => resolve from package)
    metric_attrs: dict | None = None
    # exception class names that must never be swallowed by a retry
    # loop (RETRY001) — semantic rejections, not transport faults
    non_retryable_errors: tuple = ("AdmitConflict",)
    # rel-path suffix of the metrics registry module (MET002)
    metrics_module_suffix: str = "kubernetes_tpu/metrics/__init__.py"
    # METRICS.md content override for fixture tests (None => read the
    # file next to the registry module)
    metrics_doc_text: str | None = None

    def is_sanctioned(self, rel: str, qualname: str) -> bool:
        for suffix, qn in self.sanctioned_sync:
            if qn == qualname and rel.endswith(suffix):
                return True
        return False


def apply_suppressions(module: SourceModule, findings: list[Finding]) -> None:
    """Mark findings suppressed by a matching ``ktpu: ignore`` on the
    finding's line or the line above it."""
    by_line: dict[int, list[Suppression]] = {}
    for s in module.suppressions:
        by_line.setdefault(s.line, []).append(s)
    for f in findings:
        for line in (f.line, f.line - 1):
            for s in by_line.get(line, ()):
                if f.rule in s.rules and s.reason:
                    f.suppressed = True
                    f.suppress_reason = s.reason
                    s.used = True
                    break
            if f.suppressed:
                break


def suppression_findings(module: SourceModule) -> list[Finding]:
    """KTPU000: every suppression must carry a reason."""
    out = []
    for s in module.suppressions:
        if not s.reason:
            out.append(
                Finding(
                    rule="KTPU000",
                    path=module.path,
                    line=s.line,
                    message=(
                        "suppression for %s has no reason"
                        % ",".join(s.rules)
                    ),
                    hint="write '# ktpu: ignore[%s]: <why this is safe>'"
                    % ",".join(s.rules),
                )
            )
    return out
