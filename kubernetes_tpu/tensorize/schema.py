"""The tensor schema: API objects -> padded device arrays (SURVEY.md §8.1).

This is the TPU-native replacement for the reference's per-node NodeInfo
structs (pkg/scheduler/framework/types.go#NodeInfo: Requested,
NonZeroRequested, Allocatable, pod counts) and the per-cycle CycleState
scratch. Instead of 10k heap-allocated NodeInfo objects walked by goroutines,
the snapshot is a struct-of-arrays with the **node axis last** so it lands on
TPU lanes:

    allocatable[K, N]   int64   per-resource allocatable (resource-major!)
    used[K, N]          int64   NodeInfo.Requested equivalent
    nonzero_used[2, N]  int64   NodeInfo.NonZeroRequested (cpu milli, mem bytes)
    pod_count[N]        int32   len(NodeInfo.Pods)
    max_pods[N]         int32   NodeInfo.Allocatable.AllowedPodNumber

K (the resource vocabulary) is small and lives on sublanes; N is padded to a
multiple of 128 (TPU lane width) with a validity mask. Dtypes: resources are
int64 — exact parity with the reference's resource.Quantity int64 arithmetic
comes first; a scaled-int32 fast path can be layered on later without
changing kernel signatures.

Pod batches are pod-major (``req[P, K]``) because the exact-parity solver
scans over pods and gathers one row per step.

Padding uses "impossible" values (allocatable=0, request=+inf-ish) so padded
lanes never win an argmax and never pass a filter; every array also carries
an explicit validity mask.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Iterable, Mapping, Sequence

import numpy as np

from ..api.objects import (
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Node,
    Pod,
)

LANE = 128  # TPU lane width: last-dim padding quantum

# Resources that are always in the vocabulary, in fixed order, so kernels can
# special-case cpu/memory by index (non-zero defaults apply to them only).
BASE_RESOURCES = (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE)
CPU_IDX = 0
MEM_IDX = 1


def pad_to(n: int, quantum: int = LANE) -> int:
    return max(quantum, ((n + quantum - 1) // quantum) * quantum)


def bucket_pow2(n: int, floor: int = LANE) -> int:
    """Round up to the next power-of-two-ish bucket to bound XLA recompiles
    (SURVEY.md §8.8 'recompile storms')."""
    b = floor
    while b < n:
        b *= 2
    return b


@dataclass(frozen=True)
class ResourceVocab:
    """Per-deployment resource vocabulary. cpu/memory/ephemeral-storage are
    always present at fixed indices; extended resources follow, sorted.
    The ``pods`` resource is handled as dedicated count arrays, mirroring
    NodeInfo.Allocatable.AllowedPodNumber."""

    names: tuple[str, ...]

    @functools.cached_property
    def index(self) -> dict[str, int]:
        # cached: vectorize runs once per node/pod on the host hot path
        return {n: i for i, n in enumerate(self.names)}

    def __len__(self) -> int:
        return len(self.names)

    @staticmethod
    def build(pods: Iterable[Pod], nodes: Iterable[Node]) -> "ResourceVocab":
        extended: set[str] = set()
        for p in pods:
            for r in p.resource_request():
                if r not in BASE_RESOURCES and r != RESOURCE_PODS:
                    extended.add(r)
        for n in nodes:
            for r in n.allocatable:
                if r not in BASE_RESOURCES and r != RESOURCE_PODS:
                    extended.add(r)
        return ResourceVocab(BASE_RESOURCES + tuple(sorted(extended)))

    def vectorize(self, res: Mapping[str, int]) -> np.ndarray:
        out = np.zeros(len(self.names), dtype=np.int64)
        idx = self.index
        for k, v in res.items():
            if k in idx:
                out[idx[k]] = v
        return out

    def has_unknown(self, res: Mapping[str, int]) -> bool:
        """True if ``res`` names a resource outside the vocabulary with a
        non-zero value. The vocab covers everything any node advertises, so
        an unknown requested resource can never be satisfied — the pod must
        be statically infeasible (the reference's Fit filter fails it on
        every node), NOT silently dropped."""
        idx = self.index
        return any(v > 0 and k not in idx and k != RESOURCE_PODS for k, v in res.items())


@dataclass
class NodeBatch:
    """Device-shaped snapshot of N nodes (padded to Np)."""

    vocab: ResourceVocab
    names: list[str]  # length num_nodes (unpadded)
    num_nodes: int
    padded: int

    allocatable: np.ndarray  # [K, Np] int64
    used: np.ndarray  # [K, Np] int64
    nonzero_used: np.ndarray  # [2, Np] int64
    pod_count: np.ndarray  # [Np] int32
    max_pods: np.ndarray  # [Np] int32
    valid: np.ndarray  # [Np] bool
    # static per-node feasibility from node state alone; the exact solver
    # ANDs this into every pod's mask. Starts as ~unschedulable; plugin
    # tensorizers (taints, etc.) refine it per pod class elsewhere.
    schedulable: np.ndarray  # [Np] bool  (node.Spec.Unschedulable inverted)

    def index_of(self) -> dict[str, int]:
        return {n: i for i, n in enumerate(self.names)}

    def device_arrays(self) -> dict[str, np.ndarray]:
        """The pytree the solver ships to HBM."""
        return {
            "allocatable": self.allocatable,
            "used": self.used,
            "nonzero_used": self.nonzero_used,
            "pod_count": self.pod_count,
            "max_pods": self.max_pods,
            "valid": self.valid,
            "schedulable": self.schedulable,
        }


@dataclass
class PodBatch:
    """Device-shaped batch of P pending pods (padded to Pp), in queue order."""

    vocab: ResourceVocab
    keys: list[str]  # ns/name, length num_pods
    num_pods: int
    padded: int

    req: np.ndarray  # [Pp, K] int64 — computePodResourceRequest
    req_mask: np.ndarray  # [Pp, K] bool — which resources the pod requests >0
    feasible_static: np.ndarray  # [Pp] bool — False: requests a resource no node advertises
    nonzero_req: np.ndarray  # [Pp, 2] int64 — scoring requests w/ defaults
    priority: np.ndarray  # [Pp] int32
    valid: np.ndarray  # [Pp] bool

    def device_arrays(self) -> dict[str, np.ndarray]:
        return {
            "req": self.req,
            "req_mask": self.req_mask,
            "feasible_static": self.feasible_static,
            "nonzero_req": self.nonzero_req,
            "priority": self.priority,
            "valid": self.valid,
        }


def build_node_batch(
    nodes: Sequence[Node],
    pods_by_node: Mapping[str, Sequence[Pod]] | None = None,
    vocab: ResourceVocab | None = None,
    pad: int | None = None,
) -> NodeBatch:
    """Tensorize a node snapshot.

    ``pods_by_node`` carries the already-placed (scheduled + assumed) pods per
    node; their aggregated requests become ``used``/``nonzero_used`` exactly as
    cache.AssumePod accumulates NodeInfo.Requested in the reference.
    """
    pods_by_node = pods_by_node or {}
    if vocab is None:
        all_pods = [p for ps in pods_by_node.values() for p in ps]
        vocab = ResourceVocab.build(all_pods, nodes)
    n = len(nodes)
    np_pad = pad if pad is not None else pad_to(n)
    k = len(vocab)

    allocatable = np.zeros((k, np_pad), dtype=np.int64)
    used = np.zeros((k, np_pad), dtype=np.int64)
    nonzero_used = np.zeros((2, np_pad), dtype=np.int64)
    pod_count = np.zeros(np_pad, dtype=np.int32)
    max_pods = np.zeros(np_pad, dtype=np.int32)
    valid = np.zeros(np_pad, dtype=bool)
    schedulable = np.zeros(np_pad, dtype=bool)

    for i, node in enumerate(nodes):
        allocatable[:, i] = vocab.vectorize(node.allocatable)
        max_pods[i] = node.allocatable.get(RESOURCE_PODS, 0)
        valid[i] = True
        schedulable[i] = not node.unschedulable
        placed = pods_by_node.get(node.name) or ()
        pod_count[i] = len(placed)
        for p in placed:
            used[:, i] += vocab.vectorize(p.resource_request())
            nz = p.non_zero_request()
            nonzero_used[0, i] += nz[0]
            nonzero_used[1, i] += nz[1]

    return NodeBatch(
        vocab=vocab,
        names=[nd.name for nd in nodes],
        num_nodes=n,
        padded=np_pad,
        allocatable=allocatable,
        used=used,
        nonzero_used=nonzero_used,
        pod_count=pod_count,
        max_pods=max_pods,
        valid=valid,
        schedulable=schedulable,
    )


@dataclass
class NominatedTensors:
    """Nominated-pod load for RunFilterPluginsWithNominatedPods semantics
    (framework/runtime/framework.go#addNominatedPods): when scheduling pod
    p, nominated pods with priority >= p.priority count as if already
    placed on their nominated node — the resource/count filters see their
    load, so a preemptor's freed capacity cannot be stolen by a
    lower-priority pod.

    Levels are the distinct nominated priorities, DESCENDING; row l of the
    cumulative tensors holds the total load of nominated pods with
    priority >= levels[l-1] (row 0 = no load, for pods outranking every
    nomination). A pod's row index comes from level_of(). Only the
    monotone filters (resources, pod count) consume this — adding load
    can only shrink the feasible set, so the reference's run-twice
    protocol collapses to one run for them.

    NodePorts is covered too (ADVICE r3: port conflicts are as monotone
    as resources): when the caller passes the batch's PortTensors, the
    nominated pods' hostPorts are interned into that batch's port
    vocabulary (build_port_tensors takes ``nominated`` for exactly this)
    and ``port_takes`` carries their cumulative occupancy rows — a
    conflicting pod can no longer find a preemptor's reserved node
    port-feasible during the nomination window. PodTopologySpread and
    InterPodAffinity count nominated pods at their slots inside their own
    tensorizers (build_spread_tensors / build_interpod_tensors also take
    ``nominated``, VERDICT r5 parity), not through these cumulative rows —
    their counting is per-term, not per-priority-level.
    """

    levels: np.ndarray  # [L] int32 distinct nominated priorities, desc
    used: np.ndarray  # [L+1, K, Np] int64 cumulative nominated requests
    count: np.ndarray  # [L+1, Np] int32 cumulative nominated pod counts
    # [L+1, B, Np] int32 cumulative nominated hostPort occupancy in the
    # batch's port vocab (None: no port tensors supplied / no ports)
    port_takes: np.ndarray | None = None

    @property
    def empty(self) -> bool:
        return self.levels.size == 0

    def level_of(self, priority: np.ndarray) -> np.ndarray:
        """[P] priorities -> [P] row indices: number of levels with
        priority >= the pod's (0 = none apply)."""
        # levels desc; count levels >= priority
        return np.searchsorted(-self.levels, -np.asarray(priority), side="right").astype(
            np.int32
        )


def build_nominated_tensors(
    nominated: Sequence[tuple[Pod, int]],  # (pod, node slot)
    vocab: "ResourceVocab",
    n_pad: int,
    ports=None,  # PortTensors whose vocab includes the nominated ports
) -> NominatedTensors:
    """``nominated``: unbound pods carrying status.nominatedNodeName,
    with their nominated node's snapshot slot. With ``ports`` (the
    batch's PortTensors, built with the same ``nominated`` so its vocab
    interns their hostPorts), the cumulative port-occupancy rows are
    built too."""
    if not nominated:
        return NominatedTensors(
            levels=np.zeros(0, dtype=np.int32),
            used=np.zeros((1, len(vocab), n_pad), dtype=np.int64),
            count=np.zeros((1, n_pad), dtype=np.int32),
        )
    k = len(vocab)
    prios = sorted({p.effective_priority for p, _ in nominated}, reverse=True)
    levels = np.asarray(prios, dtype=np.int32)
    # pad the level axis to a small pow2 bucket so the number of distinct
    # nominated priorities doesn't mint fresh XLA executables (§8.8
    # recompile storms); padding rows repeat the last cumulative row and
    # are never indexed (level_of <= len(prios))
    rows = 4
    while rows < len(prios) + 1:
        rows *= 2
    used = np.zeros((rows, k, n_pad), dtype=np.int64)
    count = np.zeros((rows, n_pad), dtype=np.int32)
    port_takes = None
    port_index = None
    if ports is not None and any(p.host_ports() for p, _ in nominated):
        port_index = {t: i for i, t in enumerate(ports.vocab)}
        port_takes = np.zeros(
            (rows, ports.used.shape[0], n_pad), dtype=np.int32
        )
    # each pod's load lands in every cumulative row that includes its
    # priority (its own level row and every lower-priority row below it)
    for pod, slot in nominated:
        row = prios.index(pod.effective_priority) + 1
        r = vocab.vectorize(pod.resource_request())
        used[row:, :, slot] += r[None, :]
        count[row:, slot] += 1
        if port_takes is not None:
            for t in pod.host_ports():
                v = port_index.get(t)
                if v is not None:  # vocab built with `nominated` has all
                    port_takes[row:, v, slot] += 1
    return NominatedTensors(
        levels=levels, used=used, count=count, port_takes=port_takes
    )


def build_pod_batch(
    pods: Sequence[Pod],
    vocab: ResourceVocab,
    pad: int | None = None,
) -> PodBatch:
    p = len(pods)
    pp = pad if pad is not None else bucket_pow2(p)
    k = len(vocab)

    req = np.zeros((pp, k), dtype=np.int64)
    req_mask = np.zeros((pp, k), dtype=bool)
    feasible_static = np.ones(pp, dtype=bool)
    nonzero_req = np.zeros((pp, 2), dtype=np.int64)
    priority = np.zeros(pp, dtype=np.int32)
    valid = np.zeros(pp, dtype=bool)

    for i, pod in enumerate(pods):
        rr = pod.resource_request()
        r = vocab.vectorize(rr)
        req[i] = r
        req_mask[i] = r > 0
        if vocab.has_unknown(rr):
            feasible_static[i] = False
        nz = pod.non_zero_request()
        nonzero_req[i, 0] = nz[0]
        nonzero_req[i, 1] = nz[1]
        priority[i] = pod.effective_priority
        valid[i] = True

    return PodBatch(
        vocab=vocab,
        keys=[pod.key for pod in pods],
        num_pods=p,
        padded=pp,
        req=req,
        req_mask=req_mask,
        feasible_static=feasible_static,
        nonzero_req=nonzero_req,
        priority=priority,
        valid=valid,
    )
