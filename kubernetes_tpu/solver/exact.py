"""Exact-parity solver: a lax.scan over pods in queue order (SURVEY.md §8.4
mode 1).

This replaces the reference's scheduleOne hot path
(pkg/scheduler/schedule_one.go#schedulePod -> findNodesThatFitPod ->
prioritizeNodes -> selectHost) with one compiled program: each scan step is a
dense filter-mask + score over ALL nodes at once (the per-(pod,node) Go
interface-call overhead becomes one fused XLA loop body), and the
assume-pod state mutation (cache.AssumePod) becomes an in-carry scatter so
the next step sees updated node state — preserving the reference's strict
pod-by-pod sequential semantics, which is what "binding parity" means.

Filter pipeline per step (runtime/framework.go#RunFilterPlugins, fused):
  NodeResourcesFit ∧ static class mask (NodeName ∧ NodeUnschedulable ∧
  TaintToleration ∧ NodeAffinity, precompiled per pod class) ∧ NodePorts
  (occupancy matvec over the port vocab).

Score pipeline (runtime/framework.go#RunScorePlugins: score, normalize,
weight — default-profile weights from apis/config/v1/default_plugins.go):
  1·LeastAllocated + 1·BalancedAllocation + 3·TaintToleration(norm reverse)
  + 2·NodeAffinity(norm) + 1·ImageLocality.

selectHost tie-break: the reference reservoir-samples uniformly among
max-score ties with an unseeded RNG (schedule_one.go#selectHost). Bit-parity
is impossible; we offer:
- "random": uniform among ties from a seeded PRNG key (documented divergence)
- "first":  lowest node index among ties (deterministic, used by parity tests)
Either way the pick is provably inside the reference's tie set, which is the
parity definition from SURVEY.md §8.8.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import noderesources as nr
from ..ops import plugins as pl
from ..tensorize.plugins import (
    PortTensors,
    StaticPluginTensors,
    trivial_port_tensors,
    trivial_static_tensors,
)
from ..tensorize.schema import MEM_IDX, NodeBatch, PodBatch

TIE_RANDOM = "random"
TIE_FIRST = "first"


@dataclass(frozen=True)
class ExactSolverConfig:
    tie_break: str = TIE_RANDOM
    seed: int = 0
    # Score-plugin weights; defaults mirror the default profile
    # (apis/config/v1/default_plugins.go): TaintToleration 3, NodeAffinity 2,
    # Fit/Balanced/ImageLocality 1.
    fit_weight: int = 1
    balanced_weight: int = 1
    taint_weight: int = 3
    node_affinity_weight: int = 2
    image_weight: int = 1
    balanced_fdtype: str = "float32"  # float64 for bit-parity on CPU tests


def _solve_scan(
    # node tables (read-only in the scan)
    alloc,  # [K, N] int
    max_pods,  # [N] int32
    node_valid,  # [N] bool — slot validity only
    static_mask,  # [C, N] bool — per-class static Filter plugins
    taint_cnt,  # [C, N] int32
    nodeaff_pref,  # [C, N] int32
    image_score,  # [C, N] int32
    # carried node state
    used0,  # [K, N] int
    nonzero_used0,  # [2, N] int
    pod_count0,  # [N] int32
    port_used0,  # [V, N] int32
    # per-pod inputs (scanned)
    req,  # [P, K] int
    req_mask,  # [P, K] bool
    nonzero_req,  # [P, 2] int
    pod_valid,  # [P] bool — valid & statically feasible
    class_of,  # [P] int32
    pod_conflict,  # [P, V] bool
    pod_takes,  # [P, V] int32
    key,  # PRNG key
    *,
    tie_break: str,
    w_fit: int,
    w_balanced: int,
    w_taint: int,
    w_nodeaff: int,
    w_image: int,
    fdtype,
):
    alloc2 = alloc[: MEM_IDX + 1]  # cpu, memory rows for scoring
    weights2 = jnp.ones(2, dtype=alloc.dtype)

    def step(carry, xs):
        used, nonzero_used, pod_count, port_used, k = carry
        r, rmask, nz, pvalid, cls, pconf, ptk = xs

        mask = (
            nr.fit_mask(r, rmask, alloc, used, pod_count, max_pods)
            & static_mask[cls]
            & node_valid
            & ~pl.ports_conflict_mask(pconf, port_used)
        )

        requested = nr.scoring_requested(nz, nonzero_used)
        score = w_fit * nr.least_allocated_score(requested, alloc2, weights2)
        score = score + w_balanced * nr.balanced_allocation_score(
            requested, alloc2, fdtype=fdtype
        )
        score = score.astype(jnp.int32)
        if w_taint:
            score = score + w_taint * pl.normalize_score(
                taint_cnt[cls], mask, reverse=True
            )
        if w_nodeaff:
            score = score + w_nodeaff * pl.normalize_score(
                nodeaff_pref[cls], mask, reverse=False
            )
        if w_image:
            score = score + w_image * image_score[cls]
        score = jnp.where(mask, score, -1)

        best = jnp.max(score)
        feasible = best >= 0
        ties = (score == best) & mask
        csum = jnp.cumsum(ties)
        if tie_break == TIE_RANDOM:
            k, sub = jax.random.split(k)
            n_ties = csum[-1]
            pick_rank = jax.random.randint(sub, (), 0, jnp.maximum(n_ties, 1))
        else:
            pick_rank = 0
        pick = jnp.argmax(csum > pick_rank).astype(jnp.int32)

        found = feasible & pvalid
        d = found.astype(alloc.dtype)
        used = used.at[:, pick].add(r * d)
        nonzero_used = nonzero_used.at[:, pick].add(nz * d)
        pod_count = pod_count.at[pick].add(found.astype(jnp.int32))
        port_used = port_used.at[:, pick].add(ptk * found.astype(jnp.int32))

        assignment = jnp.where(found, pick, -1).astype(jnp.int32)
        return (used, nonzero_used, pod_count, port_used, k), assignment

    (used, nonzero_used, pod_count, port_used, _), assignments = jax.lax.scan(
        step,
        (used0, nonzero_used0, pod_count0, port_used0, key),
        (req, req_mask, nonzero_req, pod_valid, class_of, pod_conflict, pod_takes),
    )
    return assignments, used, nonzero_used, pod_count, port_used


_solve_scan_jit = jax.jit(
    _solve_scan,
    static_argnames=(
        "tie_break",
        "w_fit",
        "w_balanced",
        "w_taint",
        "w_nodeaff",
        "w_image",
        "fdtype",
    ),
    donate_argnums=(7, 8, 9, 10),
)


class ExactSolver:
    """Host-facing wrapper: NodeBatch/PodBatch (+ plugin tensors) in,
    assignments out, node state written back (the device-side 'assume')."""

    def __init__(self, config: ExactSolverConfig | None = None):
        self.config = config or ExactSolverConfig()
        self._step_count = 0
        # int64 resource arithmetic is non-negotiable (memory bytes overflow
        # int32); jax 0.9+axon ignores the JAX_ENABLE_X64 env var, so enable
        # it here rather than trusting the embedding application.
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)

    def solve(
        self,
        nodes: NodeBatch,
        pods: PodBatch,
        static: StaticPluginTensors | None = None,
        ports: PortTensors | None = None,
    ) -> np.ndarray:
        """Returns assignments [num_pods] of node indices (-1 = unschedulable)
        and updates ``nodes``' used/nonzero_used/pod_count in place.

        Without ``static``/``ports`` tensors, a trivial single-class mask
        (valid ∧ schedulable) reproduces the resources-only pipeline.
        """
        cfg = self.config
        fdtype = jnp.float64 if cfg.balanced_fdtype == "float64" else jnp.float32
        key = jax.random.PRNGKey(cfg.seed + self._step_count)
        self._step_count += 1
        if static is None:
            static = trivial_static_tensors(pods, nodes.padded, nodes.schedulable)
        if ports is None:
            ports = trivial_port_tensors(pods, nodes.padded)
        assignments, used, nonzero_used, pod_count, _ = _solve_scan_jit(
            jnp.asarray(nodes.allocatable),
            jnp.asarray(nodes.max_pods),
            jnp.asarray(nodes.valid),
            jnp.asarray(static.mask),
            jnp.asarray(static.taint_cnt),
            jnp.asarray(static.nodeaff_pref),
            jnp.asarray(static.image_score),
            jnp.asarray(nodes.used),
            jnp.asarray(nodes.nonzero_used),
            jnp.asarray(nodes.pod_count),
            jnp.asarray(ports.used),
            jnp.asarray(pods.req),
            jnp.asarray(pods.req_mask),
            jnp.asarray(pods.nonzero_req),
            jnp.asarray(pods.valid & pods.feasible_static),
            jnp.asarray(static.class_of),
            jnp.asarray(ports.pod_conflict),
            jnp.asarray(ports.pod_takes),
            key,
            tie_break=cfg.tie_break,
            w_fit=cfg.fit_weight,
            w_balanced=cfg.balanced_weight,
            w_taint=cfg.taint_weight,
            w_nodeaff=cfg.node_affinity_weight,
            w_image=cfg.image_weight,
            fdtype=fdtype,
        )
        # np.array(copy=True): np.asarray on a jax array yields a READ-ONLY
        # view, which would freeze the snapshot's dirty-column writes
        nodes.used = np.array(used)
        nodes.nonzero_used = np.array(nonzero_used)
        nodes.pod_count = np.array(pod_count)
        return np.asarray(assignments)[: pods.num_pods]
