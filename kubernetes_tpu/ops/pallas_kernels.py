"""Pallas TPU kernels — the native-code tier of this framework.

SURVEY.md §3.4: the reference implements its hot loops in pure Go; the
"native equivalent" obligation here maps to Pallas TPU kernels with jax.lax
reference implementations for parity (the parity tests ARE the sanitizer).
First kernel: the (term, domain) count aggregation that PodTopologySpread
and InterPodAffinity run every scan step (ops/spread.py#_domain_aggregate,
ops/interpod.py#domain_counts currently lower it through
jax.ops.segment_sum).

domain_counts_pallas computes, for T term rows at once,

    out[t, d] = sum_n  cnt[t, n] * (dom[t, n] == d)

by materializing the one-hot domain matrix PER TILE in VMEM and contracting
it on the MXU: each (t, n-tile) grid step does a [1, NT] x [NT, D] matmul
accumulated into the [T, D] output block — the blockwise-attention trick
applied to scatter-free segment reduction (guide §4, §7). Grid iterates the
n-tile axis innermost so the output block stays resident and accumulates
(@pl.when zero-init on the first tile).

Works in interpret mode on CPU (tests) and compiled on the axon TPU.

**Production wiring decision (round 3, amended by ISSUE 13) — the
kernel IS now wired, behind ``tpuSolver.pallas`` (default OFF):**
``ops/interpod.domain_counts`` routes its [T, D] aggregation through
``domain_counts_padded`` below when ``ExactSolverConfig.pallas`` is
set, inside the production per-pod scan, with parity pinned end to end
by tests/test_pallas_kernels.py (production ExactSolver.solve, flag on
vs off, bit-identical assignments) and a ladder micro-bench in
bench.py. The DEFAULT stays off because the round-3 negative results
stand, measured and unchanged on this box's jax 0.9 + experimental
axon PJRT:

1. With ``jax_enable_x64`` enabled — which the solver REQUIRES process-wide
   (int64 resource arithmetic; memory bytes overflow int32) — Pallas
   lowering of this kernel crashes with a RecursionError inside dtype
   conversion (jax/_src/numpy/lax_numpy.py astype), both standalone and
   under lax.scan. With x64 off it compiles and matches the reference
   (parity verified on TPU), so the kernel is sound; the x64 interaction
   is a toolchain defect this build cannot work around.
2. The workload that made this aggregation expensive — hostname-topology
   terms, where d_pad ~ N and the flattened segment_sum cost ~0.8 ms per
   scan step — is now served by ops/interpod.domain_counts' IDENTITY mode
   (unique-domain rows need no aggregation at all), removing the hot case
   without any kernel.
3. The remaining small-d_pad segment_sum costs ~0.25 ms/step
   (zone-topology shapes), below the measured per-call benefit a Pallas
   replacement could deliver here even if it compiled.

On a build where the x64 lowering works, enabling the kernel is now a
config flip (``tpuSolver: {pallas: true}``), not a code change. On
non-TPU backends ``domain_counts_padded`` selects interpret mode at
trace time, which is how the tier-1 parity tests exercise the wired
path under the x64-everywhere test config.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

N_TILE = 512  # lanes per grid step (multiple of 128)
T_TILE = 8  # term rows per grid step (sublane quantum for int32-as-f32)


def _domain_counts_kernel(dom_ref, cnt_ref, out_ref, *, d_pad: int):
    j = pl.program_id(1)  # n-tile index (innermost)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    dom = dom_ref[...]  # [T_TILE, NT] int32
    cnt = cnt_ref[...]  # [T_TILE, NT] int32
    masked = jnp.where(dom >= 0, cnt, 0).astype(jnp.float32)
    iota_d = jax.lax.broadcasted_iota(jnp.int32, (N_TILE, d_pad), 1)
    rows = []
    for s in range(T_TILE):  # static unroll: each row has its own one-hot
        onehot = (dom[s].reshape(N_TILE, 1) == iota_d).astype(jnp.float32)
        rows.append(
            jax.lax.dot_general(
                masked[s].reshape(1, N_TILE),
                onehot,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [1, D]
        )
    out_ref[...] += jnp.concatenate(rows, axis=0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("d_pad", "interpret"))
def domain_counts_pallas(dom, cnt, d_pad: int, interpret: bool = False):
    """[T, D] domain totals from per-node counts.

    dom: [T, N] int32 domain ids (-1 = node lacks the key, excluded);
    cnt: [T, N] int32. T must be a multiple of T_TILE and N of N_TILE (the
    tensorizers pad instance axes to 8s and the node axis to 128s; callers
    pad up to these tiles).
    """
    t, n = dom.shape
    assert n % N_TILE == 0, f"node axis {n} not a multiple of {N_TILE}"
    assert t % T_TILE == 0, f"term axis {t} not a multiple of {T_TILE}"
    grid = (t // T_TILE, n // N_TILE)
    return pl.pallas_call(
        functools.partial(_domain_counts_kernel, d_pad=d_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((T_TILE, N_TILE), lambda i, j: (i, j)),
            pl.BlockSpec((T_TILE, N_TILE), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((T_TILE, d_pad), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t, d_pad), jnp.int32),
        interpret=interpret,
    )(dom, cnt)


def domain_counts_padded(dom, cnt, d_pad: int):
    """Production adapter for the per-pod scan (``tpuSolver.pallas``):
    pad the term axis to T_TILE and the node axis to N_TILE (pad lanes
    carry dom = -1, which the kernel masks out), run the MXU kernel,
    slice the pad rows back off. Returns the [T, D] domain totals the
    dispatcher gathers per node.

    Interpret mode is selected AT TRACE TIME on non-TPU backends (the
    tier-1 suite runs the wired path this way under x64); a TPU backend
    lowers the compiled kernel. Called from exact.py's jit scope —
    padding is trace-time reshaping, not a host sync: ktpu: hot"""
    import jax as _jax

    t, n = dom.shape
    tp = -t % T_TILE
    np_ = -n % N_TILE
    if tp or np_:
        dom = jnp.pad(dom, ((0, tp), (0, np_)), constant_values=-1)
        cnt = jnp.pad(cnt, ((0, tp), (0, np_)))
    interpret = _jax.default_backend() != "tpu"
    out = domain_counts_pallas(
        dom.astype(jnp.int32), cnt.astype(jnp.int32), d_pad,
        interpret=interpret,
    )
    return out[:t]


def domain_counts_reference(dom, cnt, d_pad: int):
    """jax.lax reference implementation (parity anchor): the segment_sum
    formulation the solver currently uses."""
    t = dom.shape[0]
    hk = dom >= 0
    dd = jnp.where(hk, dom, 0)
    seg_ids = (dd + jnp.arange(t, dtype=jnp.int32)[:, None] * d_pad).reshape(-1)
    return jax.ops.segment_sum(
        jnp.where(hk, cnt, 0).reshape(-1), seg_ids, num_segments=t * d_pad
    ).reshape(t, d_pad)
