"""Scenario profiles: named churn/fault mixes the simulator runs.

A profile is a declarative recipe — per-cycle event rates for the
generators plus fault-injection knobs — from which a seeded run derives
everything else. Rates are expected counts or probabilities consumed in
a fixed order by ``generators.ChurnGenerator``, so a profile + seed is
a complete description of a run.

Soundness constraint (enforced in ``validate``): a profile that delays
watch delivery must NOT also shrink node allocatable or perform
external competing binds. Under delayed delivery the scheduler's view
legitimately lags the cluster, and binding against a view that predates
a capacity *reduction* can transiently overcommit — exactly the
staleness the reference scheduler also accepts (kubelet admission is
the real-world backstop). The capacity invariant would flag it as a
scheduler bug when it is not one, so those knobs are mutually
exclusive per profile. Capacity-*increasing* churn (node adds, label
flaps, allocatable grows, pod deletes) is always safe to delay.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Profile:
    name: str
    # -- cluster shape / scheduler config --
    pipelined: bool = True
    # drive Scheduler.run_streaming (the streaming dispatcher's
    # device-resident solve loop) instead of run_pipelined. Orthogonal
    # to ``pipelined`` (which picks pipelined-vs-sync when streaming is
    # off); the CLI's --dispatcher flag overrides either.
    streaming: bool = False
    nodes: int = 6
    node_cpu: str = "8"
    node_mem: str = "32Gi"
    batch_size: int = 16
    group_size: int = 8
    # -- pod arrival process (uniform count per cycle, inclusive) --
    arrivals: tuple[int, int] = (2, 6)
    pod_cpu_choices: tuple[str, ...] = ("500m", "1", "2")
    pod_priorities: tuple[int, ...] = (0,)
    # hard-shape mix: P(an arrival carries the shape), drawn in order
    # spread -> anti -> ports (first hit wins; remainder is a plain fit
    # pod). Non-zero rates drive the pipelined loop's occupancy-carrying
    # path (ports/spread/interpod batches no longer drain to the
    # synchronous cycle) plus the constraint invariants that guard it.
    pod_spread_rate: float = 0.0  # zone topology spread, hard maxSkew=1
    pod_anti_rate: float = 0.0  # required hostname anti-affinity
    pod_ports_rate: float = 0.0  # hostPort from a 2-port pool
    zones: int = 3  # node zone labels: z{seq % zones}
    # -- churn rates (events per cycle; fractional = probability) --
    delete_pod_rate: float = 0.0
    node_add_rate: float = 0.0
    node_delete_rate: float = 0.0
    label_flap_rate: float = 0.0
    alloc_grow_rate: float = 0.0
    alloc_shrink_rate: float = 0.0
    external_bind_rate: float = 0.0
    # -- fault injection --
    bind_fault_rate: float = 0.0  # P(injected ApiError per scheduler bind)
    watch_delay: bool = False  # hold watch events for later delivery
    watch_dup_rate: float = 0.0  # P(an event is delivered twice)
    extender: bool = False  # configure a (faultable) HTTP extender
    extender_fault_rate: float = 0.0  # P(timeout/5xx per extender call)
    permit: bool = False  # register the stalling Permit plugin
    permit_stall_rate: float = 0.0  # P(first attempt of a pod WAITs)
    permit_timeout: float = 5.0
    # -- solver-boundary faults (kubernetes_tpu/resilience) --
    # P(an injected device/runtime error per solve dispatch) — exempts
    # the pure-host ladder rung, so the fallback ladder always has a
    # working floor (a real accelerator outage can't break host python)
    solver_fault_rate: float = 0.0
    # restrict injected solver faults to a virtual-clock window
    # [start, end); () = always. A bounded window is what lets the
    # invariants assert the breaker RE-CLOSES after the fault clears.
    solver_fault_window: tuple = ()
    # P(an arrival is a poison pod): its presence in ANY batch breaks
    # the solve at EVERY tier (tensorize/solve-breaking data), driving
    # the bisection quarantine
    poison_rate: float = 0.0
    # breaker fault window for the harness's ResilienceConfig: short
    # enough that probes/re-closes happen within a sim run's virtual
    # timeline (production default is 30s)
    resilience_open_s: float = 3.0
    # -- fleet mode (sim/fleet.py multi-scheduler drive) --
    fleet_replicas: int = 0  # default replica count for --fleet runs
    # kill one replica at this cycle (replica_loss fault): its shard is
    # re-owned by the survivors and every orphaned pod must still reach
    # a terminal journal outcome fleet-wide. -1 = never.
    replica_loss_at: int = -1
    # -- process lifecycle (crash_restart / hub_partition) --
    # crash the scheduler at this cycle, mid-batch: the first batch of
    # that cycle dies AFTER its pods are assumed/approved and BEFORE
    # any bind commits (the _pre_commit_hook seam), and a FRESH
    # Scheduler incarnation is constructed on the same ClusterState —
    # the cold-start recovery pass re-adopts everything the crash
    # orphaned. -1 = never.
    crash_at: int = -1
    # fleet drive: partition the last replica from the occupancy hub
    # over virtual cycles [hub_partition_at, hub_partition_heal). Its
    # lease is observed stale at partition start (survivors mark it
    # dead and REVOKE its commit fence — it keeps driving as a zombie
    # whose binds must all reject with Conflict), and at heal it
    # re-acquires the fence + resyncs while the survivors re-admit it.
    hub_partition_at: int = -1
    hub_partition_heal: int = -1
    # occupancy-staleness bound the fleet drive passes to FleetConfig
    # (max_row_age_s): hub_partition shrinks it so peer-row aging
    # crosses the bound inside the window and conservative admission
    # actually engages.
    fleet_max_row_age_s: float = 30.0
    # -- hub HA (fleet/ha.py: replicated hub + epoch-fenced failover) --
    # kill the PRIMARY occupancy hub at this cycle: the fleet drive
    # runs a primary + standby hub pair (op-log replication, shared
    # HubLease), replicas reach them through RemoteOccupancyExchange's
    # endpoint-failover client, and the kill opens a blackout window —
    # conservative admission engages — until the standby's lease grant
    # promotes it at the next epoch. -1 = single hub (no HA).
    hub_failover_at: int = -1
    # resurrect the OLD primary's reachability at this cycle: it must
    # keep serving its debug/read surface while 100% of replica-facing
    # writes reject with the typed HubDeposed (the stale-primary fence
    # the invariant pins). -1 = never.
    hub_failover_heal: int = -1
    # hub lease duration (virtual seconds): the fencing window — the
    # standby can only promote after the dead primary's lease expires,
    # so this bounds the failover blackout from below.
    hub_lease_s: float = 2.0
    # -- continuous rebalancer (kubernetes_tpu/rebalance) --
    # enable the background defragmentation loop on the sim scheduler
    rebalance: bool = False
    rebalance_interval_s: float = 4.0  # virtual seconds between passes
    rebalance_budget: int = 4  # max-churn: evictions per pass
    # dominant-resource packed-utilization threshold (detector.py)
    rebalance_min_packing: float = 0.6
    # P(an arrival joins the PDB-guarded cohort): labeled pods matched
    # by a seeded PodDisruptionBudget with disruptionsAllowed=0, so the
    # rebalancer's PDB gate (and the eviction subresource's 429) are
    # exercised non-vacuously — the rebalance invariant asserts none
    # of them ever moved
    pdb_guard_rate: float = 0.0
    # -- backlog drain (Scheduler.drain_backlog, ISSUE 12) --
    # pods seeded at cycle 0 BEFORE any churn (same hard-shape mix as
    # arrivals, same event/trace machinery so replay works): cycle 0's
    # drive then drains them through drain_backlog — the HBM-budget-
    # planned, chunk-aligned streaming path — instead of a plain
    # run_streaming call. 0 = off.
    backlog: int = 0
    # starting chunk size for the drain's budget planner (0 = the
    # profile batch_size)
    backlog_chunk: int = 0
    # force the budget planner to auto-split: the harness computes the
    # base chunk's per-device estimate and hands the drain a budget one
    # byte BELOW it, so plan_chunk must halve at least once — the
    # budget_splits>=1 the CI smoke pins, robust to estimator formula
    # changes (an absolute byte figure here would not be)
    backlog_force_split: bool = False
    # -- fleet backlog drain (fleet/drain.py, ROADMAP #5a) --
    # drain the cycle-0 backlog through the hub's drain-lease ledger
    # instead of per-replica run_streaming: a full-view planner runs
    # the relax mega-plan once globally, the first replica installs
    # the partitioned ledger at the hub (drain_init), and every alive
    # replica claims/drains epoch-fenced leases per cycle
    # (Scheduler.fleet_drain_backlog). Combine with replica_loss_at to
    # kill a replica mid-lease — the reassignment path the
    # check_fleet_drain invariant pins. Requires backlog > 0,
    # fleet_replicas >= 2, and the streaming drive.
    fleet_drain: bool = False
    # -- convex-relaxation mega-planner (solver/relax.py, ISSUE 19) --
    # warm-start the cycle-0 backlog drain: one relaxed global solve
    # over the whole active queue ranks the backlog before the first
    # chunk pops (Scheduler.drain_backlog(warm_start=True)), and the
    # harness runs its deterministic megaplan probe — relax+repair vs
    # the exact anchor on the same frozen snapshot — whose plan
    # validity and objective ratio ride the footer for check_megaplan.
    backlog_warm_start: bool = False
    # -- closed-loop auto-tuning (kubernetes_tpu/tuning) --
    # enable the tuning runtime on the sim scheduler (hill-climb
    # controllers over stream_depth / pipeline_split / drain chunk,
    # sim-sized evaluation windows — harness builds the TuningConfig)
    tuning: bool = False
    # mid-drive workload shift: from this cycle on, arrivals draw from
    # shift_arrivals instead of arrivals (the tuner must detect the
    # regime change, unsettle, and re-converge — the tuning invariant
    # asserts both). -1 = no shift. Events stay self-contained dicts,
    # so replay is unaffected.
    shift_at: int = -1
    shift_arrivals: tuple = ()
    # -- gang scheduling (kubernetes_tpu/gang, ISSUE 17) --
    # P(a cycle spawns a pod group): all members arrive the same cycle
    # carrying the pod-group label + min-member annotation, and the
    # scheduler must bind the whole gang atomically or none of it
    # (check_no_partial_gangs asserts exactly that, every cycle).
    # 0 = no gangs (all gang knobs are inert — existing profiles'
    # event streams stay byte-identical).
    gang_rate: float = 0.0
    gang_sizes: tuple[int, ...] = (2, 3)
    # spawn one NEVER-SATISFIABLE gang at this cycle: min-member is set
    # one above the members actually created, so the quorum can never
    # assemble — the gang must ride gang_incomplete rounds into a
    # whole-gang quarantine (the CI smoke pins quarantined_gangs >= 1
    # off this). -1 = never.
    gang_short_at: int = -1
    # GangConfig knobs for the sim scheduler (harness._base_config):
    # sim-sized so assembly timeouts and the quarantine ladder resolve
    # within a run's virtual timeline (production defaults are longer)
    gang_min_member_timeout: float = 3.0
    gang_quarantine_after: int = 3
    # heterogeneity-aware placement: nodes get an accelerator-class
    # label (gang_accel_classes[seq % len], seq-based like zones so
    # node identity stays RNG-free), gang members a workload-class
    # label, and the harness derives a deterministic effective-
    # throughput table over the cross product (Gavel's objective,
    # folded into the solve as a score term). () / 0 = term off.
    gang_accel_classes: tuple[str, ...] = ()
    gang_workload_classes: tuple[str, ...] = ()
    gang_throughput_weight: int = 0
    # -- flight telemetry (kubernetes_tpu/obs, ISSUE 18) --
    # enable the always-on telemetry stack on the sim scheduler:
    # continuous per-stage profiler + anomaly sentinel (sim-sized
    # windows, harness._base_config builds the SentinelConfig) +
    # capture-on-anomaly replay bundles (written when the run passes a
    # bundle_dir; capture EVENTS count either way, so --selfcheck's
    # dirless re-run stays byte-identical). The SLO engine rides along
    # as the sentinel's p99 source.
    telemetry: bool = False

    def validate(self) -> None:
        if self.watch_delay and (
            self.alloc_shrink_rate > 0 or self.external_bind_rate > 0
        ):
            raise ValueError(
                f"profile {self.name}: watch_delay cannot be combined with "
                "alloc_shrink_rate/external_bind_rate (delayed delivery of "
                "capacity reductions makes transient overcommit legitimate, "
                "so the capacity invariant would be unsound — see module "
                "docstring)"
            )
        if self.fleet_drain and (
            not self.backlog
            or self.fleet_replicas < 2
            or not self.streaming
        ):
            raise ValueError(
                f"profile {self.name}: fleet_drain needs a cycle-0 "
                "backlog, fleet_replicas >= 2, and the streaming drive "
                "(the drain leases feed Scheduler.drain_backlog's "
                "chunked streaming path)"
            )
        if (self.gang_rate > 0 or self.gang_short_at >= 0) and any(
            self.pod_priorities
        ):
            raise ValueError(
                f"profile {self.name}: gang arrivals cannot be combined "
                "with non-zero pod priorities (preemption can evict a "
                "bound gang member, and the gang gate cannot count "
                "already-bound members toward a re-assembly quorum — a "
                "documented design limit, see kubernetes_tpu/gang)"
            )


PROFILES: dict[str, Profile] = {
    p.name: p
    for p in (
        # the flagship: everything that can churn does, delivery is
        # delayed and duplicated (at-least-once), binds fail randomly —
        # the scenario class every advisor-found concurrency bug
        # (fence livelock, stale sessions, unlocked in-flight maps)
        # lived in. No shrinks/external binds (see module docstring).
        Profile(
            name="churn_heavy",
            arrivals=(2, 6),
            # hard-shape arrivals (spread/anti/ports) drive the
            # occupancy-carrying pipelined path and its occ fence under
            # the same delete/label churn the fit fence already faces
            pod_spread_rate=0.25,
            pod_anti_rate=0.15,
            pod_ports_rate=0.2,
            delete_pod_rate=0.8,
            node_add_rate=0.3,
            node_delete_rate=0.25,
            label_flap_rate=2.5,
            alloc_grow_rate=0.4,
            bind_fault_rate=0.15,
            watch_delay=True,
            watch_dup_rate=0.2,
        ),
        # competing actors: an external binder races the scheduler for
        # the same pods/capacity while injected bind conflicts exercise
        # the forget/requeue protocol. Prompt delivery.
        Profile(
            name="bind_storms",
            arrivals=(3, 8),
            external_bind_rate=1.5,
            bind_fault_rate=0.35,
            alloc_shrink_rate=0.2,
            delete_pod_rate=0.3,
        ),
        # topology churn: nodes come, go, shrink, grow, flap labels;
        # snapshot slot remaps and SessionDrainRequired paths dominate.
        Profile(
            name="node_flaps",
            arrivals=(1, 4),
            node_add_rate=1.0,
            node_delete_rate=0.8,
            label_flap_rate=1.5,
            alloc_grow_rate=0.5,
            alloc_shrink_rate=0.5,
        ),
        # priority inversion pressure: low-priority filler keeps nodes
        # full, high-priority arrivals must preempt their way in.
        Profile(
            name="preemption_pressure",
            nodes=4,
            arrivals=(3, 6),
            pod_cpu_choices=("2", "4"),
            pod_priorities=(0, 0, 0, 1000),
            delete_pod_rate=0.2,
        ),
        # the extender boundary under latency/timeout/5xx: ignorable=False
        # so a failed call aborts the batch (the reference's error status),
        # exercising the mid-cycle-outage requeue path every few cycles.
        Profile(
            name="extender_flaky",
            # extenders pipeline now (the verdict fold is a pre-dispatch
            # host stage), but this profile stays on the sync drive: a
            # non-ignorable extender abort mid-run_pipelined loses the
            # completed batches' results, which would silently weaken
            # the double-bind tracker's accounting (harness._drive)
            pipelined=False,
            arrivals=(2, 5),
            extender=True,
            extender_fault_rate=0.3,
            bind_fault_rate=0.1,
        ),
        # Permit-point stalls: pods park in the WaitingPods map and are
        # later allowed or timed out on the virtual clock — driven
        # through the pipelined loop (waiting settlement drains the
        # pipeline and runs a synchronous cycle per tick).
        Profile(
            name="permit_stalls",
            arrivals=(2, 5),
            permit=True,
            permit_stall_rate=0.5,
            permit_timeout=5.0,
            delete_pod_rate=0.2,
        ),
        # solver-boundary chaos: every device-tier solve dispatch fails
        # during the fault window (a dead accelerator runtime), then
        # heals. The scheduler must trip the breaker, keep binding at a
        # degraded ladder tier (ultimately the pure-host greedy), and
        # probe back to the top tier once the window passes — asserted
        # by the resilience invariant (breaker re-closed, zero pods
        # lost). Window [2, 5): cycles advance the clock 1s each, so
        # cycles at t=2..4 fault and the later cycles' arrivals drive
        # the re-close probes with real work.
        Profile(
            name="solver_flaky",
            arrivals=(2, 6),
            delete_pod_rate=0.3,
            solver_fault_rate=1.0,
            solver_fault_window=(2.0, 5.0),
        ),
        # poison pods: a fraction of arrivals carry data that breaks
        # tensorize/solve at EVERY ladder tier. The bisection must
        # isolate exactly the poison pods into terminal quarantine
        # (TTL'd re-admit) while the rest of each batch proceeds —
        # including hard shapes riding the CARRY-mode chain. The
        # breaker trips en route (descend-before-bisect) and re-closes
        # once the poison is out of the batch stream.
        Profile(
            name="poison_pods",
            arrivals=(2, 6),
            pod_spread_rate=0.2,
            pod_ports_rate=0.15,
            poison_rate=0.12,
            delete_pod_rate=0.2,
        ),
        # fleet mode: two active replicas sharding one cluster through
        # the watch bus, with a hard-shape mix that exercises the
        # cross-shard occupancy exchange (spread skew is global) and
        # the handoff protocol. Node churn stays off so the ownership
        # half of the no-global-overcommit invariant is exact; pod
        # deletes churn occupancy rows. Also drivable single-scheduler
        # (the fleet≡single binding-equivalence test leans on the
        # event stream being identical either way: no external binds,
        # no shrinks).
        Profile(
            name="fleet_mixed",
            nodes=9,
            zones=3,
            arrivals=(2, 5),
            pod_spread_rate=0.3,
            pod_anti_rate=0.15,
            pod_ports_rate=0.15,
            delete_pod_rate=0.4,
            fleet_replicas=2,
        ),
        # fleet_handoff: the handoff-FORCING fleet shape (the obs
        # cross-replica explain smoke leans on it). Two replicas shard
        # two zones; a heavy hard-zone-spread cohort means pods routed
        # to the replica whose zone is already at the global max skew
        # get reconcile-rejected twice and release through the
        # exchange's handoff rows to the peer — whose journal then
        # continues the pod's journey trace. No delete churn: a
        # handed-off pod's history must survive to the end of the run
        # so `obs explain --fleet` can render the full
        # enqueue→handoff→re-admit→bind chain.
        Profile(
            name="fleet_handoff",
            nodes=6,
            zones=2,
            arrivals=(3, 6),
            pod_spread_rate=0.6,
            pod_anti_rate=0.2,
            delete_pod_rate=0.0,
            fleet_replicas=2,
        ),
        # crash_restart: the scheduler process dies mid-batch — after
        # its pods are assumed and approved, before any bind commits —
        # and a FRESH incarnation is constructed on the same
        # ClusterState. Every piece of incarnation-local state (assumed
        # pods, Permit-parked waiters, the nominated index, in-flight
        # maps) evaporates; the recovery pass must rebuild from truth,
        # re-adopt every orphan (terminal `recovered` journal records),
        # and the merged cross-incarnation journal must stay complete
        # with zero double-binds. Permit stalls guarantee parked
        # waiters exist at the crash; priority arrivals drive orphaned
        # nominations; delete churn exercises orphans vanishing before
        # re-adoption.
        Profile(
            name="crash_restart",
            nodes=5,
            arrivals=(2, 6),
            pod_spread_rate=0.2,
            pod_ports_rate=0.15,
            pod_cpu_choices=("1", "2"),
            pod_priorities=(0, 0, 0, 1000),
            delete_pod_rate=0.3,
            permit=True,
            permit_stall_rate=0.4,
            permit_timeout=5.0,
            crash_at=4,
        ),
        # hub_partition: fleet_mixed plus the last replica partitioned
        # from the occupancy hub, its lease observed stale (survivors
        # revoke its commit fence AND retire its exchange state — 100%
        # of the zombie's bind attempts must reject with Conflict),
        # while the ZOMBIE's own cached peer view ages past the
        # staleness bound so its admission turns conservative for
        # cross-shard-constrained shapes (the survivors handle the
        # detected-dead peer via membership + retire, not staleness —
        # the silent-peer aging path is unit-tested in
        # tests/test_fencing.py). Heals mid-run: the zombie
        # re-acquires its fence, resyncs, republishes — the fleet must
        # settle clean.
        Profile(
            name="hub_partition",
            nodes=9,
            zones=3,
            arrivals=(3, 6),
            # enough PLAIN arrivals that the zombie's fenced bind path
            # actually fires during the window (spread/anti arrivals
            # are stale-rejected by conservative admission BEFORE the
            # bind — both paths must engage, and the invariant asserts
            # each did)
            pod_spread_rate=0.2,
            pod_anti_rate=0.1,
            pod_ports_rate=0.1,
            delete_pod_rate=0.3,
            fleet_replicas=2,
            hub_partition_at=2,
            hub_partition_heal=6,
            fleet_max_row_age_s=2.0,
        ),
        # hub_failover: the hub HA chaos profile — a 2-replica fleet
        # drives against a REPLICATED hub (primary + standby, op-log
        # replication, shared lease) through the endpoint-failover
        # client, and the primary is KILLED mid-drive. The blackout
        # window (kill → standby's lease grant) must degrade to the
        # proven conservative-admission path (stale rejections >= 1,
        # zero overcommit), the promotion must heal everything without
        # operator action (replicas re-attach via epoch-advance
        # detection + forced wholesale republish; zero rows / handoffs
        # / journal lines lost; hard-spread contention spanning the
        # epoch boundary still decides exactly one CAS winner — the
        # constraint/overcommit invariants run every cycle), a
        # deterministic reply-loss injection must prove the idempotent
        # flush dedup (dedup_hits >= 1), and the resurrected OLD
        # primary must keep serving reads while 100% of its
        # replica-facing writes reject with the typed HubDeposed.
        # Asserted by the hub_failover invariant; byte-deterministic
        # under --selfcheck like every profile.
        Profile(
            name="hub_failover",
            nodes=9,
            zones=3,
            arrivals=(3, 6),
            pod_spread_rate=0.3,
            pod_anti_rate=0.1,
            pod_ports_rate=0.1,
            delete_pod_rate=0.3,
            fleet_replicas=2,
            hub_failover_at=3,
            hub_failover_heal=8,
            # a 3s lease makes the blackout span >= 3 driven cycles:
            # enough that some cross-shard-constrained admission
            # attempt lands inside it at any seed (the invariant's
            # conservative-admission clause must engage non-vacuously)
            hub_lease_s=3.0,
            fleet_max_row_age_s=2.0,
        ),
        # fragmentation: heavy plain arrivals + heavy deletes carve the
        # cluster into Swiss cheese (every node partly used, packed
        # utilization low), and the continuous rebalancer must
        # consolidate: detect fragmentation from the snapshot, plan
        # with the pack-objective auction, evict under the churn
        # budget with nominated hints, and the migrations complete
        # through the ordinary scheduling path. A PDB-guarded cohort
        # (disruptionsAllowed=0) rides along — those pods must NEVER
        # move. The rebalance invariant asserts: evictions <= budget
        # every pass, zero PDB overruns, packed utilization
        # non-decreasing across settle-phase passes, and >= 1
        # completed migration when anything was evicted. Byte-
        # deterministic under --selfcheck like every profile.
        Profile(
            name="fragmentation",
            nodes=8,
            node_cpu="8",
            node_mem="32Gi",
            arrivals=(3, 7),
            pod_cpu_choices=("500m", "1"),
            delete_pod_rate=2.5,
            rebalance=True,
            rebalance_interval_s=4.0,
            rebalance_budget=4,
            rebalance_min_packing=0.6,
            pdb_guard_rate=0.25,
        ),
        # sustained_stream: the streaming dispatcher's high-arrival
        # profile — enough arrivals per cycle that several batches pop
        # back-to-back and the bounded work ring actually fills, with a
        # hard-shape mix (spread/anti/ports) so cross-batch occupancy
        # chaining and the drain-then-retensorize fallback both
        # engage, plus delete churn and delayed/duplicated watch
        # delivery so per-slot fence epochs discard stream slots
        # mid-ring. Byte-deterministic under --selfcheck like every
        # profile (the completion thread only warms transfers — apply
        # order stays driver-side).
        Profile(
            name="sustained_stream",
            streaming=True,
            nodes=8,
            arrivals=(6, 12),
            batch_size=6,
            pod_spread_rate=0.2,
            pod_anti_rate=0.1,
            pod_ports_rate=0.15,
            delete_pod_rate=0.4,
            bind_fault_rate=0.1,
            watch_delay=True,
            watch_dup_rate=0.1,
        ),
        # backlog_drain: a seeded mega-backlog (relative to the sim's
        # scale) with a hard-shape mix, drained at cycle 0 through
        # Scheduler.drain_backlog — the HBM-budget-planned chunked
        # streaming path (ISSUE 12) — then delete churn and fresh
        # arrivals over the drained cluster. backlog_force_split makes
        # the budget planner halve the chunk at least once, so the CI
        # smoke pins the auto-split path non-vacuously; the drain's
        # chunk/split/chain counters ride the footer (byte-
        # deterministic under --selfcheck like every profile). The
        # backstop must never engage during the drain (fallbacks=0
        # pinned by the smoke).
        Profile(
            name="backlog_drain",
            streaming=True,
            nodes=10,
            zones=3,
            batch_size=16,
            group_size=8,
            backlog=96,
            backlog_chunk=16,
            backlog_force_split=True,
            arrivals=(1, 3),
            pod_spread_rate=0.25,
            pod_ports_rate=0.2,
            delete_pod_rate=0.6,
        ),
        # fleet_backlog_drain: the fleet-tier drain acceptance profile
        # (fleet/drain.py, ROADMAP #5a). A seeded backlog lands at
        # cycle 0 across a 3-replica fleet; the coordinator seam runs
        # the relax mega-plan ONCE globally on a full-view planner,
        # partitions pods by planned-node shard owner (spread pods —
        # cross-shard-constrained — fall to the serialized residual
        # cohort), and installs the lease ledger at the hub. Replicas
        # drain concurrently, one chunk per cycle, through their own
        # drain_backlog slot rings; the LAST replica is killed at
        # cycle 1 — mid-lease — so its outstanding keys must return as
        # orphans and drain at a survivor (check_fleet_drain pins
        # reassigned >= 1, zero lost, zero double-binds; the CI smoke
        # greps the fleet_drain footer line). Capacity is sized so the
        # whole backlog binds (node_cpu=16 x 12 vs ~90 requested CPU);
        # no delete churn, so "every backlog pod ends bound" is exact.
        # Byte-deterministic under --selfcheck like every profile.
        Profile(
            name="fleet_backlog_drain",
            streaming=True,
            nodes=12,
            node_cpu="16",
            # one zone ON PURPOSE: hard-spread pods still carry a
            # DoNotSchedule constraint (cross-shard -> residual cohort)
            # but stay satisfiable from ANY shard. With 3 zones the
            # ring can hand a replica zero nodes in the underfilled
            # zone; after one handoff lap such pods legally park
            # unschedulable, and the drain gate here is lost==0.
            zones=1,
            batch_size=16,
            group_size=8,
            backlog=120,
            backlog_chunk=16,
            fleet_drain=True,
            fleet_replicas=3,
            replica_loss_at=1,
            arrivals=(1, 3),
            pod_cpu_choices=("500m", "1"),
            pod_spread_rate=0.2,
        ),
        # megaplan: the convex-relaxation mega-planner acceptance
        # profile (ISSUE 19). Same seeded-backlog drive as
        # backlog_drain, but the drain warm-starts: one relaxed global
        # solve ranks the whole active queue before the first chunk
        # pops, and the harness's megaplan probe runs relax+repair vs
        # the exact anchor on the frozen cycle-0 snapshot.
        # check_megaplan asserts the relaxation actually engaged
        # (iterations + ranked pods non-zero), the relaxed-then-
        # rounded plan is valid against the snapshot (no overcommit,
        # every placement schedulable), and the probe's objective
        # ratio clears the floor vs exact. Plain fit-scoped pods only
        # (no spread/ports) so the probe compares the two engines on
        # the scope both solve; mixed priorities exercise the
        # warm-start's within-priority-band reorder contract.
        # Byte-deterministic under --selfcheck like every profile.
        Profile(
            name="megaplan",
            streaming=True,
            nodes=12,
            zones=3,
            batch_size=16,
            group_size=8,
            backlog=120,
            backlog_chunk=16,
            backlog_warm_start=True,
            arrivals=(1, 3),
            pod_priorities=(0, 3, 7),
            delete_pod_rate=0.4,
        ),
        # tuning_convergence: the auto-tuning acceptance profile — a
        # sustained streaming drive long enough for the hill-climb
        # controllers (stream_depth / pipeline_split, sim-sized
        # evaluation windows) to probe both directions and settle, then
        # a MID-DRIVE WORKLOAD SHIFT (arrivals roughly double at
        # shift_at) the tuner must detect via the CounterWindow
        # signature, unsettle on, and re-settle from. The tuning
        # invariant asserts: controllers engaged (>= 1 probe), settled
        # at quiescence, zero guardrail breaches, bounded knob moves
        # (no thrash), and the shift actually detected. Byte-
        # deterministic under --selfcheck like every profile (the
        # controllers are pure host python over the virtual clock).
        Profile(
            name="tuning_convergence",
            streaming=True,
            tuning=True,
            # capacity headroom matters: the shift detector's signature
            # is the BIND rate, which only tracks the arrival rate while
            # the cluster absorbs the load — a saturating cluster's
            # decaying bind rate would read as an endless workload
            # drift and shift-storm the controllers. Sized to absorb
            # the post-shift rate through a 30-cycle soak.
            nodes=16,
            node_cpu="32",
            node_mem="128Gi",
            batch_size=16,
            arrivals=(4, 8),
            pod_spread_rate=0.15,
            pod_ports_rate=0.1,
            delete_pod_rate=0.4,
            # late enough that the controllers have settled AND the
            # baseline signature has frozen (one full window past the
            # settle point) before the regime changes
            shift_at=12,
            shift_arrivals=(12, 18),
        ),
        # replica_loss: fleet_mixed plus one replica killed mid-drive.
        # The survivors must re-own its shard (ring orphan
        # redistribution + resync) and every pod it owned — queued,
        # in-flight, or handed off — must still reach a terminal
        # journal outcome somewhere in the fleet.
        # gang: the DL-training workload profile (kubernetes_tpu/gang,
        # ISSUE 17). Most arrivals are pod groups — all members land
        # the same cycle with the pod-group label + min-member
        # annotation — and the scheduler must solve each gang as one
        # chained sub-batch and bind it atomically (all members or
        # none; check_no_partial_gangs runs every cycle). Nodes carry
        # accelerator-class labels and gangs workload classes, so the
        # heterogeneity throughput term (Gavel's objective) scores
        # non-vacuously. One never-satisfiable gang (gang_short_at)
        # must ride gang_incomplete rounds into a whole-gang
        # quarantine — the CI smoke pins partial_gangs == 0 AND
        # quarantined_gangs >= 1. Delete churn hits bound and queued
        # members alike (a queued member's deletion strands its gang
        # short → quarantine is its only exit). Priority-0 only: see
        # validate(). Two replicas make the same profile drivable
        # --fleet, where gang members route by gang id so each gang
        # assembles whole on one replica and stages through the
        # fenced CAS member-by-member.
        Profile(
            name="gang",
            nodes=8,
            zones=2,
            arrivals=(1, 3),
            gang_rate=0.7,
            gang_sizes=(2, 3),
            gang_short_at=2,
            gang_min_member_timeout=2.0,
            gang_quarantine_after=1,
            gang_accel_classes=("tpu-v5e", "tpu-v4", "gpu-a100"),
            gang_workload_classes=("transformer", "resnet"),
            gang_throughput_weight=2,
            # pod-delete churn only: it wakes parked gang members each
            # cycle (assembly-timeout rounds need re-pops) AND keeps
            # node ownership static so the profile stays fleet-drivable
            # (the no-global-overcommit invariant's ownership half is
            # exact without node churn, like fleet_mixed)
            delete_pod_rate=0.4,
            fleet_replicas=2,
        ),
        # gang_crash: the gang profile with the scheduler killed
        # mid-batch at the commit point (pods assumed + approved,
        # nothing bound). The crash seam fires BEFORE any gang bind,
        # so no gang can be half-bound by the dying incarnation, and
        # the successor's recovery pass must roll back any half-staged
        # gang rounds (_rollback_partial_gangs) before re-adopting —
        # partial_gangs must stay 0 across the incarnation boundary.
        Profile(
            name="gang_crash",
            nodes=8,
            zones=2,
            arrivals=(1, 3),
            gang_rate=0.7,
            gang_sizes=(2, 3),
            gang_short_at=2,
            gang_min_member_timeout=2.0,
            gang_quarantine_after=1,
            gang_accel_classes=("tpu-v5e", "tpu-v4", "gpu-a100"),
            gang_workload_classes=("transformer", "resnet"),
            gang_throughput_weight=2,
            delete_pod_rate=0.4,
            node_add_rate=0.2,
            crash_at=4,
        ),
        # gang_replica_loss: the gang profile driven --fleet with one
        # replica killed mid-drive. Gangs route whole (by gang id) so
        # the dead replica takes entire gangs with it — the survivor
        # re-owns them via the ring and must still land each one
        # atomically or quarantine it; no partial gang may survive
        # the failover fleet-wide.
        Profile(
            name="gang_replica_loss",
            nodes=8,
            zones=2,
            arrivals=(1, 3),
            gang_rate=0.7,
            gang_sizes=(2, 3),
            gang_short_at=2,
            gang_min_member_timeout=2.0,
            gang_quarantine_after=1,
            gang_accel_classes=("tpu-v5e", "tpu-v4", "gpu-a100"),
            gang_workload_classes=("transformer", "resnet"),
            gang_throughput_weight=2,
            delete_pod_rate=0.4,
            fleet_replicas=2,
            replica_loss_at=4,
        ),
        Profile(
            name="replica_loss",
            nodes=9,
            zones=3,
            arrivals=(2, 5),
            pod_spread_rate=0.3,
            pod_anti_rate=0.15,
            pod_ports_rate=0.15,
            delete_pod_rate=0.4,
            fleet_replicas=2,
            replica_loss_at=4,
        ),
        # anomaly_storm: the flight-telemetry acceptance profile
        # (ISSUE 18). A healthy steady-state warmup, then the
        # solver_flaky fault window [2, 5) kills every device-tier
        # solve: the breaker trips (its edge anomaly fires at the next
        # applied batch) and throughput collapses against the warmup
        # baseline (the spike rule). The sentinel must fire >= 1
        # anomaly, each firing must journal a telemetry_anomaly record
        # and capture a replay bundle, and every WRITTEN bundle must
        # re-execute offline to bit-identical assignments — the
        # telemetry invariant asserts the whole loop. Sync drive
        # (pipelined=False): sync solves dispatch unsplit with
        # allow_heal=True, so every capture is carry-clean and the
        # replay contract holds by construction. Cycles 0-1 are
        # fault-free, guaranteeing a complete capture record exists
        # before the storm. Byte-deterministic under --selfcheck like
        # every profile (capture events count without a bundle dir).
        Profile(
            name="anomaly_storm",
            pipelined=False,
            telemetry=True,
            nodes=8,
            arrivals=(4, 8),
            delete_pod_rate=0.4,
            solver_fault_rate=1.0,
            solver_fault_window=(2.0, 5.0),
        ),
    )
}

for _p in PROFILES.values():
    _p.validate()
del _p


def get_profile(name: str) -> Profile:
    try:
        return PROFILES[name]
    except KeyError:
        raise ValueError(
            f"unknown profile {name!r}; available: {', '.join(sorted(PROFILES))}"
        ) from None
