"""Device-session dirty-column heal on the bind-failure branch (VERDICT r2
weak #9): when a bind fails AFTER the device-resident solve already
applied the placement, the forget path must heal the device column from
cache truth — otherwise the session double-counts the phantom placement
and later pods see less capacity than exists."""

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ApiError, ClusterState
from kubernetes_tpu.utils.clock import FakeClock


def test_bind_fault_heals_device_session():
    clock = FakeClock()
    cs = ClusterState()
    # one node, capacity for exactly two 1-cpu pods
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "8Gi", "pods": "10"}).obj()
    )
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )

    # first pod binds normally (device session now live)
    cs.create_pod(MakePod().name("a").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/a") == "n"

    # second pod: the solve places it, then the bind FAULTS — forget must
    # roll the cache back and the heal path must roll the device back
    faults = {"n": 1}

    def bind_fault(pod, node_name):
        if faults.get(node_name, 0) > 0:
            faults[node_name] -= 1
            raise ApiError("Conflict", "injected")

    cs.bind_fault = bind_fault
    cs.create_pod(MakePod().name("b").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert r.bind_failures and r.bind_failures[0][0] == "default/b"

    # a bind-failed pod parks in the unschedulable map until an event or
    # the 5-minute leftover flush (upstream AddUnschedulableIfNotPresent
    # semantics) — use the flush. The device session must then see 1 free
    # cpu; if the phantom placement leaked, b would stay unschedulable.
    clock.advance(301.0)
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/b") == "n", (
        "device session failed to heal the faulted bind's column"
    )

    # and the node must now be genuinely full: a third pod cannot fit
    cs.create_pod(MakePod().name("c").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert "default/c" in r.unschedulable
