"""Scalar oracle for preemption (defaultpreemption PostFilter).

Transcription of pkg/scheduler/framework/preemption/preemption.go#Evaluator
+ plugins/defaultpreemption/default_preemption.go (SURVEY.md §3.1, §8.5):

- SelectVictimsOnNode: clone node state, remove ALL pods with priority <
  incoming; if the pod still doesn't fit -> node is not a candidate. Then
  try to reprieve victims: PDB-violating candidates first, then
  non-violating, each bucket in MoreImportantPod order (priority desc,
  earlier start first); a reprieved pod is re-added if the incoming pod
  still fits alongside it. Whatever cannot be reprieved is the victim set.
- filterPodsWithPDBViolation: a candidate violates if any matching PDB has
  no disruptions left (counters decrement as non-violating candidates are
  classified).
- pickOneNodeForPreemption lexicographic: fewest PDB violations -> lowest
  highest-victim-priority -> smallest priority sum -> fewest victims ->
  latest start among highest-priority victims -> first node in list order.

Two dry-run depths:
- select_victims_on_node: fit-only (NodeResourcesFit + pod count) — the
  cheap pre-screen matching the device kernel in solver/preemption.py.
- select_victims_on_node_full: the reference semantics — every candidacy
  and reprieve decision re-runs the FULL Filter pipeline
  (RunFilterPluginsWithNominatedPods per re-add), so pods blocked by
  NodePorts/PodTopologySpread/InterPodAffinity can preempt, and victims
  are never evicted for a pod that still could not schedule. Remaining
  divergence: the CSI volume-limit filter evaluates against the live
  volume context (victim evictions do not free attachment slots in the
  hypothesis), matching the [BOUNDARY] depth of volumebinding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

from ...api.objects import Node, Pod, PodDisruptionBudget

__all__ = [
    "PodDisruptionBudget",
    "more_important",
    "sort_more_important",
    "classify_pdb_violations",
    "NodeVictims",
    "select_victims_on_node",
    "select_victims_on_node_full",
    "pick_one_node",
]

PREEMPT_NEVER = "Never"


def more_important(p1: Pod, p2: Pod) -> bool:
    """util.MoreImportantPod: higher priority first; tie -> earlier start
    (longer-running) first."""
    if p1.effective_priority != p2.effective_priority:
        return p1.effective_priority > p2.effective_priority
    return p1.start_time < p2.start_time


def sort_more_important(pods: Sequence[Pod]) -> list[Pod]:
    return sorted(
        pods, key=lambda p: (-p.effective_priority, p.start_time, p.key)
    )


def classify_pdb_violations(
    candidates: Sequence[Pod], pdbs: Sequence[PodDisruptionBudget]
) -> tuple[list[Pod], list[Pod]]:
    """filterPodsWithPDBViolation: (violating, non_violating); counters
    decrement as non-violating candidates claim allowance."""
    allowed = [p.disruptions_allowed for p in pdbs]
    violating: list[Pod] = []
    non_violating: list[Pod] = []
    for pod in candidates:
        matching = [i for i, pdb in enumerate(pdbs) if pdb.matches(pod)]
        if any(allowed[i] <= 0 for i in matching):
            violating.append(pod)
        else:
            for i in matching:
                allowed[i] -= 1
            non_violating.append(pod)
    return violating, non_violating


@dataclass
class NodeVictims:
    victims: list[Pod]
    num_violating: int


def select_victims_on_node(
    pod: Pod,
    node_alloc: Mapping[str, int],
    max_pods: int,
    pods_on_node: Sequence[Pod],
    pdbs: Sequence[PodDisruptionBudget] = (),
) -> NodeVictims | None:
    """Fit-only dry run. Returns None if even evicting every lower-priority
    pod cannot make room."""
    prio = pod.effective_priority
    keep = [q for q in pods_on_node if q.effective_priority >= prio]
    potential = [q for q in pods_on_node if q.effective_priority < prio]

    def fits(current: Sequence[Pod]) -> bool:
        used: dict[str, int] = {}
        for q in current:
            for k, v in q.resource_request().items():
                used[k] = used.get(k, 0) + v
        for k, v in pod.resource_request().items():
            if v and used.get(k, 0) + v > node_alloc.get(k, 0):
                return False
        return len(current) + 1 <= max_pods

    if not fits(keep):
        return None

    violating, non_violating = classify_pdb_violations(
        sort_more_important(potential), pdbs
    )
    current = list(keep)
    victims: list[Pod] = []
    num_violating = 0
    for bucket, counts in ((violating, True), (non_violating, False)):
        for q in sort_more_important(bucket):
            if fits(current + [q]):
                current.append(q)  # reprieved
            else:
                victims.append(q)
                if counts:
                    num_violating += 1
    return NodeVictims(victims=victims, num_violating=num_violating)


def select_victims_on_node_full(
    pod: Pod,
    cand_idx: int,
    oracle,  # FullOracle over the current cluster truth
    pdbs: Sequence[PodDisruptionBudget] = (),
) -> NodeVictims | None:
    """preemption.go#SelectVictimsOnNode with the full Filter pipeline.

    Clone the candidate's state minus ALL lower-priority pods; if the
    incoming pod still fails any Filter plugin there, the node is not a
    candidate. Then reprieve victims (PDB-violating bucket first, then
    non-violating, MoreImportantPod order) — each re-add keeps the pod only
    if the full filters still pass, exactly the reference's per-re-add
    RunFilterPluginsWithNominatedPods.

    The spread/interpod PreFilter states are pod-level precomputations over
    the WHOLE cluster; they are rebuilt only for re-adds that can actually
    perturb them (the re-added pod matches a spread selector, owns required
    anti-affinity that selects the incoming pod, or matches one of the
    incoming pod's terms) — everything else reuses the current states.
    """
    from .interpod import (
        _required_aff_terms,
        _required_anti_terms,
        build_interpod_state,
        term_matches_pod,
    )
    from .noderesources import NodeState
    from .profile import OracleNode
    from .spread import build_filter_state, effective_constraints

    on = oracle.nodes[cand_idx]
    prio = pod.effective_priority
    keep = [q for q in on.pods if q.effective_priority >= prio]
    lower = [q for q in on.pods if q.effective_priority < prio]

    def build_states(current: list[Pod]):
        all_nodes = [
            (m.node, current if j == cand_idx else m.pods)
            for j, m in enumerate(oracle.nodes)
        ]
        return (
            build_filter_state(pod, all_nodes),
            build_interpod_state(pod, all_nodes),
        )

    def test(current: list[Pod], states) -> bool:
        node_test = OracleNode(
            node=on.node,
            res=NodeState(
                name=on.node.name,
                allocatable=dict(on.node.allocatable),
                max_pods=on.node.allowed_pod_number,
                schedulable=not on.node.unschedulable,
            ),
        )
        for q in current:
            node_test.add_pod(q)
        sp_state, ip_state = states
        return oracle.filter_one(pod, node_test, sp_state, ip_state)

    spread_cs = effective_constraints(pod, hard=True)
    anti_t = _required_anti_terms(pod)
    aff_t = _required_aff_terms(pod)

    def affects_states(q: Pod) -> bool:
        if spread_cs and q.namespace == pod.namespace and any(
            c.selector is not None and c.selector.matches(q.labels)
            for c in spread_cs
        ):
            return True
        if any(
            term_matches_pod(t, q, pod) for t in _required_anti_terms(q)
        ):
            return True
        return any(term_matches_pod(t, pod, q) for t in anti_t + aff_t)

    states = build_states(keep)
    if not test(keep, states):
        return None

    violating, non_violating = classify_pdb_violations(
        sort_more_important(lower), pdbs
    )
    current = list(keep)
    victims: list[Pod] = []
    num_violating = 0
    for bucket, counts in ((violating, True), (non_violating, False)):
        for q in sort_more_important(bucket):
            trial = current + [q]
            trial_states = build_states(trial) if affects_states(q) else states
            if test(trial, trial_states):
                current = trial
                states = trial_states
            else:
                victims.append(q)
                if counts:
                    num_violating += 1
    return NodeVictims(victims=victims, num_violating=num_violating)


def pick_one_node(
    candidates: Mapping[str, NodeVictims], node_order: Sequence[str]
) -> str | None:
    """pickOneNodeForPreemption lexicographic ordering."""
    if not candidates:
        return None

    def key(name: str):
        nv = candidates[name]
        if not nv.victims:
            # a no-victim candidate wins immediately upstream
            return (0, -(1 << 62), 0, 0, float("-inf"))
        max_prio = max(q.effective_priority for q in nv.victims)
        sum_prio = sum(q.effective_priority for q in nv.victims)
        latest_start_of_top = max(
            q.start_time
            for q in nv.victims
            if q.effective_priority == max_prio
        )
        return (
            nv.num_violating,
            max_prio,
            sum_prio,
            len(nv.victims),
            -latest_start_of_top,
        )

    ordered = [n for n in node_order if n in candidates]
    return min(ordered, key=key)
