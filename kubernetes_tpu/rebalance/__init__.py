"""Continuous global rebalancer — the background defragmentation engine
(ROADMAP open item #2, grounded in CvxCluster's whole-cluster allocation
and "Priority Matters"' constraint-based re-packing, PAPERS.md).

The single-shot auction (solver/single_shot.py) solves the 50k x 10k
global re-placement in ~0.2 s; this package is the production loop
around it:

- **detect** (``detector.py``): fragmentation and priority-inversion
  signals computed from the live ``Snapshot`` node tensors — pure host
  numpy over arrays the scheduler already maintains, zero new device
  syncs;
- **plan** (``planner.py``): run the auction with the ``pack``
  objective over the current cluster to get a consolidation target
  assignment, diff target vs actual placement into candidate moves;
- **bound** (``planner.select_moves``): max-churn budget per cycle,
  PDB-aware selection through ``ops/oracle/preemption.py``'s
  ``classify_pdb_violations`` machinery, priority-ordered, and only
  moves that strictly improve the packing score — an unimprovable pod
  is never touched;
- **execute** (``runtime.py``): evict through the ``ClusterState``
  eviction subresource (Conflict-on-stale, PDB-enforcing, under the
  PR 8 commit fencing so a zombie incarnation can never move anything)
  with a nominated-node hint toward the target; the evicted pod
  re-enters the ordinary scheduling queue and the existing commit path
  performs the migration.

The loop is leader/fence-gated and, in fleet mode, naturally
shard-scoped: a replica's cache IS its shard, so it only ever plans
over (and evicts from) nodes it owns.
"""

from .detector import FragmentationReport, detect
from .planner import Move, RebalancePlan, plan_moves, select_moves
from .runtime import RebalanceConfig, Rebalancer, RunRecord

__all__ = [
    "FragmentationReport",
    "detect",
    "Move",
    "RebalancePlan",
    "plan_moves",
    "select_moves",
    "RebalanceConfig",
    "Rebalancer",
    "RunRecord",
]
