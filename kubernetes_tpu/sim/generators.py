"""Churn generators: seeded event streams from scenario profiles.

``ChurnGenerator.generate(cycle)`` derives the cycle's events from the
profile's rates and ONE ``random.Random`` dedicated to generation (the
fault injectors draw from a separate stream, so mid-run fault decisions
never shift what churn a cycle produces). Events are plain dicts —
exactly what lands in the trace — and ``apply`` executes them against
the live ``ClusterState``. Replay skips ``generate`` entirely and feeds
recorded event dicts straight to ``apply``.

All choices over live cluster state go through sorted snapshots, never
raw set/dict iteration, so a run is independent of PYTHONHASHSEED.
"""

from __future__ import annotations

import random

from .. import metrics
from ..api.objects import Node, Pod
from ..state.cluster import ApiError, ClusterState
from .profiles import Profile


def make_node(
    name: str, cpu: str, mem: str, labels: dict[str, str] | None = None
) -> Node:
    from ..api.wrappers import MakeNode

    b = (
        MakeNode()
        .name(name)
        .capacity({"cpu": cpu, "memory": mem, "pods": "110"})
        .label("kubernetes.io/hostname", name)
    )
    for k, v in (labels or {}).items():
        b = b.label(k, v)
    return b.obj()


ZONE_KEY = "topology.kubernetes.io/zone"
HOST_KEY = "kubernetes.io/hostname"
SIM_PORTS = (8080, 8081)  # small pool: conflicts actually happen
# poison marker (kubernetes_tpu/resilience poison-batch quarantine):
# the SolverFaultInjector breaks any solve whose batch contains a pod
# carrying this label, at every ladder tier
POISON_LABEL = "sim.kubernetes.io/poison"
# PDB-guarded cohort (kubernetes_tpu/rebalance): the harness seeds a
# PodDisruptionBudget with disruptionsAllowed=0 over this label, so
# the rebalancer must never evict a pod carrying it
PDB_GUARD_LABEL = "sim.kubernetes.io/pdb-guard"


def make_pod(
    name: str, cpu: str, priority: int = 0, shape: str = "plain",
    port: int = 0, poison: bool = False, pdb_guard: bool = False,
    gang: str = "", gang_min: int = 0, workload_class: str = "",
) -> Pod:
    """``shape``: plain | spread (hard maxSkew=1 zone spread over the
    app=spread cohort) | anti (required hostname anti-affinity over
    app=anti) | ports (hostPort ``port``). ``poison`` marks the pod
    with POISON_LABEL (its presence breaks the solve — the bisection
    quarantine's food). ``pdb_guard`` joins the PDB-guarded cohort the
    rebalancer must never evict. ``gang`` joins the named pod group
    (kubernetes_tpu/gang): the pod carries the pod-group label plus the
    ``gang_min`` min-member annotation, and ``workload_class`` labels
    it for the heterogeneity throughput term."""
    from ..api.wrappers import MakePod

    b = MakePod().name(name).req({"cpu": cpu, "memory": "1Gi"})
    if gang:
        from ..gang import GANG_LABEL, MIN_MEMBER_ANNOTATION

        b = b.label(GANG_LABEL, gang).annotation(
            MIN_MEMBER_ANNOTATION, str(gang_min or 1)
        )
    if workload_class:
        from ..gang import WORKLOAD_CLASS_LABEL

        b = b.label(WORKLOAD_CLASS_LABEL, workload_class)
    if priority:
        b = b.priority(priority)
    if shape == "spread":
        b = b.label("app", "spread").spread_constraint(
            1, ZONE_KEY, "DoNotSchedule", {"app": "spread"}
        )
    elif shape == "anti":
        b = b.label("app", "anti").pod_anti_affinity(
            HOST_KEY, {"app": "anti"}
        )
    elif shape == "ports":
        b = b.host_port(port or SIM_PORTS[0])
    if poison:
        b = b.label(POISON_LABEL, "1")
    if pdb_guard:
        b = b.label(PDB_GUARD_LABEL, "1")
    return b.obj()


def _count(rng: random.Random, rate: float) -> int:
    """Expected-count rate -> integer count: the whole part always
    happens, the fractional part happens with its probability."""
    whole = int(rate)
    return whole + (1 if rng.random() < (rate - whole) else 0)


class ChurnGenerator:
    def __init__(
        self, profile: Profile, rng: random.Random, cluster: ClusterState
    ) -> None:
        self.profile = profile
        self.rng = rng
        self.cluster = cluster
        self._pod_seq = 0
        self._node_seq = 0
        self._flap_seq = 0
        self._gang_seq = 0

    # -- seeding (before the scheduler exists; not part of the trace —
    # replay re-derives it from the header's profile) --

    def seed_nodes(self) -> list[Node]:
        out = []
        for _ in range(self.profile.nodes):
            out.append(self._make_labeled_node())
        return out

    def _make_labeled_node(self) -> Node:
        """Node with a deterministic zone label (z{seq % zones}) so the
        spread-shaped arrivals have topology domains to spread over —
        and, on gang profiles, a seq-derived accelerator-class label
        (RNG-free like the zone, so node identity never shifts the gen
        stream) feeding the heterogeneity throughput term."""
        zone = f"z{self._node_seq % max(self.profile.zones, 1)}"
        labels = {"topology.kubernetes.io/zone": zone}
        if self.profile.gang_accel_classes:
            from ..gang import ACCEL_CLASS_LABEL

            classes = self.profile.gang_accel_classes
            labels[ACCEL_CLASS_LABEL] = classes[
                self._node_seq % len(classes)
            ]
        return make_node(
            self._next_node_name(),
            self.profile.node_cpu,
            self.profile.node_mem,
            labels=labels,
        )

    def _next_node_name(self) -> str:
        name = f"n{self._node_seq:03}"
        self._node_seq += 1
        return name

    def _next_pod_name(self) -> str:
        name = f"p{self._pod_seq:05}"
        self._pod_seq += 1
        return name

    # -- per-cycle event stream --

    def generate(self, cycle: int) -> list[dict]:
        """The cycle's churn, in a fixed category order. Each event dict
        is self-contained (wire-shape payloads) so the trace replays
        without this generator."""
        p, rng = self.profile, self.rng
        events: list[dict] = []

        # backlog seeding (backlog_drain profiles): the mega-backlog
        # lands as ordinary cycle-0 create_pod events — same hard-shape
        # draw, same trace/replay machinery — BEFORE the cycle's
        # arrivals, so cycle 0's drive sees the full backlog queued.
        # Workload shift (tuning_convergence profiles): from shift_at
        # on, arrivals draw from the shifted band — the regime change
        # the auto-tuner must detect and re-converge for.
        arrivals = p.arrivals
        if p.shift_at >= 0 and cycle >= p.shift_at and p.shift_arrivals:
            arrivals = p.shift_arrivals
        n_arrivals = rng.randint(*arrivals)
        if cycle == 0 and p.backlog:
            n_arrivals += p.backlog

        # pod arrivals (shape drawn per arrival in a fixed order so the
        # stream is a pure function of the gen RNG)
        for _ in range(n_arrivals):
            shape, port = "plain", 0
            if p.pod_spread_rate and rng.random() < p.pod_spread_rate:
                shape = "spread"
            elif p.pod_anti_rate and rng.random() < p.pod_anti_rate:
                shape = "anti"
            elif p.pod_ports_rate and rng.random() < p.pod_ports_rate:
                shape = "ports"
                port = rng.choice(SIM_PORTS)
            # poison/pdb-guard draws guarded on the rate so profiles
            # without them consume no RNG here (existing traces stay
            # byte-identical)
            poison = bool(
                p.poison_rate and rng.random() < p.poison_rate
            )
            pdb_guard = bool(
                p.pdb_guard_rate and rng.random() < p.pdb_guard_rate
            )
            pod = make_pod(
                self._next_pod_name(),
                rng.choice(p.pod_cpu_choices),
                rng.choice(p.pod_priorities),
                shape=shape,
                port=port,
                poison=poison,
                pdb_guard=pdb_guard,
            )
            events.append({"op": "create_pod", "pod": pod.to_dict()})

        # gang arrivals (kubernetes_tpu/gang): each gang's members all
        # land this cycle as ordinary create_pod events (self-contained
        # wire dicts — replay needs no gang logic here). Draws are
        # guarded on the gang knobs so non-gang profiles consume no RNG
        # (existing traces stay byte-identical).
        if p.gang_rate:
            for _ in range(_count(rng, p.gang_rate)):
                events.extend(self._gang_events(rng.choice(p.gang_sizes)))
        if p.gang_short_at >= 0 and cycle == p.gang_short_at:
            # the never-satisfiable gang: min-member is one more than
            # the members that will ever exist, so the quorum cannot
            # assemble and the whole gang must ride gang_incomplete
            # rounds into quarantine
            events.extend(
                self._gang_events(max(p.gang_sizes), short=True)
            )

        # pod deletes (any pod — pending or bound; bound deletes free
        # capacity, pending deletes exercise mid-flight removal)
        candidates = sorted(q.key for q in self.cluster.list_pods())
        for _ in range(_count(rng, p.delete_pod_rate)):
            if not candidates:
                break
            key = candidates.pop(rng.randrange(len(candidates)))
            ns, name = key.split("/", 1)
            events.append({"op": "delete_pod", "ns": ns, "name": name})

        # node adds
        for _ in range(_count(rng, p.node_add_rate)):
            node = self._make_labeled_node()
            events.append({"op": "create_node", "node": node.to_dict()})

        # node deletes (keep at least one node alive)
        names = sorted(n.name for n in self.cluster.list_nodes())
        for _ in range(_count(rng, p.node_delete_rate)):
            if len(names) <= 1:
                break
            name = names.pop(rng.randrange(len(names)))
            events.append({"op": "delete_node", "name": name})

        # label flaps (conflict-fence food: _node_change_could_help)
        for _ in range(_count(rng, p.label_flap_rate)):
            if not names:
                break
            name = rng.choice(names)
            self._flap_seq += 1
            events.append(
                {
                    "op": "flap_label",
                    "name": name,
                    "key": "sim.kubernetes.io/flap",
                    "value": f"f{self._flap_seq}",
                }
            )

        # allocatable grow/shrink (cpu only). A shrink never goes below
        # the node's CURRENT bound usage: shrinking under load is
        # legitimate cluster behavior (kubelet eviction territory, not a
        # scheduler bug), so allowing it would make the capacity
        # invariant unsound — the same reasoning that forbids combining
        # shrinks with delayed watch delivery (profiles.py). The floor
        # keeps "used > allocatable" attributable to a bad BIND only.
        used_cpu: dict[str, int] = {}
        for q in self.cluster.list_pods():
            if q.node_name:
                used_cpu[q.node_name] = used_cpu.get(
                    q.node_name, 0
                ) + q.resource_request().get("cpu", 0)
        staged_alloc: dict[str, int] = {}  # staged cpu deltas this cycle
        alloc_of = {
            n.name: n.allocatable.get("cpu", 0)
            for n in self.cluster.list_nodes()
        }
        for op, rate in (
            ("grow", p.alloc_grow_rate),
            ("shrink", p.alloc_shrink_rate),
        ):
            for _ in range(_count(rng, rate)):
                if not names:
                    break
                name = rng.choice(names)
                cur = alloc_of.get(name, 0) + staged_alloc.get(name, 0)
                if op == "shrink":
                    floor = max(used_cpu.get(name, 0), 1000)
                    if cur - 1000 < floor:
                        continue  # would undercut committed usage
                    staged_alloc[name] = staged_alloc.get(name, 0) - 1000
                else:
                    staged_alloc[name] = staged_alloc.get(name, 0) + 1000
                events.append({"op": f"alloc_{op}", "name": name})

        # external competing binds: another actor places a pending pod
        # (ground-truth fit-checked at generation time against THIS
        # cycle's staged allocatable deltas — shrinks apply before binds;
        # the scheduler may be racing for the same slot — that's the
        # point)
        for _ in range(_count(rng, p.external_bind_rate)):
            ev = self._external_bind_event(events, staged_alloc)
            if ev is None:
                break
            events.append(ev)
        return events

    def _gang_events(self, size: int, short: bool = False) -> list[dict]:
        """Create-pod events for one pod group: ``size`` members, one
        shared cpu request and workload class (DL replicas are
        homogeneous), min-member = size — or size + 1 when ``short``,
        making the gang permanently unsatisfiable."""
        p, rng = self.profile, self.rng
        self._gang_seq += 1
        gid = f"g{self._gang_seq:03}"
        wc = (
            rng.choice(p.gang_workload_classes)
            if p.gang_workload_classes
            else ""
        )
        cpu = rng.choice(p.pod_cpu_choices)
        min_member = size + 1 if short else size
        out = []
        for _ in range(size):
            pod = make_pod(
                self._next_pod_name(),
                cpu,
                gang=gid,
                gang_min=min_member,
                workload_class=wc,
            )
            out.append({"op": "create_pod", "pod": pod.to_dict()})
        return out

    def _external_bind_event(
        self, staged: list[dict], staged_alloc: dict[str, int]
    ) -> dict | None:
        staged_deletes = {
            f"{e['ns']}/{e['name']}"
            for e in staged
            if e["op"] in ("delete_pod", "external_bind")
        }
        staged_node_deletes = {
            e["name"] for e in staged if e["op"] == "delete_node"
        }
        pods = sorted(
            (q for q in self.cluster.list_pods()), key=lambda q: q.key
        )
        pending = [
            q
            for q in pods
            if not q.node_name and q.key not in staged_deletes
        ]
        if not pending:
            return None
        pod = self.rng.choice(pending)
        used: dict[str, dict[str, int]] = {}
        for q in pods:
            if q.node_name:
                u = used.setdefault(q.node_name, {})
                for r, v in q.resource_request().items():
                    u[r] = u.get(r, 0) + v
        # earlier external binds staged this cycle consume capacity too
        for e in staged:
            if e["op"] != "external_bind":
                continue
            q = next(
                (
                    x
                    for x in pods
                    if x.namespace == e["ns"] and x.name == e["name"]
                ),
                None,
            )
            if q is not None:
                u = used.setdefault(e["node"], {})
                for r, v in q.resource_request().items():
                    u[r] = u.get(r, 0) + v
        fits = []
        for node in sorted(self.cluster.list_nodes(), key=lambda n: n.name):
            if node.name in staged_node_deletes or node.unschedulable:
                continue
            u = used.get(node.name, {})
            if all(
                u.get(r, 0) + v
                <= node.allocatable.get(r, 0)
                + (staged_alloc.get(node.name, 0) if r == "cpu" else 0)
                for r, v in pod.resource_request().items()
                if v > 0 and r != "pods"
            ):
                fits.append(node.name)
        if not fits:
            return None
        return {
            "op": "external_bind",
            "ns": pod.namespace,
            "name": pod.name,
            "node": self.rng.choice(fits),
        }


def apply_event(cluster: ClusterState, ev: dict) -> None:
    """Execute one churn event against the state service. Tolerates
    NotFound/AlreadyExists/Conflict — under replay the cluster can have
    drifted only if the scheduler diverged, and the decision journal
    catches that with a better message than a KeyError here."""
    op = ev["op"]
    metrics.sim_events_total.labels(op).inc()
    try:
        if op == "create_pod":
            cluster.create_pod(Pod.from_dict(ev["pod"]))
        elif op == "delete_pod":
            cluster.delete_pod(ev["ns"], ev["name"])
        elif op == "create_node":
            cluster.create_node(Node.from_dict(ev["node"]))
        elif op == "delete_node":
            cluster.delete_node(ev["name"])
        elif op == "flap_label":
            node = cluster.get_node(ev["name"])
            import dataclasses

            labels = dict(node.labels)
            labels[ev["key"]] = ev["value"]
            cluster.update_node(dataclasses.replace(node, labels=labels))
        elif op in ("alloc_grow", "alloc_shrink"):
            node = cluster.get_node(ev["name"])
            import dataclasses

            alloc = dict(node.allocatable)
            cpu = alloc.get("cpu", 0)
            # canonical cpu ints are millicores
            delta = 1000 if op == "alloc_grow" else -1000
            alloc["cpu"] = max(cpu + delta, 1000)
            cluster.update_node(
                dataclasses.replace(node, allocatable=alloc)
            )
        elif op == "external_bind":
            cluster.bind(ev["ns"], ev["name"], ev["node"])
        else:
            raise ValueError(f"unknown sim event op {op!r}")
    except ApiError:
        # target vanished between generation and apply (or replay drift
        # that the decision journal will diagnose) — churn, not a bug
        pass
