"""Nominated-pod machinery (VERDICT r2 #5): the solver-side analog of
RunFilterPluginsWithNominatedPods / evaluateNominatedNode
(pkg/scheduler/schedule_one.go, framework/runtime/framework.go
#addNominatedPods)."""

import numpy as np

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.tensorize.schema import build_nominated_tensors, ResourceVocab
from kubernetes_tpu.utils.clock import FakeClock


def _mini_cluster():
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("only").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    return cs


def test_level_of_buckets():
    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    pods = [
        (MakePod().name("a").priority(10).req({"cpu": "1"}).obj(), 0),
        (MakePod().name("b").priority(5).req({"cpu": "1"}).obj(), 0),
    ]
    nt = build_nominated_tensors(pods, vocab, 8)
    assert list(nt.levels) == [10, 5]
    np.testing.assert_array_equal(
        nt.level_of(np.asarray([11, 10, 7, 5, 0])), [0, 1, 1, 2, 2]
    )
    # cumulative: row 1 = prio>=10 load (1 cpu), row 2 = both (2 cpu)
    assert nt.used[1, 0, 0] == 1000 and nt.used[2, 0, 0] == 2000
    assert nt.count[1, 0] == 1 and nt.count[2, 0] == 2


def test_preemptor_capacity_not_stolen():
    """The verdict's done-criterion: after preemption frees capacity, a
    lower-priority pod in the NEXT batch (while the preemptor sits in
    backoff) must not steal the nominated node."""
    clock = FakeClock()
    cs = _mini_cluster()
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )
    # victim fills the node
    victim = MakePod().name("victim").priority(0).req({"cpu": "2"}).obj()
    cs.create_pod(victim)
    cs.bind("default", "victim", "only")

    # preemptor arrives, fails, preempts: victim deleted, nomination set
    cs.create_pod(MakePod().name("preemptor").priority(10).req({"cpu": "2"}).obj())
    r1 = sched.schedule_batch()
    assert r1.preemptions and r1.preemptions[0][1] == "only"
    assert cs.get_pod("default", "preemptor").nominated_node_name == "only"

    # a lower-priority thief shows up while the preemptor is in backoff
    cs.create_pod(MakePod().name("thief").priority(1).req({"cpu": "2"}).obj())
    r2 = sched.schedule_batch()
    assert "default/thief" in r2.unschedulable, (
        "thief must see the nominated load and fail"
    )
    assert not r2.scheduled

    # backoff expires; the preemptor lands on its nominated node
    clock.advance(15.0)
    r3 = sched.schedule_batch()
    placed = dict(r3.scheduled)
    assert placed.get("default/preemptor") == "only"
    # and the thief keeps failing even after that (node genuinely full)
    clock.advance(15.0)
    r4 = sched.schedule_batch()
    assert "default/thief" in r4.unschedulable or not r4.scheduled


def test_higher_priority_pod_ignores_nomination():
    """A pod with HIGHER priority than every nomination sees no nominated
    load (addNominatedPods only adds priority >= pod's)."""
    clock = FakeClock()
    cs = _mini_cluster()
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first"), enable_preemption=False),
        clock=clock,
    )
    # a nomination from a low-priority pod (parked, no capacity issue)
    low = MakePod().name("low").priority(1).req({"cpu": "2"}).nominated_node_name("only").obj()
    cs.create_pod(low)
    # pop low out of the way: it schedules onto the empty node? No — keep it
    # pending by requesting the whole node AND have the vip arrive first.
    vip = MakePod().name("vip").priority(50).req({"cpu": "2"}).obj()
    cs.create_pod(vip)
    r = sched.schedule_batch()
    placed = dict(r.scheduled)
    # vip outranks the nomination, so the nominated load does not block it
    assert placed.get("default/vip") == "only"


def test_no_double_count_after_nominated_pod_places():
    """Once the scan places a nominated pod, its load must stop counting as
    nominated for later pods in the SAME batch (the reference removes an
    assumed pod from the nominator map). Repro: 4-cpu node, nominated
    2-cpu pod + lower-priority 2-cpu pod in one batch — both must fit."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
    )
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=FakeClock(),
    )
    cs.create_pod(
        MakePod().name("nom").priority(5).req({"cpu": "2"})
        .nominated_node_name("n").obj()
    )
    cs.create_pod(MakePod().name("b").priority(1).req({"cpu": "2"}).obj())
    r = sched.schedule_batch()
    placed = dict(r.scheduled)
    assert placed.get("default/nom") == "n"
    assert placed.get("default/b") == "n", (
        "b must see the nominated load cleared once nom placed"
    )


def test_nominated_node_tried_first():
    """evaluateNominatedNode: a nominated pod takes its nominated node even
    when another node would score higher."""
    clock = FakeClock()
    cs = ClusterState()
    # busy node (lower score) and empty node (higher score)
    cs.create_node(
        MakeNode().name("busy").capacity({"cpu": "8", "memory": "16Gi", "pods": "10"}).obj()
    )
    cs.create_node(
        MakeNode().name("empty").capacity({"cpu": "8", "memory": "16Gi", "pods": "10"}).obj()
    )
    filler = MakePod().name("filler").req({"cpu": "6"}).obj()
    cs.create_pod(filler)
    cs.bind("default", "filler", "busy")

    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )
    pod = (
        MakePod().name("p").priority(5).req({"cpu": "1"})
        .nominated_node_name("busy").obj()
    )
    cs.create_pod(pod)
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/p") == "busy"


def test_nominated_host_port_reserved():
    """ADVICE r3: port conflicts are as monotone as resources — a
    lower-priority pod wanting the nominated preemptor's hostPort must
    not find the reserved node port-feasible during the nomination
    window, even though cpu/memory would fit it."""
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("only").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "10"}
        ).obj()
    )
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )
    # victim holds the port; the preemptor (with the same hostPort) evicts
    victim = (
        MakePod().name("victim").priority(0)
        .req({"cpu": "1"}).host_port(8080).obj()
    )
    cs.create_pod(victim)
    cs.bind("default", "victim", "only")
    cs.create_pod(
        MakePod().name("preemptor").priority(10)
        .req({"cpu": "1"}).host_port(8080).obj()
    )
    r1 = sched.schedule_batch()
    assert r1.preemptions and r1.preemptions[0][1] == "only"

    # plenty of cpu remains, but the PORT is reserved by the nomination:
    # a lower-priority pod wanting 8080 must fail...
    cs.create_pod(
        MakePod().name("port-thief").priority(1)
        .req({"cpu": "1"}).host_port(8080).obj()
    )
    # ...while one without the port binds fine in the same batch
    cs.create_pod(
        MakePod().name("portless").priority(1).req({"cpu": "1"}).obj()
    )
    r2 = sched.schedule_batch()
    assert "default/port-thief" in r2.unschedulable
    assert dict(r2.scheduled).get("default/portless") == "only"

    # backoff expires; the preemptor lands and takes its port
    clock.advance(15.0)
    r3 = sched.schedule_batch()
    assert dict(r3.scheduled).get("default/preemptor") == "only"
    # the thief keeps failing: the port is now genuinely taken
    clock.advance(15.0)
    r4 = sched.schedule_batch()
    assert "default/port-thief" in r4.unschedulable or not r4.scheduled
