"""Benchmark entry point (driver-run on real TPU hardware).

Measures the FULL BASELINE.md target ladder (VERDICT r2 #3):

  #1 scheduler_perf SchedulingBasic shape: 500 pods x 500 nodes, default
     plugins, via the YAML-runner code path (test/integration/scheduler_perf).
  #2 NodeResourcesFit + BalancedAllocation: 5k homogeneous pods x 1k nodes
     through the full stack (state service -> queue -> snapshot -> exact TPU
     solve -> bind). THE HEADLINE: the grouped fast path engages here.
  #3 PodTopologySpread across 3 zones: 10k pods x 5k nodes, hard maxSkew=1
     zone constraint.
  #4 InterPodAffinity anti-affinity (the O(n^2) hot path): 5k pods x 5k
     nodes, required hostname anti-affinity.
  #5 Global rebalance north star: 50k pods x 10k nodes single-shot auction.
  #6 Sustained open-loop arrival with a sync-vs-pipelined A/B per shape
     (plain/ports/spread/anti): pods arrive at a fixed rate while the
     scheduler drains concurrently; hard shapes run through
     run_pipelined's occupancy-carrying sub-batch split. Emits
     sustained_pods_per_sec + sustained_p99_pod_latency_s (also hoisted
     to the top level from the pipelined plain shape).
  #7 Multichip A/B: the exact-parity session solve at the north-star
     shape on 1 device vs the full node-axis mesh
     (ExactSolver.solve(mesh=...)), plus the 8x-node shape (~81,920
     nodes) on the full mesh. Emits multichip_pods_per_sec +
     multichip_speedup (hoisted to the top level); skips with a reason
     string when only one device is visible.
  #8 Fleet A/B, DEVICE tier: 1 scheduler process (full device set) vs
     N active fleet replicas (each its own OS process, shard-scoped by
     the consistent-hash ring, pinned to an EXCLUSIVE 1/N mesh slice
     of the shared virtual device set, stream-dispatching) draining
     the same open-loop arrival stream at ladder #6 rates, with ONE
     occupancy hub served over localhost gRPC (fenced CAS admits +
     row traffic on the real wire). The backend is XLA CPU on every
     box (N children cannot share one libtpu) — the multiplier is the
     fleet tier scaling the whole device-path pipeline. Emits
     fleet_pods_per_sec + fleet_speedup (hoisted to the top level).
  #9 Degraded-mode A/B (kubernetes_tpu/resilience): the same sustained
     open-loop workload at the top fallback-ladder tier vs pinned to
     the pure-host serial-greedy rung (force_tier="host") — the floor
     the scheduler degrades to when every accelerator tier's breaker
     is open. Emits degraded_pods_per_sec (hoisted to the top level)
     + degradation_factor, so the cost of degradation is a measured
     number.

 #10 Rebalance loop A/B: the continuous rebalancer closing a seeded
     fragmented 51.2k x 10.24k cluster (packed utilization before vs
     after, median plan solve per the <1 s target).
 #11 Backlog drain at 10x the proven scale (ISSUE 12): a 512k-pod
     backlog drained end to end against 102,400 nodes through
     Scheduler.drain_backlog — HBM-budget-planned chunk-aligned
     sub-batches through run_streaming's slot ring with cross-batch
     occupancy chaining on a hard (zone-spread) shape; 1-device vs
     full-mesh A/B, MEDIAN drain-chunk solve time, end-state validity
     asserted, plus the single-shot auction (scarcity repair on) at
     the same shape. Emits backlog_drain_pods_per_sec +
     backlog_drain_seconds (hoisted to the top level).

Each ladder reports steady-state (warm-start) pods/s, best of 3 full
passes — compiles happen in a same-shaped warmup pass (persistent compile
cache makes restarts cheap) — plus per-workload invariant checks (all
placed; skew bound; exclusivity).

Measurement regime: the axon tunnel defers execution until the first
device->host read, then prices every sync at ~1 RTT (~0.1 s). All rows
here include per-batch assignment reads, so they are honest sync-mode
end-to-end numbers; the ``tunnel`` entry records both dispatch regimes so
the context is explicit. Batch/group sizes are large for the same reason
(pods per sync is the first-order throughput knob).

Prints ONE JSON line. ``value``/``vs_baseline`` headline ladder #2;
``vs_baseline`` divides by the TOP of the reference's in-proc band
(O(1-5k) pods/s on scheduler_perf-style runs, BASELINE.md) — the strictest
available comparator. The API-bound ~300 pods/s figure is reported
separately as vs_api_bound. Each ladder reports the solver's actual
dispatch histogram (per-pod scan vs grouped chunk kinds) instead of a
hardcoded path label; nothing is extrapolated from the easy regime.
"""

from __future__ import annotations

import json
import time

BAND_TOP_PODS_PER_SEC = 5_000.0  # top of the in-proc CPU reference band
API_BOUND_PODS_PER_SEC = 300.0  # sustained API/QPS-bound reference figure

NS_NODES = 10_240
NS_PODS = 51_200
NS_TARGET_S = 1.0


def _mk_node(i: int, zones: int = 3):
    from kubernetes_tpu.api.wrappers import MakeNode

    return (
        MakeNode()
        .name(f"node-{i:05}")
        .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
        .label("topology.kubernetes.io/zone", f"z{i % zones}")
        .label("kubernetes.io/hostname", f"node-{i:05}")
        .obj()
    )


def _mk_pod(i: int, kind: str):
    from kubernetes_tpu.api.wrappers import MakePod

    b = (
        MakePod()
        .name(f"pod-{i:05}")
        .label("app", kind)
        .req({"cpu": "250m", "memory": "512Mi"})
    )
    if kind == "spread":
        b = b.spread_constraint(
            1, "topology.kubernetes.io/zone", "DoNotSchedule", {"app": kind}
        )
    elif kind == "anti":
        b = b.pod_anti_affinity("kubernetes.io/hostname", {"app": kind})
    elif kind == "ports":
        # 8-port pool: real conflict pressure (NodePorts occupancy carry)
        # while 500 nodes x 8 ports leaves headroom for every pod
        b = b.host_port(8000 + i % 8)
    return b.obj()


def _dispatch_label(sched) -> str:
    """Derive the solver-path label from the solver's actual dispatch
    histogram instead of asserting it (round-3's hardcoded labels claimed
    grouping was disabled on workloads where the quota chunks engaged)."""
    from collections import Counter

    total: Counter = Counter()
    for solver in sched.solvers.values():
        total.update(getattr(solver, "dispatch_counts", {}))
    if not total:
        return "no solves dispatched"
    names = {
        "scan": "per-pod scan",
        "kind0": "grouped slow-replay chunks",
        "kind1": "grouped plain fast chunks",
        "kind2": "grouped spread-quota chunks",
        "kind3": "grouped anti-quota chunks",
    }
    parts = [
        f"{names.get(k, k)}={v}" for k, v in sorted(total.items())
    ]
    return "; ".join(parts)


def _run_ladder(
    n_nodes: int,
    n_pods: int,
    kind: str,
    batch: int,
    warm_pods: int,
    group: int = 512,
    reps: int = 3,
) -> dict:
    """Warm-start end-to-end run, best of ``reps`` full passes (the axon
    tunnel's throughput varies between runs on identical executables —
    README "Performance"): a same-shaped throwaway cluster compiles every
    executable (incl. the device-session heal path), then each timed pass
    builds a fresh cluster and runs the production path only.

    ``batch``/``group`` default large: the tunnel prices each
    host<->device sync at ~0.1 s regardless of payload, so pods/solve-call
    is the first-order throughput knob (the per-pod p99 latency cost of
    the bigger batch is reported alongside)."""
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    def build(n_p):
        cs = ClusterState()
        for i in range(n_nodes):
            cs.create_node(_mk_node(i))
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=batch,
                solver=ExactSolverConfig(
                    tie_break="random", group_size=group
                ),
            ),
        )
        for i in range(n_p):
            cs.create_pod(_mk_pod(i, kind))
        return cs, sched

    t0 = time.perf_counter()
    _, wsched = build(warm_pods)
    wsched.schedule_batch()
    wsched.schedule_batch()
    warmup_s = time.perf_counter() - t0

    best = None
    run_walls = []
    for _ in range(reps):
        cs, sched = build(n_pods)
        batch_times: list[tuple[float, int]] = []
        solve_s = 0.0
        scheduled = 0
        t0 = time.perf_counter()
        while True:
            tb = time.perf_counter()
            r = sched.schedule_batch()
            n = len(r.scheduled)
            if not r.progressed:
                break
            batch_times.append((time.perf_counter() - tb, n))
            solve_s += r.solve_seconds
            scheduled += n
        total = time.perf_counter() - t0
        assert scheduled == n_pods, (
            f"{kind}: only {scheduled}/{n_pods} scheduled"
        )
        _check_invariants(cs, kind)
        run_walls.append(round(total, 3))
        if best is None or total < best[0]:
            best = (total, solve_s, batch_times, sched)

    total, solve_s, batch_times, sched = best
    per_pod = sorted(t for t, n in batch_times for _ in range(n))
    p99 = per_pod[int(0.99 * (len(per_pod) - 1))] if per_pod else 0.0
    return {
        "pods": n_pods,
        "nodes": n_nodes,
        "batch": batch,
        "group": group,
        "pods_per_sec": round(n_pods / total, 1) if total else None,
        "wall_s": round(total, 3),
        "run_walls_s": run_walls,
        "device_solve_s": round(solve_s, 3),
        "p99_batch_latency_s": round(p99, 4),
        "warmup_s": round(warmup_s, 2),
        "dispatch": _dispatch_label(sched),
    }


def _check_invariants(cs, kind: str) -> None:
    """Workload-specific correctness gates — a number only counts if the
    bindings are right (BASELINE.md measurement protocol)."""
    from collections import Counter

    pods = [p for p in cs.list_pods() if p.name.startswith("pod-")]
    if kind == "spread":
        zones = Counter()
        node_zone = {n.name: n.labels["topology.kubernetes.io/zone"] for n in cs.list_nodes()}
        for p in pods:
            zones[node_zone[p.node_name]] += 1
        if zones:
            assert max(zones.values()) - min(zones.values()) <= 1, (
                f"zone skew violated: {dict(zones)}"
            )
    elif kind == "anti":
        per_node = Counter(p.node_name for p in pods)
        worst = max(per_node.values(), default=0)
        assert worst <= 1, f"hostname anti-affinity violated: {worst} pods on one node"


def _sustained_shape(
    kind: str,
    n_nodes: int,
    n_pods: int,
    rate: float,
    mode: str = "pipelined",  # "sync" | "pipelined" | "streaming"
    batch: int = 2_048,
    group: int = 256,
    split: int = 4,
    stream_depth: int = 4,
    resilience=None,  # ResilienceConfig override (ladder #9's forced
    # host-greedy arm); None = defaults (top tier)
    tuning=None,  # TuningConfig: the ladder #12 tuned arm; None = static
    obs=None,  # ObsConfig: the ladder #13 obs-on arm (full tracing +
    # journal + SLO engine); None = observability off (the default
    # every other ladder measures)
    fleet=None,  # FleetConfig factory (called per build): ladder #13
    # runs BOTH arms as a single-replica fleet so the obs-on arm's
    # journal-segment shipping to the hub is inside the measured window
) -> dict:
    """One open-loop sustained-arrival run: pods arrive at ``rate``/s
    while the scheduler drains concurrently — streaming
    (Scheduler.run_streaming, the device-resident solve loop with
    cross-batch occupancy chaining), pipelined (Scheduler.run_pipelined,
    hard shapes via the occupancy-carrying sub-batch split), or
    synchronous (schedule_batch); same workload for every arm.

    Reports POST-WARMUP steady-state throughput (the first measured
    batch, which absorbs residual warmup, is dropped; time-weighted
    over the rest), the per-pod e2e p99 (first queue entry -> bind
    commit) — BASELINE.md's sustained metric pair — the pipeline
    mode/sub-batch counters proving which path ran, and the RTT
    attribution row: hidden-vs-paid deferred reads (a read that blocked
    the driver > 1 ms paid an un-hidden tunnel round trip),
    unhidden_reads_per_batch, and the h2d/d2h transfer-byte deltas."""
    from kubernetes_tpu import metrics
    from kubernetes_tpu.perf.runner import WorkloadResult
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    def build():
        cs = ClusterState()
        for i in range(n_nodes):
            cs.create_node(_mk_node(i))
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=batch,
                # the streaming arm splits too (run_streaming threads
                # _choose_split through _dispatch_stream) — only the
                # sync arm pins 1 so the A/B isolates the dispatcher
                pipeline_split=split if mode != "sync" else 1,
                stream_depth=stream_depth,
                solver=ExactSolverConfig(
                    tie_break="random", group_size=group
                ),
                resilience=resilience,
                tuning=tuning,
                obs=obs,
                fleet=fleet() if fleet is not None else None,
            ),
        )
        return cs, sched

    def drive(sched, max_batches=None):
        if mode == "streaming":
            return sched.run_streaming(
                max_batches=max_batches or 10_000
            )
        if mode == "pipelined":
            return sched.run_pipelined(max_batches=max_batches or 10_000)
        if max_batches is not None:
            return [sched.schedule_batch()]
        return sched.run_until_settled()

    # warmup: compile this shape's executables (incl. the chained
    # sub-batch variants) on a throwaway cluster
    cs, sched = build()
    for i in range(min(n_pods, batch)):
        cs.create_pod(_mk_pod(i, kind))
    drive(sched)

    cs, sched = build()
    mode_counters = {
        m: metrics.pipeline_mode_total.labels(m)
        for m in ("overlap", "carry", "stream", "sync")
    }
    modes0 = {m: c._value.get() for m, c in mode_counters.items()}
    sub0 = metrics.pipeline_subbatches_total._value.get()
    h2d0 = metrics.h2d_bytes_total._value.get()
    d2h0 = metrics.d2h_bytes_total._value.get()
    # stats ride the perf runner's WorkloadResult so the steady-state
    # definition (drop the first measured batch, time-weighted) and the
    # e2e p99 are ONE formula shared with the SteadyStateArrival
    # threshold gate — not a bench-local reimplementation that drifts
    res = WorkloadResult("sustained", kind)
    t0 = time.perf_counter()
    prev_at = t0
    created = 0
    while created < n_pods or sched.pending:
        due = min(n_pods, int((time.perf_counter() - t0) * rate) + 1)
        while created < due:
            cs.create_pod(_mk_pod(created, kind))
            created += 1
        made_progress = False
        results = drive(
            sched, max_batches=8 if mode == "streaming" else 2
        )
        for r in results:
            n = len(r.scheduled)
            res.scheduled += n
            res.unschedulable += len(r.unschedulable)
            at = r.completed_at or time.perf_counter()
            if n:
                dt = max(at - prev_at, 1e-9)
                res.batch_samples.append((dt, n))
                res.samples.append(n / dt)
                res.measured_pods += n
                res.pod_latencies.extend(r.e2e_latencies)
            prev_at = at
            made_progress = made_progress or r.progressed
        if created >= n_pods and not made_progress:
            break  # drained (or only stuck pods remain)
    res.measure_seconds = time.perf_counter() - t0
    batches = max(sched._trace_step, 1)
    return {
        "pods": n_pods,
        "nodes": n_nodes,
        "arrival_rate_pods_per_sec": rate,
        "scheduled": res.scheduled,
        "unschedulable": res.unschedulable,
        "sustained_pods_per_sec": round(res.steady_pods_per_sec(), 1),
        "sustained_p99_pod_latency_s": round(
            res.latency_summary()["p99"], 4
        ),
        "wall_s": round(res.measure_seconds, 3),
        "pipeline_modes": {
            m: int(c._value.get() - modes0[m])
            for m, c in mode_counters.items()
        },
        "pipeline_subbatches": int(
            metrics.pipeline_subbatches_total._value.get() - sub0
        ),
        # RTT attribution (ISSUE 10): a deferred read that blocked the
        # driver > 1 ms paid an un-hidden host<->device round trip; the
        # rest were hidden behind overlapped host work / the streaming
        # completion thread. unhidden_reads_per_batch is the number the
        # device-resident loop drives toward one per event-fence.
        "rtt_attribution": {
            "reads_hidden": sched._reads_hidden,
            "reads_paid": sched._reads_paid,
            "unhidden_reads_per_batch": round(
                sched._reads_paid / batches, 4
            ),
            "batches": batches,
            "stream_chained_batches": int(
                sched.solver.dispatch_counts.get("stream_chained", 0)
            ),
            "h2d_bytes": int(metrics.h2d_bytes_total._value.get() - h2d0),
            "d2h_bytes": int(metrics.d2h_bytes_total._value.get() - d2h0),
        },
        "dispatch": _dispatch_label(sched),
        # ladder #12 tuned arm: the tuning runtime's decision/guardrail
        # accounting and final knob values
        "tuning": (
            sched.tuner.summary() if sched.tuner is not None else None
        ),
        # ladder #13 obs-on arm: the live SLO engine's final snapshot
        # (are-we-meeting-SLOs as measured DURING the run) plus the
        # journal/span volume the arm paid for
        "slo": sched.slo.snapshot() if sched.slo is not None else None,
        "obs_volume": (
            {
                "journal_records": sched.journal.total_records,
                "spans": (
                    len(sched.flight.spans()) + sched.flight.dropped_spans
                ),
            }
            if sched.journal is not None and sched.flight is not None
            else None
        ),
        # ladder #13 telemetry arm: the continuous profiler's stage
        # ledger + sentinel state as measured during the run
        "telemetry": (
            sched.telemetry.snapshot()
            if getattr(sched, "telemetry", None) is not None
            else None
        ),
    }


def ladder_sustained() -> dict:
    """#6: the sustained-arrival ladder with a per-shape
    sync-vs-pipelined-vs-STREAMING A/B/C. The hard shapes
    (ports/spread/anti) run through run_pipelined's occupancy-carrying
    path and through run_streaming's cross-batch occupancy chain — the
    streaming dispatcher (ISSUE 10) is gated on its sustained p99
    against the PR 4 pipelined arm, with the RTT attribution row
    (unhidden_reads_per_batch) proving the per-batch round-trip floor
    actually fell."""
    shapes = (
        # (kind, pods, arrival rate): rates oversupply the scheduler so
        # the measured number is scheduler capacity, not arrival cap
        ("plain", 4_000, 20_000.0),
        ("ports", 2_000, 6_000.0),
        ("spread", 3_000, 8_000.0),
        ("anti", 400, 2_000.0),
    )
    out: dict = {}
    for kind, n_pods, rate in shapes:
        sync = _sustained_shape(kind, 500, n_pods, rate, mode="sync")
        pipe = _sustained_shape(kind, 500, n_pods, rate, mode="pipelined")
        stream = _sustained_shape(
            kind, 500, n_pods, rate, mode="streaming"
        )
        pipe_p99 = pipe["sustained_p99_pod_latency_s"]
        stream_p99 = stream["sustained_p99_pod_latency_s"]
        out[kind] = {
            "sync": sync,
            "pipelined": pipe,
            "streaming": stream,
            "pipelined_vs_sync": round(
                pipe["sustained_pods_per_sec"]
                / max(sync["sustained_pods_per_sec"], 1e-9),
                3,
            ),
            "pipelined_ge_sync": bool(
                pipe["sustained_pods_per_sec"]
                >= sync["sustained_pods_per_sec"]
            ),
            # the streaming gate pair: p99 speedup over the pipelined
            # arm (>= 2x target on plain) and no-regression marker
            "streaming_p99_speedup_vs_pipelined": round(
                pipe_p99 / max(stream_p99, 1e-9), 3
            ),
            "streaming_ge_pipelined": bool(
                stream["sustained_pods_per_sec"]
                >= pipe["sustained_pods_per_sec"]
            ),
            "streaming_unhidden_reads_per_batch": stream[
                "rtt_attribution"
            ]["unhidden_reads_per_batch"],
        }
    return out


def _fleet_replica_worker(
    rid: str,
    universe: tuple,
    n_nodes: int,
    n_pods: int,
    rate: float,
    batch: int,
    group: int,
    start_at: float,
    out_q,
    kind: str = "plain",
    hub_addr: str = "",
    total_devices: int = 8,
) -> None:
    """One fleet replica as its own OS process (spawn target): builds
    its replica of the state service (every replica of a real fleet
    watches the same apiserver; here each process replays the same
    deterministic node/pod stream), runs a fleet-mode Scheduler whose
    shard filter scopes it to its ring partition, and reports its
    completion timeline on ``out_q``. Pod arrivals follow one shared
    wall-clock schedule anchored at ``start_at`` (epoch time), so the
    fleet's replicas face the same open-loop arrival process
    concurrently.

    DEVICE-TIER arms (ISSUE 11): every replica owns an EXCLUSIVE mesh
    slice of one shared virtual device set (mesh_slice = (rank, N)
    over ``total_devices`` forced host-platform devices) and drives
    the STREAMING dispatcher (PR 10) against it — the solve is the
    sharded resident-session device path end to end, N processes never
    sharing a device. The backend is XLA CPU on every box (N spawned
    children still cannot share one libtpu), so the measured multiplier
    is the fleet tier scaling the whole device-path pipeline — shard-
    scoped caches, per-slice sharded sessions, per-replica stream
    rings — under a fair hardware split (disjoint core slices). Multi-
    replica arms share ONE occupancy hub over a localhost gRPC server
    (``hub_addr`` -> RemoteOccupancyExchange): fenced CAS admits pay a
    synchronous round trip, plain row traffic rides the write-behind
    apply_ops batches — the wire discipline production would use."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={total_devices}"
        ).strip()
    if len(universe) > 1:
        # disjoint core slices per replica: two XLA CPU runtimes
        # otherwise both size their intra-op pools to the whole box
        # and thrash each other — a real fleet puts replicas on
        # separate hosts, so the honest same-box A/B is a fair
        # hardware split, not oversubscription
        try:
            cores = sorted(os.sched_getaffinity(0))
            n = len(universe)
            rank = universe.index(rid)
            share = max(len(cores) // n, 1)
            mine = cores[rank * share : (rank + 1) * share] or cores
            os.sched_setaffinity(0, mine)
        except (AttributeError, OSError):
            pass  # non-Linux: let the OS schedule
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from kubernetes_tpu.fleet import FleetConfig
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    rank = universe.index(rid)
    mesh_slice = (rank, len(universe))

    def build():
        cs = ClusterState()
        for i in range(n_nodes):
            cs.create_node(_mk_node(i, zones=8))
        fleet = (
            FleetConfig(
                replica=rid, replicas=universe, hub_address=hub_addr
            )
            if len(universe) > 1
            else None
        )
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=batch,
                mesh_slice=mesh_slice,
                solver=ExactSolverConfig(
                    tie_break="random", group_size=group
                ),
                fleet=fleet,
            ),
        )
        return cs, sched

    # warmup compile on a throwaway cluster. The shard filter routes
    # only ~1/N of created pods to this replica, so seed batch*N pods:
    # each replica must warm the FULL batch-size pod bucket it will
    # solve in the measured window (a half-shard warmup leaves the
    # measured run paying a fresh XLA compile per replica)
    cs, sched = build()
    for i in range(min(n_pods, batch * max(len(universe), 1) * 2)):
        cs.create_pod(_mk_pod(i, kind))
    sched.run_streaming()

    cs, sched = build()
    # prebuild the arrival stream: the pod OBJECTS are the synthetic
    # client's cost, not the scheduler's — building them inside the
    # measured window would bottleneck every arm on the builder
    pods = [_mk_pod(i, kind) for i in range(n_pods)]
    completions: list[tuple[float, int]] = []
    latencies: list[float] = []
    unschedulable = 0
    created = 0
    while time.time() < start_at:
        time.sleep(0.001)
    deadline = start_at + 300.0
    while time.time() < deadline:
        due = min(n_pods, int((time.time() - start_at) * rate) + 1)
        while created < due:
            cs.create_pod(pods[created])
            created += 1
        progressed = False
        for r in sched.run_streaming(max_batches=2):
            n = len(r.scheduled)
            if n:
                completions.append((time.time(), n))
                latencies.extend(r.e2e_latencies)
            unschedulable += len(r.unschedulable)
            progressed = progressed or r.progressed
        if created >= n_pods and not progressed and not sched.pending:
            break
    out_q.put(
        {
            "rid": rid,
            "completions": completions,
            "latencies": latencies,
            "unschedulable": unschedulable,
        }
    )


def _fleet_sustained(
    n_replicas: int,
    n_nodes: int,
    n_pods: int,
    rate: float,
    batch: int = 2_048,
    group: int = 256,
    kind: str = "plain",
    total_devices: int = 8,
) -> dict:
    """One open-loop sustained run driven by ``n_replicas`` active
    fleet replicas, each its OWN OS process (1 = the classic
    sole-owner scheduler, the A arm — one process, the WHOLE device
    set). This is the deployment shape the fleet tier exists for: N
    scheduler processes, each shard-scoped by the ring and pinned to
    an exclusive 1/N mesh slice of the same device set, all
    stream-dispatching concurrently against ONE occupancy hub served
    over localhost gRPC — the speedup is the fleet tier multiplying
    the device-path streaming dispatcher, wire costs included."""
    import multiprocessing

    server = None
    hub_addr = ""
    if n_replicas > 1:
        # one REAL occupancy hub for the whole fleet, served behind
        # the bulk gRPC boundary: stage/commit rows and fenced CAS
        # admits all cross a real socket (RemoteOccupancyExchange),
        # so reconcile-bearing shapes (spread/anti) measure honestly
        # too — the PR 6 private-hub refusal is gone
        from kubernetes_tpu.fleet import OccupancyExchange
        from kubernetes_tpu.server.bulk import BulkCore, make_grpc_server
        from kubernetes_tpu.state.cluster import ClusterState

        core = BulkCore(ClusterState(), exchange=OccupancyExchange())
        server, hub_port = make_grpc_server(core, port=0)
        server.start()
        hub_addr = f"127.0.0.1:{hub_port}"
    ctx = multiprocessing.get_context("spawn")
    universe = tuple(f"r{i}" for i in range(n_replicas))
    out_q = ctx.Queue()
    # anchor the shared arrival schedule far enough out that every
    # process finishes its warmup compile first
    start_at = time.time() + 25.0
    procs = [
        ctx.Process(
            target=_fleet_replica_worker,
            args=(
                rid, universe, n_nodes, n_pods, rate, batch, group,
                start_at, out_q, kind, hub_addr, total_devices,
            ),
        )
        for rid in universe
    ]
    for p in procs:
        p.start()
    try:
        results = [out_q.get(timeout=600.0) for _ in procs]
    finally:
        for p in procs:
            p.join(timeout=30.0)
        if server is not None:
            server.stop(grace=None)
    merged = sorted(x for r in results for x in r["completions"])
    scheduled = sum(n for _, n in merged)
    # steady-state: one formula for both arms — drop the first
    # completed batch (compile/ramp residue), divide the rest by the
    # wall from that completion to the last (epoch clocks, one host)
    if len(merged) > 1:
        steady = sum(n for _, n in merged[1:]) / max(
            merged[-1][0] - merged[0][0], 1e-9
        )
    elif merged:
        # a single completed batch has no steady window: report the
        # overall rate from the arrival anchor instead of a
        # divide-by-epsilon headline (review-caught)
        steady = scheduled / max(merged[0][0] - start_at, 1e-3)
    else:
        steady = 0.0
    lats = sorted(x for r in results for x in r["latencies"])
    p99 = lats[int(len(lats) * 0.99)] if lats else 0.0
    return {
        "replicas": n_replicas,
        "kind": kind,
        "tier": "device",
        "mesh_slice_devices": total_devices // max(n_replicas, 1),
        "hub": "grpc" if n_replicas > 1 else "none",
        "pods": n_pods,
        "nodes": n_nodes,
        "arrival_rate_pods_per_sec": rate,
        "scheduled": scheduled,
        "unschedulable": sum(r["unschedulable"] for r in results),
        "fleet_pods_per_sec": round(steady, 1),
        "fleet_p99_pod_latency_s": round(p99, 4),
        "wall_s": round(
            (merged[-1][0] - start_at) if merged else 0.0, 3
        ),
    }


def ladder8_fleet(n_replicas: int = 4) -> dict:
    """#8: fleet A/B — 1-replica vs N-replica sustained throughput at
    the same arrival rate on the same cluster, every replica its own
    OS process. DEVICE-TIER arms (ISSUE 11): the A arm is one process
    streaming against the whole (virtual) device set; the B arm is N
    processes, each ring-shard-scoped, pinned to an EXCLUSIVE 1/N
    mesh slice, stream-dispatching (PR 10) and sharing one occupancy
    hub over localhost gRPC — fenced CAS admits, stage/commit rows,
    and handoff polls all pay the real wire. Arrival rate = ladder
    #6's plain sustained rate, so the two ladders' numbers compose:
    the fleet multiplier applies to the same arrival regime the
    streaming dispatcher is gated on. The acceptance bar (ISSUE 11)
    is fleet_pods_per_sec >= 1.5x the 1-replica device arm."""
    # ladder #6 plain-shape arrival rate (ladder_sustained's shapes
    # table); nodes sized so each replica's shard still outweighs its
    # batch
    shape = dict(n_nodes=1_024, n_pods=16_000, rate=20_000.0)
    single = _fleet_sustained(1, **shape)
    fleet = _fleet_sustained(n_replicas, **shape)
    speedup = round(
        fleet["fleet_pods_per_sec"]
        / max(single["fleet_pods_per_sec"], 1e-9),
        3,
    )
    return {
        "config": (
            f"open-loop sustained arrival at ladder #6 rates, 1 "
            f"process x full device set vs {n_replicas} processes x "
            "exclusive 1/N mesh slices, every replica streaming "
            "(run_streaming) against its shard with ONE gRPC "
            "occupancy hub on localhost"
        ),
        "single": single,
        "fleet": fleet,
        "fleet_pods_per_sec": fleet["fleet_pods_per_sec"],
        "fleet_speedup": speedup,
    }


def ladder9_degraded() -> dict:
    """#9: degraded-mode A/B (kubernetes_tpu/resilience) — sustained
    pods/s at the TOP ladder tier vs the same workload pinned to the
    pure-host serial-greedy rung (ResilienceConfig.force_tier="host"),
    so the cost of full degradation is a measured number, not a guess.
    The host rung is the fallback ladder's floor: what the scheduler
    still delivers when every accelerator tier's breaker is open. The
    shape is kept small — the host rung is O(pods x nodes x plugins)
    Python per batch, and the point is the RATIO, not the absolute."""
    from kubernetes_tpu.resilience import ResilienceConfig

    shape = dict(
        kind="plain", n_nodes=200, n_pods=1_000, rate=8_000.0,
        batch=256, group=64, split=1,
    )
    top = _sustained_shape(mode="pipelined", **shape)
    host = _sustained_shape(
        mode="pipelined",  # force_tier routes every batch through the
        # synchronous resilient cycle either way; keeping the flag
        # equal keeps the arrival/drive loop identical for the A/B
        resilience=ResilienceConfig(force_tier="host"),
        **shape,
    )
    degraded = host["sustained_pods_per_sec"]
    return {
        "config": (
            "open-loop sustained arrival, top ladder tier vs forced "
            "host-greedy tier (ResilienceConfig.force_tier='host'), "
            f"{shape['n_pods']} pods x {shape['n_nodes']} nodes"
        ),
        "top": top,
        "host": host,
        "degraded_pods_per_sec": degraded,
        "degradation_factor": round(
            top["sustained_pods_per_sec"] / max(degraded, 1e-9), 3
        ),
    }


def ladder1_basic() -> dict:
    """#1 via the scheduler_perf YAML-runner code path (SURVEY §4.5)."""
    from kubernetes_tpu.perf.runner import PerfRunner

    ops = [
        {"opcode": "createNodes", "count": 500},
        {"opcode": "createPods", "count": 500, "collectMetrics": True},
    ]
    runner = PerfRunner()
    # warmup on the same shapes, then best of 3 measured runs (tunnel
    # throughput varies between runs on identical executables)
    runner.run_workload("SchedulingBasic", "warmup", ops, {})
    best = None
    run_walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = runner.run_workload("SchedulingBasic", "500Nodes", ops, {})
        wall = time.perf_counter() - t0
        assert res.scheduled == 500, f"#1: {res.scheduled}/500 scheduled"
        run_walls.append(round(wall, 3))
        if best is None or wall < best[0]:
            best = (wall, res)
    wall, res = best
    thr = res.throughput_summary()
    return {
        "note": (
            "500 pods solve as ONE batch, so wall time is bounded below "
            "by a single dispatch+read round trip on the tunnel (~0.2 s "
            "at the canary's RTT) plus host pop/tensorize/bind — this "
            "row measures per-batch latency floor, not sustained "
            "throughput (ladders #2-#4 measure that)"
        ),
        "pods": 500,
        "nodes": 500,
        "pods_per_sec": round(res.measured_pods / res.measure_seconds, 1)
        if res.measure_seconds
        else None,
        "wall_s": round(wall, 3),
        "run_walls_s": run_walls,
        "device_solve_s": round(res.solve_seconds, 3),
        "throughput_summary": thr,
    }


def ladder5_north_star() -> dict:
    """50k x 10k single-shot rebalance: device solve time, steady state."""
    import numpy as np
    import jax.numpy as jnp

    from kubernetes_tpu.solver.single_shot import (
        SingleShotConfig,
        _single_shot_jit,
    )

    rng = np.random.default_rng(0)
    k, c, rc = 3, 8, 8
    alloc = np.zeros((k, NS_NODES), dtype=np.int64)
    alloc[0] = 16_000
    alloc[1] = 64 * 1024**3
    rc_req = np.zeros((rc, k), dtype=np.int64)
    rc_req[:, 0] = rng.integers(1, 9, rc) * 250
    rc_req[:, 1] = rng.integers(1, 5, rc) * 1024**3
    rc_static = (np.arange(rc) % c).astype(np.int32)
    rc_of = rng.integers(0, rc, NS_PODS).astype(np.int32)
    priority = rng.integers(0, 10, NS_PODS).astype(np.int32)
    cfg = SingleShotConfig()

    def fresh():
        return [
            jnp.asarray(x)
            for x in (
                alloc,
                np.zeros((k, NS_NODES), np.int64),
                np.zeros(NS_NODES, np.int32),
                np.full(NS_NODES, 110, np.int32),
                np.ones(NS_NODES, bool),
                np.ones((c, NS_NODES), bool),
                rc_req,
                rc_static,
                rc_of,
                priority,
                np.ones(NS_PODS, bool),
            )
        ]

    kw = dict(
        max_rounds=cfg.max_rounds, price_step=cfg.price_step, top_t=cfg.top_t
    )
    t0 = time.perf_counter()
    out = _single_shot_jit(*fresh(), **kw)
    out[0].block_until_ready()
    compile_s = time.perf_counter() - t0
    # best of 3: the axon tunnel's throughput varies run to run (measured
    # 3x swings on identical executables); min is the honest device time
    solve_s = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = _single_shot_jit(*fresh(), **kw)
        out[0].block_until_ready()
        solve_s = min(solve_s, time.perf_counter() - t0)
    placed = int((np.asarray(out[0]) >= 0).sum())

    # heterogeneous variant (VERDICT r2 #6): 128 request classes x 32
    # static-plugin classes with random selector masks — the [RC, N] dedup
    # memory story at realistic class counts instead of 8 uniform classes
    rc_h, c_h = 128, 32
    rng_h = np.random.default_rng(1)
    static_mask_h = rng_h.random((c_h, NS_NODES)) < 0.6
    rc_req_h = np.zeros((rc_h, k), dtype=np.int64)
    rc_req_h[:, 0] = rng_h.integers(1, 17, rc_h) * 125
    rc_req_h[:, 1] = rng_h.integers(1, 9, rc_h) * (512 * 1024**2)
    rc_static_h = rng_h.integers(0, c_h, rc_h).astype(np.int32)
    rc_of_h = rng_h.integers(0, rc_h, NS_PODS).astype(np.int32)

    def fresh_h():
        return [
            jnp.asarray(x)
            for x in (
                alloc,
                np.zeros((k, NS_NODES), np.int64),
                np.zeros(NS_NODES, np.int32),
                np.full(NS_NODES, 110, np.int32),
                np.ones(NS_NODES, bool),
                static_mask_h,
                rc_req_h,
                rc_static_h,
                rc_of_h,
                priority,
                np.ones(NS_PODS, bool),
            )
        ]

    out_h = _single_shot_jit(*fresh_h(), **kw)
    out_h[0].block_until_ready()
    hetero_s = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out_h = _single_shot_jit(*fresh_h(), **kw)
        out_h[0].block_until_ready()
        hetero_s = min(hetero_s, time.perf_counter() - t0)
    placed_h = int((np.asarray(out_h[0]) >= 0).sum())

    exact = _north_star_exact()

    return {
        "pods": NS_PODS,
        "nodes": NS_NODES,
        "solve_s": round(solve_s, 4),
        "compile_s": round(compile_s, 2),
        "placed": placed,
        "pods_per_sec": round(placed / solve_s, 1),
        "vs_1s_target": round(NS_TARGET_S / solve_s, 2),
        "hetero_rc128_solve_s": round(hetero_s, 4),
        "hetero_rc128_placed": placed_h,
        "hetero_rc128_classes": rc_h,
        "solver": (
            "single_shot auction — documented divergences: not sequential "
            "parity, and scope is resources + static plugins only "
            "(ports/spread/interpod workloads route through the exact "
            "scan, which now meets the <1s target itself)"
        ),
        "quality_vs_exact": _quality_table(),
        **exact,
    }


def _quality_table() -> dict:
    """Auction placement quality vs the exact sequential solver on three
    pre-loaded workload shapes (VERDICT r3 #7): placed count, placed
    priority mass, and the snapshot-headroom objective (sum of the
    auction's base_score over chosen nodes — its own objective, so this
    bounds how much the exact solver's sequential-greedy placements give
    up against it, and vice versa). Scale is cut vs the headline run so
    the table costs seconds, not minutes."""
    import numpy as np

    from kubernetes_tpu.server.bulk import columnar_pod_batch
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.solver.single_shot import SingleShotSolver
    from kubernetes_tpu.tensorize.schema import ResourceVocab, pad_to

    n_nodes, n_pods = 2_048, 8_192
    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    npad = pad_to(n_nodes)
    rng = np.random.default_rng(7)

    def preloaded_nodes():
        alloc = np.zeros((3, npad), np.int64)
        alloc[0, :n_nodes] = 16_000
        alloc[1, :n_nodes] = 64 << 30
        used = np.zeros((3, npad), np.int64)
        # uneven pre-load: 0..8 resident pod-equivalents per node
        load = rng.integers(0, 9, n_nodes)
        used[0, :n_nodes] = load * 1_000
        used[1, :n_nodes] = load * (2 << 30)
        cnt = np.zeros(npad, np.int32)
        cnt[:n_nodes] = load
        return alloc, used, cnt

    def shape(name, rc, cpu_lo, cpu_hi, mem_choices):
        rc_cpu = rng.integers(cpu_lo, cpu_hi, rc) * 125
        rc_mem = rng.choice(mem_choices, rc)
        rc_of = np.sort(rng.integers(0, rc, n_pods))  # class-contiguous
        prio = rng.integers(0, 10, n_pods).astype(np.int32)
        # contiguous classes => a valid FIFO-within-priority queue order
        # for the exact scan AND its grouped fast path
        order = np.lexsort((rc_of, -prio))
        return name, rc_cpu, rc_mem, rc_of[order], prio[order]

    shapes = [
        shape("homog8_preloaded", 8, 8, 9, [2 << 30]),
        shape("hetero_rc128_preloaded", 128, 1, 17, [1 << 30, 2 << 30, 4 << 30]),
        shape("scarce_rc8", 8, 24, 33, [8 << 30]),  # demand > capacity
    ]
    table = {}
    for name, rc_cpu, rc_mem, rc_of, prio in shapes:
        alloc, used, cnt = preloaded_nodes()
        rc = len(rc_cpu)
        rc_req = np.zeros((rc, 3), np.int64)
        rc_req[:, 0] = rc_cpu
        rc_req[:, 1] = rc_mem

        def pod_batch():
            return columnar_pod_batch(
                rc_req[rc_of, 0].copy(), rc_req[rc_of, 1].copy(),
                prio.copy(), vocab,
            )

        # both solvers go through their PUBLIC entry points on the same
        # pre-loaded cluster and queue order — the quality table measures
        # the production code paths, not a hand-marshaled replica
        a_auction = SingleShotSolver().solve(
            _synthetic_node_batch(vocab, n_nodes, alloc, used, cnt),
            pod_batch(),
        )
        solver = ExactSolver(
            ExactSolverConfig(tie_break="random", group_size=256)
        )
        a_exact = solver.solve(
            _synthetic_node_batch(vocab, n_nodes, alloc, used, cnt),
            pod_batch(),
        )

        # snapshot-headroom objective (the auction's own): identical
        # formula for both assignment vectors
        alloc2 = alloc[:2, :].astype(np.float64)
        used2 = used[:2, :].astype(np.float64)
        frac = np.where(alloc2 > 0, (alloc2 - used2) / np.maximum(alloc2, 1), 0)
        base_score = (100.0 * (frac[0] + frac[1]) / 2.0).astype(np.int64)

        def stats(a):
            placed = a >= 0
            return {
                "placed": int(placed.sum()),
                "priority_mass": int(prio[placed].sum()),
                "objective": int(base_score[a[placed]].sum()),
            }

        sa, se = stats(a_auction), stats(a_exact)
        table[name] = {
            "auction": sa,
            "exact": se,
            "placed_ratio": round(sa["placed"] / max(se["placed"], 1), 4),
            "priority_mass_ratio": round(
                sa["priority_mass"] / max(se["priority_mass"], 1), 4
            ),
            "objective_ratio": round(
                sa["objective"] / max(se["objective"], 1), 4
            ),
        }
    return table



def _synthetic_node_batch(vocab, n_nodes, alloc, used=None, cnt=None):
    """One uniform synthetic NodeBatch builder for the bench workloads
    (shared by the exact north star and the quality table)."""
    import numpy as np

    from kubernetes_tpu.tensorize.schema import NodeBatch, pad_to

    npad = pad_to(n_nodes)
    live = np.arange(npad) < n_nodes
    used = np.zeros((3, npad), np.int64) if used is None else used.copy()
    cnt = np.zeros(npad, np.int32) if cnt is None else cnt.copy()
    return NodeBatch(
        vocab=vocab,
        names=[f"n{i}" for i in range(n_nodes)],
        num_nodes=n_nodes,
        padded=npad,
        allocatable=alloc.copy(),
        used=used,
        nonzero_used=used[:2].copy(),
        pod_count=cnt,
        max_pods=np.where(live, 110, 0).astype(np.int32),
        valid=live,
        schedulable=live.copy(),
    )


def _north_star_exact() -> dict:
    """The same 50k x 10k workload through the EXACT-parity grouped scan —
    the honest companion number: full sequential binding semantics at
    north-star scale (the auction's <1s rides a relaxed objective)."""
    import numpy as np

    from kubernetes_tpu.server.bulk import columnar_pod_batch
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.tensorize.schema import NodeBatch, ResourceVocab, pad_to

    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    npad = pad_to(NS_NODES)
    alloc = np.zeros((3, npad), dtype=np.int64)
    alloc[0, :NS_NODES] = 16_000
    alloc[1, :NS_NODES] = 64 << 30

    def fresh_batch():
        return _synthetic_node_batch(vocab, NS_NODES, alloc)

    cpu = np.full(NS_PODS, 1000, np.int64)
    mem = np.full(NS_PODS, 2 << 30, np.int64)
    pb = columnar_pod_batch(cpu, mem, None, vocab)
    # round-4 cost model (scripts/sweep_group.py): solve wall is dominated
    # by per-call transfer costs and nearly flat across the swept group
    # sizes; group=1024 measured best after the single-packed-download
    # rework
    solver = ExactSolver(ExactSolverConfig(tie_break="random", group_size=1024))
    solver.solve(fresh_batch(), pb)  # compile + warm the session shapes
    exact_s = float("inf")
    # min-of-5 (each rep ~1 s): the tunnel's throughput drifts ~2x across
    # minutes, and this row's <1 s target leaves the least headroom
    for _ in range(5):
        # one solve's histogram, not the warmup+reps lifetime total
        solver.dispatch_counts.clear()
        t0 = time.perf_counter()
        a = solver.solve(fresh_batch(), pb)
        exact_s = min(exact_s, time.perf_counter() - t0)
    placed = int((a >= 0).sum())
    assert placed == NS_PODS, f"exact north star placed {placed}/{NS_PODS}"
    # validity gates at full scale (a number only counts if the bindings
    # are right): every pick lands on a live node, and no node exceeds
    # cpu / memory / pod-count capacity under the actual request vectors
    # (weighted bincounts, so the gates survive heterogeneous workloads)
    assert int(a.min()) >= 0 and int(a.max()) < NS_NODES
    assert int(np.bincount(a, minlength=NS_NODES).max()) <= 110
    assert np.bincount(a, weights=cpu.astype(np.float64)).max() <= 16_000
    assert np.bincount(a, weights=mem.astype(np.float64)).max() <= 64 << 30
    # SEQUENTIAL-PARITY replay (the oracle-replay gate at full scale):
    # with identical pods on identical nodes, LeastAllocated AND
    # BalancedAllocation are strictly decreasing in a node's pod count,
    # so the reference tie set at every step is exactly the
    # minimum-count nodes — each of the 51,200 placements must land on
    # a node at the then-minimum count, in emitted order
    # every placement consumes one min-count slot, so the running minimum
    # is simply k // NS_NODES — no carried bookkeeping to desynchronize
    counts = np.zeros(NS_NODES, dtype=np.int64)
    for k, node in enumerate(a):
        assert counts[node] == k // NS_NODES, (
            f"step {k}: node at count {counts[node]}, tie set at "
            f"{k // NS_NODES} — outside the reference tie set"
        )
        counts[node] += 1
    return {
        "exact_parity_solve_s": round(exact_s, 2),
        "exact_parity_pods_per_sec": round(placed / exact_s, 1),
        "exact_parity_vs_1s_target": round(NS_TARGET_S / exact_s, 2),
        "exact_parity_dispatch": "; ".join(
            f"{k}={v}" for k, v in sorted(solver.dispatch_counts.items())
        ),
        "exact_parity_replay": (
            f"all {NS_PODS} placements verified inside the sequential "
            "reference tie set (min-count replay) + capacity gates"
        ),
    }


def ladder10_rebalance_loop() -> dict:
    """#10: the continuous rebalancer (kubernetes_tpu/rebalance) closing
    a seeded fragmented cluster at north-star scale — the A/B is packed
    utilization before vs after the loop runs to convergence.

    The cluster: the 51.2k uniform pods (1 cpu / 2Gi) scattered over the
    10.24k nodes with per-node loads drawn 1..10 (aggregate ~34% packed
    utilization on the cpu-dominant axis against the 70% packing bar,
    bin-packing lower bound ~3.2k nodes). Each cycle runs the REAL
    production pieces — ``detector.detect``, the runtime's drain-source
    gather discipline (emptiest in-use nodes first; the fullest node and
    nodes at the bar are never drained), ``planner.plan_moves`` (the
    pack-objective auction against live load with the drain sources
    masked) and ``planner.select_moves`` (churn budget / strict-gain /
    joint-feasibility bounding) — then applies the selected moves to the
    node tensors, standing in for the evict -> requeue -> re-bind
    migration path that the ``fragmentation`` sim profile and the CI
    smoke prove end to end (PDB gate included) at full fidelity."""
    import numpy as np

    from kubernetes_tpu.api.wrappers import MakePod
    from kubernetes_tpu.rebalance.detector import detect
    from kubernetes_tpu.rebalance.planner import plan_moves, select_moves
    from kubernetes_tpu.tensorize.schema import ResourceVocab, pad_to

    BUDGET = 2_048  # churn budget: evictions per cycle
    BAR = 0.7  # min_packing — the detector's fragmentation threshold
    MAX_CYCLES = 24  # "bounded number of cycles" gate

    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    npad = pad_to(NS_NODES)
    names = [f"n{i}" for i in range(NS_NODES)]
    alloc = np.zeros((3, npad), np.int64)
    alloc[0, :NS_NODES] = 16_000
    alloc[1, :NS_NODES] = 64 << 30

    rng = np.random.default_rng(10)
    loads = rng.integers(1, 11, NS_NODES)
    assert int(loads.sum()) >= NS_PODS
    pod_node = np.repeat(np.arange(NS_NODES), loads)[:NS_PODS].copy()
    prio = rng.integers(0, 10, NS_PODS)
    tmpl = MakePod().name("t").req({"cpu": "1", "memory": "2Gi"}).obj()
    req = np.asarray(vocab.vectorize(tmpl.resource_request()), np.int64)

    used = np.zeros((3, npad), np.int64)
    cnt = np.zeros(npad, np.int32)
    node_counts = np.bincount(pod_node, minlength=NS_NODES)
    cnt[:NS_NODES] = node_counts
    used[:, :NS_NODES] = req[:, None] * node_counts[None, :]
    node_pods: list[list[int]] = [[] for _ in range(NS_NODES)]
    for i, nslot in enumerate(pod_node):
        node_pods[nslot].append(int(i))

    pod_cache: dict[int, object] = {}
    key2idx: dict[str, int] = {}

    def pod_obj(i: int):
        p = pod_cache.get(i)
        if p is None:
            p = (
                MakePod()
                .name(f"pod-{i:06}")
                .priority(int(prio[i]))
                .start_time(float(i))
                .req({"cpu": "1", "memory": "2Gi"})
                .obj()
            )
            pod_cache[i] = p
            key2idx[p.key] = i
        return p

    def fill_pct() -> np.ndarray:
        # detector.packing_score, vectorized: integer dominant-resource
        # fill in percent points
        cpu_f = np.where(alloc[0] > 0, used[0] / np.maximum(alloc[0], 1), 0)
        mem_f = np.where(alloc[1] > 0, used[1] / np.maximum(alloc[1], 1), 0)
        return (100.0 * np.maximum(np.minimum(cpu_f, 1.0), np.minimum(mem_f, 1.0))).astype(np.int64)

    def gather():
        """The runtime's ``_gather`` discipline over the tensors."""
        fill = fill_pct()
        in_use = np.flatnonzero(cnt[:NS_NODES] > 0)
        order = in_use[np.lexsort((in_use, fill[in_use]))]
        bar_pts = int(BAR * 100)
        movable: list[tuple[object, int]] = []
        drains: set[int] = set()
        fixed_used = used.copy()
        fixed_cnt = cnt.copy()
        for slot in order[:-1]:  # never drain the fullest in-use node
            slot = int(slot)
            if len(movable) >= BUDGET or fill[slot] >= bar_pts:
                break
            take = sorted(node_pods[slot], key=lambda i: (prio[i], -i))
            take = take[: BUDGET - len(movable)]
            drains.add(slot)
            for i in take:
                movable.append((pod_obj(i), slot))
                fixed_used[:, slot] = np.maximum(fixed_used[:, slot] - req, 0)
                fixed_cnt[slot] = max(int(fixed_cnt[slot]) - 1, 0)
        return movable, fixed_used, fixed_cnt, frozenset(drains)

    def batch_now():
        return _synthetic_node_batch(vocab, NS_NODES, alloc, used, cnt)

    before = detect(batch_now(), min_packing=BAR)
    plan_walls: list[float] = []
    cycle_evictions: list[int] = []
    for cycle in range(MAX_CYCLES):
        batch = batch_now()
        report = detect(batch, min_packing=BAR)
        if not report.fragmented:
            break
        movable, fixed_used, fixed_cnt, drains = gather()
        if not movable:
            break
        if cycle == 0:
            # compile warm-up: the auction is deterministic, so the
            # discarded result equals the measured one
            plan_moves(batch, movable, fixed_used, fixed_cnt, drains)
        t0 = time.perf_counter()
        raw = plan_moves(batch, movable, fixed_used, fixed_cnt, drains)
        plan_walls.append(time.perf_counter() - t0)
        plan = select_moves(batch, names, raw, [], budget=BUDGET, min_gain=1)
        if not plan.moves:
            break
        assert len(plan.moves) <= BUDGET, "churn budget exceeded"
        cycle_evictions.append(len(plan.moves))
        for mv in plan.moves:
            i = key2idx[mv.pod.key]
            src, dst = mv.source_slot, mv.target_slot
            used[:, src] -= req
            used[:, dst] += req
            cnt[src] -= 1
            cnt[dst] += 1
            node_pods[src].remove(i)
            node_pods[dst].append(i)
            pod_node[i] = dst
    after = detect(batch_now(), min_packing=BAR)

    # validity gates: the A/B only counts if the end state is real —
    # every pod still placed exactly once and no node over capacity
    assert int(cnt[:NS_NODES].sum()) == NS_PODS
    assert np.all(used[0, :NS_NODES] <= alloc[0, :NS_NODES])
    assert np.all(used[1, :NS_NODES] <= alloc[1, :NS_NODES])
    assert not after.fragmented, (
        f"rebalance loop did not converge within {MAX_CYCLES} cycles "
        f"(packed {after.packed_utilization:.3f})"
    )
    gain = after.packed_utilization - before.packed_utilization
    assert gain > 0, "rebalance loop did not improve packed utilization"
    # median over the (post-warm-up) cycles: the steady-state figure —
    # min would let one lucky cycle satisfy the <1 s gate
    solve_s = float(np.median(plan_walls))
    return {
        "pods": NS_PODS,
        "nodes": NS_NODES,
        "churn_budget": BUDGET,
        "min_packing": BAR,
        "packed_utilization_before": round(before.packed_utilization, 4),
        "packed_utilization_after": round(after.packed_utilization, 4),
        "rebalance_utilization_gain": round(gain, 4),
        "nodes_in_use_before": before.nodes_in_use,
        "nodes_in_use_after": after.nodes_in_use,
        "ideal_nodes": before.ideal_nodes,
        "stranded_fraction_before": round(before.stranded_fraction, 4),
        "stranded_fraction_after": round(after.stranded_fraction, 4),
        "cycles": len(cycle_evictions),
        "max_cycles": MAX_CYCLES,
        "evictions_total": sum(cycle_evictions),
        "max_cycle_evictions": max(cycle_evictions, default=0),
        "over_budget_cycles": 0,  # asserted above, every cycle
        "rebalance_plan_solve_s": round(solve_s, 4),
        "plan_solve_max_s": round(max(plan_walls), 4),
        "vs_1s_target": round(NS_TARGET_S / solve_s, 2),
    }


BD_PODS = 512_000
BD_NODES = 102_400


def _backlog_arm(
    n_nodes: int,
    n_pods: int,
    chunk: int,
    mesh_devices: int,
    kind: str = "spread",
    group: int = 512,
    tuning=None,  # TuningConfig: ladder #12's tuned drain arm
) -> dict:
    """One backlog-drain arm: a ``n_pods`` backlog queued against
    ``n_nodes`` nodes, drained end to end through
    ``Scheduler.drain_backlog`` — the HBM-budget-planned, chunk-aligned
    streaming path with cross-batch occupancy chaining (ISSUE 12).
    ``kind='spread'`` keeps a HARD shape in the carry so the chain is
    measured on the occupancy path, not the plain-fit fast case.

    One warmup drain (chunk-sized backlog, same node/pod buckets)
    compiles every executable; the measured pass is a single full
    drain — at 512k pods the drain IS the steady state, so best-of-N
    would only re-pay the 100k-node cluster build."""
    import numpy as np

    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(_mk_node(i))
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=chunk,
            mesh_devices=mesh_devices,
            solver=ExactSolverConfig(tie_break="random", group_size=group),
            tuning=tuning,
        ),
    )
    # warmup: drain a chunk-sized backlog on the SAME cluster (same
    # node padding bucket — a throwaway small cluster would compile the
    # wrong shapes), then delete the placed pods so the measured drain
    # starts from an empty cluster
    for i in range(chunk):
        cs.create_pod(_mk_pod(i, kind))
    sched.drain_backlog(chunk_pods=chunk)
    for p in list(cs.list_pods()):
        cs.delete_pod(p.namespace, p.name)

    t0 = time.perf_counter()
    for i in range(n_pods):
        cs.create_pod(_mk_pod(i, kind))
    enqueue_s = time.perf_counter() - t0

    report = sched.drain_backlog(chunk_pods=chunk)
    assert report.drained == n_pods, (
        f"backlog drain placed {report.drained}/{n_pods}"
    )
    # streaming chain engagement: the drain must measure the resident-
    # carry path, not a silent per-chunk drain-and-retensorize fallback
    assert report.chain_fraction >= 0.5, (
        f"stream chain engaged on only {report.chain_fraction:.0%} of "
        "chunks — the drain fell back to per-chunk retensorize"
    )
    # end-state validity (the ladder-#10 convention): every pod placed
    # at most once with no node overcommitted — weighted bincounts over
    # the actual request vectors
    pods = [p for p in cs.list_pods() if p.name.startswith("pod-")]
    assert len(pods) == n_pods
    nodes_list = cs.list_nodes()
    slot = {n.name: i for i, n in enumerate(nodes_list)}
    a = np.fromiter(
        (slot[p.node_name] for p in pods), dtype=np.int64, count=n_pods
    )
    cnt = np.bincount(a, minlength=n_nodes)
    assert int(cnt.max()) <= 110, "pod-count overcommit"
    assert np.bincount(a, weights=np.full(n_pods, 250.0)).max() <= 16_000
    assert (
        np.bincount(a, weights=np.full(n_pods, 512.0 * 1024**2)).max()
        <= 64 * 1024**3
    )
    if kind == "spread":
        zone_of = np.fromiter(
            (
                int(n.labels["topology.kubernetes.io/zone"][1:])
                for n in nodes_list
            ),
            dtype=np.int64,
            count=len(nodes_list),
        )
        zones = np.bincount(zone_of[a], minlength=3)
        assert int(zones.max() - zones.min()) <= 1, (
            f"zone skew violated at drain scale: {zones.tolist()}"
        )
    return {
        "mesh_devices": mesh_devices,
        "pods": n_pods,
        "nodes": n_nodes,
        "kind": kind,
        "chunk_pods": report.chunk_pods,
        "chunks": report.chunks,
        "budget_splits": report.budget_splits,
        "budget_bytes": report.budget_bytes,
        "estimated_per_device_bytes": report.estimated_per_device_bytes,
        "estimated_h2d_bytes": report.estimated_h2d_bytes,
        "measured_h2d_bytes": report.measured_h2d_bytes,
        "h2d_model_ratio": round(
            report.measured_h2d_bytes
            / max(report.estimated_h2d_bytes, 1),
            3,
        ),
        "backlog_drain_seconds": round(report.drain_seconds, 3),
        "backlog_drain_pods_per_sec": round(report.pods_per_sec, 1),
        "sustained_p99_pod_latency_s": round(
            report.p99_e2e_latency_s, 4
        ),
        "median_chunk_solve_s": round(report.median_chunk_solve_s, 4),
        "stream_chained_batches": report.stream_chained_batches,
        "chain_fraction": round(report.chain_fraction, 4),
        "enqueue_s": round(enqueue_s, 3),
        "dispatch": _dispatch_label(sched),
        "final_chunk_pods": report.final_chunk_pods or report.chunk_pods,
        "tuning": (
            sched.tuner.summary() if sched.tuner is not None else None
        ),
    }


def _backlog_auction(n_nodes: int, n_pods: int) -> dict:
    """The single-shot auction (scarcity repair included) at the 10x
    shape — proves the whole-problem-resident quality path holds at
    512k x 102k, not just the chunked exact drain."""
    import numpy as np
    import jax.numpy as jnp

    from kubernetes_tpu.solver.single_shot import (
        SingleShotConfig,
        _single_shot_jit,
    )

    rng = np.random.default_rng(12)
    k, c, rc = 3, 8, 8
    alloc = np.zeros((k, n_nodes), dtype=np.int64)
    alloc[0] = 16_000
    alloc[1] = 64 * 1024**3
    rc_req = np.zeros((rc, k), dtype=np.int64)
    rc_req[:, 0] = rng.integers(1, 9, rc) * 250
    rc_req[:, 1] = rng.integers(1, 5, rc) * 1024**3
    rc_static = (np.arange(rc) % c).astype(np.int32)
    rc_of = rng.integers(0, rc, n_pods).astype(np.int32)
    priority = rng.integers(0, 10, n_pods).astype(np.int32)
    cfg = SingleShotConfig()
    kw = dict(
        max_rounds=cfg.max_rounds,
        price_step=cfg.price_step,
        top_t=cfg.top_t,
        repair_rounds=cfg.repair_rounds,  # scarcity repair ON at scale
    )

    def fresh():
        return [
            jnp.asarray(x)
            for x in (
                alloc,
                np.zeros((k, n_nodes), np.int64),
                np.zeros(n_nodes, np.int32),
                np.full(n_nodes, 110, np.int32),
                np.ones(n_nodes, bool),
                np.ones((c, n_nodes), bool),
                rc_req,
                rc_static,
                rc_of,
                priority,
                np.ones(n_pods, bool),
            )
        ]

    out = _single_shot_jit(*fresh(), **kw)
    out[0].block_until_ready()  # compile
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        out = _single_shot_jit(*fresh(), **kw)
        out[0].block_until_ready()
        best = min(best, time.perf_counter() - t0)
    placed = int((np.asarray(out[0]) >= 0).sum())
    return {
        "auction_pods": n_pods,
        "auction_nodes": n_nodes,
        "auction_solve_s": round(best, 3),
        "auction_placed": placed,
        "auction_placed_ratio": round(placed / n_pods, 4),
        "auction_repair_rounds": cfg.repair_rounds,
    }


def ladder11_backlog_drain(
    n_nodes: int = BD_NODES,
    n_pods: int = BD_PODS,
    chunk: int = 16_384,
) -> dict:
    """#11: 10x the proven scale — a 512k-pod backlog drained end to
    end against 102,400 nodes through ``Scheduler.drain_backlog``
    (ISSUE 12): the HBM-budget-planned chunked streaming path, with
    cross-batch occupancy chaining keeping the hard-shape carry
    device-resident across the whole drain. A/B: the exact same drain
    on 1 device vs the full node-axis mesh; the auction (scarcity
    repair on) runs once at the same shape. Reports the MEDIAN
    drain-chunk solve time (the ladder-#10 convention) and asserts
    end-state validity + chain engagement in both arms."""
    import jax

    one = _backlog_arm(n_nodes, n_pods, chunk, mesh_devices=1)
    n_dev = len(jax.devices())
    if n_dev >= 2:
        mesh = _backlog_arm(n_nodes, n_pods, chunk, mesh_devices=0)
        headline = mesh
        speedup = round(
            mesh["backlog_drain_pods_per_sec"]
            / max(one["backlog_drain_pods_per_sec"], 1e-9),
            3,
        )
    else:
        mesh = {
            "skipped": (
                f"only {n_dev} device visible; the mesh arm needs a "
                "multi-device node-axis mesh"
            )
        }
        headline = one
        speedup = None
    return {
        "config": (
            f"{n_pods} queued pods drained against {n_nodes} nodes "
            "through drain_backlog: HBM-budget-planned chunks through "
            "the streaming ring, cross-batch occupancy chaining on a "
            "hard (zone-spread) shape, 1-device vs full-mesh A/B; "
            "single-shot auction with scarcity repair at the same "
            "shape"
        ),
        "one_device": one,
        "mesh": mesh,
        "backlog_drain_pods_per_sec": headline[
            "backlog_drain_pods_per_sec"
        ],
        "backlog_drain_seconds": headline["backlog_drain_seconds"],
        "backlog_p99_pod_latency_s": headline[
            "sustained_p99_pod_latency_s"
        ],
        "backlog_mesh_speedup": speedup,
        **_backlog_auction(n_nodes, n_pods),
    }


def ladder12_autotune() -> dict:
    """#12: closed-loop auto-tuning A/B (ISSUE 13) — the SAME workload
    run with static hot-path knobs (the shipped defaults) and with the
    tuning runtime governing them (kubernetes_tpu/tuning), on the two
    shapes whose knobs it owns:

    - sustained streaming arrival (stream_depth + pipeline_split): the
      tuned arm starts at the static arm's exact config and
      hill-climbs with hysteresis + revert-on-regression, so "tuned >=
      static" is structural — a probe that regresses is rolled back
      within one evaluation window;
    - backlog drain (drain chunk size under the HBM budget guardrail):
      every tuner-proposed chunk passes solver/budget.py's per-device
      assertion BEFORE it is applied — the arm asserts ZERO guardrail
      breaches (BudgetExceeded never raised by a tuner-proposed
      shape).

    Hoists tuned_pods_per_sec + tuning_convergence_batches to the JSON
    top level for the driver capture."""
    from kubernetes_tpu.tuning.runtime import TuningConfig

    # controller windows sized so convergence is GUARANTEED inside the
    # measured run: the probe budget bounds an episode at
    # eval_batches * (2 * max_probes + 4) ≈ 36 batches, under the ~47
    # batches the sustained arm pops — so tuning_convergence_batches is
    # a real number, not a still-probing None. Hysteresis 0.15 makes a
    # wall-clock-noise accept rare (a regression must be real)
    def tuned_cfg():
        return TuningConfig(
            eval_batches=3, settle_after=1, hysteresis=0.15,
            max_probes=4,
        )

    # BOTH arms run the SHIPPED defaults (split=0 = the adaptive
    # CounterWindow rule, stream_depth=4): the A/B isolates the closed
    # loop, not a bench-pinned split override neither production
    # default uses. batch=256 over 12k pods gives the controllers
    # enough evaluation windows to settle INSIDE the measured run, so
    # tuning_convergence_batches is a real number, not a still-probing
    # None.
    sus_static = _sustained_shape(
        "plain", 500, 12_000, 20_000.0, mode="streaming", split=0,
        batch=256,
    )
    sus_tuned = _sustained_shape(
        "plain", 500, 12_000, 20_000.0, mode="streaming", split=0,
        batch=256, tuning=tuned_cfg(),
    )
    # best-of-2 per drain arm (symmetric): a full drain is one wall
    # measurement, and two identical runs differ by ±5% on the dev
    # box — best-of keeps the A/B about the config, not the scheduler
    # jitter (the ladder-#7 rep convention)
    def drain_arm(tuning):
        return max(
            (
                _backlog_arm(
                    10_240, 51_200, 4_096, mesh_devices=1,
                    kind="plain", group=512, tuning=tuning,
                )
                for _ in range(2)
            ),
            key=lambda a: a["backlog_drain_pods_per_sec"],
        )

    drain_static = drain_arm(None)
    drain_tuned = drain_arm(tuned_cfg())
    sus_ratio = sus_tuned["sustained_pods_per_sec"] / max(
        sus_static["sustained_pods_per_sec"], 1e-9
    )
    drain_ratio = drain_tuned["backlog_drain_pods_per_sec"] / max(
        drain_static["backlog_drain_pods_per_sec"], 1e-9
    )
    for arm in (sus_tuned, drain_tuned):
        t = arm["tuning"]
        assert t is not None and t["guardrail_breaches"] == 0, (
            f"guardrail breach in the tuned arm: {t}"
        )
    # no-regression gate: revert-on-regression makes the tuned arm's
    # floor the static config; a small tolerance absorbs dev-box
    # wall-clock noise between two independent runs
    assert sus_ratio >= 0.95, (
        f"tuned sustained arm regressed: {sus_ratio:.3f}x static"
    )
    assert drain_ratio >= 0.95, (
        f"tuned drain arm regressed: {drain_ratio:.3f}x static"
    )
    # convergence: the sustained arm's settle point; the drain arm's as
    # the fallback (both are real runs of the same controller config)
    conv = (
        sus_tuned["tuning"]["convergence_batches"]
        or drain_tuned["tuning"]["convergence_batches"]
    )
    return {
        "config": (
            "static-vs-tuned A/B: sustained streaming arrival "
            "(stream_depth + pipeline_split governed) and backlog "
            "drain (chunk size under the HBM budget guardrail); tuned "
            "arms start at the static arms' exact config, hill-climb "
            "with hysteresis, revert on regression, and journal every "
            "move through scheduler_tuning_*"
        ),
        "sustained": {"static": sus_static, "tuned": sus_tuned},
        "drain": {"static": drain_static, "tuned": drain_tuned},
        "tuned_pods_per_sec": sus_tuned["sustained_pods_per_sec"],
        "tuned_vs_static_sustained": round(sus_ratio, 3),
        "tuned_drain_pods_per_sec": drain_tuned[
            "backlog_drain_pods_per_sec"
        ],
        "tuned_vs_static_drain": round(drain_ratio, 3),
        "tuning_convergence_batches": conv,
        "tuned_knobs": sus_tuned["tuning"]["knobs"],
        "tuned_drain_knobs": drain_tuned["tuning"]["knobs"],
        "guardrail_breaches": 0,  # asserted above for both tuned arms
    }


def ladder13_obs_overhead() -> dict:
    """#13: observability-overhead A/B (ISSUE 14) — the SAME sustained
    streaming workload with the FULL obs layer on (spans + bounded
    flight recorder + per-pod decision journal + live SLO engine) vs
    everything off, proving the whole fleet-wide tracing/SLO tentpole
    costs <= 5% sustained throughput. Best-of-3 per arm (the ladder-#7
    rep convention, widened: a 5% bound is inside two independent
    runs' wall-clock noise on the dev box, best-of is what makes the
    A/B about the config).

    Both arms run as a SINGLE-REPLICA fleet over an in-process
    occupancy hub, so the obs-on arm's journal-segment shipping to the
    hub's aggregation surface (the cross-replica explain source) is
    INSIDE the measured window — the overhead number covers tracing +
    SLO + journal shipping, not just the local layer.

    Hoists slo_p99_pod_latency_s (the SLO engine's own live p99 from
    the obs-on arm — the 'are we meeting SLOs right now' number
    measured while the bench ran) and obs_overhead_fraction to the
    JSON top level.

    ISSUE 18 refresh: a THIRD arm re-measures the same workload with
    the full flight-telemetry loop on top of the obs layer —
    continuous per-stage profiler + anomaly sentinel (+ the bundle
    capturer armed, writing nothing) — and the <= 5% budget is
    asserted against THAT arm: the always-on telemetry claim is only
    honest if the whole stack fits the budget, not just the tracing
    half. Also hoists profiler_overhead_fraction (the telemetry arm's
    marginal cost over the obs arm) and anomaly_detection_lag_batches
    (how many batches a production-window sentinel needs to flag a
    50% sustained-throughput collapse — measured offline, where the
    regression is scripted rather than hoped for)."""
    from kubernetes_tpu.fleet import FleetConfig, OccupancyExchange
    from kubernetes_tpu.obs import ObsConfig, SentinelConfig, SloConfig

    def obs_on_cfg():
        return ObsConfig(
            spans=True,
            journal=True,
            # serve-mode bounds: a long-lived process would configure
            # exactly this (the unbounded sim retention is a sim
            # contract, not the production shape)
            journal_capacity=65_536,
            slo=SloConfig(latency_objective_s=30.0),
        )

    def telemetry_cfg():
        # serve --telemetry on top of --obs --slo: profiler + sentinel
        # at production window sizes; the capture ring is armed (the
        # sentinel implies it) but no bundle_dir, so a capture would
        # count without touching disk — exactly the always-on shape
        cfg = obs_on_cfg()
        cfg.profile = True
        cfg.sentinel = SentinelConfig()
        return cfg

    shape = dict(
        kind="plain", n_nodes=500, n_pods=12_000, rate=20_000.0,
        mode="streaming", split=0, batch=256,
    )

    hubs: list = []

    def fleet_cfg():
        # one fresh single-replica fleet + private in-process hub per
        # scheduler build (warmup and measured runs must not share
        # state); single-replica degenerates gracefully — ownership-
        # only admission, no peer rows — and BOTH arms pay it, so the
        # A/B still isolates the obs layer + its hub journal shipping
        hub = OccupancyExchange()
        hubs.append(hub)
        return FleetConfig(replica="r0", replicas=("r0",), exchange=hub)

    def arm(obs_cfg):
        return max(
            (
                _sustained_shape(
                    shape["kind"], shape["n_nodes"], shape["n_pods"],
                    shape["rate"], mode=shape["mode"],
                    split=shape["split"], batch=shape["batch"],
                    obs=obs_cfg, fleet=fleet_cfg,
                )
                for _ in range(3)
            ),
            key=lambda a: a["sustained_pods_per_sec"],
        )

    off = arm(None)
    on = arm(obs_on_cfg())
    tele = arm(telemetry_cfg())
    shipped = sum(len(h.journal_lines()) for h in hubs)
    assert shipped > 0, (
        "the obs-on arm never shipped a journal segment to the hub"
    )
    ratio = on["sustained_pods_per_sec"] / max(
        off["sustained_pods_per_sec"], 1e-9
    )
    overhead = max(1.0 - ratio, 0.0)
    assert on["slo"] is not None, "the obs-on arm must run the SLO engine"
    assert on["obs_volume"]["journal_records"] > 0
    assert overhead <= 0.05, (
        f"observability overhead {overhead:.3f} exceeds the 5% budget "
        f"(on={on['sustained_pods_per_sec']}, "
        f"off={off['sustained_pods_per_sec']} pods/s)"
    )
    # the telemetry arm: full loop on, measured against the SAME off
    # baseline — the <= 5% budget now covers profiler + sentinel too
    tele_ratio = tele["sustained_pods_per_sec"] / max(
        off["sustained_pods_per_sec"], 1e-9
    )
    telemetry_overhead = max(1.0 - tele_ratio, 0.0)
    assert telemetry_overhead <= 0.05, (
        f"flight-telemetry overhead {telemetry_overhead:.3f} exceeds "
        f"the 5% budget (telemetry={tele['sustained_pods_per_sec']}, "
        f"off={off['sustained_pods_per_sec']} pods/s)"
    )
    tsnap = tele["telemetry"]
    assert tsnap is not None and tsnap["profile"]["batches"] > 0, (
        "the telemetry arm's profiler never closed a batch ledger entry"
    )
    # the profiler's marginal cost over the plain obs arm (clamped:
    # best-of-3 noise can leave the richer arm faster)
    profiler_overhead = max(
        1.0
        - tele["sustained_pods_per_sec"]
        / max(on["sustained_pods_per_sec"], 1e-9),
        0.0,
    )
    lag_batches = _anomaly_detection_lag_batches()
    return {
        "config": (
            "obs-overhead A/B on the sustained streaming shape "
            "(12k pods x 500 nodes @ 20k/s, batch 256): spans + "
            "journal + flight recorder + live SLO engine ON vs "
            "everything OFF, best-of-3 per arm, BOTH arms a single-"
            "replica fleet over an in-process occupancy hub so the "
            "on-arm's journal-segment shipping to the hub aggregation "
            "surface is inside the measured window; asserts the whole "
            "layer costs <= 5% sustained throughput"
        ),
        "off": off,
        "on": on,
        "telemetry": tele,
        "obs_overhead_fraction": round(overhead, 4),
        "obs_on_pods_per_sec": on["sustained_pods_per_sec"],
        "obs_off_pods_per_sec": off["sustained_pods_per_sec"],
        "telemetry_overhead_fraction": round(telemetry_overhead, 4),
        "telemetry_pods_per_sec": tele["sustained_pods_per_sec"],
        "profiler_overhead_fraction": round(profiler_overhead, 4),
        "anomaly_detection_lag_batches": lag_batches,
        "profiled_batches": tsnap["profile"]["batches"],
        "slo_p99_pod_latency_s": on["slo"]["p99_pod_latency_s"],
        "slo_healthy": on["slo"]["healthy"],
        "journal_records": on["obs_volume"]["journal_records"],
        "spans": on["obs_volume"]["spans"],
        "hub_journal_lines_shipped": shipped,
    }


def _anomaly_detection_lag_batches() -> int:
    """How many batches the PRODUCTION-window sentinel needs to flag a
    50% sustained-throughput collapse, measured offline: feed a scripted
    healthy baseline through an :class:`AnomalySentinel` at default
    (serve-sized) windows, collapse pods/s by half, and count windows
    until the spike rule fires. Offline because the regression must be
    scripted, not hoped for — the live bench arms are healthy by
    design. Deterministic: pure host arithmetic, no clocks."""
    from kubernetes_tpu.obs.sentinel import AnomalySentinel, SentinelConfig

    cfg = SentinelConfig()
    sentinel = AnomalySentinel(cfg)
    seq = 0

    def window(pods_per_sec: float) -> list:
        nonlocal seq
        seq += 1
        sample = sentinel.ring.append(
            t=float(seq), batches=cfg.window_batches,
            pods=int(pods_per_sec), signals={"pods_per_sec": pods_per_sec},
        )
        return sentinel.observe_window(sample)

    # healthy baseline: enough history for the slow window + warmup
    for _ in range(cfg.slow_windows + cfg.fast_windows + cfg.min_windows):
        assert not window(1000.0), "sentinel fired on a flat baseline"
    # the collapse: count windows until the spike rule fires
    lag_windows = 0
    while True:
        lag_windows += 1
        assert lag_windows <= 100, (
            "sentinel never detected a 50% sustained-throughput collapse"
        )
        if window(500.0):
            break
    return lag_windows * cfg.window_batches


def ladder14_hub_failover() -> dict:
    """#14: hub-failover blackout window (ISSUE 15) — a 2-replica
    fleet drives a plain backlog plus a required-anti-affinity cohort
    (the cross-shard admission path: peer-view fetch, CAS, staleness
    bounds) through the REAL endpoint-failover client against a
    replicated hub pair (primary + standby, op-log replication, shared
    real-time lease), and the primary is KILLED mid-drive. Measures the number the HA tentpole
    exists to bound: wall seconds from the kill to the FIRST
    post-promotion committed admit (promotion latency is lease-expiry
    gated, so the lease duration is the floor), plus the per-pod e2e
    p99 of pods bound inside that window and the admit rate before /
    during / after — proving conservative admission engaged during the
    blackout (staleness bound < blackout: cross-shard-constrained
    placements reject rather than risk overcommit) and full-rate admit
    resumed after it. The resurrected old primary must reject a write
    probe with the typed HubDeposed. Hoists hub_failover_blackout_s
    and hub_failover_p99_latency_s to the JSON top level."""
    from kubernetes_tpu.fleet import (
        FleetConfig,
        HubDeposed,
        HubLease,
        LocalHubClient,
        OccupancyExchange,
        PodRow,
        StandbyReplicator,
    )
    from kubernetes_tpu.fleet.runtime import RemoteOccupancyExchange
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.sim.generators import ZONE_KEY, make_node, make_pod
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState
    from kubernetes_tpu.utils.clock import Clock

    # small stable shapes on purpose: the blackout window is a
    # LATENCY measurement (lease expiry + promotion + re-attach), not
    # a throughput one — a constant-size arrival drip keeps the XLA
    # pad shapes warm after the warmup phase so the window isn't
    # polluted by CPU-backend recompiles
    n_nodes, lease_s = 32, 3.0
    wave_pods, warm_pods = 16, 192
    kill_wave, total_waves = 24, 64
    clock = Clock()
    lease = HubLease(clock=clock, duration_s=lease_s)
    primary = OccupancyExchange(clock=clock, hub_id="hub-a", lease=lease)
    assert primary.try_promote() == 1
    standby = OccupancyExchange(clock=clock, hub_id="hub-b", lease=lease)
    replicator = StandbyReplicator(standby, LocalHubClient(primary))
    cluster = ClusterState()
    for i in range(n_nodes):
        # 3 zones over 2 replicas (the sim's fleet geometry): one
        # replica owns two zones, so a zone-spread pod routed to it
        # has in-shard slack and the hop-capped handoff walk cannot
        # wedge on 2-zone parity
        cluster.create_node(
            make_node(
                f"n{i:04d}", "64", "256Gi", {ZONE_KEY: f"z{i % 3}"}
            )
        )
    universe = ("r0", "r1")
    scheds = {}
    adapters = []
    for rid in universe:
        adapter = RemoteOccupancyExchange(
            "", rid,
            clients=[LocalHubClient(primary), LocalHubClient(standby)],
            clock=clock, flush_client_id=f"{rid}-bench",
        )
        adapters.append(adapter)
        scheds[rid] = Scheduler(
            cluster,
            SchedulerConfig(
                batch_size=wave_pods,
                mesh_devices=1,
                solver=ExactSolverConfig(
                    tie_break="first", group_size=8
                ),
                fleet=FleetConfig(
                    replica=rid, replicas=universe, exchange=adapter,
                    # staleness bound BELOW the lease-gated blackout
                    # (so conservative admission must engage inside
                    # it) but comfortably ABOVE the steady-state drive
                    # cadence — a bound tighter than one real-time
                    # loop iteration reads healthy peers as stale and
                    # starves the spread cohort outright
                    max_row_age_s=2.0,
                ),
            ),
        )
    enq_t: dict[str, float] = {}
    bind_t: dict[str, float] = {}
    seq = {"n": 0}

    def arrive(count):
        now = clock.now()
        for _ in range(count):
            i = seq["n"]
            seq["n"] += 1
            pod = make_pod(
                f"p{i:05d}", "200m",
                # a required-anti-affinity cohort drives the
                # cross-shard admission path (peer-view fetch + CAS +
                # the staleness machinery the blackout test needs)
                # WITHOUT the zone-spread shape: a maxSkew-1 cohort
                # under a deterministic local solver can ping-pong on
                # the global recheck at REAL-clock backoff pace (the
                # PR 6 scope note the virtual-time sims exercise with
                # churn); anti pods are locally enforceable, so the
                # ladder measures failover latency, not that scope
                # note. Cohort sized well under the node count so
                # every pod is satisfiable.
                shape="anti" if i % 64 == 0 else "plain",
            )
            cluster.create_pod(pod)
            enq_t[pod.key] = now

    t_kill = t_promote = t_first_after = None

    def drive():
        nonlocal t_first_after
        before = len(bind_t)
        for rid in universe:
            for r in scheds[rid].run_until_settled(max_batches=4):
                now = clock.now()
                for pod, _node in r.scheduled:
                    bind_t[pod] = now
                    if t_promote is not None and t_first_after is None:
                        t_first_after = now
        if len(bind_t) == before:
            # stalled round: cross-shard-rejected pods park
            # unschedulable and their production retry path is the
            # periodic flush (5 min on the serve loop) — the bench
            # driver ticks it eagerly so the measurement window isn't
            # dominated by a wall-clock park (backoff still applies)
            for rid in universe:
                scheds[rid].queue.move_all_to_active_or_backoff(
                    "BenchFlush"
                )

    # warmup: compile every pad shape the drip will produce (plain +
    # spread batches, the handoff trickle's partial pow2 pads) before
    # the measured window opens
    arrive(warm_pods)
    warm_deadline = time.perf_counter() + 240.0

    def _warm_done():
        # warmup exists to compile the drip's shapes, not to prove
        # completeness (the sim owns that): every PLAIN pod bound and
        # at least one anti pod through the cross-shard admit path
        plain_warm = [
            k for k in enq_t if int(k.rsplit("p", 1)[-1]) % 64 != 0
        ]
        anti_bound = sum(
            1
            for k in bind_t
            if int(k.rsplit("p", 1)[-1]) % 64 == 0
        )
        return (
            all(k in bind_t for k in plain_warm) and anti_bound >= 1
        )

    while not _warm_done() and time.perf_counter() < warm_deadline:
        drive()
        primary.try_promote()
        try:
            replicator.poll()
        except Exception:
            pass
    assert _warm_done(), (
        f"warmup never settled: {len(bind_t)}/{warm_pods} bound"
    )
    deadline = time.perf_counter() + 300.0
    wave = 0
    while (
        wave < total_waves or len(bind_t) < len(enq_t)
    ) and time.perf_counter() < deadline:
        if wave < total_waves:
            arrive(wave_pods)
        wave += 1
        drive()
        if t_kill is None:
            primary.try_promote()  # same-holder lease renew
            try:
                replicator.poll()
            except Exception:
                pass
            if wave >= kill_wave:
                t_kill = clock.now()
                primary.set_down(True)
        elif t_promote is None:
            if standby.try_promote() is not None:
                t_promote = clock.now()
        else:
            standby.try_promote()  # keep the new primary's lease fresh
    n_pods = len(enq_t)
    stale_rejections = sum(
        s.fleet.stale_rejections for s in scheds.values()
    )
    client_failovers = sum(a.failovers for a in adapters)
    # the resurrected old primary: reads serve, writes fence
    primary.set_down(False)
    try:
        primary.stage(
            "r0",
            PodRow(
                pod="probe/p", node="n0000", zone="z0",
                namespace="probe", labels=(("app", "probe"),),
            ),
        )
        stale_write_rejected = False
    except HubDeposed:
        stale_write_rejected = True
    for adapter in adapters:
        try:
            adapter.close()
        except Exception:
            pass
    assert t_kill is not None and t_promote is not None
    assert t_first_after is not None, (
        "no admit ever committed after the promotion — the fleet "
        "never healed"
    )
    # placement-completeness CORRECTNESS is the sim's job (zero lost
    # rows/handoffs under invariants); the ladder's bar is that the
    # failover cost no real capacity: every plain pod binds and the
    # hard-spread cohort stays effectively complete (a straggler
    # waiting out a real-clock backoff at the deadline is latency,
    # not loss)
    unbound = [k for k in enq_t if k not in bind_t]
    assert all(
        int(k.rsplit("p", 1)[-1]) % 64 == 0 for k in unbound
    ), f"plain pods unbound after heal: {unbound[:5]}"
    assert len(bind_t) >= n_pods * 0.99, (
        f"only {len(bind_t)}/{n_pods} pods bound — the failover lost "
        "real capacity"
    )
    assert stale_write_rejected, (
        "the deposed old primary accepted a write probe"
    )
    assert standby.hub_epoch == 2 and standby.role == "primary"
    blackout_s = t_first_after - t_kill
    assert blackout_s < 60.0, f"unbounded blackout: {blackout_s:.1f}s"
    # rate before / after, and the e2e p99 of pods bound in the window
    t0 = min(enq_t.values())
    pre = [t for t in bind_t.values() if t <= t_kill]
    post = [t for t in bind_t.values() if t >= t_first_after]
    pre_rate = len(pre) / max(max(pre) - t0, 1e-9) if pre else 0.0
    post_rate = (
        len(post) / max(max(post) - t_first_after, 1e-9)
        if len(post) > 1
        else 0.0
    )
    window = sorted(
        bound_at - enq_t[pod]
        for pod, bound_at in bind_t.items()
        if t_kill <= bound_at <= t_first_after
    )
    p99_window = (
        window[min(int(len(window) * 0.99), len(window) - 1)]
        if window
        else 0.0
    )
    return {
        "config": (
            f"hub-failover blackout: 2 replicas x {n_pods} pods "
            "(required-anti-affinity cohort for the cross-shard admit "
            f"path, {wave_pods}/wave drip) x "
            f"{n_nodes} nodes over a replicated hub pair (real-time "
            f"lease {lease_s}s, op-log replication, endpoint-failover "
            f"client); primary killed at wave {kill_wave}; staleness "
            "bound 2s (< blackout) so conservative admission engages "
            "mid-blackout"
        ),
        "hub_failover_blackout_s": round(blackout_s, 3),
        "hub_failover_p99_latency_s": round(p99_window, 3),
        "promotion_s": round(t_promote - t_kill, 3),
        "lease_s": lease_s,
        "pods_bound": len(bind_t),
        "pods_unbound_at_deadline": len(unbound),
        "bound_in_window": len(window),
        "pre_kill_pods_per_sec": round(pre_rate, 1),
        "post_heal_pods_per_sec": round(post_rate, 1),
        "stale_rejections": stale_rejections,
        "client_failovers": client_failovers,
        "flush_dedup_hits": (
            primary.flush_dedup_hits + standby.flush_dedup_hits
        ),
        "stale_primary_write_rejected": stale_write_rejected,
        "replication_ops": replicator.ops_applied,
    }


def ladder15_gang() -> dict:
    """#15: gang throughput + time-to-full-gang (ISSUE 17) — a
    DL-training backlog of pod GROUPS (gangs) over an accelerator-
    heterogeneous cluster, driven through the gang gate's park /
    assemble / atomic-commit machinery. Members of every gang arrive
    SPLIT across two waves on purpose: wave 0 parks every half-gang
    (gang_incomplete, zero binds — the all-or-nothing invariant under
    load), wave 1 completes them and the gate re-pulls the parked
    halves via take_for_gang, so the measured window covers the whole
    assembly lifecycle, not just a lucky same-batch arrival. Measures
    gang-member binds/sec end to end, the per-gang time from first
    member creation to the atomic commit (p50/p99 — the number the
    gang gate exists to bound), and the fraction of workload-classed
    pods the heterogeneity throughput term steered onto their fastest
    accelerator class. Asserts zero partial gangs at every
    observation point and exactly one atomic commit per gang. Hoists
    gang_pods_per_sec and gang_time_to_full_p99_s to the JSON top
    level."""
    from kubernetes_tpu import metrics
    from kubernetes_tpu.gang import ACCEL_CLASS_LABEL, GangConfig
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.sim.generators import make_node, make_pod
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    n_nodes, n_gangs, gang_size = 48, 40, 4
    warm_gangs = 24
    classes = ("tpu-v5e", "tpu-v4", "gpu-a100")
    # transformer gangs run fastest on v5e, resnet on v4 — the Gavel-
    # style objective should steer each class to its best accelerator
    # since capacity is deliberately nowhere near binding
    table = {
        "transformer": {"tpu-v5e": 1.0, "tpu-v4": 0.7, "gpu-a100": 0.4},
        "resnet": {"tpu-v5e": 0.7, "tpu-v4": 1.0, "gpu-a100": 0.4},
    }
    best = {"transformer": "tpu-v5e", "resnet": "tpu-v4"}
    cluster = ClusterState()
    accel_of = {}
    for i in range(n_nodes):
        accel = classes[i % len(classes)]
        accel_of[f"n{i:03d}"] = accel
        cluster.create_node(
            make_node(
                f"n{i:03d}", "64", "256Gi", {ACCEL_CLASS_LABEL: accel}
            )
        )
    sched = Scheduler(
        cluster,
        SchedulerConfig(
            batch_size=64,
            mesh_devices=1,
            solver=ExactSolverConfig(tie_break="first", group_size=8),
            gang=GangConfig(
                # assembly gaps here are batch-cadence, not operator
                # timescale: keep the timeout/quarantine machinery far
                # out of the measurement's way
                min_member_timeout=600.0,
                quarantine_after=1_000,
                throughput_weight=8,
                class_throughput=table,
            ),
        ),
    )
    clock = sched.clock
    wc_of: dict[str, str] = {}
    gang_of_pod: dict[str, str] = {}
    created_at: dict[str, float] = {}
    bind_t: dict[str, float] = {}
    seq = {"n": 0}

    def arrive_members(gid: str, wc: str, count: int):
        if gid not in created_at:
            created_at[gid] = clock.now()
        for _ in range(count):
            i = seq["n"]
            seq["n"] += 1
            pod = make_pod(
                f"{gid}-m{i:04d}", "500m",
                gang=gid, gang_min=gang_size, workload_class=wc,
            )
            cluster.create_pod(pod)
            wc_of[pod.key] = wc
            gang_of_pod[pod.key] = f"default/{gid}"

    def drive():
        for r in sched.run_until_settled(max_batches=16):
            now = clock.now()
            for pod, _node in r.scheduled:
                bind_t[pod] = now
        # all-or-nothing at every observation point: a gang is either
        # fully bound or fully pending, never split
        by_gid: dict[str, int] = {}
        for k in bind_t:
            by_gid[gang_of_pod[k]] = by_gid.get(gang_of_pod[k], 0) + 1
        partial = {
            g: c for g, c in by_gid.items() if c != gang_size
        }
        assert not partial, f"partially bound gangs: {partial}"

    # warmup: complete gangs, same 64-batch pad shapes the measured
    # waves produce, so the window isn't polluted by CPU-backend
    # recompiles
    for g in range(warm_gangs):
        arrive_members(f"warm{g:03d}", "transformer", gang_size)
    drive()
    assert len(bind_t) == warm_gangs * gang_size, (
        f"warmup never settled: {len(bind_t)} bound"
    )
    commits0 = metrics.gang_commits_total._value.get()
    bound0 = metrics.gang_bound_pods_total._value.get()
    warm_keys = set(bind_t)
    t0 = clock.now()
    # wave 0: HALF of every gang — the gate must park all of them
    for g in range(n_gangs):
        wc = "transformer" if g % 2 == 0 else "resnet"
        arrive_members(f"g{g:03d}", wc, gang_size // 2)
    drive()
    assert len(bind_t) == len(warm_keys), (
        "a half-assembled gang bound pods"
    )
    # wave 1: the completing halves — take_for_gang re-pulls the
    # parked members and every gang commits atomically
    for g in range(n_gangs):
        wc = "transformer" if g % 2 == 0 else "resnet"
        arrive_members(f"g{g:03d}", wc, gang_size // 2)
    drive()
    wall_s = max(clock.now() - t0, 1e-9)
    n_pods = n_gangs * gang_size
    measured = {k: t for k, t in bind_t.items() if k not in warm_keys}
    assert len(measured) == n_pods, (
        f"only {len(measured)}/{n_pods} gang pods bound"
    )
    commits = int(metrics.gang_commits_total._value.get() - commits0)
    assert commits == n_gangs, (
        f"{commits} atomic commits for {n_gangs} gangs"
    )
    assert (
        metrics.gang_bound_pods_total._value.get() - bound0 == n_pods
    )
    # heterogeneity steering: fraction of measured pods whose node
    # carries their workload class's fastest accelerator
    on_best = sum(
        1
        for k in measured
        if accel_of[cluster.get_pod(*k.split("/")).node_name]
        == best[wc_of[k]]
    )
    best_frac = on_best / n_pods
    assert best_frac > 0.5, (
        f"throughput term never steered: {best_frac:.2f} on best class"
    )
    ttf = sorted(
        max(
            measured[k]
            for k in measured
            if gang_of_pod[k] == f"default/g{g:03d}"
        )
        - created_at[f"g{g:03d}"]
        for g in range(n_gangs)
    )
    p50 = ttf[len(ttf) // 2]
    p99 = ttf[min(int(len(ttf) * 0.99), len(ttf) - 1)]
    return {
        "config": (
            f"{n_gangs} gangs x {gang_size} members over {n_nodes} "
            f"nodes in {len(classes)} accelerator classes; members "
            "split across two arrival waves (park -> assemble -> "
            "atomic commit); heterogeneity throughput term weight "
            f"{sched.config.gang.throughput_weight}"
        ),
        "gang_pods_per_sec": round(n_pods / wall_s, 1),
        "gang_time_to_full_p50_s": round(p50, 3),
        "gang_time_to_full_p99_s": round(p99, 3),
        "gangs_committed": commits,
        "gang_pods_bound": len(measured),
        "partial_gangs": 0,  # asserted after every drive above
        "best_accel_fraction": round(best_frac, 3),
    }


def pallas_microbench() -> dict:
    """The tpuSolver.pallas ladder micro-bench (ISSUE 13 satellite):
    the InterPodAffinity (term, domain) aggregation — jitted
    segment_sum reference vs the wired Pallas kernel
    (domain_counts_padded) — at a zone-topology production shape. On a
    TPU backend this measures the compiled MXU kernel; on CPU the
    kernel necessarily runs in INTERPRET mode, which measures the
    wiring's correctness cost, not kernel speed — reported as such
    (the round-3/round-13 negative results in ops/pallas_kernels.py
    explain why the default stays off)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_tpu.ops.pallas_kernels import (
        domain_counts_padded,
        domain_counts_reference,
    )

    t, n, d_pad = 16, 2_048, 16
    rng = np.random.default_rng(5)
    dom = jnp.asarray(
        rng.integers(-1, d_pad, size=(t, n)).astype(np.int32)
    )
    cnt = jnp.asarray(rng.integers(0, 5, size=(t, n)).astype(np.int32))
    ref = jax.jit(domain_counts_reference, static_argnames=("d_pad",))
    pal = jax.jit(domain_counts_padded, static_argnames=("d_pad",))
    out_ref = np.asarray(ref(dom, cnt, d_pad=d_pad))
    out_pal = np.asarray(pal(dom, cnt, d_pad=d_pad))
    np.testing.assert_array_equal(out_ref, out_pal)

    def best_of(fn, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(dom, cnt, d_pad=d_pad)[0].block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    ref_s = best_of(ref)
    pal_s = best_of(pal)
    backend = jax.default_backend()
    return {
        "shape": f"[{t} terms x {n} nodes] -> [{t} x {d_pad}]",
        "backend": backend,
        "mode": "compiled" if backend == "tpu" else "interpret",
        "segment_sum_s": round(ref_s, 6),
        "pallas_s": round(pal_s, 6),
        "pallas_vs_segment_sum": round(ref_s / max(pal_s, 1e-9), 3),
        "parity": True,  # asserted above
        "note": (
            "wired behind tpuSolver.pallas (default off): see "
            "ops/pallas_kernels.py for the measured x64-lowering and "
            "identity-fast-path negative results that keep the "
            "default"
        ),
    }


def ladder7_multichip() -> dict:
    """#7: multichip A/B — the exact-parity grouped SESSION solve at the
    north-star shape (51,200 x 10,240) on 1 device vs the full node-axis
    mesh, plus the 8x-node shape (~81,920 nodes — the HBM-growth target)
    on the full mesh only. Each timed rep is a fresh device session
    (upload + solve + assignment read), symmetric across both arms; the
    sharded arm must pick bit-identical nodes (the device-count
    invariance contract). Skips cleanly when only one device is
    visible."""
    import jax
    import numpy as np

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {
            "skipped": (
                f"only {n_dev} device visible; the multichip A/B needs a "
                "multi-device mesh (virtual-CPU variant: "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            )
        }

    from kubernetes_tpu.parallel.sharding import node_mesh
    from kubernetes_tpu.server.bulk import columnar_pod_batch
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.tensorize.schema import ResourceVocab, pad_to

    mesh = node_mesh()
    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    cfg = ExactSolverConfig(tie_break="random", group_size=1024)

    def run(n_nodes, n_pods, use_mesh, reps=3):
        npad = pad_to(n_nodes)
        alloc = np.zeros((3, npad), dtype=np.int64)
        alloc[0, :n_nodes] = 16_000
        alloc[1, :n_nodes] = 64 << 30
        cpu = np.full(n_pods, 1000, np.int64)
        mem = np.full(n_pods, 2 << 30, np.int64)
        pb = columnar_pod_batch(cpu, mem, None, vocab)
        m = mesh if use_mesh else None
        cv = np.ones(npad, dtype=np.int64)
        # compile warm (untimed); the timed reps then pay a fresh
        # session upload + solve + read each
        ExactSolver(cfg).solve(
            _synthetic_node_batch(vocab, n_nodes, alloc), pb,
            col_versions=cv, mesh=m,
        )
        best = float("inf")
        a = None
        for _ in range(reps):
            batch = _synthetic_node_batch(vocab, n_nodes, alloc)
            solver = ExactSolver(cfg)
            t0 = time.perf_counter()
            a = solver.solve(batch, pb, col_versions=cv, mesh=m)
            best = min(best, time.perf_counter() - t0)
        a = np.asarray(a)
        placed = int((a >= 0).sum())
        assert placed == n_pods, (
            f"multichip {n_pods}x{n_nodes}: placed {placed}/{n_pods}"
        )
        assert int(a.max()) < n_nodes  # no padding-row bindings
        return best, a

    t1, a1 = run(NS_NODES, NS_PODS, False)
    tn, an = run(NS_NODES, NS_PODS, True)
    # the device-count-invariance contract AT SCALE: the sharded arm must
    # pick bit-identical nodes, or the speedup below is meaningless
    assert np.array_equal(a1, an), (
        "multichip: sharded solve diverged from the 1-device solve"
    )
    t8x, _ = run(NS_NODES * 8, NS_PODS, True, reps=2)
    return {
        "config": (
            "exact grouped session solve, fresh session per rep "
            "(upload+solve+read), min over reps; A/B at the north-star "
            "shape, 8x-node shape on the full mesh"
        ),
        "devices": n_dev,
        "pods": NS_PODS,
        "nodes": NS_NODES,
        "solve_1dev_s": round(t1, 3),
        "solve_mesh_s": round(tn, 3),
        "multichip_pods_per_sec": round(NS_PODS / tn, 1),
        "multichip_speedup": round(t1 / tn, 2),
        "bit_invariant_vs_1dev": True,  # asserted above
        "nodes_8x": NS_NODES * 8,
        "solve_8x_nodes_mesh_s": round(t8x, 3),
        "latency_ratio_8x_vs_1x": round(t8x / tn, 2),
    }


def served_grpc() -> dict:
    """Ladder #2's workload THROUGH THE WIRE: columnar pod batch over the
    bulk gRPC boundary (SyncNodes + Solve), measuring end-to-end wire
    pods/s including framing, transport, tensorize, and the device solve."""
    import numpy as np

    from kubernetes_tpu.server.bulk import BulkClient, BulkCore, make_grpc_server
    from kubernetes_tpu.state.cluster import ClusterState

    cs = ClusterState()
    core = BulkCore(cs)
    server, port = make_grpc_server(core, port=0)
    server.start()
    try:
        client = BulkClient(f"127.0.0.1:{port}")
        client.sync_nodes(
            names=[f"n{i:05}" for i in range(1_000)],
            cpu_milli=[16_000] * 1_000,
            mem_bytes=[64 << 30] * 1_000,
            max_pods=[110] * 1_000,
        )
        cpu = np.full(5_000, 250, np.int64)
        mem = np.full(5_000, 512 << 20, np.int64)
        client.solve(cpu_milli=cpu, mem_bytes=mem)  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            meta, arrays = client.solve(cpu_milli=cpu, mem_bytes=mem)
            best = min(best, time.perf_counter() - t0)
        placed = int((arrays["assignments"] >= 0).sum())
        assert placed == 5_000, f"served: {placed}/5000 placed"
        client.close()
    finally:
        server.stop(grace=None)
    return {
        "config": "ladder #2 workload over the bulk gRPC boundary",
        "pods": 5_000,
        "nodes": 1_000,
        "wire_round_trip_s": round(best, 3),
        "pods_per_sec": round(5_000 / best, 1),
    }


MP_PODS = 2_000_000
MP_NODES = 200_000


def _megaplan_tensors(n_nodes: int, n_pods: int, seed: int = 12):
    """The _backlog_auction synthetic recipe with a heterogeneous node
    preload: the pack objective needs a fill gradient (an empty cluster
    scores every node identically and the objective ratio would be
    0/0). Returns the raw solver tensors + the per-node integer pack
    score both engines' placements are valued under."""
    import numpy as np

    rng = np.random.default_rng(seed)
    k, c, rc = 3, 8, 8
    alloc = np.zeros((k, n_nodes), dtype=np.int64)
    alloc[0] = 16_000
    alloc[1] = 64 * 1024**3
    load = rng.integers(0, 9, n_nodes)
    used = np.zeros((k, n_nodes), dtype=np.int64)
    used[0] = load * 1_000
    used[1] = load * (2 * 1024**3)
    cnt = load.astype(np.int32)
    rc_req = np.zeros((rc, k), dtype=np.int64)
    rc_req[:, 0] = rng.integers(1, 9, rc) * 250
    rc_req[:, 1] = rng.integers(1, 5, rc) * 1024**3
    rc_static = (np.arange(rc) % c).astype(np.int32)
    rc_of = rng.integers(0, rc, n_pods).astype(np.int32)
    priority = rng.integers(0, 10, n_pods).astype(np.int32)
    headroom = (
        100.0
        * (
            (alloc[0] - used[0]) / np.maximum(alloc[0], 1)
            + (alloc[1] - used[1]) / np.maximum(alloc[1], 1)
        )
        / 2.0
    ).astype(np.int64)
    pack_score = 100 - headroom
    return {
        "alloc": alloc,
        "used": used,
        "cnt": cnt,
        "max_pods": np.full(n_nodes, 110, np.int32),
        "node_valid": np.ones(n_nodes, bool),
        "static_mask": np.ones((c, n_nodes), bool),
        "rc_req": rc_req,
        "rc_static": rc_static,
        "rc_of": rc_of,
        "priority": priority,
        "pod_valid": np.ones(n_pods, bool),
        "pack_score": pack_score,
    }


def ladder16_megaplan(
    n_nodes: int = BD_NODES, n_pods: int = BD_PODS
) -> dict:
    """#16: the convex-relaxation mega-planner (ISSUE 19) vs the
    auction at the PLAN posture (plan_auction_config: pack objective,
    top_t=8, no repair phase — exactly what rebalance/planner.py
    dispatches), on one preloaded heterogeneous 512k x 102.4k shape:

    - wall time: the relaxed solve (dual ascent + deterministic
      rounding, one jitted program) must beat the auction's plan solve
      by >= 10x — the headline the planner's "auto" engine routing is
      justified by;
    - quality: the relax+round plan, tail-repaired through the SAME
      plan auction config, must value >= 0.95 of the auction plan
      under the shared integer pack score;
    - scale: a 2M-pod x 200k-node relaxed solve, pre-checked against
      the solver/budget.py HBM model (relax_estimate under the device
      budget, assert_index_headroom with the relax rc lane), completes
      with end-state validity asserted — the shape past the auction's
      planning ceiling."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from kubernetes_tpu.rebalance.planner import plan_auction_config
    from kubernetes_tpu.solver import budget as hbm
    from kubernetes_tpu.solver.budget import assert_index_headroom
    from kubernetes_tpu.solver.relax import RelaxConfig, _relax_jit
    from kubernetes_tpu.solver.single_shot import _single_shot_jit

    rcfg = RelaxConfig(objective="pack")
    acfg = plan_auction_config()
    akw = dict(
        max_rounds=acfg.max_rounds,
        price_step=acfg.price_step,
        top_t=acfg.top_t,
        repair_rounds=acfg.repair_rounds,
        pack=True,
    )

    def relax_call(ts):
        # used/pod_count are donated — fresh device arrays per call
        return _relax_jit(
            jnp.asarray(ts["alloc"]),
            jnp.asarray(ts["used"]),
            jnp.asarray(ts["cnt"]),
            jnp.asarray(ts["max_pods"]),
            jnp.asarray(ts["node_valid"]),
            jnp.asarray(ts["static_mask"]),
            jnp.asarray(ts["rc_req"]),
            jnp.asarray(ts["rc_static"]),
            jnp.asarray(ts["rc_of"]),
            jnp.asarray(ts["priority"]),
            jnp.asarray(ts["pod_valid"]),
            jnp.float32(rcfg.tol),
            jnp.float32(rcfg.temp),
            jnp.float32(rcfg.step),
            max_iters=rcfg.max_iters,
            pack=True,
        )

    def auction_call(ts):
        return _single_shot_jit(
            jnp.asarray(ts["alloc"]),
            jnp.asarray(ts["used"]),
            jnp.asarray(ts["cnt"]),
            jnp.asarray(ts["max_pods"]),
            jnp.asarray(ts["node_valid"]),
            jnp.asarray(ts["static_mask"]),
            jnp.asarray(ts["rc_req"]),
            jnp.asarray(ts["rc_static"]),
            jnp.asarray(ts["rc_of"]),
            jnp.asarray(ts["priority"]),
            jnp.asarray(ts["pod_valid"]),
            **akw,
        )

    def timed(fn, ts):
        fn(ts)[0].block_until_ready()  # compile
        best, out = float("inf"), None
        for _ in range(2):
            t0 = time.perf_counter()
            out = fn(ts)
            out[0].block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best, out

    ts = _megaplan_tensors(n_nodes, n_pods)
    auction_s, a_out = timed(auction_call, ts)
    relax_s, r_out = timed(relax_call, ts)
    a_assigned = np.asarray(a_out[0])
    r_assigned = np.asarray(r_out[0])

    # tail repair through the SAME plan auction config, against the
    # post-rounding occupancy (the RelaxSolver wiring, on raw tensors)
    tail = r_assigned < 0
    repair_s = 0.0
    if tail.any():
        t0 = time.perf_counter()
        rep = _single_shot_jit(
            jnp.asarray(ts["alloc"]),
            r_out[1],  # used after rounding (donated onward)
            r_out[2],  # pod_count after rounding
            jnp.asarray(ts["max_pods"]),
            jnp.asarray(ts["node_valid"]),
            jnp.asarray(ts["static_mask"]),
            jnp.asarray(ts["rc_req"]),
            jnp.asarray(ts["rc_static"]),
            jnp.asarray(ts["rc_of"]),
            jnp.asarray(ts["priority"]),
            jnp.asarray(tail),
            **akw,
        )
        rep[0].block_until_ready()
        repair_s = time.perf_counter() - t0
        r_assigned = np.where(tail, np.asarray(rep[0]), r_assigned)

    def objective(assigned):
        placed = assigned >= 0
        return int(ts["pack_score"][assigned[placed]].sum()), int(
            placed.sum()
        )

    obj_a, placed_a = objective(a_assigned)
    obj_r, placed_r = objective(r_assigned)
    ratio = obj_r / max(obj_a, 1)
    speedup = auction_s / max(relax_s, 1e-9)
    # the perf bar is defined AT the ladder shape (the auction's round
    # count — and so the gap — grows with scale); debug downscales
    # still report both numbers but only the real shape enforces them
    if n_pods >= BD_PODS and n_nodes >= BD_NODES:
        assert speedup >= 10.0, (
            f"relax plan solve only {speedup:.1f}x faster than the "
            f"auction's ({relax_s:.3f}s vs {auction_s:.3f}s)"
        )
    assert ratio >= 0.95, (
        f"post-repair pack objective ratio {ratio:.4f} < 0.95 "
        f"({obj_r} vs {obj_a})"
    )

    # -- the 2M-pod arm: budget-model pre-check, then the solve --
    n_dev = len(jax.devices())
    est = hbm.relax_estimate(
        MP_NODES, MP_PODS, rc=8, mesh_devices=n_dev
    )
    budget = hbm.device_budget_bytes(0)
    assert est.per_device_bytes <= budget, (
        f"2M-pod relax shape over budget: {est.per_device_bytes} B "
        f"per device vs {budget} B"
    )
    assert_index_headroom(est.pod_pad, est.node_pad, rc_pad=est.rc_pad)
    ts2 = _megaplan_tensors(MP_NODES, MP_PODS, seed=13)
    mp_s, mp_out = timed(relax_call, ts2)
    mp_assigned = np.asarray(mp_out[0])
    placed_mp = mp_assigned >= 0
    # end-state validity at 2M: every placement on a real node, no
    # resource or pod-count overcommit (weighted bincounts over the
    # actual per-class request vectors)
    assert mp_assigned[placed_mp].min(initial=0) >= 0
    assert mp_assigned.max() < MP_NODES
    req_pod = ts2["rc_req"][ts2["rc_of"]]
    for kk in range(2):
        load_k = np.bincount(
            mp_assigned[placed_mp],
            weights=req_pod[placed_mp, kk].astype(np.float64),
            minlength=MP_NODES,
        )
        free_k = (ts2["alloc"][kk] - ts2["used"][kk]).astype(np.float64)
        assert (load_k <= free_k + 0.5).all(), f"resource {kk} overcommit"
    cnt_load = np.bincount(mp_assigned[placed_mp], minlength=MP_NODES)
    assert (
        cnt_load + ts2["cnt"] <= ts2["max_pods"]
    ).all(), "pod-count overcommit"
    mp_rate = MP_PODS / max(mp_s, 1e-9)

    return {
        "config": (
            f"plan posture A/B at {n_pods} pods x {n_nodes} preloaded "
            "nodes: pack-objective plan auction (top_t=8, no repair "
            "phase) vs the convex relaxation (dual ascent + "
            "deterministic rounding, one jitted program) with the "
            "same auction config repairing the integrality tail; "
            f"then a {MP_PODS}-pod x {MP_NODES}-node relaxed solve "
            "under the HBM budget model with end-state validity"
        ),
        "pods": n_pods,
        "nodes": n_nodes,
        "auction_plan_seconds": round(auction_s, 3),
        "relax_plan_seconds": round(relax_s, 3),
        "relax_plan_speedup": round(speedup, 1),
        "relax_repair_seconds": round(repair_s, 3),
        "relax_objective_ratio": round(ratio, 4),
        "auction_placed": placed_a,
        "relax_placed": placed_r,
        "relax_iterations": int(r_out[6]),
        "relax_residual": round(float(r_out[7]), 5),
        # converged duals, aggregated: the autoscaler cost signal —
        # nonzero mean = the shape is contended somewhere
        "dual_price_mean": round(
            float(
                (np.asarray(r_out[4]).sum(axis=0) + np.asarray(r_out[5]))
                .mean()
            ),
            3,
        ),
        "megaplan": {
            "pods": MP_PODS,
            "nodes": MP_NODES,
            "relax_solve_seconds": round(mp_s, 3),
            "megaplan_pods_per_sec": round(mp_rate, 1),
            "placed": int(placed_mp.sum()),
            "placed_ratio": round(float(placed_mp.mean()), 4),
            "iterations": int(mp_out[6]),
            "residual": round(float(mp_out[7]), 5),
            "estimated_per_device_bytes": est.per_device_bytes,
            "budget_bytes": budget,
            "end_state_valid": True,  # asserted above
        },
        "megaplan_pods_per_sec": round(mp_rate, 1),
    }


def _fleet_drain_worker(
    rid: str,
    universe: tuple,
    n_nodes: int,
    pod_idx,
    chunk: int,
    start_at: float,
    out_q,
    hub_addr: str = "",
    total_devices: int = 8,
) -> None:
    """One fleet-drain replica as its own OS process (spawn target).

    B arm (len(universe) > 1): builds its replica of the state service
    holding ONLY the pods the coordinator's plan routed near it (its
    base partition + the whole residual cohort — any replica may end up
    the residual's serialized claimant), then loops
    ``Scheduler.fleet_drain_backlog`` — claim a hub drain lease, drain
    it through this replica's own slot ring, complete it — until the
    hub ledger reports the global drain complete. A arm (singleton
    universe): the classic sole-owner ``drain_backlog`` over the whole
    backlog in one process with the whole device set — same worker,
    same env/affinity/warmup idiom, so the A/B is process-shape only.

    Reports its (pod_index, node_index) binds so the parent can merge
    the fleet's end state and assert validity: every pod bound exactly
    once (no pod lost, none double-drained), no node overcommitted."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={total_devices}"
        ).strip()
    if len(universe) > 1:
        # disjoint core slices per replica (the ladder-#8 fairness
        # rule): a real fleet runs replicas on separate hosts, so the
        # same-box A/B is a hardware split, not oversubscription
        try:
            cores = sorted(os.sched_getaffinity(0))
            n = len(universe)
            rank = universe.index(rid)
            share = max(len(cores) // n, 1)
            mine = cores[rank * share : (rank + 1) * share] or cores
            os.sched_setaffinity(0, mine)
        except (AttributeError, OSError):
            pass  # non-Linux: let the OS schedule
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from kubernetes_tpu.fleet import FleetConfig
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver import budget as hbm
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    rank = universe.index(rid)
    fleet_mode = len(universe) > 1
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(_mk_node(i))
    fleet = (
        FleetConfig(
            replica=rid,
            replicas=universe,
            hub_address=hub_addr,
            cas_domain=True,  # leg c: domain-scoped CAS opt-in
        )
        if fleet_mode
        else None
    )
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=chunk,
            mesh_slice=(rank, len(universe)),
            solver=ExactSolverConfig(tie_break="random", group_size=512),
            fleet=fleet,
        ),
    )
    # multi-process replicas own EXCLUSIVE device slices, so each
    # replica's drain should plan against the full per-device budget;
    # fleet_drain_backlog splits by fleet size unconditionally (the
    # co-hosted sim/test shape), so pre-multiply to undo the split
    budget = hbm.device_budget_bytes(0) * max(len(universe), 1)

    # warmup: compile the chunk-size drain bucket on the SAME node
    # padding bucket. In fleet mode the ring routes only ~1/N of
    # created pods to this replica's queue, so seed chunk*N*2 pods
    # (indices offset past the measured backlog — warmup keys must
    # never collide with the hub ledger's), then delete them all
    base = 10_000_000  # offset: never a backlog index
    warm = chunk * (2 * len(universe) if fleet_mode else 1)
    for j in range(warm):
        cs.create_pod(_mk_pod(base + j, "plain"))
    sched.drain_backlog(chunk_pods=chunk, budget_bytes=budget)
    for p in list(cs.list_pods()):
        cs.delete_pod(p.namespace, p.name)

    # the measured backlog: ONLY this worker's plan slice (plus the
    # shared residual cohort in fleet mode) — the coordinator already
    # partitioned the 512k backlog, shipping every pod to every
    # replica is exactly the redundancy the fleet drain removes
    my_keys = set()
    for i in pod_idx:
        pod = _mk_pod(i, "plain")
        my_keys.add(f"{pod.namespace}/{pod.name}")
        cs.create_pod(pod)

    while time.time() < start_at:
        time.sleep(0.001)
    t_last = time.time()
    drained = 0
    cas_conflicts0 = _bench_counter_value("fleet_admit_cas_conflict_total")
    stalled = ""
    if fleet_mode:
        idle = 0
        while True:
            out = sched.fleet_drain_backlog(
                chunk_pods=chunk, budget_bytes=budget, plan_keys=my_keys
            )
            if out["drained"]:
                drained += out["drained"]
                t_last = time.time()
                idle = 0
            if any(x["remaining"] for x in out["leases"]):
                stalled = f"lease stranded {out['leases']}"
                break
            st = sched.fleet.exchange.drain_status()
            if st.get("complete"):
                break
            idle += 1
            if idle > 600:  # ~30 s of claim-nothing polls: deadlock
                stalled = f"no claimable lease, ledger {st}"
                break
            time.sleep(0.05)
    else:
        rep = sched.drain_backlog(chunk_pods=chunk, budget_bytes=budget)
        drained = rep.drained
        t_last = time.time()
    binds = [
        (int(p.name[4:]), int(p.node_name[5:]))
        for p in cs.list_pods()
        if p.node_name and p.name.startswith("pod-")
    ]
    out_q.put(
        {
            "rid": rid,
            "drained": drained,
            "t_done": t_last,
            "binds": binds,
            "stalled": stalled,
            "cas_conflicts": (
                _bench_counter_value("fleet_admit_cas_conflict_total")
                - cas_conflicts0
            ),
        }
    )


def _bench_counter_value(name: str) -> float:
    """Best-effort read of a kubernetes_tpu counter metric's current
    value (0.0 when the metric does not exist or the registry backend
    hides samples) — bench reporting only, never an assertion input."""
    try:
        from kubernetes_tpu import metrics as m

        counter = getattr(m, name)
        return float(counter._value.get())  # prometheus_client Counter
    except Exception:
        return 0.0


def _domain_cas_ab(n_admits: int = 4_096, zones: int = 8) -> dict:
    """Leg-c measure-first micro A/B: the SAME interleaving — every
    admit races one label-free peer write in a DIFFERENT zone — under
    the hub-wide CAS vs the domain-scoped CAS
    (``compare_and_stage(..., domain_scope=True)``). The hub-wide
    compare charges every one of these admits a re-fetch round for an
    interleaving that provably cannot touch its admission; the domain
    compare charges none of them."""
    from kubernetes_tpu.fleet import (
        AdmitConflict,
        NodeRow,
        OccupancyExchange,
        PENDING,
        PodRow,
    )

    def row(pod: str, z: int, state=PENDING) -> PodRow:
        return PodRow(
            pod=pod, node=f"n{z}", zone=f"z{z}", namespace="default",
            labels=(), state=state,
        )

    out = {}
    for scope in (False, True):
        hub = OccupancyExchange()
        hub.publish_nodes(
            "r0", [NodeRow(f"n{z}", f"z{z}") for z in range(zones)]
        )
        hub.publish_nodes("r1", [NodeRow(f"nx{zones}", "z0")])
        conflicts = 0
        t0 = time.perf_counter()
        for i in range(n_admits):
            z = i % zones
            v = hub.version
            # the interleaved peer write: label-free, NEXT zone over
            hub.stage("r1", row(f"default/peer-{i}", (z + 1) % zones))
            try:
                hub.compare_and_stage(
                    "r0", row(f"default/adm-{i}", z), v,
                    domain_scope=scope,
                )
            except AdmitConflict:
                conflicts += 1
                hub.stage("r0", row(f"default/adm-{i}", z))
        dt = time.perf_counter() - t0
        out["domain" if scope else "full"] = {
            "admits": n_admits,
            "cas_conflicts": conflicts,
            "seconds": round(dt, 3),
        }
    out["conflict_rounds_avoided"] = (
        out["full"]["cas_conflicts"] - out["domain"]["cas_conflicts"]
    )
    return out


def ladder17_fleet_drain(
    n_replicas: int = 4,
    n_nodes: int = BD_NODES,
    n_pods: int = BD_PODS,
    chunk: int = 16_384,
) -> dict:
    """#17: the FLEET-tier backlog drain (ISSUE 20) at the ladder-#11
    shape — the same 512k-pod backlog against 102,400 nodes, drained
    by 1 process vs N replica processes coordinated through the hub's
    drain-lease ledger. The parent plays coordinator: one global relax
    plan (ISSUE 19) over the backlog, partitioned by planned-node ring
    owner (``fleet/drain.py``) with every 512th pod forced cross-shard
    into the serialized residual cohort, registered at a REAL gRPC
    occupancy hub via ``drain_init``. Each B-arm replica process
    builds only its slice of the backlog, claims epoch-fenced drain
    leases, and drains them through its own slot ring under its own
    HBM budget (``cas_domain`` on — leg c). The parent merges every
    worker's binds and asserts fleet-wide end-state validity: all
    ``n_pods`` bound exactly once (lost=0, double_bind=0), no node
    overcommitted. The >= 1.5x fleet speedup bar is enforced AT the
    ladder shape (debug downscales report, full scale gates)."""
    import multiprocessing

    import numpy as np

    from kubernetes_tpu.fleet import OccupancyExchange, drain
    from kubernetes_tpu.fleet.ring import HashRing, ring_nodes_from
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.server.bulk import BulkCore, make_grpc_server
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    universe = tuple(f"r{i}" for i in range(n_replicas))

    # -- the coordinator's planning half: one global relax plan ------
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(_mk_node(i))
    planner = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=chunk,
            solver=ExactSolverConfig(tie_break="random", group_size=512),
        ),
    )
    keys = []
    for i in range(n_pods):
        pod = _mk_pod(i, "plain")
        keys.append(f"{pod.namespace}/{pod.name}")
        cs.create_pod(pod)
    t0 = time.perf_counter()
    plan = planner.relax_plan_backlog()
    plan_s = time.perf_counter() - t0
    assignment = HashRing(universe).assign(
        ring_nodes_from(cs.list_nodes())
    )
    # every 512th pod plays the constrained cross-shard shape: the
    # partitioner sends it to the residual cohort, whose ONE
    # serialized lease keeps the fenced-CAS admit semantics intact
    partitions, residual = drain.partition_backlog(
        keys, plan, assignment,
        cross_shard=lambda k: int(k.rsplit("-", 1)[1]) % 512 == 0,
    )
    key_to_idx = {k: i for i, k in enumerate(keys)}
    part_idx = {
        rid: [key_to_idx[k] for k in ks]
        for rid, ks in partitions.items()
    }
    residual_idx = [key_to_idx[k] for k in residual]
    del cs, planner, plan, key_to_idx  # free before the fleet runs

    # -- the hub: a real gRPC occupancy exchange, ledger installed ---
    exchange = OccupancyExchange()
    core = BulkCore(ClusterState(), exchange=exchange)
    server, hub_port = make_grpc_server(core, port=0)
    server.start()
    hub_addr = f"127.0.0.1:{hub_port}"
    exchange.drain_init("r0", partitions, residual)

    ctx = multiprocessing.get_context("spawn")
    out_q = ctx.Queue()

    def run_arm(arm_universe: tuple) -> list:
        start_at = time.time() + 40.0  # clear every warmup compile
        procs = []
        for rid in arm_universe:
            idx = (
                sorted(part_idx.get(rid, []) + residual_idx)
                if len(arm_universe) > 1
                else list(range(n_pods))
            )
            procs.append(
                ctx.Process(
                    target=_fleet_drain_worker,
                    args=(
                        rid, arm_universe, n_nodes, idx, chunk,
                        start_at, out_q, hub_addr, 8,
                    ),
                )
            )
        for p in procs:
            p.start()
        try:
            results = [out_q.get(timeout=1_800.0) for _ in procs]
        finally:
            for p in procs:
                p.join(timeout=30.0)
        return [start_at, results]

    try:
        # B first (the ledger is armed and single-use per drain_init);
        # then the A arm reuses the same worker with a singleton
        # universe — no fleet, no hub, whole backlog, whole device set
        b_start, b_results = run_arm(universe)
        a_start, a_results = run_arm(("r0",))
    finally:
        server.stop(grace=None)

    for r in b_results + a_results:
        assert not r["stalled"], f"{r['rid']}: {r['stalled']}"

    # -- merged fleet end state: every pod bound EXACTLY once --------
    merged = [b for r in b_results for b in r["binds"]]
    a = np.array([b[0] for b in merged], dtype=np.int64)
    nd = np.array([b[1] for b in merged], dtype=np.int64)
    assert len(np.unique(a)) == len(a), "a pod drained twice (double bind)"
    lost = n_pods - len(a)
    assert lost == 0, f"{lost} backlog pod(s) ended unbound fleet-wide"
    cnt = np.bincount(nd, minlength=n_nodes)
    assert int(cnt.max()) <= 110, "pod-count overcommit"
    assert np.bincount(nd, weights=np.full(len(nd), 250.0)).max() <= 16_000
    assert (
        np.bincount(nd, weights=np.full(len(nd), 512.0 * 1024**2)).max()
        <= 64 * 1024**3
    )

    st = exchange.drain_status()
    b_done = max(r["t_done"] for r in b_results)
    b_wall = max(b_done - b_start, 1e-9)
    fleet_rate = n_pods / b_wall
    a_wall = max(a_results[0]["t_done"] - a_start, 1e-9)
    single_rate = a_results[0]["drained"] / a_wall
    speedup = fleet_rate / max(single_rate, 1e-9)
    # the perf bar is defined AT the ladder shape (ladder-#16 rule):
    # debug downscales report both arms but only full scale enforces
    if n_pods >= BD_PODS and n_nodes >= BD_NODES:
        assert speedup >= 1.5, (
            f"fleet drain only {speedup:.2f}x over the sole-owner "
            f"drain ({fleet_rate:.0f} vs {single_rate:.0f} pods/s)"
        )
    return {
        "config": (
            f"{n_pods}-pod backlog x {n_nodes} nodes: one global "
            "relax plan partitioned by planned-node ring owner, "
            f"drained by {n_replicas} replica processes claiming "
            "epoch-fenced hub drain leases (gRPC hub, domain-scoped "
            "CAS on, every 512th pod serialized through the residual "
            "cohort) vs the same backlog through one sole-owner "
            "drain_backlog process; merged end-state validity "
            "asserted fleet-wide"
        ),
        "replicas": n_replicas,
        "pods": n_pods,
        "nodes": n_nodes,
        "chunk_pods": chunk,
        "plan_seconds": round(plan_s, 3),
        "partition_sizes": {
            rid: len(ix) for rid, ix in sorted(part_idx.items())
        },
        "residual_pods": len(residual_idx),
        "single": {
            "drained": a_results[0]["drained"],
            "wall_s": round(a_wall, 3),
            "pods_per_sec": round(single_rate, 1),
        },
        "fleet": {
            "drained": sum(r["drained"] for r in b_results),
            "bound": len(a),
            "wall_s": round(b_wall, 3),
            "fleet_drain_pods_per_sec": round(fleet_rate, 1),
            "leases": st.get("leases", 0),
            "leases_reassigned": st.get("reassigned", 0),
            "ledger_complete": bool(st.get("complete")),
            "cas_conflicts": sum(
                r["cas_conflicts"] for r in b_results
            ),
            "per_replica_drained": {
                r["rid"]: r["drained"] for r in b_results
            },
        },
        "fleet_drain_pods_per_sec": round(fleet_rate, 1),
        "fleet_drain_speedup": round(speedup, 3),
        "lost": lost,
        "double_bind": 0,  # asserted above (unique pod indices)
        "domain_cas": _domain_cas_ab(),
        "end_state_valid": True,  # asserted above
    }


def main() -> None:
    import jax

    # jax 0.9 + axon ignores the JAX_ENABLE_X64 env var; resource arithmetic
    # is int64 (memory bytes overflow int32), so set it via config.
    jax.config.update("jax_enable_x64", True)
    from kubernetes_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    # tunnel canary: the axon client defers execution until the first
    # device->host read, after which every sync costs ~1 RTT (~0.1 s).
    # Record the trivial-dispatch time before and after the first read so
    # the regime every number below was measured in is explicit.
    import numpy as _np
    import jax.numpy as _jnp

    _triv = jax.jit(lambda x: x * 3 + 1)
    _x = _jnp.arange(8)
    _triv(_x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(5):
        _triv(_x).block_until_ready()
    pre_read_ms = (time.perf_counter() - t0) / 5 * 1e3
    _np.asarray(_triv(_x))  # first D2H read: switches to sync mode
    t0 = time.perf_counter()
    for _ in range(5):
        _triv(_x).block_until_ready()
    rtt_ms = (time.perf_counter() - t0) / 5 * 1e3

    ladders = {}
    ladders["1_basic_500x500"] = {
        "config": "SchedulingBasic, default plugins, YAML-runner path",
        **ladder1_basic(),
    }
    # batch sizes: measured sweet spots — every ladder runs as ONE solve
    # call (pods-per-sync is the tunnel's first-order knob; since the
    # compact-wire rework the padding chunks of a 16384 bucket cost
    # nearly nothing, so 1x16384 beats 3x4096 for the 10k-pod spread row
    # by ~1.5x)
    ladders["2_fit_5kx1k"] = {
        "config": "Fit+BalancedAllocation, homogeneous",
        **_run_ladder(1_000, 5_000, "plain", batch=8_192, warm_pods=5_000),
    }
    ladders["3_spread_10kx5k"] = {
        "config": "PodTopologySpread hard maxSkew=1, 3 zones",
        **_run_ladder(5_000, 10_000, "spread", batch=16_384, warm_pods=10_000),
    }
    ladders["4_interpod_5kx5k"] = {
        "config": "InterPodAffinity required hostname anti-affinity",
        **_run_ladder(5_000, 5_000, "anti", batch=8_192, warm_pods=5_000),
    }
    ladders["5_rebalance_50kx10k"] = {
        "config": "global rebalance, single batched auction solve",
        **ladder5_north_star(),
    }
    sustained = ladder_sustained()
    ladders["6_sustained_arrival"] = {
        "config": (
            "open-loop sustained arrival, sync-vs-pipelined-vs-"
            "streaming A/B/C per shape; hard shapes (ports/spread/"
            "anti) run through run_pipelined's occupancy-carrying "
            "sub-batch split AND run_streaming's device-resident "
            "cross-batch chain; rtt_attribution rows break deferred "
            "reads into hidden vs paid"
        ),
        **sustained,
    }
    multichip = ladder7_multichip()
    ladders["7_multichip"] = multichip
    fleet = ladder8_fleet()
    ladders["8_fleet"] = fleet
    degraded = ladder9_degraded()
    ladders["9_degraded"] = degraded
    backlog = ladder11_backlog_drain()
    ladders["11_backlog_drain"] = backlog
    autotune = ladder12_autotune()
    ladders["12_autotune"] = autotune
    obs_overhead = ladder13_obs_overhead()
    ladders["13_obs_overhead"] = obs_overhead
    hub_failover = ladder14_hub_failover()
    ladders["14_hub_failover"] = hub_failover
    gang = ladder15_gang()
    ladders["15_gang"] = gang
    megaplan = ladder16_megaplan()
    ladders["16_megaplan"] = megaplan
    fleet_drain = ladder17_fleet_drain()
    ladders["17_fleet_drain"] = fleet_drain
    ladders["pallas_domain_counts"] = pallas_microbench()
    rebalance = ladder10_rebalance_loop()
    ladders["10_rebalance_loop"] = {
        "config": (
            "continuous rebalancer A/B on a seeded fragmented "
            "51.2k x 10.24k cluster: detector + drain gather + "
            "pack-auction plan + budget/gain/PDB-bounded selection "
            "per cycle, loop run to detector convergence"
        ),
        **rebalance,
    }
    ladders["served_grpc_5kx1k"] = served_grpc()
    ladders["tunnel"] = {
        "pre_first_read_dispatch_ms": round(pre_read_ms, 3),
        "post_first_read_dispatch_ms": round(rtt_ms, 1),
        "note": (
            "axon defers execution until the first device->host read; "
            "after it every host<->device sync costs ~1 tunnel RTT. All "
            "ladder numbers above include per-batch assignment reads, "
            "i.e. they are post-first-read (sync-mode) numbers."
        ),
    }

    headline = ladders["2_fit_5kx1k"]["pods_per_sec"]
    # headline sustained pair (the pipelined open-loop plain shape):
    # sustained pods/s and per-pod e2e p99 under queueing
    sus_head = sustained["plain"]["pipelined"]
    print(
        json.dumps(
            {
                "metric": (
                    "pods scheduled/sec, BASELINE ladder #2 (5k pods x 1k "
                    "nodes, full default plugin pipeline, warm start, "
                    "end-to-end); all six ladder rows in `ladders`"
                ),
                "value": headline,
                "unit": "pods/s",
                "sustained_pods_per_sec": sus_head[
                    "sustained_pods_per_sec"
                ],
                "sustained_p99_pod_latency_s": sus_head[
                    "sustained_p99_pod_latency_s"
                ],
                # ladder #6 streaming hoist (ISSUE 10): the streaming
                # dispatcher's plain-shape sustained p99 and its p99
                # speedup over the PR 4 pipelined arm (the >= 2x gate),
                # plus the amortized un-hidden reads per batch (the
                # per-event-fence RTT floor; < 1.0 means the per-batch
                # floor fell)
                "streaming_p99_pod_latency_s": sustained["plain"][
                    "streaming"
                ]["sustained_p99_pod_latency_s"],
                "streaming_speedup": sustained["plain"][
                    "streaming_p99_speedup_vs_pipelined"
                ],
                "streaming_unhidden_reads_per_batch": sustained[
                    "plain"
                ]["streaming_unhidden_reads_per_batch"],
                # ladder #7 hoist: real numbers when a mesh ran, the skip
                # reason string when only one device is visible
                "multichip_pods_per_sec": multichip.get(
                    "multichip_pods_per_sec",
                    multichip.get("skipped"),
                ),
                "multichip_speedup": multichip.get(
                    "multichip_speedup", multichip.get("skipped")
                ),
                # ladder #8 hoist: N-replica fleet sustained throughput
                # and its speedup over the 1-replica arm
                "fleet_pods_per_sec": fleet["fleet_pods_per_sec"],
                "fleet_speedup": fleet["fleet_speedup"],
                # ladder #9 hoist: sustained pods/s on the fallback
                # ladder's pure-host floor — what degraded mode costs
                "degraded_pods_per_sec": degraded[
                    "degraded_pods_per_sec"
                ],
                # ladder #10 hoist: packed-utilization gain the
                # rebalance loop recovered on the seeded fragmented
                # north-star cluster, and its steady-state plan solve
                "rebalance_utilization_gain": rebalance[
                    "rebalance_utilization_gain"
                ],
                "rebalance_plan_solve_s": rebalance[
                    "rebalance_plan_solve_s"
                ],
                # ladder #11 hoist (ISSUE 12): the 10x-scale backlog
                # drain — 512k pods against 102,400 nodes through the
                # HBM-budget-planned chunked streaming path — end-to-
                # end drain rate and wall time (mesh arm when a mesh
                # ran, 1-device otherwise)
                "backlog_drain_pods_per_sec": backlog[
                    "backlog_drain_pods_per_sec"
                ],
                "backlog_drain_seconds": backlog[
                    "backlog_drain_seconds"
                ],
                # ladder #12 hoist (ISSUE 13): the auto-tuned sustained
                # streaming arm — tuned >= static asserted inside the
                # ladder (revert-on-regression makes the static config
                # the tuned arm's floor), convergence in batches, zero
                # guardrail breaches asserted
                "tuned_pods_per_sec": autotune["tuned_pods_per_sec"],
                "tuning_convergence_batches": autotune[
                    "tuning_convergence_batches"
                ],
                # ladder #13 hoist (ISSUE 14): what the whole obs
                # layer (fleet-wide tracing + journal + SLO engine)
                # costs on the sustained stream, asserted <= 5% inside
                # the ladder, and the SLO engine's own live p99 from
                # the obs-on arm
                "slo_p99_pod_latency_s": obs_overhead[
                    "slo_p99_pod_latency_s"
                ],
                "obs_overhead_fraction": obs_overhead[
                    "obs_overhead_fraction"
                ],
                # ladder #13 refresh (ISSUE 18): the full flight-
                # telemetry loop's cost on the same stream — profiler +
                # sentinel on top of the obs layer, asserted <= 5%
                # inside the ladder — the profiler's marginal cost over
                # the plain obs arm, and how many batches the
                # production-window sentinel needs to flag a 50%
                # sustained-throughput collapse (scripted offline)
                "profiler_overhead_fraction": obs_overhead[
                    "profiler_overhead_fraction"
                ],
                "anomaly_detection_lag_batches": obs_overhead[
                    "anomaly_detection_lag_batches"
                ],
                # ladder #14 hoist (ISSUE 15): the hub-failover
                # blackout window — wall seconds from the primary-hub
                # kill to the first post-promotion committed admit
                # (conservative admission engaged during it, full-rate
                # admit after it, asserted inside the ladder) — and
                # the e2e p99 of pods bound inside that window
                "hub_failover_blackout_s": hub_failover[
                    "hub_failover_blackout_s"
                ],
                "hub_failover_p99_latency_s": hub_failover[
                    "hub_failover_p99_latency_s"
                ],
                # ladder #15 hoist (ISSUE 17): gang-member binds/sec
                # through the gang gate's park/assemble/atomic-commit
                # path (split-wave arrivals, zero partial gangs and
                # one commit per gang asserted inside the ladder) and
                # the per-gang first-member-to-commit p99
                "gang_pods_per_sec": gang["gang_pods_per_sec"],
                "gang_time_to_full_p99_s": gang[
                    "gang_time_to_full_p99_s"
                ],
                # ladder #16 hoist (ISSUE 19): the convex-relaxation
                # mega-planner — relaxed plan solve wall time at the
                # 512k x 102.4k plan shape (>= 10x over the auction's
                # plan solve asserted inside the ladder), the post-
                # repair pack objective ratio vs the auction plan
                # (>= 0.95 asserted), and the 2M-pod global plan rate
                # under the HBM budget with end-state validity
                "relax_plan_seconds": megaplan["relax_plan_seconds"],
                "relax_objective_ratio": megaplan[
                    "relax_objective_ratio"
                ],
                "megaplan_pods_per_sec": megaplan[
                    "megaplan_pods_per_sec"
                ],
                # ladder #17 hoist (ISSUE 20): the fleet-tier backlog
                # drain — the 512k backlog partitioned by the global
                # relax plan and drained by N replica processes
                # claiming epoch-fenced hub drain leases — the merged
                # fleet drain rate and its speedup over the
                # sole-owner drain_backlog arm (>= 1.5x asserted
                # inside the ladder, with fleet-wide end-state
                # validity: every pod bound exactly once)
                "fleet_drain_pods_per_sec": fleet_drain[
                    "fleet_drain_pods_per_sec"
                ],
                "fleet_drain_speedup": fleet_drain[
                    "fleet_drain_speedup"
                ],
                "vs_baseline": round(headline / BAND_TOP_PODS_PER_SEC, 2),
                "baseline_note": (
                    "vs_baseline divides by the TOP of the reference's "
                    "in-proc band (5k pods/s); vs_api_bound uses the "
                    "~300 pods/s sustained API-bound figure"
                ),
                "vs_api_bound": round(headline / API_BOUND_PODS_PER_SEC, 2),
                "ladders": ladders,
            }
        )
    )


if __name__ == "__main__":
    main()
