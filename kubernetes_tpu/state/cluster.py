"""In-memory cluster-state service — the [BOUNDARY] stand-in for
apiserver + etcd (SURVEY.md §8.3).

What it emulates (and what the scheduler actually exercises of the real
thing):
- typed Pod/Node storage with a single monotonically-increasing
  resourceVersion stream (etcd revision equivalent);
- optimistic concurrency: updates carrying a stale resourceVersion are
  rejected with Conflict, like apiserver's 409s;
- watch streams: subscribers receive ADDED/MODIFIED/DELETED events in
  commit order, like client-go Reflector/informers (delivery is synchronous
  in-process — the informer layer of SURVEY §3.3 collapses to an event bus);
- the **pods/{name}/binding subresource**
  (pkg/registry/core/pod/storage/storage.go#BindingREST.Create): atomically
  sets spec.nodeName on a still-unbound pod; rejects if the pod is gone,
  already bound, or the target node doesn't exist — the reject paths the
  scheduler's assume/forget protocol must survive;
- fault injection hooks (bind_fault) so tests can simulate conflicts and
  node disappearance mid-cycle (SURVEY §6.3).
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Callable, Iterable, Literal

from ..api.objects import (
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
)

EventType = Literal["ADDED", "MODIFIED", "DELETED"]


class ApiError(Exception):
    def __init__(self, reason: str, message: str = "", fenced: bool = False):
        # Conflict | NotFound | AlreadyExists | Invalid | TooManyRequests
        # (429: the eviction subresource's PDB-exhausted rejection)
        self.reason = reason
        # True when a Conflict came from the fencing-token check: the
        # caller's fence token is revoked/superseded (it is a zombie).
        # A typed flag, not a message-prefix contract, so rewording the
        # message cannot silently break the scheduler's classification.
        self.fenced = fenced
        super().__init__(f"{reason}: {message}")


@dataclass
class Event:
    type: EventType
    kind: str  # "Pod" | "Node" | "Event"
    obj: object  # Pod | Node | EventRecord
    resource_version: int


Watcher = Callable[[Event], None]


@dataclass
class EventRecord:
    """events.k8s.io/v1 Event analog (the scheduler's operator-facing
    history: staging/src/k8s.io/api/events/v1/types.go#Event). The
    broadcaster's correlator dedup collapses repeats of the same
    (regarding, reason, note) into one record with a bumped count, like
    the reference's EventAggregator."""

    namespace: str
    regarding_kind: str  # "Pod" | "Node"
    regarding_namespace: str
    regarding_name: str
    reason: str  # Scheduled | FailedScheduling | Preempted | Nominated...
    note: str
    type: str = "Normal"  # Normal | Warning
    action: str = "Scheduling"
    reporting_controller: str = "kubernetes-tpu-scheduler"
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    name: str = ""  # generated: <regarding>.<seq>
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def to_dict(self) -> dict:
        """events.k8s.io/v1 wire shape."""
        return {
            "apiVersion": "events.k8s.io/v1",
            "kind": "Event",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "resourceVersion": str(self.resource_version),
            },
            "regarding": {
                "kind": self.regarding_kind,
                "namespace": self.regarding_namespace,
                "name": self.regarding_name,
            },
            "reason": self.reason,
            "note": self.note,
            "type": self.type,
            "action": self.action,
            "reportingController": self.reporting_controller,
            "deprecatedCount": self.count,
            "deprecatedFirstTimestamp": self.first_timestamp,
            "deprecatedLastTimestamp": self.last_timestamp,
        }


class ClusterState:
    """In-memory store guarded by one RLock (``self.lock``), the analog of
    the reference's mutex-guarded cache (SURVEY §6.2). The serve path
    mutates it from three threads (aiohttp event loop ingest, the scheduler
    drain executor, gRPC workers); every public method takes the lock, and
    watch callbacks fire under it so subscriber state (queue/cache) updates
    are serialized with the writes that caused them. The Scheduler holds
    the same lock across a whole schedule_batch, which makes its
    pop -> solve -> bind cycle atomic with respect to ingest."""

    def __init__(self, clock=None) -> None:
        from ..utils.clock import Clock

        self.lock = threading.RLock()
        # event timestamps (TTL sweeps, first/lastTimestamp) come off an
        # injectable clock so the sim's virtual timeline covers the state
        # service too; callers that pass explicit timestamps (the
        # scheduler's recorder) are unaffected
        self.clock = clock or Clock()
        self._rv = 0
        self._pods: dict[str, Pod] = {}  # key = ns/name
        self._nodes: dict[str, Node] = {}
        self._pdbs: dict[str, PodDisruptionBudget] = {}
        self._pvs: dict[str, PersistentVolume] = {}
        self._pvcs: dict[str, PersistentVolumeClaim] = {}
        self._services: dict[str, object] = {}
        # DRA (resource.k8s.io subset, api/dra.py): keyed by name (slices,
        # classes are cluster-scoped) / ns-name (claims). dra_generation
        # bumps on every DRA-object write so the allocator's base-context
        # cache invalidates exactly when the inventory/claims change.
        self._resource_slices: dict[str, object] = {}
        self._device_classes: dict[str, object] = {}
        self._resource_claims: dict[str, object] = {}
        self.dra_generation = 0
        # coordination.k8s.io Leases (leader election)
        self._leases: dict[str, object] = {}
        self._events: dict[str, EventRecord] = {}
        self._events_by_agg: dict[tuple, EventRecord] = {}
        self._event_seq = 0
        self.event_ttl = 3600.0  # reference --event-ttl default
        self._events_sweep_at = 256  # next TTL size-sweep threshold
        self._events_last_sweep = 0.0
        # (watcher, optional event filter) pairs — see subscribe()
        self._watchers: list[tuple[Watcher, Callable[[Event], bool] | None]] = []
        # fault injection: called with (pod, node_name) before a bind commits;
        # raise ApiError to simulate apiserver-side rejection
        self.bind_fault: Callable[[Pod, str], None] | None = None
        # fencing tokens (the classic lease-epoch pattern, server-side):
        # role -> the currently valid token. grant_fence bumps and hands
        # out a fresh token; revoke_fence bumps WITHOUT handing it out,
        # so every outstanding token for the role goes stale. A bind
        # carrying a stale token is rejected with Conflict — the commit
        # path's zombie fence (a scheduler incarnation that lost its
        # lease or was superseded can never land a bind).
        self._fences: dict[str, int] = {}
        self._fence_holders: dict[str, str] = {}
        # role -> rejected-commit count (the sim's zombie invariant
        # asserts 100% of a fenced incarnation's commits land here)
        self.fence_rejections: dict[str, int] = {}

    # -- watch plumbing --

    def subscribe(self, w: Watcher, filter: Callable[[Event], bool] | None = None) -> None:
        """Register a watcher, optionally behind a server-side event
        filter — the analog of an apiserver field-selector watch. The
        fleet tier subscribes each scheduler replica with its
        shard-filter predicate (fleet/runtime.py#event_filter) so a
        replica's informer stream — and therefore its cache — covers
        exactly the nodes and pods its shard owns. Filters run under
        the cluster lock in commit order, like the watchers they
        guard."""
        self._watchers.append((w, filter))

    def unsubscribe(self, w: Watcher) -> None:
        """Remove a watcher (bound methods compare equal by func +
        instance, so ``unsubscribe(obj.handler)`` works). The sim's
        fault harness uses this to interpose a delayed/duplicating
        delivery bus between the state service and the scheduler."""
        for i, (cb, _flt) in enumerate(self._watchers):
            if cb == w:
                del self._watchers[i]
                return
        raise ApiError("NotFound", "watcher not subscribed")

    def _emit(self, etype: EventType, kind: str, obj: Pod | Node) -> None:
        """Deliver one event to every subscriber. Delivery is ISOLATED:
        an exception in one subscriber's filter or callback is caught
        and counted (scheduler_watch_delivery_error_total) so it can
        neither prevent delivery to the remaining subscribers nor
        corrupt the event sequence (the rv was committed before any
        delivery started). The mutation that emitted the event has
        already landed — swallowing a subscriber's crash here is the
        informer-relay contract, not data loss."""
        from .. import metrics

        ev = Event(etype, kind, obj, self._rv)
        for w, flt in list(self._watchers):
            try:
                if flt is None or flt(ev):
                    w(ev)
            except Exception:
                metrics.watch_delivery_error_total.inc()
                import logging

                logging.getLogger("kubernetes_tpu.cluster").exception(
                    "watch subscriber raised during %s %s delivery "
                    "(rv %d); remaining subscribers still served",
                    etype, kind, self._rv,
                )

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    @property
    def resource_version(self) -> int:
        return self._rv

    # -- pods --

    def create_pod(self, pod: Pod) -> Pod:
        if pod.key in self._pods:
            raise ApiError("AlreadyExists", pod.key)
        pod.resource_version = self._next_rv()
        self._pods[pod.key] = pod
        self._emit("ADDED", "Pod", pod)
        return pod

    def get_pod(self, namespace: str, name: str) -> Pod:
        key = f"{namespace}/{name}"
        try:
            return self._pods[key]
        except KeyError:
            raise ApiError("NotFound", key) from None

    def update_pod(self, pod: Pod, expect_rv: int | None = None) -> Pod:
        cur = self.get_pod(pod.namespace, pod.name)
        if expect_rv is not None and cur.resource_version != expect_rv:
            raise ApiError("Conflict", f"{pod.key} rv {cur.resource_version} != {expect_rv}")
        pod.resource_version = self._next_rv()
        self._pods[pod.key] = pod
        self._emit("MODIFIED", "Pod", pod)
        return pod

    def patch_pod_status(
        self, namespace: str, name: str, *, nominated_node_name: str | None = None,
        phase: str | None = None
    ) -> Pod:
        pod = self.get_pod(namespace, name)
        if nominated_node_name is not None:
            pod.nominated_node_name = nominated_node_name
        if phase is not None:
            pod.phase = phase
        pod.resource_version = self._next_rv()
        self._emit("MODIFIED", "Pod", pod)
        return pod

    def delete_pod(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        pod = self._pods.pop(key, None)
        if pod is None:
            raise ApiError("NotFound", key)
        self._next_rv()
        self._emit("DELETED", "Pod", pod)
        # DRA deallocating-controller stand-in ([BOUNDARY]): a deleted pod
        # leaves every claim's reservedFor; a claim nobody reserves loses
        # its allocation, freeing the devices (the resourceclaim
        # controller's deallocation, collapsed into the state service)
        if pod.resource_claim_names:
            for cname in pod.resource_claim_names:
                c = self._resource_claims.get(f"{namespace}/{cname}")
                if c is None or key not in c.reserved_for:
                    continue
                c.reserved_for = tuple(
                    k for k in c.reserved_for if k != key
                )
                if not c.reserved_for:
                    c.allocated_node = ""
                    c.results = ()
                c.resource_version = self._next_rv()
                self.dra_generation += 1
                self._emit("MODIFIED", "ResourceClaim", c)

    def list_pods(self) -> list[Pod]:
        return list(self._pods.values())

    # -- fencing tokens (commit-path zombie fence) --

    def grant_fence(self, role: str, holder: str = "") -> int:
        """Issue a fresh fencing token for ``role`` (a lease identity:
        the scheduler's leader lease, a fleet replica's per-shard
        lease). Granting invalidates every previously issued token for
        the role — a new incarnation taking over automatically fences
        its predecessor. Models the lease epoch committed at the
        apiserver; callers pass the token back on bind()."""
        token = self._fences.get(role, 0) + 1
        self._fences[role] = token
        self._fence_holders[role] = holder
        return token

    def revoke_fence(self, role: str) -> None:
        """Invalidate the role's current token WITHOUT granting a new
        one: every outstanding holder is fenced until someone re-grants
        (re-acquires the lease). The fleet calls this when a peer's
        lease goes stale — the membership change is committed HERE, at
        the authority, so a partitioned zombie that can still reach the
        state service finds its commits rejected."""
        self._fences[role] = self._fences.get(role, 0) + 1
        self._fence_holders[role] = ""

    def fence_valid(self, role: str, token: int) -> bool:
        return self._fences.get(role) == token

    def bind(
        self,
        namespace: str,
        name: str,
        node_name: str,
        fence: "tuple[str, int] | None" = None,
    ) -> None:
        """POST pods/{name}/binding — the commit point of a scheduling
        cycle. ``fence`` = (role, token) from grant_fence: a stale
        token is rejected with Conflict before anything else is
        examined — a fenced (lease-lost, partitioned, or superseded)
        incarnation can never land a commit, no matter what its stale
        cache believes about ownership."""
        if fence is not None:
            role, token = fence
            if not self.fence_valid(role, token):
                self.fence_rejections[role] = (
                    self.fence_rejections.get(role, 0) + 1
                )
                raise ApiError(
                    "Conflict",
                    f"fenced: token {token} for role {role!r} is no "
                    f"longer valid (current "
                    f"{self._fences.get(role)}); the incarnation lost "
                    "its lease or was superseded",
                    fenced=True,
                )
        pod = self.get_pod(namespace, name)
        if pod.node_name:
            raise ApiError("Conflict", f"{pod.key} already bound to {pod.node_name}")
        if node_name not in self._nodes:
            raise ApiError("NotFound", f"node {node_name}")
        if self.bind_fault is not None:
            self.bind_fault(pod, node_name)
        pod.node_name = node_name
        pod.resource_version = self._next_rv()
        self._emit("MODIFIED", "Pod", pod)

    def bind_gang(
        self,
        bindings: "list[tuple[str, str, str]]",
        fence: "tuple[str, int] | None" = None,
    ) -> None:
        """All-or-nothing bind of a pod group: ``bindings`` is a list
        of (namespace, name, node_name). EVERY precondition — the
        fencing token (checked once, the whole gang shares one commit
        epoch), each pod's existence and unbound state, each target
        node's existence, and the injected ``bind_fault`` hook per
        pair — is validated BEFORE the first mutation, so a rejection
        anywhere leaves the store byte-identical and no partial gang
        can ever land. Models one transactional apiserver request (the
        co-scheduler's PodGroup bind); the watch bus sees the same
        per-pod MODIFIED events a sequence of single binds would
        emit, in binding order."""
        if fence is not None:
            role, token = fence
            if not self.fence_valid(role, token):
                self.fence_rejections[role] = (
                    self.fence_rejections.get(role, 0) + 1
                )
                raise ApiError(
                    "Conflict",
                    f"fenced: token {token} for role {role!r} is no "
                    f"longer valid (current "
                    f"{self._fences.get(role)}); the incarnation lost "
                    "its lease or was superseded",
                    fenced=True,
                )
        pods = []
        for namespace, name, node_name in bindings:
            pod = self.get_pod(namespace, name)
            if pod.node_name:
                raise ApiError(
                    "Conflict",
                    f"{pod.key} already bound to {pod.node_name}",
                )
            if node_name not in self._nodes:
                raise ApiError("NotFound", f"node {node_name}")
            if self.bind_fault is not None:
                self.bind_fault(pod, node_name)
            pods.append((pod, node_name))
        # validation passed for the WHOLE gang: commit atomically
        for pod, node_name in pods:
            pod.node_name = node_name
            pod.resource_version = self._next_rv()
            self._emit("MODIFIED", "Pod", pod)

    def evict(
        self,
        namespace: str,
        name: str,
        *,
        expect_rv: int | None = None,
        fence: "tuple[str, int] | None" = None,
        nominated_node: str = "",
    ) -> Pod:
        """POST pods/{name}/eviction — the policy/v1 Eviction
        subresource analog, the API the continuous rebalancer
        (kubernetes_tpu/rebalance) moves pods through.

        Order of checks mirrors the reference registry
        (pkg/registry/core/pod/storage/eviction.go): the fencing token
        first (a zombie rebalancer incarnation can never move
        anything), then existence, then optimistic concurrency
        (``expect_rv`` → Conflict, like an eviction carrying a
        preconditions.resourceVersion), then the PodDisruptionBudget
        gate — a matching PDB with ``disruptionsAllowed == 0`` rejects
        with 429 TooManyRequests and the eviction does NOT happen.
        A granted eviction decrements every matching PDB's allowance
        immediately (the reference's registry does the same; the
        disruption controller replenishing it is out of scope) and
        emits an events.k8s.io record.

        [BOUNDARY] divergence, deliberate: the reference eviction
        DELETES the pod and a workload controller recreates a
        replacement that then schedules fresh. This store has no
        controllers, so delete + recreate collapse into one step — the
        pod returns to Pending (nodeName cleared) under its own
        identity, optionally carrying ``nominated_node`` as the
        status.nominatedNodeName hint the recreated pod would get from
        the rebalancer's target assignment. On the watch bus the
        collapse is visible as the SAME pair every subscriber already
        handles: a DELETED event (nodeName still set — assigned-pod
        delete: caches release occupancy, shard filters route it to the
        node's owner) followed by an ADDED event (unbound — queues
        re-admit it, routed to the pod's owner). Pod identity surviving
        the eviction is what keeps the decision journal's per-pod
        history continuous across a migration."""
        if fence is not None:
            role, token = fence
            if not self.fence_valid(role, token):
                self.fence_rejections[role] = (
                    self.fence_rejections.get(role, 0) + 1
                )
                raise ApiError(
                    "Conflict",
                    f"fenced: token {token} for role {role!r} is no "
                    f"longer valid (current {self._fences.get(role)}); "
                    "the incarnation lost its lease or was superseded",
                    fenced=True,
                )
        pod = self.get_pod(namespace, name)
        if not pod.node_name:
            raise ApiError(
                "Invalid", f"{pod.key} is not bound; nothing to evict"
            )
        if expect_rv is not None and pod.resource_version != expect_rv:
            raise ApiError(
                "Conflict",
                f"{pod.key} rv {pod.resource_version} != {expect_rv}",
            )
        matching = [
            pdb for pdb in self._pdbs.values() if pdb.matches(pod)
        ]
        for pdb in matching:
            if pdb.disruptions_allowed <= 0:
                raise ApiError(
                    "TooManyRequests",
                    f"cannot evict {pod.key}: PDB {pdb.key} has "
                    "disruptionsAllowed == 0",
                )
        for pdb in matching:
            pdb.disruptions_allowed -= 1
        source = pod.node_name
        self.record_event(
            pod, "Evicted",
            f"evicted from {source} by the rebalancer"
            + (f"; nominated toward {nominated_node}" if nominated_node else ""),
            action="Eviction",
        )
        # the delete half: nodeName still set, so every subscriber's
        # assigned-pod-delete path (cache release, occupancy fences,
        # fleet row withdraw, waking parked pods) runs unchanged. The
        # DELETED carries a SNAPSHOT of the pod — events hold their
        # object by reference, and a buffered consumer (the sim's
        # delayed watch bus) must still read the bound state at pump
        # time, after the recreate half below has mutated the live pod
        import dataclasses

        self._next_rv()
        self._emit("DELETED", "Pod", dataclasses.replace(pod))
        pod.node_name = ""
        pod.phase = "Pending"
        if nominated_node:
            pod.nominated_node_name = nominated_node
        pod.resource_version = self._next_rv()
        # the recreate half: an unbound ADDED re-admits the pod through
        # the ordinary queue-add routing (with the nomination indexed)
        self._emit("ADDED", "Pod", pod)
        # DRA deallocating-controller stand-in, same as delete_pod: an
        # evicted pod leaves every claim's reservedFor; a claim nobody
        # reserves loses its allocation, freeing the devices (the
        # recreated pod re-allocates at its next scheduling)
        if pod.resource_claim_names:
            for cname in pod.resource_claim_names:
                c = self._resource_claims.get(f"{namespace}/{cname}")
                if c is None or pod.key not in c.reserved_for:
                    continue
                c.reserved_for = tuple(
                    k for k in c.reserved_for if k != pod.key
                )
                if not c.reserved_for:
                    c.allocated_node = ""
                    c.results = ()
                c.resource_version = self._next_rv()
                self.dra_generation += 1
                self._emit("MODIFIED", "ResourceClaim", c)
        return pod

    # -- nodes --

    def create_node(self, node: Node) -> Node:
        if node.name in self._nodes:
            raise ApiError("AlreadyExists", node.name)
        node.resource_version = self._next_rv()
        self._nodes[node.name] = node
        self._emit("ADDED", "Node", node)
        return node

    def get_node(self, name: str) -> Node:
        try:
            return self._nodes[name]
        except KeyError:
            raise ApiError("NotFound", name) from None

    def update_node(self, node: Node, expect_rv: int | None = None) -> Node:
        cur = self.get_node(node.name)
        if expect_rv is not None and cur.resource_version != expect_rv:
            raise ApiError("Conflict", f"{node.name} rv {cur.resource_version} != {expect_rv}")
        node.resource_version = self._next_rv()
        self._nodes[node.name] = node
        self._emit("MODIFIED", "Node", node)
        return node

    def delete_node(self, name: str) -> None:
        node = self._nodes.pop(name, None)
        if node is None:
            raise ApiError("NotFound", name)
        self._next_rv()
        self._emit("DELETED", "Node", node)

    def list_nodes(self) -> list[Node]:
        return list(self._nodes.values())

    # -- PodDisruptionBudgets (policy/v1 slice preemption reads) --

    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        if pdb.key in self._pdbs:
            raise ApiError("AlreadyExists", pdb.key)
        pdb.resource_version = self._next_rv()
        self._pdbs[pdb.key] = pdb
        return pdb

    def delete_pdb(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        if self._pdbs.pop(key, None) is None:
            raise ApiError("NotFound", key)
        self._next_rv()

    def list_pdbs(self) -> list[PodDisruptionBudget]:
        return list(self._pdbs.values())

    # -- Services (PodTopologySpread System-defaulting input) --

    def create_service(self, svc) -> object:
        if svc.key in self._services:
            raise ApiError("AlreadyExists", svc.key)
        svc.resource_version = self._next_rv()
        self._services[svc.key] = svc
        return svc

    def delete_service(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        if self._services.pop(key, None) is None:
            raise ApiError("NotFound", key)
        self._next_rv()

    def list_services(self) -> list:
        return list(self._services.values())

    # -- PersistentVolumes / Claims (volume plugin inputs) --

    def create_pv(self, pv: PersistentVolume) -> PersistentVolume:
        if pv.name in self._pvs:
            raise ApiError("AlreadyExists", pv.name)
        pv.resource_version = self._next_rv()
        self._pvs[pv.name] = pv
        return pv

    def list_pvs(self) -> list[PersistentVolume]:
        return list(self._pvs.values())

    def update_pv(self, pv: PersistentVolume) -> PersistentVolume:
        if pv.name not in self._pvs:
            raise ApiError("NotFound", pv.name)
        pv.resource_version = self._next_rv()
        self._pvs[pv.name] = pv
        return pv

    def create_pvc(self, pvc: PersistentVolumeClaim) -> PersistentVolumeClaim:
        if pvc.key in self._pvcs:
            raise ApiError("AlreadyExists", pvc.key)
        pvc.resource_version = self._next_rv()
        self._pvcs[pvc.key] = pvc
        return pvc

    def list_pvcs(self) -> list[PersistentVolumeClaim]:
        return list(self._pvcs.values())

    def update_pvc(self, pvc: PersistentVolumeClaim) -> PersistentVolumeClaim:
        if pvc.key not in self._pvcs:
            raise ApiError("NotFound", pvc.key)
        pvc.resource_version = self._next_rv()
        self._pvcs[pvc.key] = pvc
        return pvc

    # -- DRA: ResourceSlices / DeviceClasses / ResourceClaims --

    def create_resource_slice(self, s) -> object:
        if s.name in self._resource_slices:
            raise ApiError("AlreadyExists", s.name)
        s.resource_version = self._next_rv()
        self.dra_generation += 1
        self._resource_slices[s.name] = s
        self._emit("ADDED", "ResourceSlice", s)
        return s

    def delete_resource_slice(self, name: str) -> None:
        s = self._resource_slices.pop(name, None)
        if s is None:
            raise ApiError("NotFound", name)
        self._next_rv()
        self.dra_generation += 1
        self._emit("DELETED", "ResourceSlice", s)

    def list_resource_slices(self) -> list:
        return list(self._resource_slices.values())

    def create_device_class(self, dc) -> object:
        if dc.name in self._device_classes:
            raise ApiError("AlreadyExists", dc.name)
        dc.resource_version = self._next_rv()
        self.dra_generation += 1
        self._device_classes[dc.name] = dc
        self._emit("ADDED", "DeviceClass", dc)
        return dc

    def delete_device_class(self, name: str) -> None:
        dc = self._device_classes.pop(name, None)
        if dc is None:
            raise ApiError("NotFound", name)
        self._next_rv()
        self.dra_generation += 1
        self._emit("DELETED", "DeviceClass", dc)

    def list_device_classes(self) -> list:
        return list(self._device_classes.values())

    def create_resource_claim(self, c) -> object:
        if c.key in self._resource_claims:
            raise ApiError("AlreadyExists", c.key)
        c.resource_version = self._next_rv()
        self.dra_generation += 1
        self._resource_claims[c.key] = c
        self._emit("ADDED", "ResourceClaim", c)
        return c

    def get_resource_claim(self, namespace: str, name: str) -> object:
        key = f"{namespace}/{name}"
        try:
            return self._resource_claims[key]
        except KeyError:
            raise ApiError("NotFound", key) from None

    def update_resource_claim(self, c, expect_rv: int | None = None) -> object:
        cur = self._resource_claims.get(c.key)
        if cur is None:
            raise ApiError("NotFound", c.key)
        if expect_rv is not None and cur.resource_version != expect_rv:
            raise ApiError(
                "Conflict",
                f"{c.key} rv {cur.resource_version} != {expect_rv}",
            )
        c.resource_version = self._next_rv()
        self.dra_generation += 1
        self._resource_claims[c.key] = c
        self._emit("MODIFIED", "ResourceClaim", c)
        return c

    def delete_resource_claim(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        c = self._resource_claims.pop(key, None)
        if c is None:
            raise ApiError("NotFound", key)
        self._next_rv()
        self.dra_generation += 1
        self._emit("DELETED", "ResourceClaim", c)

    def list_resource_claims(self) -> list:
        return list(self._resource_claims.values())

    # -- Leases (coordination.k8s.io/v1 subset; leader election) --

    def create_lease(self, lease) -> object:
        import dataclasses

        if lease.key in self._leases:
            raise ApiError("AlreadyExists", lease.key)
        lease.resource_version = self._next_rv()
        self._leases[lease.key] = dataclasses.replace(lease)
        return lease

    def get_lease(self, namespace: str, name: str) -> object:
        """Returns a SNAPSHOT copy: electors mutate their read before the
        compare-and-swap update, and handing out the live object would
        let a losing challenger corrupt the store (the rv check must be
        the only write path)."""
        import dataclasses

        key = f"{namespace}/{name}"
        try:
            return dataclasses.replace(self._leases[key])
        except KeyError:
            raise ApiError("NotFound", key) from None

    def update_lease(self, lease, expect_rv: int | None = None) -> object:
        import dataclasses

        cur = self._leases.get(lease.key)
        if cur is None:
            raise ApiError("NotFound", lease.key)
        if expect_rv is not None and cur.resource_version != expect_rv:
            raise ApiError(
                "Conflict",
                f"{lease.key} rv {cur.resource_version} != {expect_rv}",
            )
        lease.resource_version = self._next_rv()
        self._leases[lease.key] = dataclasses.replace(lease)
        return lease

    def list_leases(self) -> list:
        import dataclasses

        return [dataclasses.replace(le) for le in self._leases.values()]

    # -- bulk helpers for benchmarks --

    def create_nodes(self, nodes: Iterable[Node]) -> None:
        for n in nodes:
            self.create_node(n)

    def create_pods(self, pods: Iterable[Pod]) -> None:
        for p in pods:
            self.create_pod(p)

    # -- events (events.k8s.io/v1 subset; SURVEY §6.5 events row) --

    def record_event(
        self,
        regarding: "Pod | Node",
        reason: str,
        note: str,
        type_: str = "Normal",
        action: str = "Scheduling",
        timestamp: float | None = None,
    ) -> EventRecord:
        """EventBroadcaster + correlator analog: repeats of the same
        (regarding, reason, note) bump count/lastTimestamp on the existing
        record (EventAggregator's dedup key, minus source — one scheduler
        here); new tuples create a record. Emits on the watch bus with
        kind="Event" either way."""
        ts = self.clock.now() if timestamp is None else timestamp
        # reference apiserver gives Events a TTL (1h default) instead of
        # durable storage. Pruning must not trust insertion order: a
        # count-bumped old record keeps a FRESH last_timestamp at the
        # head, so a head-stop sweep would block forever (review-caught).
        # Instead run a full sweep whenever the store doubles past the
        # last sweep's size — amortized O(1) per record, bounded memory —
        # OR when a full TTL has elapsed since the last sweep, so small
        # stores (below the size threshold) still expire records at most
        # one TTL late.
        if len(self._events) >= self._events_sweep_at or (
            self._events and ts - self._events_last_sweep > self.event_ttl
        ):
            self._events_last_sweep = ts
            cutoff = ts - self.event_ttl
            for rec in [
                r
                for r in self._events.values()
                if r.last_timestamp < cutoff
            ]:
                del self._events[rec.key]
                self._events_by_agg.pop(
                    (
                        rec.regarding_kind, rec.namespace,
                        rec.regarding_name, rec.reason, rec.note,
                    ),
                    None,
                )
            self._events_sweep_at = max(256, 2 * len(self._events))
        ns = getattr(regarding, "namespace", "") or "default"
        kind = "Pod" if isinstance(regarding, Pod) else "Node"
        agg_key = (kind, ns, regarding.name, reason, note)
        rec = self._events_by_agg.get(agg_key)
        if rec is not None:
            rec.count += 1
            rec.last_timestamp = ts
            rec.resource_version = self._next_rv()
            self._emit("MODIFIED", "Event", rec)
            return rec
        self._event_seq += 1
        rec = EventRecord(
            namespace=ns,
            regarding_kind=kind,
            regarding_namespace=ns if kind == "Pod" else "",
            regarding_name=regarding.name,
            reason=reason,
            note=note,
            type=type_,
            action=action,
            first_timestamp=ts,
            last_timestamp=ts,
            name=f"{regarding.name}.{self._event_seq:x}",
            resource_version=self._next_rv(),
        )
        self._events[rec.key] = rec
        self._events_by_agg[agg_key] = rec
        self._emit("ADDED", "Event", rec)
        return rec

    def list_events(
        self,
        namespace: str | None = None,
        regarding_name: str | None = None,
    ) -> list[EventRecord]:
        """List in creation order, optionally field-selected the way
        kubectl describe does (involvedObject.name=...)."""
        out = []
        for rec in self._events.values():
            if namespace is not None and rec.namespace != namespace:
                continue
            if (
                regarding_name is not None
                and rec.regarding_name != regarding_name
            ):
                continue
            out.append(rec)
        return out


def _locked(fn):
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self.lock:
            return fn(self, *args, **kwargs)

    return wrapper


# Guard every public method with the instance RLock (reentrant: e.g. the
# scheduler's preemption path calls delete_pod while holding the lock
# across schedule_batch).
for _name, _fn in list(vars(ClusterState).items()):
    if _name.startswith("_") or not callable(_fn):
        continue
    setattr(ClusterState, _name, _locked(_fn))
del _name, _fn
