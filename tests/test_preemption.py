"""Preemption: oracle unit tests, kernel-vs-oracle parity, e2e PostFilter."""

import numpy as np

from kubernetes_tpu.api.labels import selector_from_match_labels
from kubernetes_tpu.api.objects import PodDisruptionBudget
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle import preemption as opr
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.solver.preemption import PreemptionEvaluator
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.tensorize.schema import ResourceVocab, build_node_batch
from kubernetes_tpu.utils.clock import FakeClock


def mk_node(name, cpu="4", pods="10"):
    return MakeNode().name(name).capacity({"cpu": cpu, "memory": "16Gi", "pods": pods}).obj()


def mk_pod(name, cpu, prio=0, start=0.0, labels=None):
    b = MakePod().name(name).req({"cpu": cpu}).priority(prio).start_time(start)
    if labels:
        b = b.labels(labels)
    return b.obj()


# -- oracle unit tests ------------------------------------------------------


def test_oracle_selects_minimal_victims():
    node = mk_node("n", cpu="4")
    on_node = [
        mk_pod("low-big", "2", prio=1, start=1.0),
        mk_pod("low-small", "1", prio=2, start=2.0),
        mk_pod("high", "1", prio=100, start=0.0),
    ]
    # incoming needs 2 cpu; free = 4 - 4 = 0. Removing low-big (2c) suffices.
    incoming = mk_pod("in", "2", prio=50)
    nv = opr.select_victims_on_node(incoming, {"cpu": 4000}, 10, on_node)
    assert nv is not None
    # reprieve order: low-small (prio 2) first -> re-added? used after
    # removal = high 1c + incoming 2c = 3c; re-add low-small 1c -> 4c fits;
    # re-add low-big 2c -> 6c > 4c -> victim
    assert [v.name for v in nv.victims] == ["low-big"]


def test_oracle_none_when_impossible():
    node = mk_node("n", cpu="4")
    on_node = [mk_pod("high", "4", prio=100)]
    incoming = mk_pod("in", "2", prio=50)
    assert opr.select_victims_on_node(incoming, {"cpu": 4000}, 10, on_node) is None


def test_oracle_pdb_classification():
    pdb = PodDisruptionBudget(
        name="pdb", selector=selector_from_match_labels({"app": "db"}),
        disruptions_allowed=1,
    )
    pods = [
        mk_pod("db1", "1", prio=1, labels={"app": "db"}),
        mk_pod("db2", "1", prio=2, labels={"app": "db"}),
        mk_pod("web", "1", prio=3, labels={"app": "web"}),
    ]
    violating, non_violating = opr.classify_pdb_violations(
        opr.sort_more_important(pods), [pdb]
    )
    # budget allows 1 disruption: first classified (web? order is priority
    # desc: web, db2, db1) -> web no pdb; db2 takes the allowance; db1 violates
    assert [p.name for p in violating] == ["db1"]
    assert {p.name for p in non_violating} == {"web", "db2"}


def test_oracle_pick_one_node_ordering():
    v_small = opr.NodeVictims([mk_pod("a", "1", prio=5)], 0)
    v_big = opr.NodeVictims(
        [mk_pod("b", "1", prio=5), mk_pod("c", "1", prio=3)], 0
    )
    v_viol = opr.NodeVictims([mk_pod("d", "1", prio=1)], 1)
    pick = opr.pick_one_node(
        {"n1": v_big, "n2": v_small, "n3": v_viol}, ["n1", "n2", "n3"]
    )
    assert pick == "n2"  # fewest violations first, then sum/count
    # no-victim candidate always wins
    v_none = opr.NodeVictims([], 0)
    assert (
        opr.pick_one_node({"n1": v_small, "n4": v_none}, ["n1", "n4"]) == "n4"
    )


# -- kernel vs oracle -------------------------------------------------------


def test_kernel_matches_oracle_victims():
    rng = np.random.default_rng(3)
    nodes = [mk_node(f"n{i}", cpu="8", pods="20") for i in range(6)]
    placed: dict[str, list] = {}
    for i, n in enumerate(nodes):
        placed[n.name] = [
            mk_pod(
                f"p{i}-{j}",
                f"{int(rng.integers(1, 4))}",
                prio=int(rng.integers(0, 80)),
                start=float(rng.random()),
            )
            for j in range(int(rng.integers(1, 6)))
        ]
    incoming = mk_pod("in", "6", prio=60)

    all_pods = [incoming] + [p for ps in placed.values() for p in ps]
    vocab = ResourceVocab.build(all_pods, nodes)
    nbatch = build_node_batch(nodes, placed, vocab=vocab)
    placed_by_slot = {i: placed[n.name] for i, n in enumerate(nodes)}
    static_row = np.ones(nbatch.padded, dtype=bool)

    result = PreemptionEvaluator().evaluate(
        incoming, nbatch, [n.name for n in nodes] + [""] * (nbatch.padded - 6),
        placed_by_slot, static_row,
    )

    # oracle: per-node victims + pickOne
    candidates = {}
    for n in nodes:
        nv = opr.select_victims_on_node(
            incoming, {"cpu": 8000, "memory": 16 * 1024**3}, 20, placed[n.name]
        )
        # zero-victim nodes are not candidates (the pod would have been
        # schedulable there) — mirror the kernel's exclusion
        if nv is not None and nv.victims:
            candidates[n.name] = nv
    expect = opr.pick_one_node(candidates, [n.name for n in nodes])

    if expect is None:
        assert result is None
    else:
        assert result is not None
        assert result.node_name == expect
        assert sorted(v.key for v in result.victims) == sorted(
            v.key for v in candidates[expect].victims
        )


def test_kernel_respects_pdb():
    nodes = [mk_node("n0", cpu="4"), mk_node("n1", cpu="4")]
    placed = {
        "n0": [mk_pod("db", "4", prio=1, labels={"app": "db"})],
        "n1": [mk_pod("web", "4", prio=1, labels={"app": "web"})],
    }
    pdb = PodDisruptionBudget(
        name="db-pdb", selector=selector_from_match_labels({"app": "db"}),
        disruptions_allowed=0,
    )
    incoming = mk_pod("in", "3", prio=50)
    all_pods = [incoming] + placed["n0"] + placed["n1"]
    vocab = ResourceVocab.build(all_pods, nodes)
    nbatch = build_node_batch(nodes, placed, vocab=vocab)
    static_row = np.ones(nbatch.padded, dtype=bool)
    result = PreemptionEvaluator().evaluate(
        incoming, nbatch, ["n0", "n1"] + [""] * (nbatch.padded - 2),
        {0: placed["n0"], 1: placed["n1"]}, static_row, [pdb],
    )
    # both nodes need their pod evicted; web is not PDB-protected -> n1 wins
    assert result is not None
    assert result.node_name == "n1"
    assert [v.name for v in result.victims] == ["web"]


# -- e2e through the scheduler ---------------------------------------------


def test_e2e_preemption_evicts_and_reschedules():
    cs = ClusterState()
    for i in range(2):
        cs.create_node(mk_node(f"node-{i}", cpu="4"))
    # fill both nodes with low-priority pods
    for i in range(2):
        cs.create_pod(
            MakePod().name(f"low-{i}").node(f"node-{i}").req({"cpu": "4"})
            .priority(1).obj()
        )
    clock = FakeClock()
    sched = Scheduler(
        cs,
        SchedulerConfig(batch_size=8, solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )
    cs.create_pod(MakePod().name("vip").req({"cpu": "2"}).priority(100).obj())

    r1 = sched.schedule_batch()
    assert r1.unschedulable == ["default/vip"]
    assert len(r1.preemptions) == 1
    pod_key, node, victims = r1.preemptions[0]
    assert pod_key == "default/vip"
    assert len(victims) == 1
    # victim deleted from the cluster; vip nominated
    assert all(p.name != victims[0].split("/")[1] for p in cs.list_pods())
    vip = cs.get_pod("default", "vip")
    assert vip.nominated_node_name == node

    # backoff then retry: vip lands on the freed node
    clock.advance(2.0)
    r2 = sched.schedule_batch()
    assert ("default/vip", node) in r2.scheduled


def test_preemption_skipped_when_failure_is_not_resources():
    # pod fails for anti-affinity, not resources: the fit-only dry-run sees
    # zero victims everywhere and must NOT nominate/evict anything
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("node-0").capacity({"cpu": "8", "memory": "16Gi", "pods": "10"})
        .label("zone", "z0").obj()
    )
    cs.create_pod(
        MakePod().name("king").node("node-0").req({"cpu": "1"}).priority(1000)
        .label("app", "king").obj()
    )
    # an unrelated low-priority pod so the lower-priority pre-check passes
    cs.create_pod(
        MakePod().name("bystander").node("node-0").req({"cpu": "1"}).priority(1).obj()
    )
    clock = FakeClock()
    sched = Scheduler(cs, SchedulerConfig(batch_size=4), clock=clock)
    cs.create_pod(
        MakePod().name("vip").req({"cpu": "1"}).priority(100)
        .pod_anti_affinity("zone", match_labels={"app": "king"}).obj()
    )
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/vip"]
    assert not r.preemptions
    assert cs.get_pod("default", "vip").nominated_node_name == ""
    assert len(cs.list_pods()) == 3  # nothing evicted


def test_first_pod_affinity_rejects_keyless_node():
    # first-pod exception must not admit a node lacking the topology key
    from kubernetes_tpu.ops.oracle import interpod as oip

    keyless = MakeNode().name("bare").capacity({"cpu": "8", "pods": "10"}).obj()
    zoned = (
        MakeNode().name("zoned").capacity({"cpu": "8", "memory": "16Gi", "pods": "10"})
        .label("zone", "z0").obj()
    )
    pod = (
        MakePod().name("p").label("app", "grp").req({"cpu": "1"})
        .pod_affinity("zone", match_labels={"app": "grp"})
        .obj()
    )
    all_nodes = [(keyless, []), (zoned, [])]
    assert not oip.interpod_filter(pod, keyless, all_nodes)
    assert oip.interpod_filter(pod, zoned, all_nodes)

    # and through the solver: the pod must land on the zoned node only
    from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
    from kubernetes_tpu.solver.exact import ExactSolver
    from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
    from kubernetes_tpu.tensorize.plugins import (
        build_port_tensors,
        build_static_tensors,
    )
    from kubernetes_tpu.tensorize.spread import build_spread_tensors
    from kubernetes_tpu.tensorize.schema import build_pod_batch

    nodes = [keyless, zoned]
    pods = [pod]
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - 2)
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    ipa = build_interpod_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    a = ExactSolver(ExactSolverConfig(tie_break="first")).solve(
        nbatch, pbatch, static, ports, spread, ipa
    )
    assert a[0] == 1  # zoned node


def test_e2e_preemption_never_policy():
    cs = ClusterState()
    cs.create_node(mk_node("node-0", cpu="4"))
    cs.create_pod(
        MakePod().name("low").node("node-0").req({"cpu": "4"}).priority(1).obj()
    )
    clock = FakeClock()
    sched = Scheduler(cs, SchedulerConfig(batch_size=4), clock=clock)
    cs.create_pod(
        MakePod().name("polite").req({"cpu": "2"}).priority(100)
        .preemption_policy("Never").obj()
    )
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/polite"]
    assert not r.preemptions
    assert len(cs.list_pods()) == 2  # nothing evicted


# -- full-filter dry-run (ports/spread/interpod-blocked preemptors) ---------


def test_preemption_evicts_anti_affinity_owner():
    """A pod blocked ONLY by pod anti-affinity (resources fine) preempts the
    lower-priority pod that owns the conflicting labels — possible only with
    the full-filter dry-run (the fit-only screen sees zero victims)."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("node-0").capacity({"cpu": "8", "memory": "16Gi", "pods": "10"})
        .label("zone", "z0").obj()
    )
    cs.create_pod(
        MakePod().name("king").node("node-0").req({"cpu": "1"}).priority(1)
        .label("app", "king").obj()
    )
    clock = FakeClock()
    sched = Scheduler(cs, SchedulerConfig(batch_size=4), clock=clock)
    cs.create_pod(
        MakePod().name("vip").req({"cpu": "1"}).priority(100)
        .pod_anti_affinity("zone", match_labels={"app": "king"}).obj()
    )
    r1 = sched.schedule_batch()
    assert r1.unschedulable == ["default/vip"]
    assert len(r1.preemptions) == 1
    _, node, victims = r1.preemptions[0]
    assert node == "node-0" and victims == ["default/king"]
    clock.advance(2.0)
    r2 = sched.schedule_batch()
    assert ("default/vip", "node-0") in r2.scheduled


def test_preemption_evicts_spread_violators():
    """A pod blocked by a DoNotSchedule spread constraint preempts enough
    selector-matching pods to bring the skew within bounds; the reprieve
    re-runs the spread filter per re-add."""
    cs = ClusterState()
    for z in (0, 1):
        cs.create_node(
            MakeNode().name(f"node-{z}").capacity({"cpu": "8", "memory": "16Gi", "pods": "10"})
            .label("zone", f"z{z}").obj()
        )
    # two web pods on z0 (low priority), z1 fully blocked by a high-prio pod
    for i in range(2):
        cs.create_pod(
            MakePod().name(f"web-{i}").node("node-0").req({"cpu": "1"})
            .priority(1).start_time(float(i)).label("app", "web").obj()
        )
    cs.create_pod(
        MakePod().name("fort").node("node-1").req({"cpu": "8"}).priority(1000).obj()
    )
    clock = FakeClock()
    sched = Scheduler(cs, SchedulerConfig(batch_size=4), clock=clock)
    cs.create_pod(
        MakePod().name("vip").req({"cpu": "1"}).priority(100).label("app", "web")
        .spread_constraint(1, "zone", "DoNotSchedule", {"app": "web"}).obj()
    )
    r1 = sched.schedule_batch()
    assert r1.unschedulable == ["default/vip"]
    assert len(r1.preemptions) == 1
    _, node, victims = r1.preemptions[0]
    # both web pods must go: evicting just one leaves skew 1+1-0 = 2 > 1
    assert node == "node-0"
    assert sorted(victims) == ["default/web-0", "default/web-1"]
    clock.advance(2.0)
    r2 = sched.schedule_batch()
    assert ("default/vip", "node-0") in r2.scheduled


def test_preemption_evicts_host_port_owner():
    """A pod blocked only by a host-port conflict preempts the lower-priority
    port owner (fit-only dry-run cannot see freed ports)."""
    cs = ClusterState()
    cs.create_node(mk_node("node-0", cpu="8"))
    cs.create_pod(
        MakePod().name("old-lb").node("node-0").req({"cpu": "1"}).priority(1)
        .host_port(8080).obj()
    )
    clock = FakeClock()
    sched = Scheduler(cs, SchedulerConfig(batch_size=4), clock=clock)
    cs.create_pod(
        MakePod().name("new-lb").req({"cpu": "1"}).priority(100)
        .host_port(8080).obj()
    )
    r1 = sched.schedule_batch()
    assert r1.unschedulable == ["default/new-lb"]
    assert len(r1.preemptions) == 1
    _, node, victims = r1.preemptions[0]
    assert node == "node-0" and victims == ["default/old-lb"]
    clock.advance(2.0)
    r2 = sched.schedule_batch()
    assert ("default/new-lb", "node-0") in r2.scheduled


def test_full_dry_run_never_evicts_uselessly():
    """If the blocker is an un-evictable higher-priority pod, the full
    dry-run must refuse to nominate even though lower-priority pods exist
    on the node (they would die for nothing)."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("node-0").capacity({"cpu": "8", "memory": "16Gi", "pods": "10"})
        .label("zone", "z0").obj()
    )
    cs.create_pod(
        MakePod().name("king").node("node-0").req({"cpu": "1"}).priority(1000)
        .label("app", "king").obj()
    )
    cs.create_pod(
        MakePod().name("bystander").node("node-0").req({"cpu": "1"}).priority(1).obj()
    )
    clock = FakeClock()
    sched = Scheduler(cs, SchedulerConfig(batch_size=4), clock=clock)
    cs.create_pod(
        MakePod().name("vip").req({"cpu": "1"}).priority(100)
        .pod_anti_affinity("zone", match_labels={"app": "king"}).obj()
    )
    r1 = sched.schedule_batch()
    assert r1.unschedulable == ["default/vip"]
    assert not r1.preemptions
    assert len(cs.list_pods()) == 3  # nothing evicted
