"""Invariant checkers the simulator runs after every scheduler drive.

Each checker is a small, separately-testable unit (mirrors the
known-bad-fixture-per-rule pattern of tests/test_static_analysis.py —
tests/test_sim_invariants.py feeds each one a crafted violation):

- ``BindTransitionTracker``  — no double-bind: watches the state
  service directly (ground truth, no injected delay) and flags any pod
  whose nodeName moves A→B, plus any pod the scheduler reports
  scheduled twice without an intervening delete;
- ``check_capacity``         — per-node allocatable is never exceeded
  by the bound-pod request sum (and pod count never exceeds the node's
  pods allocatable);
- ``check_lost_pods``        — every unbound pod this scheduler owns is
  accounted for: scheduling queue (active/backoff/unschedulable/gated),
  in-flight map, WaitingPods map, or still-undelivered watch ADDs.
  Anything else fell out of the bookkeeping and would never schedule;
- ``check_constraints``      — hard-shape placements hold: hostPort
  exclusivity per node and required hostname anti-affinity among bound
  pods (the checks guarding the pipelined loop's occupancy-carrying
  path; spread skew is deliberately unchecked — node churn re-shapes
  domains after placement);
- ``check_no_partial_gangs`` — no pod group is ever partially bound:
  a gang with one bound and one unbound live member means the atomic
  gang commit (kubernetes_tpu/gang) leaked a partial bind;
- ``MonotonicCounters``      — sampled Counter series never decrease;
- ``check_resilience``       — under injected solver-boundary faults:
  the fallback ladder engaged (breaker trips), the breaker re-closed
  to the top tier after the fault window, and poison batches were
  isolated into quarantine instead of lost;
- eventual progress is checked by the harness's settle loop (bounded
  rounds of drain + virtual-clock advance), emitting a ``progress``
  violation when the loop fails to quiesce — the livelock detector the
  PR-1 pipeline backstop exists to satisfy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterable

from .. import metrics
from ..state.cluster import ClusterState, Event


@dataclass(frozen=True)
class Violation:
    invariant: str  # double_bind | capacity | lost_pod | progress |
    # monotonic | constraint | journal | global_overcommit |
    # resilience | recovery | fencing | rebalance | gang | telemetry
    cycle: int
    detail: str

    def as_dict(self) -> dict:
        return {
            "invariant": self.invariant,
            "cycle": self.cycle,
            "detail": self.detail,
        }


def _record(violations: list[Violation], inv: str, cycle: int, detail: str):
    metrics.sim_invariant_violations_total.labels(inv).inc()
    violations.append(Violation(inv, cycle, detail))


class BindTransitionTracker:
    """Subscribes straight to the state service (never through the
    delayed bus) and accumulates double-bind violations as they
    happen. ``drain`` collects them tagged with the current cycle."""

    def __init__(self, cluster: ClusterState) -> None:
        self._node_of: dict[str, str] = {
            p.key: p.node_name for p in cluster.list_pods() if p.node_name
        }
        self._pending: list[str] = []
        self._sched_bound: set[str] = set()
        # EVICTED-pod deletes observed BEFORE the scheduler's bind
        # report for that pod drained (record_results runs once per
        # drive, so an evict-and-rebind inside one drive delivers its
        # DELETED while _sched_bound is still empty): each credit
        # legitimizes exactly one re-bind of the key. Only evictions
        # bank — the subresource emits an Events-API `Evicted` record
        # immediately before its DELETED, and keying on it keeps the
        # double-bind check strict for every OTHER bound-pod delete
        # (a churn-deleted pod's key must never legally re-bind).
        self._delete_credits: dict[str, int] = {}
        self._evict_marks: dict[str, int] = {}
        cluster.subscribe(self._on_event)

    def _on_event(self, ev: Event) -> None:
        if ev.kind == "Event":
            if getattr(ev.obj, "reason", "") == "Evicted":
                key = (
                    f"{ev.obj.regarding_namespace}/"
                    f"{ev.obj.regarding_name}"
                )
                self._evict_marks[key] = self._evict_marks.get(key, 0) + 1
            return
        if ev.kind != "Pod":
            return
        pod = ev.obj
        if ev.type == "DELETED":
            self._node_of.pop(pod.key, None)
            evicted = self._evict_marks.get(pod.key, 0) > 0
            if evicted:
                self._evict_marks[pod.key] -= 1
                if not self._evict_marks[pod.key]:
                    del self._evict_marks[pod.key]
            if pod.key in self._sched_bound:
                self._sched_bound.discard(pod.key)
            elif pod.node_name and evicted:
                # an EVICTED bound pod deleted before its bind report
                # drained: bank the delete (see _delete_credits).
                # Plain deletes and pending-pod deletes bank nothing —
                # they can't legitimize a re-bind.
                self._delete_credits[pod.key] = (
                    self._delete_credits.get(pod.key, 0) + 1
                )
            return
        if not pod.node_name:
            return
        prev = self._node_of.get(pod.key)
        if prev is not None and prev != pod.node_name:
            self._pending.append(
                f"pod {pod.key} rebound {prev} -> {pod.node_name}"
            )
        self._node_of[pod.key] = pod.node_name

    def record_results(self, scheduled: Iterable[tuple[str, str]]) -> None:
        """Feed one drive's BatchResult.scheduled entries: a pod bound
        twice by the scheduler — with neither an observed delete nor a
        banked bound-delete credit in between — is a double-bind even
        if the state service masked it."""
        for key, node in scheduled:
            if key in self._sched_bound:
                if self._delete_credits.get(key, 0) > 0:
                    self._delete_credits[key] -= 1
                    if not self._delete_credits[key]:
                        del self._delete_credits[key]
                else:
                    self._pending.append(
                        f"scheduler bound pod {key} twice (latest to {node})"
                    )
            self._sched_bound.add(key)

    def drain(self, cycle: int, violations: list[Violation]) -> None:
        for detail in self._pending:
            _record(violations, "double_bind", cycle, detail)
        self._pending.clear()


def check_capacity(
    cluster: ClusterState, cycle: int, violations: list[Violation]
) -> None:
    nodes = {n.name: n for n in cluster.list_nodes()}
    used: dict[str, dict[str, int]] = {}
    count: dict[str, int] = {}
    for pod in cluster.list_pods():
        if not pod.node_name or pod.node_name not in nodes:
            continue  # node deleted after the bind: capacity is moot
        u = used.setdefault(pod.node_name, {})
        for r, v in pod.resource_request().items():
            u[r] = u.get(r, 0) + v
        count[pod.node_name] = count.get(pod.node_name, 0) + 1
    for name in sorted(used):
        node = nodes[name]
        for r in sorted(used[name]):
            v = used[name][r]
            if r == "pods" or v <= 0:
                continue
            if v > node.allocatable.get(r, 0):
                _record(
                    violations, "capacity", cycle,
                    f"node {name}: {r} used {v} > allocatable "
                    f"{node.allocatable.get(r, 0)}",
                )
        if count.get(name, 0) > node.allowed_pod_number:
            _record(
                violations, "capacity", cycle,
                f"node {name}: {count[name]} pods > allowed "
                f"{node.allowed_pod_number}",
            )


def check_constraints(
    cluster: ClusterState, cycle: int, violations: list[Violation]
) -> None:
    """Hard-shape placement invariants over the CURRENT bound pods —
    the checks that guard the pipelined loop's occupancy-carrying path:

    - **hostPort exclusivity**: no two bound pods on one node share a
      (port, protocol). Time-robust: a real kubelet would refuse the
      second pod no matter when each bound.
    - **required hostname anti-affinity**: a bound pod whose required
      anti term (topologyKey kubernetes.io/hostname) matches ANOTHER
      pod bound to the same node. Sound here because sim pod labels are
      immutable, hostname labels never flap, and the profiles that
      generate anti shapes run no external binds (a delayed watch can
      only make the scheduler OVER-count peers — conservative).

    Topology-spread skew is deliberately NOT checked: node churn moves
    domain membership after placement, so a historical placement can
    look skewed without any scheduler bug.
    """
    by_node: dict[str, list] = {}
    for pod in cluster.list_pods():
        if pod.node_name:
            by_node.setdefault(pod.node_name, []).append(pod)
    for name in sorted(by_node):
        pods = sorted(by_node[name], key=lambda q: q.key)
        ports_seen: dict[tuple, str] = {}
        for pod in pods:
            for port in pod.host_ports():
                prev = ports_seen.get(port)
                if prev is not None:
                    _record(
                        violations, "constraint", cycle,
                        f"node {name}: hostPort {port} held by both "
                        f"{prev} and {pod.key}",
                    )
                else:
                    ports_seen[port] = pod.key
        for pod in pods:
            anti = (
                pod.affinity.pod_anti_affinity
                if pod.affinity is not None
                else None
            )
            if anti is None or not anti.required:
                continue
            for term in anti.required:
                if (
                    term.topology_key != "kubernetes.io/hostname"
                    or term.label_selector is None
                ):
                    continue
                for other in pods:
                    if other.key == pod.key:
                        continue
                    if not term.matches_namespace(
                        pod.namespace, other.namespace
                    ):
                        continue
                    if term.label_selector.matches(other.labels):
                        _record(
                            violations, "constraint", cycle,
                            f"node {name}: {pod.key} requires hostname "
                            f"anti-affinity but co-resides with "
                            f"matching pod {other.key}",
                        )
                        break


def check_no_partial_gangs(
    cluster: ClusterState, cycle: int, violations: list[Violation]
) -> None:
    """No pod group is ever partially bound (the gang tentpole's sim
    contract, ISSUE 17): a violation is a gang with at least one BOUND
    and at least one UNBOUND live member. Sound because the scheduler
    binds a gang only through ``ClusterState.bind_gang`` — atomic under
    the cluster lock — and this runs after every drive: any path that
    bound some members and released the rest would be caught here
    before the next cycle's churn. Delete churn cannot fake a
    violation (removing a bound member leaves the survivors all-bound;
    removing a queued member leaves them all-unbound), and a
    half-CREATED gang mid-arrival is all-unbound too.
    """
    from ..gang import GangTracker

    bound: dict[str, list[str]] = {}
    unbound: dict[str, list[str]] = {}
    for pod in cluster.list_pods():
        gid = GangTracker.gang_of(pod)
        if gid is None:
            continue
        side = bound if pod.node_name else unbound
        side.setdefault(gid, []).append(pod.key)
    for gid in sorted(set(bound) & set(unbound)):
        _record(
            violations, "gang", cycle,
            f"pod group {gid} is partially bound: "
            f"bound={sorted(bound[gid])} pending={sorted(unbound[gid])} "
            "— gang commit must be atomic (all members or none)",
        )


def check_lost_pods(
    cluster: ClusterState,
    scheduler,
    cycle: int,
    violations: list[Violation],
    undelivered: Callable[[], set[str]] = lambda: set(),
) -> None:
    tracked = set(scheduler.queue.entries())
    tracked |= set(scheduler._in_flight)
    tracked |= set(scheduler._waiting)
    # quarantined pods are parked by the resilience layer with a TTL'd
    # re-admit — tracked, not lost
    tracked |= set(scheduler._quarantine)
    tracked |= undelivered()
    for pod in cluster.list_pods():
        if pod.node_name or pod.scheduler_name not in scheduler.solvers:
            continue
        if pod.key not in tracked:
            _record(
                violations, "lost_pod", cycle,
                f"pod {pod.key} is unbound but tracked by neither the "
                "queue, the in-flight map, the WaitingPods map, nor an "
                "undelivered watch event",
            )


def check_journal_completeness(
    cluster: ClusterState,
    scheduler,
    cycle: int,
    violations: list[Violation],
    last_outcomes: dict[str, dict],
    sched_bound: set[str],
    undelivered: set[str] = frozenset(),
) -> None:
    """Trace-completeness invariant for the obs decision journal: every
    pod the scheduler ever owned has a journal history with a terminal
    outcome — scheduler-bound pods end on ``bound``; unbound (and
    delivered, ungated) pods end on a terminal failure outcome. A pod
    ending on a non-terminal record (``discarded``/``permit_wait``)
    after quiescence means a code path dropped the pod without
    journaling its fate — exactly the blind spot the journal exists to
    close."""
    from ..obs.journal import TERMINAL_OUTCOMES

    entries = scheduler.queue.entries()
    for pod in sorted(cluster.list_pods(), key=lambda p: p.key):
        if pod.key in undelivered:
            continue  # the scheduler cannot journal what it never saw
        rec = last_outcomes.get(pod.key)
        if pod.node_name:
            # externally-bound pods never enter a scheduling cycle;
            # only binds this scheduler reported are held to account
            if pod.key in sched_bound and (
                rec is None or rec["outcome"] != "bound"
            ):
                _record(
                    violations, "journal", cycle,
                    f"scheduler-bound pod {pod.key} lacks a terminal "
                    "'bound' journal record (last: "
                    f"{rec['outcome'] if rec else None})",
                )
            continue
        if pod.scheduler_name not in scheduler.solvers:
            continue  # ignored at queue-add, like frameworkForPod misses
        if entries.get(pod.key) == "gated":
            continue  # never entered a scheduling cycle
        if rec is None:
            _record(
                violations, "journal", cycle,
                f"unbound pod {pod.key} never appeared in the decision "
                "journal",
            )
        elif rec["outcome"] not in TERMINAL_OUTCOMES:
            _record(
                violations, "journal", cycle,
                f"unbound pod {pod.key}'s last journal outcome "
                f"{rec['outcome']!r} is non-terminal",
            )


def check_no_global_overcommit(
    cluster: ClusterState,
    cycle: int,
    violations: list[Violation],
    binds: Iterable[tuple[str, str, str]] = (),
    owners: "dict[str, str] | None" = None,
) -> None:
    """The fleet tier's flagship invariant (ISSUE 6): with N active
    replicas each solving a shard concurrently, the FLEET as a whole
    must never overcommit a node. Two halves:

    - **disjoint ownership** — every bind a replica reported this
      drive landed on a node the ring assigned to that replica at the
      time (``binds`` = (replica, pod key, node), ``owners`` = the
      node -> replica assignment snapshotted right after the drive).
      A buggy ring or a stale partition view shows up here even when
      capacity happens to hold;
    - **global capacity** — the bound-pod request sum per node never
      exceeds allocatable, counted across ALL replicas' commits (the
      single-scheduler capacity check, re-run fleet-wide — two
      replicas double-booking one node trips this even if each
      replica's local view was consistent).
    """
    if owners is not None:
        for replica, pod_key, node in binds:
            actual = owners.get(node)
            if actual != replica:
                _record(
                    violations, "global_overcommit", cycle,
                    f"replica {replica} bound {pod_key} to node {node} "
                    f"owned by {actual!r} (shards must be disjoint)",
                )
    check_capacity(cluster, cycle, violations)


def check_fleet_journal_completeness(
    cluster: ClusterState,
    schedulers: list,
    cycle: int,
    violations: list[Violation],
    sched_bound: set[str],
) -> None:
    """Journal completeness held FLEET-WIDE: a pod may legitimately
    traverse several replicas' journals (routed, handed off, adopted
    after a replica loss), so the invariant merges every replica's
    records — latest by (t, step), terminal preferred on ties — and
    requires each owned pod's merged history to end terminally, and
    each fleet-bound pod to end ``bound``. The blind spot this closes:
    a replica loss orphaning pods that then never reach a terminal
    outcome anywhere."""
    from ..obs.journal import TERMINAL_OUTCOMES, fleet_merge_key
    import json

    # merge key: the PR 8 tie-break, now shared with `obs explain
    # --fleet` (obs/journal.py fleet_merge_key) — latest virtual time
    # wins; on a t-tie prefer terminal, then 'bound' (a bind is
    # irrevocable, so no same-instant record from another replica can
    # supersede it — e.g. a fenced zombie's bind_failure racing the
    # survivor's successful bind in the same cycle), then the
    # within-replica step (steps are NOT comparable across replicas,
    # so it only breaks same-replica ties)
    _key = fleet_merge_key

    merged: dict[str, dict] = {}
    for sched in schedulers:
        if sched.journal is None:
            continue
        for line in sched.journal.lines:
            rec = json.loads(line)
            cur = merged.get(rec["pod"])
            if cur is None or _key(rec) >= _key(cur):
                merged[rec["pod"]] = rec
    solver_names = set()
    for sched in schedulers:
        solver_names |= set(sched.solvers)
    tracked_entries: dict[str, str] = {}
    for sched in schedulers:
        tracked_entries.update(sched.queue.entries())
    for pod in sorted(cluster.list_pods(), key=lambda p: p.key):
        rec = merged.get(pod.key)
        if pod.node_name:
            if pod.key in sched_bound and (
                rec is None or rec["outcome"] != "bound"
            ):
                _record(
                    violations, "journal", cycle,
                    f"fleet-bound pod {pod.key} lacks a terminal "
                    "'bound' record in any replica's journal (last: "
                    f"{rec['outcome'] if rec else None})",
                )
            continue
        if pod.scheduler_name not in solver_names:
            continue
        if tracked_entries.get(pod.key) == "gated":
            continue
        if rec is None:
            _record(
                violations, "journal", cycle,
                f"unbound pod {pod.key} never appeared in any "
                "replica's decision journal",
            )
        elif rec["outcome"] not in TERMINAL_OUTCOMES:
            _record(
                violations, "journal", cycle,
                f"unbound pod {pod.key}'s merged last outcome "
                f"{rec['outcome']!r} (replica "
                f"{rec.get('replica', '?')}) is non-terminal",
            )


def check_resilience(
    scheduler,
    cycle: int,
    violations: list[Violation],
    *,
    device_faults: int = 0,
    poison_hits: int = 0,
) -> None:
    """Degraded-mode resilience invariants, checked after quiescence
    for profiles that injected solver-boundary faults:

    - **fallback engaged** — injected device faults must have tripped
      at least one breaker (the ladder actually absorbed the outage;
      zero trips would mean the faults never reached the solve path);
    - **breaker re-closed** — once the fault window has passed and the
      scheduler has settled, every profile must be back at the TOP
      ladder tier (probes climbed back up; a permanently-degraded
      scheduler after a transient fault is a resilience bug);
    - **poison isolated** — poison-pod hits must have produced at
      least one quarantine (the bisection found the poison instead of
      infinitely retrying or losing the batch). Terminal journaling of
      the quarantined pods is covered by the journal-completeness
      invariant (``quarantined`` is a terminal outcome).
    """
    r = scheduler.resilience
    if device_faults > 0 and r.trips < 1:
        _record(
            violations, "resilience", cycle,
            f"{device_faults} device solver faults were injected but "
            "no breaker ever tripped — the ladder never engaged",
        )
    if device_faults > 0:
        for profile in scheduler.solvers:
            idx = r.tier_index(profile)
            if idx != 0:
                _record(
                    violations, "resilience", cycle,
                    f"profile {profile} is still at ladder tier "
                    f"{r.ladder[idx]!r} after the fault window — the "
                    "breaker never re-closed",
                )
        if r.trips >= 1 and r.recloses < 1:
            # tier_index alone goes vacuous once the settle loop has
            # advanced virtual time past every open window (elapsed
            # windows count as the top tier) — require a PROBE to have
            # actually succeeded, not just the clock to have moved
            # (device-fault profiles keep arrivals flowing after the
            # window precisely so a real probe runs)
            _record(
                violations, "resilience", cycle,
                f"breaker tripped {r.trips}x but never re-closed via "
                "a successful probe — the scheduler only LOOKS "
                "recovered because the fault windows elapsed",
            )
    if poison_hits > 0 and not scheduler._quarantine_counts:
        _record(
            violations, "resilience", cycle,
            f"{poison_hits} poison-batch failures were injected but "
            "no pod was ever quarantined — the bisection never "
            "isolated the poison",
        )


def check_tuning(
    cycle: int,
    violations: list[Violation],
    *,
    summary: dict,
    expect_shift: bool = False,
    max_moves_per_knob: int = 8,
) -> None:
    """Closed-loop auto-tuning invariants (kubernetes_tpu/tuning),
    checked after quiescence for profiles that enabled the tuner:

    - **engaged** — the controllers must have probed at least once
      (zero probes means the tick never reached them and every other
      clause would pass vacuously);
    - **settled** — after churn stops, every controller must be
      settled (a tuner still thrashing a knob on a steady workload is
      the oscillation hysteresis exists to prevent). Scoped to
      CONVERGENCE OPPORTUNITY: a shift detected near the end of the
      drive leaves the tuner legitimately mid-re-convergence, so the
      clause only fires when the batches seen since the last unsettle
      reach the controllers' structural settle bound (probe budget x
      evaluation windows — summary's ``settle_bound``);
    - **no guardrail breach** — a tuner-APPLIED value failing its
      guard (e.g. a drain chunk whose HBM estimate exceeds the budget)
      must never happen: proposals are guarded before application, so
      ``guardrail_breaches`` is pinned at exactly 0;
    - **no knob thrash** — accepted moves per knob are bounded
      (``max_moves_per_knob``): the hysteresis margin makes an A<->B
      oscillation structurally impossible within one workload regime,
      so an unbounded move count means the margin logic broke;
    - **shift detected** — when the profile shifted the workload
      mid-drive, the tuner must have seen it (``shifts >= 1``) — a
      settled tuner that sleeps through a regime change serves the OLD
      workload's knobs forever.
    """
    probes = summary.get("probes", 0)
    if probes < 1:
        _record(
            violations, "tuning", cycle,
            "the tuning runtime never probed a knob — the controllers "
            "never engaged (every other tuning clause is vacuous)",
        )
        return
    if summary.get("settled") != 1 and summary.get(
        "batches_since_unsettle", 10**9
    ) >= summary.get("settle_bound", 0):
        _record(
            violations, "tuning", cycle,
            "tuning controllers still unsettled after quiescence "
            f"despite {summary.get('batches_since_unsettle')} batches "
            f"of opportunity (bound {summary.get('settle_bound')}): "
            f"knobs={summary.get('knobs')} — the hysteresis/settle "
            "machinery failed to converge on a steady workload",
        )
    breaches = summary.get("guardrail_breaches", 0)
    if breaches != 0:
        _record(
            violations, "tuning", cycle,
            f"{breaches} guardrail breach(es): a tuner-applied value "
            "failed its guard — proposals must be rejected BEFORE "
            "application, never applied and rolled back",
        )
    moves = summary.get("max_knob_moves", 0)
    if moves > max_moves_per_knob:
        _record(
            violations, "tuning", cycle,
            f"a knob accepted {moves} moves (> {max_moves_per_knob}) — "
            "knob thrash: the hysteresis margin is not bounding the "
            "climb",
        )
    if expect_shift and summary.get("shifts", 0) < 1:
        _record(
            violations, "tuning", cycle,
            "the profile shifted the workload mid-drive but the tuner "
            "never detected it — settled knobs are serving a workload "
            "that no longer exists",
        )


def merged_last_outcomes(journal_line_sets) -> dict[str, dict]:
    """Last-record-wins merge of decision journals across scheduler
    INCARNATIONS (the process-lifecycle analog of the fleet merge):
    within one incarnation records append in virtual-time order, and a
    successor incarnation's records all follow its predecessor's on the
    shared timeline, so feeding the line sets in incarnation order and
    letting the last record win yields each pod's true final outcome.
    The journal-completeness invariant then holds ACROSS a crash: the
    recovery pass's terminal ``recovered`` records close every history
    the dead incarnation left dangling."""
    import json

    out: dict[str, dict] = {}
    for lines in journal_line_sets:
        for line in lines:
            rec = json.loads(line)
            out[rec["pod"]] = rec
    return out


def check_recovery(
    cycle: int,
    violations: list[Violation],
    *,
    crash_expected: bool,
    crashes: int,
    incarnations: int,
    orphans_at_restart: int,
    recovered_records: int,
) -> None:
    """Crash-restart recovery invariants (the crash_restart profile):

    - **crash engaged** — the profile demanded a mid-batch kill and
      one actually fired (zero crashes would make every other
      assertion vacuous);
    - **fresh incarnation** — a crash was followed by a restarted
      Scheduler (incarnations advanced);
    - **orphans re-adopted and journaled** — the pods the crash
      orphaned (unbound at restart) each got a terminal ``recovered``
      record from the fresh incarnation, so the merged
      cross-incarnation journal stays complete. Bounded recovery —
      every orphan accounted for immediately after the restart — is
      asserted by the lost-pod check the harness runs right after
      constructing the new incarnation.
    """
    if not crash_expected:
        return
    if crashes < 1:
        _record(
            violations, "recovery", cycle,
            "the profile demanded a mid-batch crash but none fired — "
            "the process-lifecycle fault never engaged",
        )
        return
    if incarnations < 2:
        _record(
            violations, "recovery", cycle,
            f"{crashes} crash(es) fired but only {incarnations} "
            "incarnation(s) ever ran — the restart never happened",
        )
    if orphans_at_restart > 0 and recovered_records < 1:
        _record(
            violations, "recovery", cycle,
            f"the crash orphaned {orphans_at_restart} unbound pod(s) "
            "but the fresh incarnation journaled zero terminal "
            "'recovered' records — cross-incarnation journal "
            "completeness cannot hold",
        )


def check_hub_partition(
    cycle: int,
    violations: list[Violation],
    *,
    fenced_commits: int,
    zombie_binds_while_fenced: int,
    stale_rejections: int,
) -> None:
    """Partition-safety invariants (the hub_partition profile):

    - **all-zombie-commits-fenced** — every bind the fenced replica
      attempted was rejected with Conflict: zero of its commits landed
      while its fence was revoked, and at least one attempt actually
      happened (zero attempts would make the fence assertion vacuous);
    - **conservative admission engaged** — while peer occupancy rows
      were aged out past the staleness bound, at least one cross-shard-
      constrained placement was rejected as stale rather than admitted
      against rows that may hide peers' placements. (That no violating
      placement ever landed is asserted by the constraint/overcommit
      checks that run every cycle.)
    """
    if fenced_commits < 1:
        _record(
            violations, "fencing", cycle,
            "the zombie replica never had a commit rejected by the "
            "fence — the zombie-writes-after-lease-loss fault never "
            "engaged",
        )
    if zombie_binds_while_fenced > 0:
        _record(
            violations, "fencing", cycle,
            f"{zombie_binds_while_fenced} bind(s) by the fenced "
            "replica LANDED — the commit fence leaked a zombie write",
        )
    if stale_rejections < 1:
        _record(
            violations, "fencing", cycle,
            "no placement was ever rejected by the occupancy-staleness "
            "bound — conservative admission never engaged during the "
            "partition",
        )


def check_hub_failover(
    cycle: int,
    violations: list[Violation],
    *,
    promotions: int,
    epoch: int,
    deposed_write_rejections: int,
    flush_dedup_hits: int,
    stale_rejections: int,
    hub_journal_missing: int,
    old_primary_reads_ok,
    expect_dedup: bool = True,
) -> None:
    """Hub-HA invariants (the hub_failover profile): a primary-hub
    kill mid-drive must heal WITHOUT operator action, and the fencing
    epoch must make the old primary harmless.

    - **exactly one failover** — the standby promoted once, and the
      fleet ends at epoch 2 (initial grant + one takeover; more would
      mean lease flapping, zero would mean the fault never engaged);
    - **stale-primary writes rejected** — the resurrected old primary
      rejected >= 1 replica-facing write with the typed HubDeposed
      (that none LANDED is covered by the overcommit/constraint checks
      that run every cycle — here we pin that the fence actually
      fired, not vacuously);
    - **idempotent flush proven** — the injected reply-loss-after-
      apply forced >= 1 hub-side dedup hit (the double-apply hazard's
      regression clause, exercised inside the chaos loop);
    - **conservative admission engaged** — the blackout window drove
      >= 1 staleness rejection instead of admitting against a view
      the dead hub could no longer refresh;
    - **journal aggregation complete** — after heal, every line each
      replica's journal shipped is present in the serving hub's
      aggregation surface (zero lost to the failover: pre-kill lines
      arrived via replication, blackout lines via the cursor-retrying
      client buffers);
    - **old primary serves reads** — its debug/status surface stayed
      readable after resurrection (the operator's post-mortem path).
    """
    if promotions != 1:
        _record(
            violations, "hub_failover", cycle,
            f"expected exactly one standby promotion, saw {promotions} "
            "(0 = the kill never engaged, >1 = lease flapping)",
        )
    if epoch != 2:
        _record(
            violations, "hub_failover", cycle,
            f"fleet ended at hub epoch {epoch}, expected 2 (initial "
            "grant + exactly one epoch-fenced takeover)",
        )
    if deposed_write_rejections < 1:
        _record(
            violations, "hub_failover", cycle,
            "the deposed old primary never rejected a replica-facing "
            "write — the stale-primary fence was never exercised",
        )
    if expect_dedup and flush_dedup_hits < 1:
        _record(
            violations, "hub_failover", cycle,
            "no write-behind flush was deduped — the injected "
            "reply-loss-after-apply never forced the idempotency path",
        )
    if stale_rejections < 1:
        _record(
            violations, "hub_failover", cycle,
            "no placement was rejected by the staleness bound during "
            "the blackout — conservative admission never engaged",
        )
    if hub_journal_missing > 0:
        _record(
            violations, "hub_failover", cycle,
            f"{hub_journal_missing} journal line(s) shipped by "
            "replicas are missing from the serving hub's aggregation "
            "surface after heal — the failover lost history",
        )
    if old_primary_reads_ok is False:
        _record(
            violations, "hub_failover", cycle,
            "the resurrected old primary failed to serve its "
            "read/status surface — post-mortem reads must survive "
            "deposition",
        )


class RebalanceTracker:
    """Independent witness for the rebalancer's eviction activity:
    subscribes straight to the state service and counts the Events-API
    ``Evicted`` records the eviction subresource emits, re-checking PDB
    allowances against its OWN mirror (seeded from the PDBs' original
    ``disruptionsAllowed``, decremented per observed eviction) — so a
    bug in the enforcement code cannot vouch for itself."""

    def __init__(self, cluster: ClusterState) -> None:
        import dataclasses

        self._cluster = cluster
        # snapshot the PDBs at construction: selector + the ORIGINAL
        # allowance (the live objects decrement as evictions land)
        self._pdbs = [
            dataclasses.replace(pdb) for pdb in cluster.list_pdbs()
        ]
        self._allow = [pdb.disruptions_allowed for pdb in self._pdbs]
        self.evictions = 0
        self.evicted_keys: list[str] = []
        self.pdb_overruns = 0
        cluster.subscribe(self._on_event)

    def _on_event(self, ev: Event) -> None:
        if ev.kind != "Event":
            return
        rec = ev.obj
        if getattr(rec, "reason", "") != "Evicted":
            return
        self.evictions += 1
        key = f"{rec.regarding_namespace}/{rec.regarding_name}"
        self.evicted_keys.append(key)
        try:
            pod = self._cluster.get_pod(
                rec.regarding_namespace, rec.regarding_name
            )
        except Exception:
            return  # vanished before delivery: nothing to match
        for i, pdb in enumerate(self._pdbs):
            if pdb.matches(pod):
                self._allow[i] -= 1
                if self._allow[i] < 0:
                    self.pdb_overruns += 1


def check_rebalance(
    cycle: int,
    violations: list[Violation],
    *,
    history,
    budget: int,
    pdb_overruns: int,
    migrations_completed: int,
    churn_end_t: float,
    final_packing: float,
    expect_runs: bool = True,
    tol: float = 0.02,
) -> None:
    """Continuous-rebalancer invariants (the fragmentation profile),
    checked after quiescence:

    - **engaged** — the profile demanded rebalancing and at least one
      pass actually ran (zero passes would make everything else
      vacuous);
    - **churn budget** — no pass evicted more than the configured
      budget;
    - **PDB never violated** — the independent tracker's allowance
      mirror never went negative (a PDB-guarded pod moving at 0
      disruptions allowed is exactly the bug the eviction subresource
      must make impossible);
    - **migrations complete** — evictions are only half a migration:
      when anything was evicted, at least one evicted pod must have
      re-bound (an evict-and-strand rebalancer destroys capacity);
    - **utilization monotonic** — across the SETTLE-phase passes
      (``t >= churn_end_t``: churn has stopped, so packing changes are
      the rebalancer's alone) the packed utilization each pass observes
      must be non-decreasing (within ``tol``), and the final packed
      utilization must not regress below the first settle-phase pass's.
      During-churn passes are exempt: arrivals and deletes legitimately
      move packing both ways under the rebalancer's feet.
    """
    if not history:
        if expect_runs:
            _record(
                violations, "rebalance", cycle,
                "the profile demanded rebalancing but no pass ever "
                "ran — the defragmentation loop never engaged",
            )
        return
    for r in history:
        if r.evicted > budget:
            _record(
                violations, "rebalance", cycle,
                f"rebalance pass at t={r.t} evicted {r.evicted} pods "
                f"> churn budget {budget}",
            )
    if pdb_overruns > 0:
        _record(
            violations, "rebalance", cycle,
            f"{pdb_overruns} eviction(s) landed on pods whose "
            "PodDisruptionBudget had no disruptions left — the PDB "
            "gate leaked",
        )
    total_evicted = sum(r.evicted for r in history)
    if total_evicted > 0 and migrations_completed < 1:
        _record(
            violations, "rebalance", cycle,
            f"{total_evicted} eviction(s) but zero completed "
            "migrations — the rebalancer evicts and strands",
        )
    settle = [r for r in history if r.t >= churn_end_t]
    for prev, cur in zip(settle, settle[1:]):
        if cur.packing_before < prev.packing_before - tol:
            _record(
                violations, "rebalance", cycle,
                "packed utilization regressed across settle-phase "
                f"rebalance passes: {prev.packing_before:.4f} -> "
                f"{cur.packing_before:.4f}",
            )
    if settle and migrations_completed >= 1 and (
        final_packing < settle[0].packing_before - tol
    ):
        _record(
            violations, "rebalance", cycle,
            f"final packed utilization {final_packing:.4f} regressed "
            f"below the first settle-phase pass's "
            f"{settle[0].packing_before:.4f}",
        )


def packed_utilization(cluster: ClusterState) -> float:
    """Dominant-resource fill of the in-use nodes, from cluster TRUTH
    (pod objects, not the scheduler's snapshot) — the invariant-side
    mirror of rebalance/detector.py's packed_utilization, computed
    through an independent path so the two can disagree when one is
    buggy."""
    nodes = {n.name: n for n in cluster.list_nodes()}
    used: dict[str, dict[str, int]] = {}
    for pod in cluster.list_pods():
        if pod.node_name and pod.node_name in nodes:
            u = used.setdefault(pod.node_name, {})
            for r, v in pod.resource_request().items():
                u[r] = u.get(r, 0) + v
    if not used:
        return 1.0
    tot_u = {"cpu": 0, "memory": 0}
    tot_a = {"cpu": 0, "memory": 0}
    for name, u in used.items():
        alloc = nodes[name].allocatable
        for r in ("cpu", "memory"):
            tot_u[r] += u.get(r, 0)
            tot_a[r] += alloc.get(r, 0)
    fracs = [
        tot_u[r] / tot_a[r] for r in ("cpu", "memory") if tot_a[r] > 0
    ]
    return max(fracs) if fracs else 1.0


class MonotonicCounters:
    """Counter series must never decrease between checks. ``sample``
    is injectable so known-bad tests can feed a regressing series; the
    default reads the live metrics registry."""

    WATCHED = (
        "scheduler_schedule_attempts_total",
        "scheduler_queue_incoming_pods_total",
        "scheduler_tpu_solves_discarded_total",
        "scheduler_pipeline_fallback_total",
        "scheduler_preemption_attempts_total",
    )

    def __init__(self, sample: Callable[[], dict[str, float]] | None = None):
        self._sample = sample or self._sample_registry
        self._last: dict[str, float] = {}

    @staticmethod
    def _sample_registry() -> dict[str, float]:
        out: dict[str, float] = {}
        for family in metrics.REGISTRY.collect():
            for s in family.samples:
                if not s.name.endswith("_total"):
                    continue
                if s.name in MonotonicCounters.WATCHED:
                    out[s.name] = out.get(s.name, 0.0) + s.value
        return out

    def observe(self, cycle: int, violations: list[Violation]) -> None:
        cur = self._sample()
        for name in sorted(self._last):
            if cur.get(name, 0.0) < self._last[name]:
                _record(
                    violations, "monotonic", cycle,
                    f"counter {name} went backwards: "
                    f"{self._last[name]} -> {cur.get(name, 0.0)}",
                )
        self._last = cur


def check_telemetry(
    cycle: int,
    violations: list[Violation],
    *,
    summary: dict,
    bundle_dir: str | None = None,
) -> None:
    """Flight-telemetry invariants, checked after quiescence for
    profiles that enabled the always-on telemetry loop
    (``profile.telemetry``). This is the closed-loop forensic
    contract — profile, detect, capture, replay — asserted end to end:

    - **sentinel fired** — a profile that injects a health regression
      (the ``anomaly_storm`` solver-fault window) must have produced
      at least one anomaly; a silent sentinel through a storm means
      the detection rules never engaged;
    - **capture engaged** — every anomaly/breaker trigger routes
      through the bundle capturer, so at least one capture event must
      have been counted (capture counting is independent of whether a
      bundle directory was configured — the ``--selfcheck`` re-run
      leans on that);
    - **bundles replay bit-identical** — every bundle directory
      written under ``bundle_dir`` must re-execute offline to the
      exact assignments the live run produced. A replay mismatch is
      the worst telemetry bug there is: a forensic artifact that lies.
      Chained/split solves are legitimately non-replayable standalone
      (the bundle records why), but when a directory was configured at
      least one written bundle must close the loop.
    """
    if summary.get("anomalies", 0) < 1:
        _record(
            violations, "telemetry", cycle,
            "telemetry profile ran a fault storm but the anomaly "
            "sentinel never fired",
        )
    if summary.get("bundles_captured", 0) < 1:
        _record(
            violations, "telemetry", cycle,
            "anomaly/breaker triggers fired but no capture event was "
            "counted — the capture seam is disconnected",
        )
    if not bundle_dir:
        return
    import os

    from ..obs.bundle import replay_bundle

    dirs = sorted(
        d for d in os.listdir(bundle_dir) if d.startswith("bundle-")
    )
    if not dirs:
        _record(
            violations, "telemetry", cycle,
            "a bundle directory was configured but no bundle was "
            "written to it",
        )
        return
    replayed_ok = 0
    for d in dirs:
        try:
            rep = replay_bundle(os.path.join(bundle_dir, d))
        except Exception as exc:  # noqa: BLE001 - any failure is the finding
            _record(
                violations, "telemetry", cycle,
                f"bundle {d} failed to load/replay: {exc!r}",
            )
            continue
        if not rep["replayable"]:
            continue
        if rep["ok"]:
            replayed_ok += 1
        else:
            _record(
                violations, "telemetry", cycle,
                f"bundle {d} replay diverged from the live run: "
                f"{rep['detail']}",
            )
    if replayed_ok < 1:
        _record(
            violations, "telemetry", cycle,
            f"{len(dirs)} bundles written but none replayed "
            "bit-identical — the forensic loop never closed",
        )


def check_megaplan(
    cycle: int,
    violations: list[Violation],
    *,
    summary: dict | None,
    ratio_floor: float = 0.9,
) -> None:
    """Convex-relaxation mega-planner invariants (megaplan profiles,
    ISSUE 19), checked after quiescence. Three claims, all asserted
    non-vacuously:

    - **engaged** — the warm-start relaxation actually iterated AND
      re-ranked at least one backlog pod before the first chunk
      popped; a megaplan profile that drains in plain FIFO order is
      the feature silently disconnected, not a pass;
    - **valid** — the probe's relaxed+rounded+repaired plan survived
      the sequential oracle's feasibility replay (every placed pick in
      the feasible set given identical history — no overcommit, every
      filter honored). Tie-set parity is deliberately not required: a
      global plan trades per-step greedy optimality for packing;
    - **quality** — the plan's placements clear ``ratio_floor`` of the
      oracle's own greedy run on the identical snapshot. The floor is
      the acceptance bar for trusting the relaxation to ORDER work:
      a plan much worse than greedy would make the warm-start an
      anti-signal.
    """
    if summary is None:
        _record(
            violations, "megaplan", cycle,
            "megaplan profile ran but the pre-drain probe produced no "
            "summary — the probe never saw a backlog",
        )
        return
    if summary.get("iterations", 0) < 1:
        _record(
            violations, "megaplan", cycle,
            "warm-start relaxation never iterated — the mega-planner "
            "did not engage",
        )
    if summary.get("ranked", 0) < 1:
        _record(
            violations, "megaplan", cycle,
            "relaxed plan re-ranked zero backlog pods — the "
            "warm-start reorder seam is disconnected",
        )
    if not summary.get("plan_valid", False):
        _record(
            violations, "megaplan", cycle,
            "relaxed+rounded+repaired plan failed the oracle "
            f"feasibility replay ({summary.get('plan_errors', '?')} "
            "errors)",
        )
    ratio = summary.get("objective_ratio", 0.0)
    if ratio < ratio_floor:
        _record(
            violations, "megaplan", cycle,
            f"megaplan objective ratio {ratio} below the "
            f"{ratio_floor} floor vs the exact anchor "
            f"({summary.get('relax_placed')} vs "
            f"{summary.get('exact_placed')} placed)",
        )


def check_fleet_drain(
    cycle: int,
    violations: list[Violation],
    *,
    backlog: int,
    drained: int,
    double_binds: int,
    lost: int,
    leases_reassigned: int,
    expect_reassign: bool,
) -> None:
    """Fleet backlog-drain invariants (the fleet_backlog_drain
    profile, ROADMAP #5a), checked after quiescence. The hub's lease
    ledger promises exactly-once drain semantics across a fleet of
    concurrent drainers — including through a mid-drain replica kill:

    - **engaged** — a backlog existed and the ledger recorded drain
      progress; a fleet-drain profile that drains nothing (or whose
      coordinator never installed a ledger) is the feature silently
      disconnected, not a pass;
    - **none lost** — every cycle-0 backlog pod ended bound somewhere
      in the fleet (``lost`` counts the stragglers). A dead replica's
      outstanding lease keys must come back as orphans and drain at a
      survivor;
    - **none doubled** — zero backlog pods were reported scheduled by
      more than one replica: the one-granted-lease-per-pod rule held
      (the per-cycle double-bind tracker asserts the cluster-level
      half; this clause pins the drain-lease partitioning itself);
    - **reassignment engaged** (kill profiles) — the dead replica's
      lease actually returned and a survivor claimed it at least once;
      zero reassignments under a replica kill means the
      return-on-retire seam is disconnected.
    """
    if backlog < 1:
        _record(
            violations, "fleet_drain", cycle,
            "fleet-drain profile ran with an empty backlog — the "
            "drain invariants are vacuous",
        )
        return
    if drained < 1:
        _record(
            violations, "fleet_drain", cycle,
            "the drain ledger recorded zero pods drained — the "
            "coordinator/lease seam never engaged",
        )
    if lost > 0:
        _record(
            violations, "fleet_drain", cycle,
            f"{lost} backlog pod(s) ended unbound fleet-wide — the "
            "drain lost work (a returned lease's keys must be "
            "reassigned, not dropped)",
        )
    if double_binds > 0:
        _record(
            violations, "fleet_drain", cycle,
            f"{double_binds} backlog pod(s) were scheduled by more "
            "than one replica — a pod belonged to two drain leases",
        )
    if expect_reassign and leases_reassigned < 1:
        _record(
            violations, "fleet_drain", cycle,
            "a replica died mid-drain but no lease was ever "
            "reassigned — the return-on-retire seam is disconnected",
        )
