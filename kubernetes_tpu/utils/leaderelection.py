"""Lease-based leader election — the client-go
tools/leaderelection analog (SURVEY §3.3: active/passive HA; losing the
lease exits the process).

Mirrors leaderelection.go#tryAcquireOrRenew over a coordination.k8s.io/v1
Lease subset stored in the cluster state service with optimistic
concurrency: the holder renews ``renewTime`` every ``retry_period``;
challengers take over only when ``renewTime + lease_duration`` has
passed; a holder that cannot renew within ``renew_deadline`` reports
leadership lost (the reference's OnStoppedLeading → process exit).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..state.cluster import ApiError, ClusterState
from .clock import Clock


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease subset (spec fields the elector
    uses)."""

    name: str
    namespace: str = "kube-system"
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def to_dict(self) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "resourceVersion": str(self.resource_version),
            },
            "spec": {
                "holderIdentity": self.holder_identity,
                "leaseDurationSeconds": int(self.lease_duration_seconds),
                "acquireTime": self.acquire_time,
                "renewTime": self.renew_time,
            },
        }


@dataclass
class LeaderElector:
    cluster: ClusterState
    identity: str
    name: str = "kubernetes-tpu-scheduler"
    namespace: str = "kube-system"
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    clock: Clock = field(default_factory=Clock)
    is_leader: bool = False
    # fleet mode (kubernetes_tpu/fleet): per-shard lease identity.
    # Replica i of an active-active fleet elects on its OWN lease
    # ``<name>-shard-<i>`` instead of contending with its peers on one
    # global lease — N replicas hold N leases concurrently, and a
    # shard lease going stale is exactly the membership signal
    # FleetMembership.refresh_from_leases reads. None = the classic
    # single active/passive lease.
    shard: int | None = None

    def __post_init__(self) -> None:
        """leaderelection.go#LeaderElectionConfig validation: the
        protocol is only sound when leaseDuration > renewDeadline >
        retryPeriod (all positive) — a renew deadline at or beyond the
        lease duration lets a challenger take over while the holder
        still believes it leads, and a retry period at or beyond the
        renew deadline guarantees missing the deadline on one lost
        renewal."""
        if self.retry_period <= 0:
            raise ValueError(
                f"retry_period must be positive, got {self.retry_period}"
            )
        if self.renew_deadline <= self.retry_period:
            raise ValueError(
                "renew_deadline must exceed retry_period "
                f"({self.renew_deadline} <= {self.retry_period})"
            )
        if self.lease_duration <= self.renew_deadline:
            raise ValueError(
                "lease_duration must exceed renew_deadline "
                f"({self.lease_duration} <= {self.renew_deadline})"
            )
        if self.shard is not None:
            if self.shard < 0:
                raise ValueError(
                    f"shard must be non-negative, got {self.shard}"
                )
            self.name = f"{self.name}-shard-{self.shard}"

    @property
    def _key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def try_acquire_or_renew(self) -> bool:
        """One tryAcquireOrRenew step: True iff this identity holds the
        lease afterwards. Optimistic-concurrency conflicts report False
        (the caller retries next period, like the reference)."""
        now = self.clock.now()
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except ApiError:
            lease = Lease(
                name=self.name,
                namespace=self.namespace,
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now,
                renew_time=now,
            )
            try:
                self.cluster.create_lease(lease)
            except ApiError:
                self.is_leader = False
                return False  # lost the creation race
            self.is_leader = True
            return True
        if (
            lease.holder_identity
            and lease.holder_identity != self.identity
            and now < lease.renew_time + lease.lease_duration_seconds
        ):
            self.is_leader = False
            return False  # held by a live leader
        expect = lease.resource_version
        if lease.holder_identity != self.identity:
            lease.acquire_time = now
        lease.holder_identity = self.identity
        lease.lease_duration_seconds = self.lease_duration
        lease.renew_time = now
        try:
            self.cluster.update_lease(lease, expect_rv=expect)
        except ApiError:
            self.is_leader = False
            return False  # someone else won the update race
        self.is_leader = True
        return True

    def run(
        self,
        stop: threading.Event,
        on_started_leading=None,
        on_stopped_leading=None,
    ) -> None:
        """RunOrDie's loop shape: block acquiring, call
        ``on_started_leading`` once, renew every retry_period; when a
        renewal hasn't succeeded within renew_deadline (or the lease was
        taken), call ``on_stopped_leading`` and return — the caller is
        expected to exit, like the reference."""
        while not stop.is_set():
            if self.try_acquire_or_renew():
                break
            if stop.wait(self.retry_period):
                return
        if stop.is_set():
            return
        if on_started_leading is not None:
            on_started_leading()
        # one timebase for the whole protocol: the injected clock stamps
        # lease renewals AND measures the renew deadline, so holder
        # self-demotion and challenger takeover can't drift apart (and
        # the loss path is drivable with a fake clock)
        last_renew = self.clock.now()
        while not stop.is_set():
            if stop.wait(self.retry_period):
                return
            if self.try_acquire_or_renew():
                last_renew = self.clock.now()
            elif self.clock.now() - last_renew > self.renew_deadline:
                self.is_leader = False
                if on_stopped_leading is not None:
                    on_stopped_leading()
                return
